"""psim analog (src/crush/psim.cc): toy placement simulator — build a
synthetic hierarchy, place N objects, report the per-device utilization
spread.  Quick sanity of CRUSH balance without a cluster.

Usage: python -m ceph_tpu.tools.psim [--hosts H] [--per-host D]
          [--objects N] [--numrep R]
"""

from __future__ import annotations

import argparse
import json


def simulate(hosts: int = 16, per_host: int = 4, objects: int = 4096,
             numrep: int = 3) -> dict:
    import numpy as np

    from ceph_tpu.common.context import default_context
    from ceph_tpu.crush import build_two_level_map

    crush_map, _root, rid = build_two_level_map(hosts, per_host)
    n_dev = hosts * per_host
    reweight = np.full(n_dev, 0x10000, dtype=np.int64)
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 2 ** 32, (objects,), dtype=np.uint32)
    # the production bulk-placement path: the shared mapping service's
    # cached mapper + dispatch-engine submission, not a private mapper
    svc = default_context().mapping_service()
    out = np.asarray(svc.place(crush_map, rid, xs, numrep, reweight))
    counts = np.zeros(n_dev, dtype=np.int64)
    for col in range(out.shape[1]):
        valid = out[:, col] >= 0
        np.add.at(counts, out[valid, col], 1)
    expected = objects * numrep / n_dev
    return {
        "devices": n_dev, "objects": objects, "numrep": numrep,
        "placements": int(counts.sum()),
        "expected_per_device": round(expected, 1),
        "min": int(counts.min()), "max": int(counts.max()),
        "stddev_pct": round(float(counts.std() / expected * 100), 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="psim")
    ap.add_argument("--hosts", type=int, default=16)
    ap.add_argument("--per-host", type=int, default=4)
    ap.add_argument("--objects", type=int, default=4096)
    ap.add_argument("--numrep", type=int, default=3)
    a = ap.parse_args(argv)
    print(json.dumps(simulate(a.hosts, a.per_host, a.objects, a.numrep)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
