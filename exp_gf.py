"""Experiment: GF(2^8) encode kernel variants at the north-star shape.

Compares the shipped nibble one-hot kernel against bit-matrix GF(2) designs:
  v0  nibble one-hot bf16  (shipped): (T, k*32) @ (k*32, m*8)
  v1  bit-rows int8:                  (T, k*8)  @ (k*8, m*8)
  v2  bit-rows blockdiag-4 int8:      (T/4, k*32) @ blockdiag -> (T/4, m*32)
  v3  v2 in bf16
Shape: k=8 m=4, 4 KiB chunks, 2048 stripes (64 MiB per call).
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf.tables import gf_mul, nibble_bit_table
from ceph_tpu.ops.gf_kernel import _encode_xla as _encode_impl, ec_encode_ref
from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from bench import chained_seconds_per_step

K, M = 8, 4
CHUNK = 4096
STRIPES = 2048


def bit_matrix(coeff: np.ndarray) -> np.ndarray:
    """(k*8, m*8) GF(2) matrix: W[j*8+s, i*8+r] = bit r of coeff[i,j] * 2^s."""
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    w = np.zeros((k * 8, m * 8), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            for s in range(8):
                p = gf_mul(int(coeff[i, j]), 1 << s)
                for r in range(8):
                    w[j * 8 + s, i * 8 + r] = (p >> r) & 1
    return w


_BITW = np.arange(8, dtype=np.int32)
TILE = 1 << 15


def _tile_loop(x, fn, rows_out, group=1):
    rows = x.shape[0]
    t = TILE
    if rows <= t:
        return fn(x)
    pad = (-rows) % t
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], dtype=x.dtype)])
    tiles = x.reshape(-1, t, *x.shape[1:])
    out = jax.lax.map(fn, tiles)
    return out.reshape(-1, *out.shape[2:])[:rows]


@functools.partial(jax.jit, static_argnames=("k", "m", "dtype"))
def enc_bits(w, data, *, k, m, dtype):
    s, _, b = data.shape
    x = jnp.transpose(data, (0, 2, 1)).reshape(s * b, k)

    def tile(xt):
        t = xt.shape[0]
        bits = ((xt[:, :, None].astype(jnp.int32) >> _BITW) & 1)
        bits = bits.reshape(t, k * 8).astype(dtype)
        acc = jax.lax.dot_general(
            bits, w.astype(dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32 if dtype == jnp.bfloat16 else jnp.int32)
        pb = acc.astype(jnp.int32) & 1
        return jnp.sum(pb.reshape(t, m, 8) << _BITW, axis=-1).astype(jnp.uint8)

    packed = _tile_loop(x, tile, s * b)
    return jnp.transpose(packed.reshape(s, b, m), (0, 2, 1)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "g", "dtype"))
def enc_blockdiag(wblk, data, *, k, m, g, dtype):
    s, _, b = data.shape
    x = jnp.transpose(data, (0, 2, 1)).reshape(s * b, k)

    def tile(xt):
        t = xt.shape[0]
        bits = ((xt[:, :, None].astype(jnp.int32) >> _BITW) & 1)
        bits = bits.reshape(t // g, g * k * 8).astype(dtype)
        acc = jax.lax.dot_general(
            bits, wblk.astype(dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32 if dtype == jnp.bfloat16 else jnp.int32)
        pb = acc.astype(jnp.int32) & 1  # (t/g, g*m*8)
        return jnp.sum(pb.reshape(t, m, 8) << _BITW, axis=-1).astype(jnp.uint8)

    packed = _tile_loop(x, tile, s * b)
    return jnp.transpose(packed.reshape(s, b, m), (0, 2, 1)).astype(jnp.uint8)


def main():
    gen = gen_cauchy1_matrix(K, M)
    coding = gen[K:]
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (STRIPES, K, CHUNK), dtype=np.uint8)
    data = jnp.asarray(data_np)
    data_bytes = STRIPES * K * CHUNK
    ref = ec_encode_ref(coding, data_np[:4])

    w_nib = jnp.asarray(nibble_bit_table(coding))
    wb = bit_matrix(coding)
    w_bits = jnp.asarray(wb)
    g = 4
    wblk_np = np.zeros((g * K * 8, g * M * 8), dtype=np.uint8)
    for i in range(g):
        wblk_np[i * K * 8:(i + 1) * K * 8, i * M * 8:(i + 1) * M * 8] = wb
    w_blk = jnp.asarray(wblk_np)

    variants = {
        "v0_nibble_bf16": lambda d: _encode_impl(w_nib, d, k=K, m=M, dot_dtype=jnp.bfloat16),
        "v1_bits_int8": lambda d: enc_bits(w_bits, d, k=K, m=M, dtype=jnp.int8),
        "v1_bits_bf16": lambda d: enc_bits(w_bits, d, k=K, m=M, dtype=jnp.bfloat16),
        "v2_blk4_int8": lambda d: enc_blockdiag(w_blk, d, k=K, m=M, g=g, dtype=jnp.int8),
        "v3_blk4_bf16": lambda d: enc_blockdiag(w_blk, d, k=K, m=M, g=g, dtype=jnp.bfloat16),
    }

    for name, fn in variants.items():
        try:
            out = np.asarray(fn(data[:4]))
            ok = np.array_equal(out, ref)
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: {e}")
            continue

        def step(d, fn=fn):
            p = fn(d)
            return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

        t = chained_seconds_per_step(step, data)
        print(f"{name}: {'OK ' if ok else 'BAD'} {data_bytes / t / 1e9:8.2f} GB/s")


if __name__ == "__main__":
    main()
