"""Journaler: an append-only entry log striped over RADOS objects
(src/osdc/Journaler.{h,cc} analog) — the MDS journals every metadata
mutation through this before acking, and replays it after a crash.

Layout mirrors the reference: a head object (`<name>.head`) persists
{write_pos, expire_pos, layout params}; entries live in a byte stream
striped over `<name>.<objno>` data objects (Striper layout), each entry
framed [u32 len][payload][u32 crc32].  append_entry buffers; flush
writes the buffer and then the head (data before head, so a torn flush
replays short, never corrupt).  trim advances expire_pos and removes
wholly-expired stream bytes from the head's view.
"""

from __future__ import annotations

import struct
import zlib

from ceph_tpu.osdc.striper import StripeLayout, StripedObject

_FRAME = struct.Struct("<I")


class Journaler:
    def __init__(self, ioctx, name: str,
                 layout: StripeLayout | None = None):
        self.io = ioctx
        self.name = name
        self.layout = layout or StripeLayout(stripe_unit=1 << 16,
                                             stripe_count=1,
                                             object_size=1 << 20)
        self.stream = StripedObject(ioctx, name, self.layout)
        self.write_pos = 0
        self.expire_pos = 0
        self._buf = bytearray()

    def _head_obj(self) -> str:
        return f"{self.name}.head"

    # -- lifecycle ------------------------------------------------------------

    def create(self) -> None:
        self.write_pos = 0
        self.expire_pos = 0
        self._write_head()

    def open(self) -> None:
        """Read the head (Journaler::recover)."""
        omap = self.io.get_omap(self._head_obj())
        self.write_pos = int(omap.get("write_pos", b"0").decode())
        self.expire_pos = int(omap.get("expire_pos", b"0").decode())

    def _write_head(self) -> None:
        self.io.set_omap(self._head_obj(), {
            "write_pos": str(self.write_pos).encode(),
            "expire_pos": str(self.expire_pos).encode()})

    # -- append side ----------------------------------------------------------

    def append_entry(self, payload: bytes) -> int:
        """Buffer one entry; returns its end position once flushed."""
        self._buf += _FRAME.pack(len(payload))
        self._buf += payload
        self._buf += _FRAME.pack(zlib.crc32(payload))
        return self.write_pos + len(self._buf)

    def flush(self) -> None:
        """Write buffered entries, then persist the head.  Data lands
        before the head advance: a crash between the two replays the
        old range — entries are re-applied, never half-read."""
        if not self._buf:
            return
        data = bytes(self._buf)
        self._buf.clear()
        self.stream.write(data, offset=self.write_pos)
        self.write_pos += len(data)
        self._write_head()

    # -- replay / trim --------------------------------------------------------

    def replay(self, cb, start_pos: int | None = None) -> int:
        """Read entries in [start_pos or expire_pos, write_pos), calling
        cb(payload, end_pos) — end_pos is the entry's end offset, the
        resume token a mirror client persists (Journaler::try_read_entry
        loop; client positions are how rbd-mirror tracks progress).
        Returns the count."""
        n = 0
        pos = self.expire_pos if start_pos is None else start_pos
        while pos + _FRAME.size <= self.write_pos:
            hdr = self.stream.read(pos, _FRAME.size)
            (plen,) = _FRAME.unpack(hdr)
            end = pos + _FRAME.size + plen + _FRAME.size
            if end > self.write_pos:
                break  # torn tail: flush never completed
            payload = self.stream.read(pos + _FRAME.size, plen)
            (crc,) = _FRAME.unpack(
                self.stream.read(pos + _FRAME.size + plen, _FRAME.size))
            if zlib.crc32(payload) != crc:
                raise IOError(
                    f"journal {self.name}: crc mismatch at {pos}")
            cb(payload, end)
            pos = end
            n += 1
        return n

    def trim(self, upto: int | None = None) -> None:
        """Expire everything before `upto` (default: all replayed/known
        entries).  The backing store must already reflect them."""
        self.expire_pos = self.write_pos if upto is None else upto
        self._write_head()
