"""Bit-exactness of the batched JAX CRUSH kernels vs the scalar oracle."""

import numpy as np
import jax.numpy as jnp
import pytest

from ceph_tpu.crush import build_flat_map, crush_do_rule
from ceph_tpu.crush.hashfn import crush_hash32_2, crush_hash32_3
from ceph_tpu.crush.mapper_ref import crush_ln as crush_ln_ref
from ceph_tpu.crush.mapper_ref import _bucket_straw2_choose
from ceph_tpu.crush.types import Bucket, CRUSH_BUCKET_STRAW2
from ceph_tpu.ops import crush_kernel as ck


def test_hash32_2_matches_scalar():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    b = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    got = np.asarray(ck.hash32_2(a, b))
    want = np.array([crush_hash32_2(int(x), int(y)) for x, y in zip(a, b)],
                    dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_hash32_3_matches_scalar():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    b = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    c = rng.integers(0, 2**32, 1000, dtype=np.uint32)
    got = np.asarray(ck.hash32_3(a, b, c))
    want = np.array([crush_hash32_3(int(x), int(y), int(z))
                     for x, y, z in zip(a, b, c)], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_crush_ln_exhaustive_16bit():
    """straw2 only feeds crush_ln 16-bit inputs (hash & 0xffff) — check all."""
    xs = np.arange(1 << 16, dtype=np.uint32)
    got = np.asarray(ck.crush_ln(xs))
    want = np.array([crush_ln_ref(int(x)) for x in xs], dtype=np.int64)
    np.testing.assert_array_equal(got, want)


def test_crush_ln_domain_is_16bit():
    """Inputs beyond 0xffff index out of the ln tables in the reference C too
    (mapper.c feeds crush_ln only hash & 0xffff, :335); the contract is 16-bit."""
    assert int(ck.crush_ln(jnp.uint32(0xFFFF))) == crush_ln_ref(0xFFFF)
    assert int(ck.crush_ln(jnp.uint32(0))) == crush_ln_ref(0)


def test_straw2_choose_matches_oracle():
    rng = np.random.default_rng(3)
    size = 17
    ids = np.arange(size, dtype=np.int32)
    weights = rng.integers(1, 0x40000, size).astype(np.int64)
    weights[5] = 0  # zero-weight item must never win
    bucket = Bucket(id=-1, type=1, alg=CRUSH_BUCKET_STRAW2,
                    items=[int(i) for i in ids],
                    item_weights=[int(w) for w in weights])
    xs = rng.integers(0, 2**32, 500, dtype=np.uint32)
    rs = rng.integers(0, 10, 500, dtype=np.uint32)
    got = np.asarray(ck.straw2_choose_index(jnp.asarray(xs), ids,
                                            jnp.asarray(rs), weights))
    for x, r, g in zip(xs, rs, got):
        want = _bucket_straw2_choose(bucket, int(x), int(r), None, 0)
        assert bucket.items[int(g)] == want


@pytest.mark.parametrize("numrep", [1, 3])
def test_flat_firstn_matches_oracle(numrep):
    rng = np.random.default_rng(4)
    n_osds = 40
    weights = [0x10000] * 30 + [0x8000] * 5 + [0x20000] * 5
    m, _root, rule = build_flat_map(n_osds, weights)
    reweight = [0x10000] * n_osds
    reweight[3] = 0        # marked out
    reweight[7] = 0x8000   # half reweighted -> probabilistic rejection
    xs = rng.integers(0, 2**32, 256, dtype=np.uint32)
    got = np.asarray(ck.flat_firstn(
        jnp.asarray(xs), np.arange(n_osds, dtype=np.int32),
        np.asarray(weights, dtype=np.int64),
        np.asarray(reweight, dtype=np.int64), numrep=numrep))
    for i, x in enumerate(xs):
        want = crush_do_rule(m, rule, int(x), numrep, reweight)
        mine = [int(v) for v in got[i] if v != 0x7FFFFFFF]
        assert want == mine, f"x={x}: want {want} got {mine}"
