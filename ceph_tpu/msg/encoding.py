"""Versioned binary encoding (bufferlist encode/decode + denc analog).

The reference hand-rolls little-endian encode/decode on bufferlists with
(version, compat_version, length) framing via ENCODE_START/ENCODE_FINISH
(include/encoding.h).  This is the same scheme: primitive little-endian
writers, length-prefixed containers, and a versioned-section helper so old
decoders can skip unknown trailing fields — the property the reference's
ceph-dencoder corpus checks pin.
"""

from __future__ import annotations

import struct


class Encoder:
    def __init__(self):
        self._parts: list[bytes] = []

    # -- primitives (little-endian, fixed width) ------------------------------

    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v & 0xFF))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<H", v & 0xFFFF))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v & 0xFFFFFFFF))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v & (2**64 - 1)))
        return self

    def s32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<i", v))
        return self

    def s64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "Encoder":
        self._parts.append(struct.pack("<d", v))
        return self

    def bytes(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self._parts.append(bytes(v))
        return self

    def str(self, v: str) -> "Encoder":
        return self.bytes(v.encode("utf-8"))

    def list(self, items, item_fn) -> "Encoder":
        self.u32(len(items))
        for it in items:
            item_fn(self, it)
        return self

    def map(self, d: dict, key_fn, val_fn) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            key_fn(self, k)
            val_fn(self, d[k])
        return self

    # -- versioned sections (ENCODE_START/FINISH) -----------------------------

    def versioned(self, version: int, compat: int, body_fn) -> "Encoder":
        """Emit [version u8][compat u8][len u32][body]; decoders newer fields
        can be appended without breaking old readers."""
        body = Encoder()
        body_fn(body)
        payload = body.tobytes()
        self.u8(version).u8(compat).u32(len(payload))
        self._parts.append(payload)
        return self

    def tobytes(self) -> bytes:
        return b"".join(self._parts)


class DecodeError(Exception):
    pass


class Decoder:
    def __init__(self, data: bytes, offset: int = 0, end: int | None = None):
        self._d = data
        self._o = offset
        self._end = len(data) if end is None else end

    def _take(self, n: int) -> bytes:
        if self._o + n > self._end:
            raise DecodeError(
                f"buffer exhausted: need {n} at {self._o}, end {self._end}")
        v = self._d[self._o:self._o + n]
        self._o += n
        return v

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bytes(self) -> bytes:
        return self._take(self.u32())

    def str(self) -> str:
        return self.bytes().decode("utf-8")

    def list(self, item_fn) -> list:
        return [item_fn(self) for _ in range(self.u32())]

    def map(self, key_fn, val_fn) -> dict:
        return {key_fn(self): val_fn(self) for _ in range(self.u32())}

    def versioned(self, my_version: int, body_fn):
        """Decode a versioned section; raises DecodeError if the encoder's
        compat version exceeds what we understand (DECODE_START semantics),
        and skips trailing bytes written by newer encoders."""
        version = self.u8()
        compat = self.u8()
        length = self.u32()
        if compat > my_version:
            raise DecodeError(
                f"struct compat {compat} > understood {my_version}")
        section_end = self._o + length
        sub = Decoder(self._d, self._o, section_end)
        out = body_fn(sub, version)
        self._o = section_end  # skip unknown trailing fields
        return out

    def remaining(self) -> int:
        return self._end - self._o
