"""PGLog / merge_log semantics (src/osd/PGLog.h analog): append/index,
dup-reqid detection, divergent-entry rollback at the true divergence point,
missing-set computation, and the end-to-end primary-death divergence repair
on a MiniCluster (the scenario src/osd/PG.cc peering exists to solve).
"""

import threading
import time

import pytest

from ceph_tpu.osd.pg import (
    EVERSION_ZERO, LOG_DELETE, LOG_MODIFY, PG, LogEntry, PGLog)


def e(ep, seq, oid, op=LOG_MODIFY, prior=EVERSION_ZERO, reqid=(0, 0)):
    return LogEntry(op=op, oid=oid, version=(ep, seq), prior_version=prior,
                    reqid=reqid)


class TestPGLog:
    def test_append_indexes_latest(self):
        log = PGLog()
        log.append(e(1, 1, "a"))
        log.append(e(1, 2, "b"))
        log.append(e(1, 3, "a", prior=(1, 1)))
        assert log.head == (1, 3)
        assert log.index["a"].version == (1, 3)
        assert log.index["b"].version == (1, 2)

    def test_reqid_dedup(self):
        log = PGLog()
        log.append(e(1, 1, "a", reqid=(7, 42)))
        assert log.has_reqid((7, 42))
        assert not log.has_reqid((7, 43))

    def test_rewind_drops_and_reindexes(self):
        log = PGLog()
        for i in range(1, 5):
            log.append(e(1, i, f"o{i}", reqid=(1, i)))
        dropped = log.rewind((1, 2))
        assert [d.version for d in dropped] == [(1, 3), (1, 4)]
        assert log.head == (1, 2)
        assert "o3" not in log.index and not log.has_reqid((1, 3))

    def test_entries_since(self):
        log = PGLog()
        log.append(e(1, 1, "a"))
        log.append(e(2, 2, "b"))
        assert [x.version for x in log.entries_since((1, 1))] == [(2, 2)]

    def test_encode_decode_roundtrip(self):
        from ceph_tpu.msg.encoding import Decoder, Encoder
        log = PGLog()
        log.append(e(1, 1, "a", reqid=(9, 1)))
        log.append(e(2, 2, "b", op=LOG_DELETE, prior=(1, 1)))
        enc = Encoder()
        log.encode(enc)
        log2 = PGLog.decode(Decoder(enc.tobytes()))
        assert [x.version for x in log2.entries] == [(1, 1), (2, 2)]
        assert log2.index["b"].is_delete()
        assert log2.has_reqid((9, 1))


class TestMergeLog:
    def test_replica_catches_up(self):
        """Plain catch-up: auth log strictly extends mine."""
        pg = PG((1, 0))
        pg.log.append(e(1, 1, "a"))
        pg.info.last_update = (1, 1)
        auth = [e(1, 1, "a"), e(1, 2, "b"), e(2, 3, "a", prior=(1, 1))]
        removed, recover = pg.merge_log(auth, lambda oid: (1, 1)
                                        if oid == "a" else None)
        assert removed == []
        assert set(recover) == {"a", "b"}
        assert pg.missing["a"].need == (2, 3)
        assert pg.info.last_update == (2, 3)

    def test_replica_skips_objects_it_already_has(self):
        pg = PG((1, 0))
        auth = [e(1, 1, "a")]
        _, recover = pg.merge_log(auth, lambda oid: (1, 1))
        assert recover == [] and pg.missing == {}

    def test_delete_in_auth_log_removes_local(self):
        pg = PG((1, 0))
        pg.log.append(e(1, 1, "a"))
        auth = [e(1, 1, "a"), e(1, 2, "a", op=LOG_DELETE, prior=(1, 1))]
        removed, recover = pg.merge_log(auth, lambda oid: (1, 1))
        assert removed == ["a"] and recover == []

    def test_divergent_head_rolled_back(self):
        """My log runs past the auth head: divergent tail is rewound and
        the objects are re-fetched at the authoritative version."""
        pg = PG((1, 0))
        for ent in [e(1, 1, "a"), e(1, 2, "b"), e(1, 3, "a", prior=(1, 1))]:
            pg.log.append(ent)
        auth = [e(1, 1, "a"), e(1, 2, "b")]
        removed, recover = pg.merge_log(auth, lambda oid: (1, 3)
                                        if oid == "a" else (1, 2))
        assert removed == []
        assert recover == ["a"]
        assert pg.missing["a"].need == (1, 1)
        assert pg.log.head == (1, 2)

    def test_divergence_below_auth_head(self):
        """The revived-primary case: my divergent entry (old epoch) has a
        LOWER version than the auth head (new epoch) — the divergence scan
        must find the shared prefix, not compare heads."""
        pg = PG((1, 0))
        pg.log.append(e(1, 1, "a"))
        pg.log.append(e(1, 2, "x"))           # divergent: only I saw this
        auth = [e(1, 1, "a"), e(3, 2, "x"), e(3, 3, "y")]
        removed, recover = pg.merge_log(auth, lambda oid: (1, 2)
                                        if oid == "x" else None)
        assert removed == []
        assert set(recover) == {"x", "y"}
        assert pg.missing["x"].need == (3, 2)
        assert [x.version for x in pg.log.entries] == \
            [(1, 1), (3, 2), (3, 3)]

    def test_divergent_create_is_deleted(self):
        """Object created only on the divergent branch: no auth entry, so
        the local copy is removed outright."""
        pg = PG((1, 0))
        pg.log.append(e(1, 1, "a"))
        pg.log.append(e(1, 2, "ghost"))
        auth = [e(1, 1, "a"), e(3, 2, "b")]
        removed, recover = pg.merge_log(auth, lambda oid: None)
        assert removed == ["ghost"]
        assert set(recover) == {"b"}

    def test_peer_missing_from_log(self):
        pg = PG((1, 0))
        for ent in [e(1, 1, "a"), e(1, 2, "b"),
                    e(2, 3, "b", op=LOG_DELETE, prior=(1, 2))]:
            pg.log.append(ent)
        missing = pg.peer_missing_from_log((1, 1))
        assert list(missing) == []  # b was deleted; nothing to push
        missing = pg.peer_missing_from_log(EVERSION_ZERO)
        assert list(missing) == ["a"]


class TestDivergenceConvergence:
    """The VERDICT round-1 acceptance scenario: primary dies mid-write with
    replicas never seeing the repop, writes continue through the new
    primary, the old primary revives — histories must converge."""

    def test_revived_primary_converges(self, tmp_path):
        from ceph_tpu.client.rados import ceph_str_hash_rjenkins
        from ceph_tpu.osd.osdmap import pg_to_pgid
        from ceph_tpu.tools.vstart import MiniCluster

        c = MiniCluster(n_osds=3, ms_type="loopback",
                        store_type="filestore",
                        base_path=str(tmp_path)).start()
        try:
            c.wait_for_osd_count(3)
            client = c.client(timeout=30.0)
            pool = c.create_pool(client, pg_num=4, size=3)
            io = client.open_ioctx(pool)
            io.write_full("div", b"version-A")

            m = c.mon.osdmap
            ps = ceph_str_hash_rjenkins("div")
            pg = pg_to_pgid(ps, m.pools[pool].pg_num)
            _up, old_primary, _a, _ap = m.pg_to_up_acting_osds(pool, pg)

            # second write: primary logs + applies locally, but the repops
            # never reach the replicas (fault injection à la
            # OSD.h debug_heartbeat_drops_remaining)
            c.osds[old_primary].debug_drop_rep_ops = 2
            blocked = threading.Thread(
                target=lambda: _swallow(lambda: io.write_full(
                    "div", b"version-B")))
            blocked.start()
            time.sleep(0.3)   # let the primary log it locally

            # primary dies; mon remaps; client resends through new primary
            c.kill_osd(old_primary)
            res, _ = client.mon_command({"prefix": "osd down",
                                         "id": str(old_primary)})
            assert res == 0
            c.wait_for_epoch(c.mon.osdmap.epoch)
            blocked.join(timeout=20)
            assert not blocked.is_alive(), "resent write never completed"

            # a third write the old primary will never have seen
            io.write_full("div", b"version-C")

            # old primary revives with its divergent log
            c.run_osd(old_primary)
            c.wait_for_osd_count(3)
            c.wait_for_epoch(c.mon.osdmap.epoch)
            deadline = time.time() + 20
            cid = f"{pool}.{pg}"
            while time.time() < deadline:
                stores_agree = all(
                    _read_safe(c.osds[o].store, cid, "div") == b"version-C"
                    for o in c.osds)
                heads = {c.osds[o].pgs[(pool, pg)].log.head
                         for o in c.osds if (pool, pg) in c.osds[o].pgs}
                if stores_agree and len(heads) == 1:
                    break
                time.sleep(0.1)
            for o in c.osds:
                assert _read_safe(c.osds[o].store, cid, "div") == \
                    b"version-C", f"osd.{o} did not converge"
            heads = {c.osds[o].pgs[(pool, pg)].log.head for o in c.osds}
            assert len(heads) == 1, f"logs diverged: {heads}"
            # and the client still reads the one true history
            assert io.read("div") == b"version-C"
        finally:
            c.stop()


def _swallow(fn):
    try:
        fn()
    except Exception:
        pass


def _read_safe(store, cid, oid):
    try:
        return store.read(cid, oid)
    except KeyError:
        return None
