"""ceph-monstore-tool analog: inspect a monitor's Paxos store offline.

The mon store is a LogDB with the "paxos" prefix holding versioned
committed map blobs (v_1..v_last_committed) — the layout Paxos commits
into (mon/paxos.py).  Ops:

    dump                      last_committed + per-version blob sizes
    get-osdmap [VERSION]      decoded osdmap summary (default: latest)
    rewrite-last-committed N  truncate history to N (disaster recovery)

Usage: python -m ceph_tpu.tools.monstore_tool PATH CMD [...]
"""

from __future__ import annotations

import json
import sys

from ceph_tpu.objectstore.kv import LogDB


def _last_committed(db) -> int:
    lc = db.get("paxos", "last_committed")
    return int(lc.decode()) if lc else 0


def dump(db) -> dict:
    lc = _last_committed(db)
    versions = {}
    for v in range(1, lc + 1):
        blob = db.get("paxos", f"v_{v}")
        versions[v] = len(blob) if blob else None
    return {"last_committed": lc, "versions": versions}


def get_osdmap(db, version: int | None = None) -> dict:
    from ceph_tpu.osd.map_codec import decode_osdmap
    v = version or _last_committed(db)
    blob = db.get("paxos", f"v_{v}")
    if blob is None:
        raise KeyError(f"no committed value at version {v}")
    m = decode_osdmap(blob)
    return {
        "version": v, "epoch": m.epoch, "max_osd": m.max_osd,
        "up_osds": [o for o in range(m.max_osd) if m.is_up(o)],
        "pools": {p: {"pg_num": pool.pg_num, "size": pool.size,
                      "type": pool.type} for p, pool in m.pools.items()},
    }


def rewrite_last_committed(db, n: int) -> dict:
    lc = _last_committed(db)
    if n > lc:
        raise ValueError(f"cannot advance last_committed ({n} > {lc})")
    t = db.get_transaction()
    for v in range(n + 1, lc + 1):
        t.rmkey("paxos", f"v_{v}")
    t.set("paxos", "last_committed", str(n).encode())
    db.submit_transaction(t)
    return {"last_committed": n, "dropped": lc - n}


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print(__doc__)
        return 2
    path, cmd, rest = argv[0], argv[1], argv[2:]
    db = LogDB(path)
    db.open()
    try:
        if cmd == "dump":
            print(json.dumps(dump(db), indent=1))
        elif cmd == "get-osdmap":
            v = int(rest[0]) if rest else None
            print(json.dumps(get_osdmap(db, v), indent=1))
        elif cmd == "rewrite-last-committed":
            print(json.dumps(rewrite_last_committed(db, int(rest[0]))))
        else:
            print(__doc__)
            return 2
        return 0
    finally:
        db.close()


if __name__ == "__main__":
    raise SystemExit(main())
