"""Device-accelerated background integrity (the batched deep-scrub
pipeline): the scrub_digest kernel channel's bit-exactness and fault
ladder, the rebuilt scrub path's missing-peer and verified-repair
semantics, the EC branch's detect-and-repair, and the
background_best_effort QoS lane the whole thing schedules in."""

from __future__ import annotations

import threading
import time
import zlib

import numpy as np
import pytest

from ceph_tpu.common import failpoint
from ceph_tpu.objectstore import Transaction
from ceph_tpu.ops import telemetry
from ceph_tpu.ops import checksum_kernel as ck
from ceph_tpu.ops.dispatch import (
    DeviceDispatchEngine, submit_scrub_digest)
from ceph_tpu.client.rados import ceph_str_hash_rjenkins
from ceph_tpu.osd.osdmap import pg_to_pgid
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


def _engine(**kw):
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats(), **kw)
    eng.fault_backoff_ms = 1.0
    eng.fault_backoff_max_ms = 5.0
    eng.probe_interval = 0.05
    return eng


def _wait_breaker(eng, channel, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.breaker_states().get(channel) == state:
            return True
        time.sleep(0.02)
    return False


# -- the digest kernel channel ------------------------------------------------

class TestDigestKernel:
    #: edge sizes: empty, sub-word, word-aligned, odd, bucket edges
    SIZES = [0, 1, 2, 3, 4, 5, 7, 8, 9, 31, 63, 64, 255, 256, 257,
             1000, 1024, 2047]

    def test_bit_exact_property_random_sizes_and_patterns(self):
        """The acceptance pin: the batched digest (through the engine,
        padding and aux operands included) equals the literal
        shard_crc loop for random sizes and byte patterns."""
        rng = np.random.default_rng(7)
        eng = _engine()
        try:
            for round_ in range(2):
                sizes = list(self.SIZES) + [
                    int(s) for s in rng.integers(0, 5000, 12)]
                blobs = [rng.integers(0, 256, s, dtype=np.uint8)
                         .tobytes() for s in sizes]
                got = np.asarray(
                    submit_scrub_digest(eng, blobs).result(60))
                assert got.shape == (len(blobs), 2)
                for i, b in enumerate(blobs):
                    assert int(got[i, 0]) == (zlib.crc32(b)
                                              & 0xFFFFFFFF), (round_, i)
                    assert int(got[i, 1]) == ck.gf_digest_ref(
                        np.frombuffer(b, dtype=np.uint8)), (round_, i)
        finally:
            eng.stop()

    def test_single_bit_flip_changes_both_digests(self):
        rng = np.random.default_rng(3)
        row = rng.integers(0, 256, 513, dtype=np.uint8)
        base = ck.scrub_digest_ref(row[None, :], [513])[0]
        for pos in (0, 1, 255, 512):
            flipped = row.copy()
            flipped[pos] ^= 0x10
            d = ck.scrub_digest_ref(flipped[None, :], [513])[0]
            assert d[0] != base[0], pos
            assert d[1] != base[1], pos

    def test_width_buckets_are_shared_pow2(self):
        """Different PGs coalesce because the submit key is only the
        padded width bucket."""
        assert ck.row_width(0) == ck.MIN_WIDTH
        assert ck.row_width(5) == ck.MIN_WIDTH
        assert ck.row_width(9) == 16
        assert ck.row_width(4096) == 4096
        assert ck.row_width(4097) == 8192

    def test_transient_fault_retries_bit_exact(self):
        eng = _engine()
        try:
            failpoint.set("dispatch.launch:scrub_digest", "nth:1")
            blobs = [b"retry-me" * 40, b"x" * 7]
            got = np.asarray(submit_scrub_digest(eng, blobs).result(60))
            for i, b in enumerate(blobs):
                assert int(got[i, 0]) == (zlib.crc32(b) & 0xFFFFFFFF)
            d = eng.stats.fault_dump()
            assert d["retries"] >= 1 and d["retry_successes"] >= 1, d
        finally:
            eng.stop()

    def test_hard_outage_opens_breaker_falls_back_then_recloses(self):
        """The PR 11 fault ladder on the fifth channel: a hard device
        outage opens the scrub_digest breaker, every batch is served
        by the bit-exact shard_crc oracle, and clearing the fault lets
        the background probe re-close the breaker."""
        eng = _engine()
        eng.breaker_threshold = 2
        try:
            failpoint.set("dispatch.launch:scrub_digest", "always")
            blobs = [b"outage" * 50, b"", b"z" * 129]
            for _ in range(3):
                got = np.asarray(
                    submit_scrub_digest(eng, blobs).result(60))
                for i, b in enumerate(blobs):
                    assert int(got[i, 0]) == (zlib.crc32(b)
                                              & 0xFFFFFFFF)
            d = eng.stats.fault_dump()
            assert d["breaker_opens"] >= 1, d
            assert d["fallback_batches"] >= 1, d
            assert eng.breaker_states()["scrub_digest"] == \
                telemetry.BREAKER_OPEN
            failpoint.clear()
            assert _wait_breaker(eng, "scrub_digest",
                                 telemetry.BREAKER_CLOSED)
            got = np.asarray(submit_scrub_digest(
                eng, [b"healed" * 3]).result(60))
            assert int(got[0, 0]) == (zlib.crc32(b"healed" * 3)
                                      & 0xFFFFFFFF)
        finally:
            eng.stop()


# -- the rebuilt scrub path (MiniCluster) -------------------------------------

@pytest.fixture(scope="class")
def cluster():
    """Class-scoped: the semantics tests each use their own pool and
    oids, and the one test that KILLS an osd builds its own cluster —
    sharing the MiniCluster keeps the suite's wall-clock down (the
    870 s tier-1 budget is tight)."""
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    try:
        yield c
    finally:
        c.stop()


def _pg_of(cluster, pool, oid):
    m = cluster.mon.osdmap
    pg = pg_to_pgid(ceph_str_hash_rjenkins(oid), m.pools[pool].pg_num)
    up, primary, _a, _ap = m.pg_to_up_acting_osds(pool, pg)
    return pg, up, primary


class TestScrubSemantics:
    def test_missing_peer_recorded_never_clean(self):
        """A replica that never replies lands in missing_peers (after
        one retry) and the PG is NOT reported clean — the seed dropped
        it from maps and compared its objects as if the copy never
        existed."""
        client = cluster_ = None
        c = MiniCluster(n_osds=3, ms_type="loopback").start()
        try:
            c.wait_for_osd_count(3)
            client = c.client()
            pool = c.create_pool(client, pg_num=4, size=3)
            io = client.open_ioctx(pool)
            io.write_full("mp", b"present" * 100)
            time.sleep(0.3)
            pg, up, primary = _pg_of(c, pool, "mp")
            victim = next(o for o in up if o != primary)
            c.kill_osd(victim)
            rep = c.osds[primary].scrub_pg((pool, pg), timeout=1.0)
            assert rep["missing_peers"] == [victim], rep
            assert rep["clean"] is False, rep
            # the surviving copies still compared clean
            assert rep["inconsistent"] == [], rep
            st = c.osds[primary].ctx.admin.execute("dump_scrub_stats")
            assert st["missing_peer_scrubs"] >= 1, st
        finally:
            _ = client, cluster_
            c.stop()

    def test_replica_corruption_repaired_and_verified(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("sc", b"truth" * 200)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "sc")
        victim_id = next(o for o in up if o != primary)
        victim = cluster.osds[victim_id]
        cid = f"{pool}.{pg}"
        victim.store.apply_transaction(
            Transaction().truncate(cid, "sc", 0)
            .write(cid, "sc", 0, b"lies!" * 200))
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert "sc" in rep["inconsistent"], rep
        # the fire-and-forget fix: the repair only counted after its
        # digest was re-fetched and matched the authority triple
        assert ("sc", victim_id) in rep["repaired"], rep
        assert rep["repair_unverified"] == [], rep
        assert victim.store.read(cid, "sc") == b"truth" * 200
        rep2 = cluster.osds[primary].scrub_pg((pool, pg))
        assert rep2["inconsistent"] == [] and rep2["clean"], rep2

    def test_primary_outlier_repull_verified(self, cluster):
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("pc", b"quorum" * 150)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "pc")
        prim = cluster.osds[primary]
        cid = f"{pool}.{pg}"
        prim.store.apply_transaction(
            Transaction().truncate(cid, "pc", 0)
            .write(cid, "pc", 0, b"drifted"))
        rep = prim.scrub_pg((pool, pg))
        assert "pc" in rep["inconsistent"], rep
        assert ("pc", primary) in rep["repaired"], rep
        assert prim.store.read(cid, "pc") == b"quorum" * 150
        assert io.read("pc") == b"quorum" * 150

    def test_ec_shard_corruption_detected_decoded_repaired(self,
                                                           cluster):
        """The EC branch satellite: corrupt one shard on disk, the
        hinfo sweep flags it (the owner's own scrub map reports
        SCRUB_CORRUPT), the batched decode path rebuilds it, and a
        re-scrub comes back clean — the seed's EC branch only
        reported, never repaired."""
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4,
                                   pool_type="erasure", k=2, m=1)
        io = client.open_ioctx(pool)
        body = b"erasure-coded-truth!" * 100
        io.write_full("eobj", body)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "eobj")
        shard = 1 if up[0] == primary else 0
        owner = up[shard]
        cid = f"{pool}.{pg}"
        soid = f"eobj:{shard}"
        store = cluster.osds[owner].store
        chunk = store.read(cid, soid)
        flipped = bytes(b ^ 0x55 for b in chunk)
        store.apply_transaction(
            Transaction().truncate(cid, soid, 0)
            .write(cid, soid, 0, flipped))
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert soid in rep["inconsistent"], rep
        assert (soid, owner) in rep["repaired"], rep
        assert rep["repair_unverified"] == [], rep
        # the rebuilt shard carries the original bytes + a matching
        # hinfo, and the object reads back whole
        assert store.read(cid, soid) == chunk
        rep2 = cluster.osds[primary].scrub_pg((pool, pg))
        assert rep2["inconsistent"] == [] and rep2["clean"], rep2
        assert io.read("eobj") == body

    def test_version_skew_not_treated_as_corruption(self, cluster):
        """Scrub maps are gathered seconds apart under load: a copy at
        a DIFFERENT version than the logged head is an in-flight
        write, not corruption — scrub must neither report nor "repair"
        it (the repair would push a stale copy over an acked newer
        write, the lost_rep failure the scrub-storm soak exposed)."""
        from ceph_tpu.osd.daemon import enc_version
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("vs", b"acked-old" * 50)
        time.sleep(0.3)
        pg, up, primary = _pg_of(cluster, pool, "vs")
        victim_id = next(o for o in up if o != primary)
        victim = cluster.osds[victim_id]
        cid = f"{pool}.{pg}"
        # simulate mid-gather skew: the replica's copy has advanced
        # past the primary's logged head (a landing newer write)
        newer = b"acked-newer" * 50
        victim.store.apply_transaction(
            Transaction().truncate(cid, "vs", 0)
            .write(cid, "vs", 0, newer)
            .setattr(cid, "vs", "_v", enc_version((99, 99))))
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert "vs" not in rep["inconsistent"], rep
        assert all(oid != "vs" for oid, _o in rep["repaired"]), rep
        # the newer copy was NOT clobbered by a stale repair push,
        # and the primary never marked its own copy missing
        assert victim.store.read(cid, "vs") == newer
        assert "vs" not in cluster.osds[primary].pgs[
            (pool, pg)].missing

    def test_scrub_map_rides_the_digest_channel(self, cluster):
        """The batched path is the live default: a scrub increments
        the digest-batch ledger (device channel, not the scalar loop)
        and the kernel registry sees scrub_digest calls."""
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        for i in range(6):
            io.write_full(f"d{i}", f"payload-{i}".encode() * 50)
        time.sleep(0.3)
        pg, _up, primary = _pg_of(cluster, pool, "d0")
        before = cluster.osds[primary].ctx.admin.execute(
            "dump_scrub_stats")["digest_batches"]
        rep = cluster.osds[primary].scrub_pg((pool, pg))
        assert rep["clean"], rep
        st = cluster.osds[primary].ctx.admin.execute(
            "dump_scrub_stats")
        assert st["digest_batches"] > before, st
        assert telemetry.dump().get("scrub_digest", {}).get(
            "calls", 0) >= 1

    def test_scrub_all_pgs_serves_from_background_lane(self, cluster):
        """The sweep driver's chunks are dmclock-arbitrated in the
        background_best_effort class — visible in dump_qos_stats —
        and the aggregate report + sweep ledger land in
        dump_scrub_stats."""
        client = cluster.client()
        pool = cluster.create_pool(client, pg_num=8, size=3)
        io = client.open_ioctx(pool)
        for i in range(10):
            io.write_full(f"bg{i}", f"bg-{i}".encode() * 30)
        time.sleep(0.3)
        total_pgs = 0
        for osd in cluster.osds.values():
            agg = osd.scrub_all_pgs()
            total_pgs += agg["pgs"]
            assert agg["clean"], agg
        assert total_pgs >= 8
        served = 0
        for osd in cluster.osds.values():
            d = osd.ctx.admin.execute("dump_qos_stats")
            row = d["classes"].get("background_best_effort")
            if row:
                served += sum(row["served"].values())
            st = osd.ctx.admin.execute("dump_scrub_stats")
            assert st["qos_class"] == "background_best_effort"
        assert served > 0
        swept = [osd.ctx.admin.execute("dump_scrub_stats")["sweeps"]
                 for osd in cluster.osds.values()]
        assert sum(swept) >= 3, swept


class TestScrubObservability:
    def test_mgr_report_carries_scrub_tail(self):
        from ceph_tpu.mgr.daemon import MMgrReport
        msg = MMgrReport(osd_id=3, scrub={"objects_scrubbed": 7,
                                          "repaired": 1})
        from ceph_tpu.msg.message import Message
        back = Message.decode(msg.encode())
        assert back.scrub == {"objects_scrubbed": 7, "repaired": 1}

    def test_mosd_scrub_oid_filter_roundtrip(self):
        from ceph_tpu.messages.osd_msgs import MOSDScrub
        from ceph_tpu.msg.message import Message
        m = MOSDScrub(pgid=(4, 2), scrub_id=9, from_osd=1,
                      oids=["a", "b:0"])
        back = Message.decode(m.encode())
        assert back.oids == ["a", "b:0"]
        assert Message.decode(
            MOSDScrub(pgid=(4, 2), scrub_id=9,
                      from_osd=1).encode()).oids is None

    def test_scrub_telemetry_sink_rolls_up(self):
        sink = telemetry.scrub_stats()
        base = sink.dump().get("objects_scrubbed", 0)
        sink.inc("objects_scrubbed", 5)
        assert sink.dump()["objects_scrubbed"] == base + 5
        s = telemetry.scrub_summary()
        assert "repair_unverified" in s and "repaired" in s
