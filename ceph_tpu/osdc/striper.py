"""Striper — RAID-0 of a byte stream over RADOS objects
(src/osdc/Striper.cc + src/libradosstriper/ analog; the framework's
"long-context" scaling primitive: one large logical stream spread over
many independently-placed objects so reads/writes parallelize across
PGs and OSDs).

Layout follows file_layout_t: stripe_unit bytes per strip, stripe_count
objects per stripe row, object_size bytes per object.  Logical offset →
(object number, object offset) exactly as Striper::file_to_extents.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class StripeLayout:
    """file_layout_t subset."""

    stripe_unit: int = 1 << 16
    stripe_count: int = 4
    object_size: int = 1 << 22

    def __post_init__(self):
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")

    def num_objects(self, size: int) -> int:
        """Backing objects covering a logical size (object-map width).
        Within a partial object set the first ceil(rem/su) stripe units
        land on min(sc, that) distinct objects."""
        if size <= 0:
            return 0
        su, sc = self.stripe_unit, self.stripe_count
        set_bytes = self.object_size * sc
        full_sets, rem = divmod(size, set_bytes)
        n = full_sets * sc
        if rem:
            blocks = -(-rem // su)
            n += min(sc, blocks)
        return n

    def object_logical_extents(self, objno: int, size: int):
        """[(logical_off, len)] of the bytes objno backs, clamped to the
        image size — the inverse of extents() at stripe-unit granularity
        (Striper::extent_to_file).  Adjacent units are coalesced."""
        su, sc = self.stripe_unit, self.stripe_count
        per_obj = self.object_size // su
        objectsetno, stripepos = divmod(objno, sc)
        out: list[tuple[int, int]] = []
        for u in range(per_obj):
            stripeno = objectsetno * per_obj + u
            logical = (stripeno * sc + stripepos) * su
            if logical >= size:
                break
            n = min(su, size - logical)
            if out and out[-1][0] + out[-1][1] == logical:
                out[-1] = (out[-1][0], out[-1][1] + n)
            else:
                out.append((logical, n))
        return out

    def extents(self, offset: int, length: int):
        """[(objno, obj_off, len)] covering [offset, offset+length)
        (Striper::file_to_extents)."""
        su, sc = self.stripe_unit, self.stripe_count
        per_obj = self.object_size // su    # stripe units per object
        out = []
        pos = offset
        end = offset + length
        while pos < end:
            blockno = pos // su
            stripeno = blockno // sc
            stripepos = blockno % sc
            objectsetno = stripeno // per_obj
            objectno = objectsetno * sc + stripepos
            block_off = pos % su
            obj_off = (stripeno % per_obj) * su + block_off
            n = min(su - block_off, end - pos)
            out.append((objectno, obj_off, n))
            pos += n
        return out


class Striper:
    """Pure layout math, shared by StripedObject / rbd."""

    def __init__(self, layout: StripeLayout):
        self.layout = layout

    def object_name(self, prefix: str, objno: int) -> str:
        return f"{prefix}.{objno:016x}"


class StripedObject:
    """A large logical object striped over an IoCtx
    (libradosstriper surface: write/read/truncate-ish + size)."""

    SIZE_KEY = "striper.size"

    def __init__(self, ioctx, name: str,
                 layout: StripeLayout | None = None):
        self.io = ioctx
        self.name = name
        self.layout = layout or StripeLayout()
        self.striper = Striper(self.layout)

    def _size_obj(self) -> str:
        return f"{self.name}.meta"

    def size(self) -> int:
        try:
            omap = self.io.get_omap(self._size_obj())
        except OSError:
            return 0
        blob = omap.get(self.SIZE_KEY)
        return int(blob.decode()) if blob else 0

    def _set_size(self, size: int) -> None:
        self.io.set_omap(self._size_obj(),
                         {self.SIZE_KEY: str(size).encode()})

    def write(self, data: bytes, offset: int = 0) -> None:
        pos = 0
        for objno, obj_off, n in self.layout.extents(offset, len(data)):
            self.io.write(self.striper.object_name(self.name, objno),
                          data[pos:pos + n], offset=obj_off)
            pos += n
        if offset + len(data) > self.size():
            self._set_size(offset + len(data))

    def read(self, offset: int = 0, length: int = 0,
             snapid: int = 0) -> bytes:
        """snapid reads each backing object as of that pool snapshot
        (librados snap_set analog); pass an explicit length then — the
        size object reflects the CURRENT size, not the snap's."""
        total = self.size()
        if length <= 0 or offset + length > total and not snapid:
            length = max(0, total - offset)
        parts = []
        for objno, obj_off, n in self.layout.extents(offset, length):
            try:
                chunk = self.io.read(
                    self.striper.object_name(self.name, objno),
                    length=n, offset=obj_off, snapid=snapid)
            except OSError:
                chunk = b""
            if len(chunk) < n:          # sparse hole: zero-fill
                chunk = chunk + bytes(n - len(chunk))
            parts.append(chunk)
        return b"".join(parts)

    def truncate(self, new_size: int) -> None:
        """Zero the bytes beyond new_size and shrink the logical size
        (discarded data must not resurface on a later grow)."""
        total = self.size()
        if new_size < total:
            self.write(bytes(total - new_size), offset=new_size)
        self._set_size(new_size)

    def remove(self) -> None:
        total = self.size()
        seen = set()
        for objno, _off, _n in self.layout.extents(0, max(total, 1)):
            seen.add(objno)
        for objno in seen:
            try:
                self.io.remove(self.striper.object_name(self.name,
                                                        objno))
            except OSError:
                pass
        try:
            self.io.remove(self._size_obj())
        except OSError:
            pass
