"""OpTracker event timelines + lockdep lock-order checking (SURVEY §5
aux subsystems: common/TrackedOp, osd/OpRequest, common/lockdep)."""

import threading
import time

import pytest

from ceph_tpu.common.lockdep import (
    DebugRLock, LockOrderError, enable, reset)
from ceph_tpu.common.op_tracker import OpTracker


class TestOpTracker:
    def test_timeline_and_history(self):
        trk = OpTracker(complaint_time=0.05,
                        history_slow_threshold=0.01)
        op = trk.create_request("osd_op(client.1.7 1.0 obj)")
        op.mark_event("reached_pg")
        d = trk.dump_ops_in_flight()
        assert d["num_ops"] == 1
        assert [e["event"] for e in d["ops"][0]["type_data"]["events"]] \
            == ["initiated", "reached_pg"]
        time.sleep(0.06)
        assert any("slow request" in w
                   for w in trk.check_ops_in_flight())
        op.mark_event("commit_sent")
        op.finish()
        assert trk.dump_ops_in_flight()["num_ops"] == 0
        h = trk.dump_historic_ops()
        assert h["num_ops"] == 1
        assert h["ops"][0]["duration"] >= 0.06
        assert h["slowest"]                      # crossed slow threshold
        assert trk.check_ops_in_flight() == []
        op.finish()                              # idempotent

    def test_history_ring_bounded(self):
        trk = OpTracker(history_size=5, history_slow_threshold=99)
        for i in range(12):
            trk.create_request(f"op{i}").finish()
        h = trk.dump_historic_ops()
        assert h["num_ops"] == 5
        assert h["ops"][0]["description"] == "op7"

    def test_live_osd_exposes_tracked_ops(self):
        from ceph_tpu.tools.vstart import MiniCluster
        c = MiniCluster(n_osds=3, ms_type="loopback").start()
        try:
            c.wait_for_osd_count(3)
            client = c.client(timeout=15.0)
            pool = c.create_pool(client, pg_num=4, size=3)
            io = client.open_ioctx(pool)
            for i in range(4):
                io.write_full(f"t{i}", b"x" * 128)
            assert io.read("t0") == b"x" * 128
            hist = {}
            for d in c.osds.values():
                hist.update({o["description"]: o for o in
                             d.op_tracker.dump_historic_ops()["ops"]})
            assert hist, "no completed ops recorded"
            some = next(iter(hist.values()))
            events = [e["event"] for e in some["type_data"]["events"]]
            assert events[0] == "initiated"
            assert any(e.startswith("reply result=") for e in events)
            assert events[-1] == "done"
            # nothing leaks in-flight once the cluster is quiescent
            time.sleep(0.3)
            for d in c.osds.values():
                assert d.op_tracker.dump_ops_in_flight()["num_ops"] == 0
        finally:
            c.stop()


class TestLockdep:
    def setup_method(self):
        reset()
        enable(True)

    def teardown_method(self):
        enable(False)
        reset()

    def test_cycle_detected(self):
        a, b = DebugRLock("a"), DebugRLock("b")
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError):
            with b:
                with a:
                    pass

    def test_consistent_order_and_reentrancy_ok(self):
        a, b = DebugRLock("x"), DebugRLock("y")
        for _ in range(3):
            with a:
                with a:          # re-entrant: no self edge
                    with b:
                        pass

    def test_three_lock_cycle(self):
        a, b, c = (DebugRLock(n) for n in ("l1", "l2", "l3"))
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(LockOrderError):
            with c:
                with a:
                    pass

    def test_condition_wait_records_order_and_wakes(self):
        """make_condition wraps a DebugRLock: `with cv:` records order
        edges like any mutex, and wait/notify work through the
        Condition protocol delegation (_is_owned/_release_save/
        _acquire_restore)."""
        from ceph_tpu.common.lockdep import make_condition, make_lock
        cv = make_condition("CV::test")
        outer = make_lock("Outer::test")
        state = {"go": False}

        def waker():
            time.sleep(0.05)
            with cv:
                state["go"] = True
                cv.notify_all()

        t = threading.Thread(target=waker)
        t.start()
        with outer:                  # edge Outer::test -> CV::test
            with cv:
                assert cv.wait_for(lambda: state["go"], timeout=5.0)
        t.join()
        # the reverse order is now a violation
        with pytest.raises(LockOrderError):
            with cv:
                with outer:
                    pass

    def test_export_graph_edges(self):
        from ceph_tpu.common import lockdep
        a, b = DebugRLock("exp_a"), DebugRLock("exp_b")
        with a:
            with b:
                pass
        g = lockdep.export_graph()
        assert {"a": "exp_a", "b": "exp_b"} == {
            k: v for k, v in next(
                e for e in g["edges"]
                if e["a"] == "exp_a").items() if k != "site"}

    def test_threads_have_independent_held_stacks(self):
        a, b = DebugRLock("t1"), DebugRLock("t2")
        errs = []

        def worker():
            try:
                with b:
                    time.sleep(0.05)
            except Exception as e:   # pragma: no cover
                errs.append(e)

        t = threading.Thread(target=worker)
        with a:
            t.start()
            time.sleep(0.02)
        t.join()
        assert not errs


class TestLockdepLiveCluster:
    def test_daemon_lock_order_clean_under_workload(self):
        """g_lockdep-style CI pass: run a replicated+EC workload with
        every daemon lock order-checked; any cycle in
        osd/mon/paxos/elector/store lock acquisition fails the test."""
        from ceph_tpu.common import lockdep
        lockdep.reset()
        lockdep.enable(True)
        try:
            from ceph_tpu.tools.vstart import MiniCluster
            c = MiniCluster(n_osds=4, ms_type="loopback",
                            heartbeats=True).start()
            try:
                c.wait_for_osd_count(4)
                client = c.client(timeout=30.0)
                pool = c.create_pool(client, pg_num=8, size=3)
                io = client.open_ioctx(pool)
                for i in range(10):
                    io.write_full(f"ld{i}", b"z" * 256)
                for i in range(10):
                    assert io.read(f"ld{i}") == b"z" * 256
                # kill an osd; heartbeat failure reports mark it down
                # and i/o proceeds on the survivors — exercising the
                # peering/recovery/heartbeat lock paths under lockdep
                c.kill_osd(0)
                io.write_full("after-kill", b"k" * 64)
                c.run_osd(0)
                time.sleep(1.0)
                assert io.read("after-kill") == b"k" * 64
            finally:
                c.stop()
            assert lockdep.violations == [], lockdep.violations[0]
        finally:
            lockdep.enable(False)
            lockdep.reset()
