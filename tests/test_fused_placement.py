"""Device-resident placement pipeline (ops.placement_kernel + the
fused mapping-service path): bit-exactness of the fused
raw→up→acting ladder vs the scalar ``pg_to_up_acting_osds`` oracle
under random churn, delta-exactness of the on-device fused diff vs the
scalar diff, the dispatch-engine/mesh channel, the balancer's batched
what-if scoring, the shard_map wrapper that lets pallas kernels ride
sharded batches, and the fused-vs-fallback observability."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.ops import telemetry
from ceph_tpu.ops import placement_kernel as pk
from ceph_tpu.osd import OSDMap, PGPool, SharedPGMappingService
from ceph_tpu.osd.mapping import (
    _finish_from, pps_batch_scalar, scalar_rows)
from ceph_tpu.osd.osdmap import (
    OSD_EXISTS, OSD_UP, POOL_TYPE_ERASURE)


def _base_map(hosts=4, per_host=3, epoch=2, pg_num=32):
    crush, _root, rule = build_two_level_map(hosts, per_host)
    n = hosts * per_host
    m = OSDMap(crush=crush, epoch=epoch)
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, size=3, crush_rule=rule,
                        pg_num=pg_num)
    m.pools[2] = PGPool(pool_id=2, size=4, crush_rule=rule,
                        pg_num=pg_num // 2, type=POOL_TYPE_ERASURE)
    return m, rule


def _full_oracle(m: OSDMap) -> dict:
    return {(pid, pg): m.pg_to_up_acting_osds(pid, pg)
            for pid, pool in m.pools.items()
            for pg in range(pool.pg_num)}


def _churn_once(m: OSDMap, rng, rule: int) -> OSDMap:
    """One epoch of churn spanning EVERY pipeline-tail input: weights,
    state, affinity, pg_temp (incl. empty rows), primary_temp,
    full pg_upmap rows (incl. invalid entries), upmap item pairs, and
    pg growth."""
    new = m.copy()
    new.epoch = m.epoch + 1
    n = new.max_osd
    kind = int(rng.integers(0, 9))
    osd = int(rng.integers(0, n))
    pid = int(rng.choice(list(new.pools)))
    pg = int(rng.integers(0, new.pools[pid].pg_num))
    if kind == 0:
        new.osd_weight[osd] = int(rng.choice(
            (0, 0x4000, 0x8000, 0xC000, 0x10000)))
    elif kind == 1:
        new.osd_state[osd] = new.osd_state[osd] & ~OSD_UP
    elif kind == 2:
        new.osd_state[osd] = OSD_EXISTS | OSD_UP
    elif kind == 3:
        new.osd_primary_affinity[osd] = int(rng.choice(
            (0, 0x4000, 0x8000, 0x10000)))
    elif kind == 4:
        if (pid, pg) in new.pg_temp:
            del new.pg_temp[(pid, pg)]
        else:
            # rows bounded by the max pool size: longer rows only move
            # the shared width W (a fresh jit shape per value — pure
            # suite-runtime cost); the beyond-size width path is
            # pinned by the unit test's 30-churn map instead
            ln = int(rng.integers(0, 5))   # 0: present-but-empty row
            new.pg_temp[(pid, pg)] = [
                int(x) for x in rng.integers(0, n, ln)]
    elif kind == 5:
        if (pid, pg) in new.primary_temp:
            del new.primary_temp[(pid, pg)]
        else:
            new.primary_temp[(pid, pg)] = osd
    elif kind == 6:
        # full upmap row — sometimes invalid (out-of-range / out osd),
        # which the validity gate must reject like the oracle
        if (pid, pg) in new.pg_upmap:
            del new.pg_upmap[(pid, pg)]
        else:
            ln = int(rng.integers(1, 5))
            new.pg_upmap[(pid, pg)] = [
                int(x) for x in rng.integers(0, n + 2, ln)]
    elif kind == 7:
        if (pid, pg) in new.pg_upmap_items:
            del new.pg_upmap_items[(pid, pg)]
        else:
            new.pg_upmap_items[(pid, pg)] = [
                (int(rng.integers(0, n + 2)), int(rng.integers(0, n + 2)))
                for _ in range(int(rng.integers(1, 3)))]
    else:
        old_pool = new.pools[pid]
        new.pools[pid] = PGPool(
            pool_id=pid, size=old_pool.size, crush_rule=rule,
            pg_num=old_pool.pg_num * 2, pgp_num=old_pool.pgp_num,
            type=old_pool.type)
    return new


# -- kernel unit exactness ----------------------------------------------------

def test_ladder_unit_matches_finish_from():
    """Direct run_ladder over dense operands == the host pipeline tail
    for every PG of a replicated AND an erasure pool, across a map
    carrying every override kind (incl. a NONE-frm pair and an empty
    pg_temp row)."""
    rng = np.random.default_rng(7)
    m, rule = _base_map()
    for _ in range(30):
        m = _churn_once(m, rng, rule)
    m.pg_temp[(1, 0)] = []
    m.pg_upmap_items[(2, 0)] = [(0x7FFFFFFF, 1)]
    weights = np.zeros(m.max_osd, dtype=np.int64)
    weights[:len(m.osd_weight)] = m.osd_weight
    raw_tab, pps_tab = {}, {}
    for pid, pool in m.pools.items():
        pgids = np.arange(pool.pg_num, dtype=np.uint32)
        pps_tab[pid] = pps_batch_scalar(pool, pgids)
        raw_tab[pid] = scalar_rows(m.crush, pool.crush_rule,
                                   pps_tab[pid], pool.size, weights)
    width, pairs = pk.pool_widths(m)
    vectors = m.dense_osd_vectors()
    for pid, pool in m.pools.items():
        packed = pk.run_ladder(pk.build_operands(
            m, pid, pool, raw_tab[pid], pps_tab[pid], width=width,
            pairs=pairs, vectors=vectors))
        for pg in range(pool.pg_num):
            assert pk.unpack_row(packed[pg], width) == _finish_from(
                m, pool, pid, pg, raw_tab, pps_tab), (pid, pg)


def test_none_frm_pair_never_pollutes_pad_cells():
    """Regression: on a hole-free erasure row padded to a wider shared
    width, a NONE-frm pair must NOT match a pad cell — writing ``to``
    into the pad would make a later pair's ``to not in raw`` check
    wrongly fail (the scalar list has no cells past the row length)."""
    m, _rule = _base_map()
    pool = m.pools[2]                  # erasure, size 4
    # raw: one full row, no genuine NONE holes; width padded to 6
    raw = np.array([[0, 1, 2, 3]], dtype=np.int32)
    pps = np.array([12345], dtype=np.uint32)
    x = 7                              # valid, absent from the row
    m.pg_upmap_items = {(2, 0): [(0x7FFFFFFF, x), (1, x)]}
    state, weight, affinity = m.dense_osd_vectors()
    width = 6
    up_rows, up_len, items, temp_rows, temp_len, ptemp = \
        m.dense_pool_overrides(2, 1, width, 2)
    packed = pk.run_ladder(pk.LadderOperands(
        raw=pk.pad_raw(raw, width), pps=pps,
        raw_len=np.array([4], dtype=np.int32),
        up_rows=up_rows, up_len=up_len, items=items,
        temp_rows=temp_rows, temp_len=temp_len, ptemp=ptemp,
        state=state, weight=weight, affinity=affinity,
        erasure=True, width=width))
    # oracle: pair 1 (NONE frm) skipped, pair 2 rewrites 1 -> x
    want = m._finish_pg_mapping(pool, (2, 0), [0, 1, 2, 3], 12345)
    assert pk.unpack_row(packed[0], width) == want
    assert x in want[0]                # the rewrite really applied


def test_ladder_bucket_padding_bit_exact():
    """run_ladder's pow2 PG-axis bucketing (all-zero pad rows, sliced
    off) never perturbs live rows: a non-pow2 slice of a pool equals
    the corresponding rows of the full-pool call."""
    rng = np.random.default_rng(11)
    m, rule = _base_map()
    for _ in range(10):
        m = _churn_once(m, rng, rule)
    weights = np.zeros(m.max_osd, dtype=np.int64)
    weights[:len(m.osd_weight)] = m.osd_weight
    width, pairs = pk.pool_widths(m)
    vectors = m.dense_osd_vectors()
    pool = m.pools[1]
    pgids = np.arange(pool.pg_num, dtype=np.uint32)
    pps = pps_batch_scalar(pool, pgids)
    raw = scalar_rows(m.crush, pool.crush_rule, pps, pool.size,
                      weights)
    full = pk.run_ladder(pk.build_operands(
        m, 1, pool, raw, pps, width=width, pairs=pairs,
        vectors=vectors))
    ops = pk.build_operands(m, 1, pool, raw, pps, width=width,
                            pairs=pairs, vectors=vectors)
    cut = 13          # pads 13 -> 16 with zero rows
    for f in ("raw", "pps", "raw_len", "up_rows", "up_len", "items",
              "temp_rows", "temp_len", "ptemp"):
        setattr(ops, f, getattr(ops, f)[:cut])
    np.testing.assert_array_equal(pk.run_ladder(ops), full[:cut])


# -- service property test ----------------------------------------------------

def test_fused_service_matches_oracle_and_exact_delta():
    """Property test (the PR's bit-exactness contract): a FUSED
    service under random churn serves every lookup identical to the
    scalar oracle, its delta is EXACTLY the scalar old-vs-new diff,
    and the epochs really ran fused (device diff, no host tail)."""
    rng = np.random.default_rng(1234)
    m, rule = _base_map()
    svc = SharedPGMappingService()      # engine-less: fused by default
    st = telemetry.mapping_stats()
    before = st.dump()
    svc.update_to(m)
    oracle = _full_oracle(m)
    for (pid, pg), want in oracle.items():
        assert svc.lookup(m, pid, pg) == want
    for _ in range(12):
        new = _churn_once(m, rng, rule)
        upd = svc.update_to(new, from_epoch=m.epoch)
        new_oracle = _full_oracle(new)
        for (pid, pg), want in new_oracle.items():
            assert svc.lookup(new, pid, pg) == want, (pid, pg)
        exact = sorted(k for k, v in new_oracle.items()
                       if oracle.get(k) != v)
        assert not upd.full
        assert sorted(upd.changed) == exact
        m, oracle = new, new_oracle
    after = st.dump()
    assert after["fused_epochs"] - before["fused_epochs"] == 13
    assert after["unfused_epochs"] == before["unfused_epochs"]
    assert after["fused_lookups"] > before["fused_lookups"]
    # the tail collapsed: fused epochs added zero host-tail seconds
    assert (after["phase_seconds"]["host_tail"]["sum"]
            == before["phase_seconds"]["host_tail"]["sum"])


def test_fused_off_knob_restores_host_tail_path():
    """fused=False (the osdmap_mapping_fused escape hatch) keeps the
    PR 5 host-tail behavior: identical results, unfused counters."""
    rng = np.random.default_rng(5)
    m, rule = _base_map()
    svc = SharedPGMappingService(fused=False)
    st = telemetry.mapping_stats()
    before = st.dump()
    svc.update_to(m)
    new = _churn_once(m, rng, rule)
    upd = svc.update_to(new, from_epoch=m.epoch)
    assert not upd.full
    old_oracle = _full_oracle(m)
    exact = sorted(k for k, v in _full_oracle(new).items()
                   if old_oracle.get(k) != v)
    assert sorted(upd.changed) == exact
    after = st.dump()
    assert after["unfused_epochs"] - before["unfused_epochs"] == 2
    assert after["fused_lookups"] == before["fused_lookups"]


def test_tail_divergent_same_epoch_copy_never_reads_fused_rows():
    """A copy of the service's map at the SAME epoch with equal RAW
    signatures but different tail inputs (an extra pg_temp) binds to
    the cache — but must be served by the host tail against ITS OWN
    map, never the fused rows built from the service's map."""
    m, _rule = _base_map()
    svc = SharedPGMappingService()
    svc.update_to(m)
    twin = m.copy()
    twin.pg_temp = dict(twin.pg_temp)
    twin.pg_temp[(1, 3)] = [1, 2]       # tail diverges, raw sig equal
    st = telemetry.mapping_stats()
    before = st.dump()
    for pg in range(8):
        assert svc.lookup(twin, 1, pg) \
            == twin.pg_to_up_acting_osds(1, pg)
    after = st.dump()
    # served from cache (raw rows), but not one fused read
    assert after["lookups"] - before["lookups"] == 8
    assert after["fused_lookups"] == before["fused_lookups"]
    # an exact copy DOES read fused rows
    exact_twin = m.copy()
    before = st.dump()
    for pg in range(8):
        assert svc.lookup(exact_twin, 1, pg) \
            == exact_twin.pg_to_up_acting_osds(1, pg)
    after = st.dump()
    assert after["fused_lookups"] - before["fused_lookups"] == 8


def test_min_pgs_floor_keeps_toy_maps_unfused():
    """A context-backed service under the default
    osdmap_mapping_min_pgs floor skips the fused build on toy maps
    (compile latency must not land on tiny-cluster map handling)."""
    from ceph_tpu.common.context import CephTpuContext

    ctx = CephTpuContext("fused-floor-test")   # min_pgs default 1024
    svc = ctx.mapping_service()
    m, _rule = _base_map()                     # 48 PGs total
    st = telemetry.mapping_stats()
    before = st.dump()
    svc.update_to(m)
    after = st.dump()
    assert after["unfused_epochs"] - before["unfused_epochs"] == 1
    assert after["fused_epochs"] == before["fused_epochs"]
    for pg in range(4):
        assert svc.lookup(m, 1, pg) == m.pg_to_up_acting_osds(1, pg)
    eng = ctx._dispatch
    if eng is not None:
        eng.stop()


# -- engine / mesh channel ----------------------------------------------------

def test_fused_rides_dispatch_engine_and_mesh():
    """A context-backed fused service submits the ladder through the
    dispatch engine (pg_finish batches appear; on this 8-device test
    env they mesh-shard across all chips) and stays bit-exact,
    including the delta."""
    from ceph_tpu.common.context import CephTpuContext

    ctx = CephTpuContext("fused-engine-test")
    ctx.conf.set("osdmap_mapping_min_pgs", 0)
    m, rule = _base_map(pg_num=64)
    svc = ctx.mapping_service()
    d0 = telemetry.dispatch_stats().dump()
    svc.update_to(m)
    d1 = telemetry.dispatch_stats().dump()
    assert d1["batches"] > d0["batches"]
    oracle = _full_oracle(m)
    for (pid, pg), want in oracle.items():
        assert svc.lookup(m, pid, pg) == want, (pid, pg)
    rng = np.random.default_rng(3)
    for _ in range(4):
        new = _churn_once(m, rng, rule)
        upd = svc.update_to(new, from_epoch=m.epoch)
        new_oracle = _full_oracle(new)
        for (pid, pg), want in new_oracle.items():
            assert svc.lookup(new, pid, pg) == want, (pid, pg)
        assert not upd.full
        assert sorted(upd.changed) == sorted(
            k for k, v in new_oracle.items() if oracle.get(k) != v)
        m, oracle = new, new_oracle
    import jax
    if len(jax.devices()) > 1:
        # the ladder batches really fanned out over the mesh
        assert telemetry.dispatch_stats().dump()["sharded_flushes"] > 0
    st = telemetry.mapping_stats().dump()
    assert st["fused_epochs"] >= 5
    eng = ctx._dispatch
    if eng is not None:
        eng.stop()


# -- balancer what-if ---------------------------------------------------------

def test_what_if_up_matches_host_up_of():
    """Batched what-if scoring == the balancer's per-candidate host
    pipeline (raw + pair rewrites + state filter), including invalid
    pairs that must be rejected."""
    rng = np.random.default_rng(21)
    m, rule = _base_map()
    for _ in range(8):
        m = _churn_once(m, rng, rule)
    svc = SharedPGMappingService()
    svc.update_to(m)
    pool = m.pools[1]
    n = m.max_osd
    cands = []
    for pg in range(pool.pg_num):
        prs = [(int(rng.integers(0, n + 2)), int(rng.integers(0, n + 2)))
               for _ in range(int(rng.integers(0, 3)))]
        cands.append((pg, prs))
    got = svc.what_if_up(m, 1, cands)
    assert got is not None
    for (pg, prs), up in zip(cands, got):
        raw = svc.raw_row(m, 1, pg)
        assert raw is not None
        raw = list(raw)
        for frm, to in prs:
            if frm in raw and to not in raw and m.exists(to) \
                    and not m._is_out(to):
                raw[raw.index(frm)] = to
        want, _ = m._raw_to_up_osds(pool, raw)
        assert up == want, (pg, prs)


def test_balancer_plan_identical_with_and_without_fused_scoring():
    """calc_pg_upmaps produces the SAME plan whether candidate
    scoring runs through the fused batch path or the host fallback."""
    from ceph_tpu import balancer

    crush, _root, rule = build_two_level_map(4, 2)
    m = OSDMap(crush=crush, epoch=2)
    m.set_max_osd(8)
    for o in range(8):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, size=2, crush_rule=rule, pg_num=64)
    with_fused = balancer.calc_pg_upmaps(m, max_deviation=1)
    orig = balancer._shared_service
    try:
        balancer._shared_service = lambda _m: None
        without = balancer.calc_pg_upmaps(m, max_deviation=1)
    finally:
        balancer._shared_service = orig
    assert with_fused == without


# -- shard_map wrappers -------------------------------------------------------

def test_shard_map_rows_pallas_encode_mesh_bit_exact():
    """The shard_map wrapper runs the fused Pallas encode per shard
    over a mesh-sharded batch, bit-exact vs the numpy oracle, with the
    output still sharded like the input (interpret mode: the TPU
    compile path is covered by the benchmark on TPU hosts)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ceph_tpu.gf.matrix import gen_cauchy1_matrix
    from ceph_tpu.gf.tables import bit_matrix
    from ceph_tpu.ops.gf_kernel import (
        _G, _SB, _blockdiag, _encode_pallas, ec_encode_ref,
        shard_map_rows)
    from ceph_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device backend")
    k, mm, chunk = 4, 2, 512
    coeff = gen_cauchy1_matrix(k, mm)[k:]
    w_blk = jnp.asarray(_blockdiag(bit_matrix(coeff), _G))
    mesh = make_mesh(len(jax.devices()))
    rng = np.random.default_rng(17)
    s = _SB * len(jax.devices())
    data = rng.integers(0, 256, (s, k, chunk), dtype=np.uint8)
    spec = PartitionSpec(tuple(mesh.axis_names), None, None)
    placed = jax.device_put(jnp.asarray(data),
                            NamedSharding(mesh, spec))

    out = shard_map_rows(
        lambda d, w: _encode_pallas(w, d, k=k, m=mm, bc=chunk,
                                    interpret=True),
        placed, w_blk)
    assert len(out.sharding.device_set) == len(jax.devices())
    np.testing.assert_array_equal(np.asarray(out),
                                  ec_encode_ref(coeff, data))


def test_fastpath_pallas_sharded_batch_matches_scalar_oracle():
    """BatchMapper.do_rule routes a mesh-sharded batch through the
    shard_map-wrapped Pallas fastpath (the lifted PR 7 guard) and the
    result equals the scalar rule oracle row for row."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ceph_tpu.crush.fastpath import FastMapper, detect
    from ceph_tpu.crush.mapper_jax import BatchMapper
    from ceph_tpu.ops.pallas_straw2 import PallasColumns
    from ceph_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device backend")
    crush_map, _root, rid = build_two_level_map(6, 4)
    fr = detect(crush_map, rid)
    assert fr is not None
    fm = FastMapper(fr)
    assert fm._pallas is None        # CPU backend: not auto-selected
    fm._pallas = PallasColumns(fr, interpret=True)
    bm = BatchMapper(crush_map)
    bm._fast_cache[rid] = fm

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(23)
    n = 16 * n_dev
    xs = rng.integers(0, 2 ** 32, (n,), dtype=np.uint32)
    reweight = np.full(crush_map.max_devices, 0x10000, dtype=np.int64)
    reweight[1] = 0
    reweight[5] = 0x8000
    spec = PartitionSpec(tuple(mesh.axis_names))
    placed = jax.device_put(jnp.asarray(xs), NamedSharding(mesh, spec))
    out = bm.do_rule(rid, placed, 3, reweight)
    # the sharded fastpath entry really compiled
    assert any(isinstance(kk, tuple) and kk and kk[0] == "fast_sh"
               for kk in bm._jit_cache)
    want = scalar_rows(crush_map, rid, xs, 3, reweight)
    np.testing.assert_array_equal(np.asarray(out), want)


# -- observability ------------------------------------------------------------

def test_fused_families_in_prometheus_scrape():
    from test_kernel_telemetry import _scrape, parse_exposition

    fams = parse_exposition(_scrape())
    for fam, typ in (
            ("ceph_kernel_mapping_fused_epochs_total", "counter"),
            ("ceph_kernel_mapping_unfused_epochs_total", "counter"),
            ("ceph_kernel_mapping_fused_lookups_total", "counter"),
            ("ceph_kernel_mapping_host_tail_share", "gauge")):
        assert fam in fams, fam
        assert fams[fam]["type"] == typ
