"""GF(2^8) algebra: field axioms, matrix generators, inversion, and the
oracle-vs-JAX kernel bit-exactness contract.

Mirrors the reference's EC unit-test strategy (SURVEY.md §4: encode/decode
round-trips with memcmp, exhaustive erasure sweeps — src/test/erasure-code/
TestErasureCodeIsa.cc:35-60,399,525)."""

import itertools

import numpy as np
import pytest

from ceph_tpu.gf import (
    gen_cauchy1_matrix,
    gen_rs_vandermonde_matrix,
    gf_div,
    gf_inv,
    gf_invert_matrix,
    gf_matmul,
    gf_mul,
    gf_pow,
    mul_table,
    nibble_bit_table,
)
from ceph_tpu.ops import ec_encode_jax, ec_encode_ref, make_encoder

rng = np.random.default_rng(0xCEF)


def slow_gf_mul(a: int, b: int) -> int:
    """Bitwise carry-less multiply + reduction, independent of the table path."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
    return r


def test_mul_matches_slow_path():
    for a in range(0, 256, 7):
        for b in range(0, 256, 5):
            assert gf_mul(a, b) == slow_gf_mul(a, b)


def test_mul_table_full():
    mt = mul_table()
    a = rng.integers(0, 256, 500)
    b = rng.integers(0, 256, 500)
    for x, y in zip(a, b):
        assert mt[x, y] == slow_gf_mul(int(x), int(y))


def test_field_axioms():
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1
        assert gf_div(a, a) == 1
        assert gf_mul(a, 1) == a
        assert gf_pow(a, 255) == 1  # multiplicative group order


def test_generator_is_primitive():
    seen = set()
    x = 1
    for _ in range(255):
        seen.add(x)
        x = gf_mul(x, 2)
    assert len(seen) == 255


def test_cauchy_matrix_shape_and_mds():
    k, m = 8, 4
    g = gen_cauchy1_matrix(k, m)
    assert g.shape == (k + m, k)
    assert (g[:k] == np.eye(k, dtype=np.uint8)).all()
    # MDS: every k-row submatrix invertible (sample + all 2-erasure cases)
    for erased in itertools.combinations(range(k + m), m):
        rows = [i for i in range(k + m) if i not in erased][:k]
        assert gf_invert_matrix(g[rows]) is not None


def test_vandermonde_guarded_region_invertible():
    # reference guards k<=21 for m=4 (ErasureCodeIsa.cc:330-361); check a safe config
    k, m = 8, 3
    g = gen_rs_vandermonde_matrix(k, m)
    for erased in itertools.combinations(range(k + m), 2):
        rows = [i for i in range(k + m) if i not in erased][:k]
        assert gf_invert_matrix(g[rows]) is not None


def test_invert_roundtrip_and_singular():
    a = gen_cauchy1_matrix(6, 3)[3:9]  # a full-rank 6x6 block
    inv = gf_invert_matrix(a)
    assert inv is not None
    assert (gf_matmul(a, inv) == np.eye(6, dtype=np.uint8)).all()
    singular = np.zeros((4, 4), dtype=np.uint8)
    singular[0, 0] = 1
    assert gf_invert_matrix(singular) is None


def test_encode_ref_xor_property():
    # m=1 with all-ones coeff row is plain XOR (region_xor analog,
    # ErasureCodeIsa.cc:118-130 m==1 fast path)
    k, b = 5, 64
    data = rng.integers(0, 256, (k, b)).astype(np.uint8)
    coeff = np.ones((1, k), dtype=np.uint8)
    parity = ec_encode_ref(coeff, data)
    assert (parity[0] == np.bitwise_xor.reduce(data, axis=0)).all()


@pytest.mark.parametrize("k,m", [(4, 2), (8, 4), (10, 4), (3, 5)])
def test_jax_kernel_bit_exact_vs_oracle(k, m):
    g = gen_cauchy1_matrix(k, m)
    coeff = g[k:]
    data = rng.integers(0, 256, (3, k, 128)).astype(np.uint8)
    want = ec_encode_ref(coeff, data)
    got = np.asarray(ec_encode_jax(coeff, data))
    assert (want == got).all()


def test_jax_kernel_int8_path():
    import jax.numpy as jnp

    g = gen_cauchy1_matrix(8, 4)
    data = rng.integers(0, 256, (2, 8, 256)).astype(np.uint8)
    want = ec_encode_ref(g[8:], data)
    got = np.asarray(ec_encode_jax(g[8:], data, dot_dtype=jnp.int8))
    assert (want == got).all()


def test_decode_roundtrip_via_inverted_matrix():
    """Erase chunks, rebuild via inverted submatrix + same kernel — the decode
    structure of ErasureCodeIsa.cc:150-310."""
    k, m = 8, 4
    g = gen_cauchy1_matrix(k, m)
    data = rng.integers(0, 256, (k, 512)).astype(np.uint8)
    parity = ec_encode_ref(g[k:], data)
    stored = np.concatenate([data, parity], axis=0)  # (k+m, B)

    for erased in [(0,), (0, 9), (1, 3, 11), (0, 1, 2, 3)]:
        avail = [i for i in range(k + m) if i not in erased][:k]
        b = g[avail]
        d = gf_invert_matrix(b)
        assert d is not None
        # decode coefficient rows for each erased chunk
        rows = []
        for e in erased:
            if e < k:
                rows.append(d[e])
            else:
                rows.append(gf_matmul(g[e][None, :], d)[0])
        c = np.stack(rows).astype(np.uint8)
        rebuilt = ec_encode_ref(c, stored[avail])
        want = np.stack([stored[e] for e in erased])
        assert (rebuilt == want).all()


def test_make_encoder_reuse():
    g = gen_cauchy1_matrix(4, 2)
    enc = make_encoder(g[4:])
    d1 = rng.integers(0, 256, (2, 4, 64)).astype(np.uint8)
    d2 = rng.integers(0, 256, (2, 4, 64)).astype(np.uint8)
    assert (np.asarray(enc(d1)) == ec_encode_ref(g[4:], d1)).all()
    assert (np.asarray(enc(d2)) == ec_encode_ref(g[4:], d2)).all()


def test_nibble_bit_table_shape():
    g = gen_cauchy1_matrix(8, 4)
    w = nibble_bit_table(g[8:])
    assert w.shape == (8 * 32, 4 * 8)
    assert set(np.unique(w)) <= {0, 1}


def test_pallas_encoder_interpret():
    """The fused Pallas block-diagonal kernel, bit-exact vs the oracle
    (interpret mode — the TPU lowering is exercised by bench/entry)."""
    import jax.numpy as jnp
    from ceph_tpu.gf.tables import bit_matrix
    from ceph_tpu.ops.gf_kernel import _blockdiag, _encode_pallas, _G, _SB

    g = gen_cauchy1_matrix(8, 4)
    coding = g[8:]
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (_SB * 2, 8, 512), dtype=np.uint8)
    w_blk = jnp.asarray(_blockdiag(bit_matrix(coding), _G))
    out = _encode_pallas(w_blk, jnp.asarray(data), k=8, m=4, bc=512,
                         interpret=True)
    np.testing.assert_array_equal(np.asarray(out), ec_encode_ref(coding, data))


def test_bit_matrix_properties():
    """bit_matrix rows are the GF(2) images of c * 2^s — multiplying a pure
    power-of-two byte through the kernel equals the table row."""
    from ceph_tpu.gf.tables import bit_matrix, gf_mul

    g = gen_cauchy1_matrix(6, 3)
    coding = g[6:]
    w = bit_matrix(coding)
    assert w.shape == (6 * 8, 3 * 8)
    for j in range(6):
        for s in range(8):
            data = np.zeros((6, 1), dtype=np.uint8)
            data[j, 0] = 1 << s
            par = ec_encode_ref(coding, data)
            for i in range(3):
                expect = gf_mul(int(coding[i, j]), 1 << s)
                assert par[i, 0] == expect
                got = sum(int(w[j * 8 + s, i * 8 + r]) << r for r in range(8))
                assert got == expect
