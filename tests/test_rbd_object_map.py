"""librbd object-map / fast-diff (src/librbd/object_map/ analog):
allocation bitmap maintained write-ahead, per-snapshot frozen copies,
diff/du/export-diff computed from maps alone (O(written), no data
stats), clone fast path, and rebuild-after-corruption."""

from __future__ import annotations

import pytest

from ceph_tpu.rbd import (
    FEATURE_FAST_DIFF,
    FEATURE_OBJECT_MAP,
    Image,
)
from ceph_tpu.rbd_object_map import (
    OBJECT_EXISTS,
    OBJECT_EXISTS_CLEAN,
    ObjectMap,
)
from ceph_tpu.tools.vstart import MiniCluster

MiB = 1 << 20


class CountingIoCtx:
    """Transparent ioctx proxy counting data-plane calls (the
    O(written) assertions)."""

    def __init__(self, inner):
        self._inner = inner
        self.counts = {"read": 0, "stat": 0}

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in self.counts and callable(attr):
            def wrapper(*a, **kw):
                self.counts[name] += 1
                return attr(*a, **kw)
            return wrapper
        return attr

    def reset(self):
        for k in self.counts:
            self.counts[k] = 0


@pytest.fixture(scope="module")
def rig():
    c = MiniCluster(n_osds=3).start()
    c.wait_for_osd_count(3)
    client = c.client()
    pool = c.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    yield {"io": io, "cluster": c}
    c.stop()


def _mk(rig, name, size=8 * MiB, feats=(FEATURE_OBJECT_MAP,
                                        FEATURE_FAST_DIFF)):
    img = Image.create(rig["io"], name, size=size, order=20,
                       stripe_unit=1 << 16, stripe_count=2)
    for f in (FEATURE_OBJECT_MAP, FEATURE_FAST_DIFF):
        if f in feats:
            img.feature_enable(f)
    return img


def test_map_tracks_writes_and_du(rig):
    img = _mk(rig, "om1")
    assert img.du()["used_objects"] == 0
    img.write(b"A" * 4096, 0)
    img.write(b"B" * 4096, 6 * MiB)
    du = img.du()
    assert du["used_objects"] == 2
    assert du["provisioned_objects"] == 8   # 8 MiB / 1 MiB objects
    om = ObjectMap.load(rig["io"], "om1")
    assert om.count(OBJECT_EXISTS) == 2


def test_snapshot_freezes_map_and_fast_diff(rig):
    img = _mk(rig, "om2")
    img.write(b"x" * 4096, 0)
    img.snap_create("s1")
    # head demoted to EXISTS_CLEAN; snap map frozen with EXISTS
    head = ObjectMap.load(rig["io"], "om2")
    assert head.count(OBJECT_EXISTS_CLEAN) == 1
    img.write(b"y" * 4096, 2 * MiB)
    img.snap_create("s2")
    img.write(b"z" * 4096, 4 * MiB)

    # diff since the beginning (None -> head): all three objects
    assert len({off for off, _l, e in img.diff() if e}) >= 3
    # s1 -> s2: exactly the object written between them
    d = [x for x in img.diff("s1", "s2") if x[2]]
    offs = {off for off, _l, _e in d}
    assert any(off == 2 * MiB for off in offs), offs
    assert all(off != 4 * MiB for off in offs), offs
    # s2 -> head: only the newest write
    d = [x for x in img.diff("s2", None) if x[2]]
    assert {off for off, _l, _e in d} & {4 * MiB}
    assert all(off != 0 for off, _l, _e in d)


def test_diff_reads_no_data_objects(rig):
    io = CountingIoCtx(rig["io"])
    img = Image.create(io, "om3", size=64 * MiB, order=20,
                       stripe_unit=1 << 16, stripe_count=2)
    img.feature_enable(FEATURE_OBJECT_MAP)
    img.write(b"w" * 4096, 0)
    img.write(b"w" * 4096, 32 * MiB)
    io.reset()
    d = [x for x in img.diff() if x[2]]
    assert d, "diff found nothing"
    # map-only: a couple of header/map reads, ZERO per-object stats —
    # on a 64-object image a stat-based diff would cost 64 stats
    assert io.counts["stat"] == 0, io.counts
    assert io.counts["read"] <= 3, io.counts


def test_clone_copies_o_written(rig):
    io = CountingIoCtx(rig["io"])
    img = Image.create(io, "om4", size=64 * MiB, order=20,
                       stripe_unit=1 << 16, stripe_count=2)
    img.feature_enable(FEATURE_OBJECT_MAP)
    img.write(b"only" * 1024, 5 * MiB)
    img.snap_create("base")
    img.snap_protect("base")
    io.reset()
    dst = img.clone("om4-child", "base")
    # data reads proportional to WRITTEN extents (1 object's stripe
    # units), nowhere near the 64-object full-image copy
    assert io.counts["read"] <= 24, io.counts
    got = dst.read(5 * MiB, 4096)
    assert got == (b"only" * 1024)[:4096]


def test_export_import_diff_roundtrip(rig):
    img = _mk(rig, "om5", size=4 * MiB)
    img.write(b"gen1" * 256, 0)
    img.snap_create("s1")
    img.write(b"gen2" * 256, 1 * MiB)
    blob = img.export_diff("s1")
    dst = _mk(rig, "om5-dst", size=4 * MiB)
    # incremental streams name their base snapshot: a target without it
    # is refused (frankenimage guard), one with it applies cleanly
    with pytest.raises(ValueError):
        dst.import_diff(blob)
    dst.write(b"gen1" * 256, 0)          # seed the base state...
    dst.snap_create("s1")                # ...and mark it as s1
    dst.import_diff(blob)
    assert dst.read(1 * MiB, 1024) == b"gen2" * 256
    assert dst.read(0, 1024) == b"gen1" * 256


def test_rebuild_after_corruption(rig):
    img = _mk(rig, "om6")
    img.write(b"real" * 512, 0)
    img.write(b"real" * 512, 3 * MiB)
    # corrupt the map object outright
    rig["io"].write_full("rbd_object_map.om6", b"\x01garbage")
    with pytest.raises(OSError):
        img.du()
    found = img.rebuild_object_map()
    assert found == 2
    assert img.du()["used_objects"] == 2
    # and the rebuilt map agrees with a fresh write
    img.write(b"more" * 512, 5 * MiB)
    assert img.du()["used_objects"] == 3


def test_resize_shrinks_map(rig):
    img = _mk(rig, "om7", size=8 * MiB)
    img.write(b"end" * 512, 7 * MiB)
    assert img.du()["provisioned_objects"] == 8
    img.resize(2 * MiB)
    du = img.du()
    assert du["provisioned_objects"] == 2
    assert du["used_objects"] == 0       # the written object was beyond
    img.resize(8 * MiB)
    assert img.read(7 * MiB, 1024) == bytes(1024)  # zeros, not stale


def test_intermediate_rewrite_not_missed(rig):
    # obj rewritten between s1 and s2, then s3 taken: diff(s1, s3) and
    # diff(s1, head) must both report it even though the target map
    # shows it EXISTS_CLEAN (the chain walk)
    img = _mk(rig, "om8", size=4 * MiB)
    img.write(b"base" * 256, 0)
    img.snap_create("s1")
    img.write(b"rewrite" * 256, 0)       # dirty between s1 and s2
    img.snap_create("s2")
    img.snap_create("s3")
    for to in ("s3", None):
        d = [x for x in img.diff("s1", to) if x[2]]
        assert any(off == 0 for off, _l, _e in d), (to, d)
    # but diff(s2, s3) is empty: nothing changed in that window
    assert [x for x in img.diff("s2", "s3") if x[2]] == []


def test_snap_remove_preserves_dirty_bits(rig):
    # write between s1 and s2, remove s2: diff(s1, head) must still
    # report the object (dirty bits folded into the heir map)
    img = _mk(rig, "om9", size=4 * MiB)
    img.write(b"base" * 256, 0)
    img.snap_create("s1")
    img.write(b"mid" * 256, 1 * MiB)
    img.snap_create("s2")
    img.snap_remove("s2")
    d = [x for x in img.diff("s1", None) if x[2]]
    assert any(off == 1 * MiB for off, _l, _e in d), d
