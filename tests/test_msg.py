"""Wire-layer tests: encoding round-trips, message framing + crc, loopback and
TCP messengers with policies, map codec round-trips (the dencoder analog)."""

import threading
import time

import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.messages import (
    MOSDOp, MOSDOpReply, MOSDPing, MOSDECSubOpWrite, OSDOpField)
from ceph_tpu.messages.osd_msgs import OP_WRITE
from ceph_tpu.msg import Decoder, Encoder, EntityName, Message, Messenger
from ceph_tpu.msg.encoding import DecodeError
from ceph_tpu.msg.messenger import ConnectionPolicy, Dispatcher
from ceph_tpu.osd import OSDMap, PGPool
from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap


def test_encoding_primitives_roundtrip():
    e = (Encoder().u8(255).u16(65535).u32(2**32 - 1).u64(2**64 - 1)
         .s32(-5).s64(-(2**62)).f64(1.5).str("héllo").bytes(b"\x00\x01")
         .list([1, 2, 3], lambda en, v: en.u32(v))
         .map({"a": 1, "b": 2}, lambda en, k: en.str(k),
              lambda en, v: en.u32(v)))
    d = Decoder(e.tobytes())
    assert d.u8() == 255 and d.u16() == 65535
    assert d.u32() == 2**32 - 1 and d.u64() == 2**64 - 1
    assert d.s32() == -5 and d.s64() == -(2**62)
    assert d.f64() == 1.5 and d.str() == "héllo" and d.bytes() == b"\x00\x01"
    assert d.list(lambda dd: dd.u32()) == [1, 2, 3]
    assert d.map(lambda dd: dd.str(), lambda dd: dd.u32()) == {"a": 1, "b": 2}
    assert d.remaining() == 0


def test_versioned_section_skips_future_fields():
    # a v2 encoder appends a field; a v1 decoder must skip it cleanly
    e = Encoder()
    e.versioned(2, 1, lambda b: (b.u32(7), b.str("future-field")))
    e.u32(99)  # data after the section

    d = Decoder(e.tobytes())
    val = d.versioned(1, lambda b, v: b.u32())
    assert val == 7
    assert d.u32() == 99

    # compat above ours must fail
    e2 = Encoder()
    e2.versioned(3, 3, lambda b: b.u32(1))
    with pytest.raises(DecodeError):
        Decoder(e2.tobytes()).versioned(1, lambda b, v: b.u32())


def test_message_frame_roundtrip_and_crc():
    op = MOSDOp(client_id=7, tid=42, pgid=(1, 9), oid="obj-1",
                ops=[OSDOpField(OP_WRITE, 0, 5, b"hello")], epoch=3)
    op.seq = 11
    data = op.encode()
    back = Message.decode(data)
    assert isinstance(back, MOSDOp)
    assert (back.client_id, back.tid, back.pgid, back.oid, back.epoch,
            back.seq) == (7, 42, (1, 9), "obj-1", 3, 11)
    assert back.ops[0].data == b"hello"
    # corrupt one payload byte -> crc failure
    bad = bytearray(data)
    bad[25] ^= 0xFF
    with pytest.raises(DecodeError):
        Message.decode(bytes(bad))


class _Collector(Dispatcher):
    def __init__(self):
        self.got = []
        self.resets = []
        self.event = threading.Event()

    def ms_dispatch(self, msg):
        self.got.append(msg)
        self.event.set()
        return True

    def ms_handle_reset(self, con):
        self.resets.append(con)


def test_loopback_messenger_roundtrip():
    a = Messenger.create(EntityName("client", 1), "loopback")
    b = Messenger.create(EntityName("osd", 0), "loopback")
    coll = _Collector()
    b.add_dispatcher_tail(coll)
    a.bind("a")
    b.bind("b")
    a.start()
    b.start()
    try:
        con = a.connect_to("b", EntityName("osd", 0))
        con.send_message(MOSDPing(from_osd=-1, op=MOSDPing.PING, stamp=1.0))
        assert coll.event.wait(2)
        msg = coll.got[0]
        assert isinstance(msg, MOSDPing)
        assert msg.connection.peer_name == EntityName("client", 1)
    finally:
        a.shutdown()
        b.shutdown()


def test_tcp_messenger_request_reply():
    server = Messenger.create(EntityName("osd", 3), "async")
    client = Messenger.create(EntityName("client", 9), "async")
    got_reply = _Collector()

    class Echo(Dispatcher):
        def ms_dispatch(self, msg):
            if isinstance(msg, MOSDOp):
                msg.connection.send_message(
                    MOSDOpReply(tid=msg.tid, result=0, epoch=msg.epoch))
                return True
            return False

    server.set_policy("client", ConnectionPolicy.lossy_client())
    server.add_dispatcher_tail(Echo())
    client.add_dispatcher_tail(got_reply)
    server.bind("127.0.0.1:0")
    server.start()
    client.start()
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 3))
        con.send_message(MOSDOp(client_id=9, tid=77, pgid=(1, 2), oid="x",
                                epoch=5))
        assert got_reply.event.wait(5)
        reply = got_reply.got[0]
        assert isinstance(reply, MOSDOpReply) and reply.tid == 77
    finally:
        client.shutdown()
        server.shutdown()


def test_tcp_many_messages_ordered():
    server = Messenger.create(EntityName("osd", 4), "async")
    client = Messenger.create(EntityName("client", 2), "async")
    coll = _Collector()
    server.add_dispatcher_tail(coll)
    server.bind("127.0.0.1:0")
    server.start()
    client.start()
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 4))
        n = 200
        for i in range(n):
            con.send_message(MOSDECSubOpWrite(
                reqid=(2, i), pgid=(1, 0), oid=f"o{i}", shard=i % 12,
                chunk=bytes([i % 256]) * 128))
        deadline = time.time() + 10
        while len(coll.got) < n and time.time() < deadline:
            time.sleep(0.01)
        assert len(coll.got) == n
        assert [m.reqid[1] for m in coll.got] == list(range(n))  # ordered
    finally:
        client.shutdown()
        server.shutdown()


def test_osdmap_codec_roundtrip():
    crush, _root, rule = build_two_level_map(4, 3)
    m = OSDMap(crush=crush)
    m.set_max_osd(12)
    for o in range(12):
        m.mark_up(o)
    m.mark_down(5)
    m.osd_primary_affinity[2] = 0x8000
    m.pools[1] = PGPool(pool_id=1, size=3, crush_rule=rule, pg_num=32)
    m.pools[2] = PGPool(pool_id=2, type=3, size=4, crush_rule=0, pg_num=16)
    m.pg_upmap[(1, 3)] = [0, 1, 2]
    m.pg_upmap_items[(1, 4)] = [(0, 7)]
    m.pg_temp[(1, 5)] = [2, 3, 4]
    m.primary_temp[(1, 5)] = 3
    m.epoch = 42

    back = decode_osdmap(encode_osdmap(m))
    assert back.epoch == 42 and back.max_osd == 12
    assert back.pools[1].pg_num == 32 and back.pools[2].is_erasure()
    assert back.pg_upmap[(1, 3)] == [0, 1, 2]
    assert back.pg_upmap_items[(1, 4)] == [(0, 7)]
    assert back.pg_temp[(1, 5)] == [2, 3, 4]
    assert back.primary_temp[(1, 5)] == 3
    # placement identical through the codec
    for pg in range(32):
        assert back.pg_to_up_acting_osds(1, pg) == m.pg_to_up_acting_osds(1, pg)


def test_event_stack_thread_count():
    """The event-driven stack costs 2 messenger threads per daemon
    regardless of connection count (the epoll-AsyncMessenger property
    the threaded stack lacks: it spawns ~2 threads per connection)."""
    import threading

    from ceph_tpu.tools.vstart import MiniCluster

    before = {t.name for t in threading.enumerate()}
    c = MiniCluster(n_osds=10, ms_type="async", heartbeats=True).start()
    try:
        c.wait_for_osd_count(10)
        client = c.client()
        pool = c.create_pool(client, pg_num=16, size=3)
        io = client.open_ioctx(pool)
        for i in range(10):
            io.write_full(f"o{i}", b"x" * 512)
        # 10 osds + 1 mon + 1 client = 12 messengers; heartbeats mesh
        # the osds all-to-all, so connections >> messengers
        ms_threads = [t.name for t in threading.enumerate()
                      if t.name.startswith("ms-") and t.name not in before]
        n_daemons = 12
        assert len(ms_threads) <= 2 * n_daemons, ms_threads
        conns = sum(len(o.msgr._conns) for o in c.osds.values())
        assert conns > 2 * 10, f"expected a meshed cluster, got {conns}"
    finally:
        c.stop()


def test_event_and_threaded_stacks_interoperate():
    """Same v1-lite wire protocol: a threaded-stack client talks to an
    event-stack server and vice versa."""
    import time as _t

    from ceph_tpu.messages import MOSDPing
    from ceph_tpu.msg.messenger import Dispatcher, EntityName, Messenger

    for srv_type, cli_type in (("async", "threaded"),
                               ("threaded", "async")):
        got = []

        class D(Dispatcher):
            def ms_dispatch(self, msg):
                got.append(msg)
                return True

        srv = Messenger.create(EntityName("osd", 7), srv_type)
        srv.set_auth(b"sharedkey")
        srv.add_dispatcher_tail(D())
        srv.bind("127.0.0.1:0")
        srv.start()
        cli = Messenger.create(EntityName("client", 8), cli_type)
        cli.set_auth(b"sharedkey")
        cli.start()
        con = cli.connect_to(srv.my_addr, EntityName("osd", 7))
        for _ in range(3):
            con.send_message(MOSDPing(from_osd=8, stamp=_t.time()))
        deadline = _t.time() + 5
        while len(got) < 3 and _t.time() < deadline:
            _t.sleep(0.02)
        assert len(got) == 3, f"{srv_type}<-{cli_type}: got {len(got)}"
        cli.shutdown()
        srv.shutdown()
