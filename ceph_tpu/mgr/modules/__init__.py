"""Mgr module ecosystem (src/pybind/mgr/* analogs).  Every submodule
exports a ``Module`` class subclassing
:class:`ceph_tpu.mgr.module.MgrModule`; the host loads them by name
from the always-on set plus the mon-persisted enabled list."""
