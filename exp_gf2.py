"""Experiment 2: tile sizes + transpose-free einsum GF bit-matrix encode."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.gf_kernel import ec_encode_ref
from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from bench import chained_seconds_per_step
from exp_gf import bit_matrix, K, M, CHUNK, STRIPES

_BITW = np.arange(8, dtype=np.int32)


@functools.partial(jax.jit, static_argnames=("k", "m", "dtype", "tile"))
def enc_bits_tile(w, data, *, k, m, dtype, tile):
    s, _, b = data.shape
    x = jnp.transpose(data, (0, 2, 1)).reshape(s * b, k)

    def body(xt):
        t = xt.shape[0]
        bits = ((xt[:, :, None].astype(jnp.int32) >> _BITW) & 1)
        bits = bits.reshape(t, k * 8).astype(dtype)
        acc = jax.lax.dot_general(
            bits, w.astype(dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32 if dtype == jnp.bfloat16 else jnp.int32)
        pb = acc.astype(jnp.int32) & 1
        return jnp.sum(pb.reshape(t, m, 8) << _BITW, axis=-1).astype(jnp.uint8)

    rows = s * b
    if tile == 0 or rows <= tile:
        packed = body(x)
    else:
        packed = jax.lax.map(body, x.reshape(-1, tile, k)).reshape(rows, m)
    return jnp.transpose(packed.reshape(s, b, m), (0, 2, 1)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "dtype", "tile"))
def enc_einsum(w, data, *, k, m, dtype, tile):
    """No transpose: bits (S, k*8, B); out[s, y, b] = sum_x W[x,y] bits[s,x,b]."""
    s, _, b = data.shape

    def body(d):  # d (ts, k, B)
        ts = d.shape[0]
        bits = ((d[:, :, None, :].astype(jnp.int32) >> _BITW[None, None, :, None]) & 1)
        bits = bits.reshape(ts, k * 8, b).astype(dtype)
        acc = jax.lax.dot_general(
            w.astype(dtype), bits, (((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32 if dtype == jnp.bfloat16 else jnp.int32)
        # acc (m*8, ts, B)
        pb = acc.astype(jnp.int32) & 1
        out = jnp.sum(pb.reshape(m, 8, ts, b) << _BITW[None, :, None, None], axis=1)
        return jnp.transpose(out, (1, 0, 2)).astype(jnp.uint8)  # (ts, m, B)

    if tile == 0 or s <= tile:
        return body(data)
    return jax.lax.map(body, data.reshape(-1, tile, k, b)).reshape(s, m, b)


def main():
    gen = gen_cauchy1_matrix(K, M)
    coding = gen[K:]
    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 256, (STRIPES, K, CHUNK), dtype=np.uint8)
    data = jnp.asarray(data_np)
    data_bytes = STRIPES * K * CHUNK
    ref = ec_encode_ref(coding, data_np[:4])
    w_bits = jnp.asarray(bit_matrix(coding))

    variants = {
        "rows_int8_t17": lambda d: enc_bits_tile(w_bits, d, k=K, m=M, dtype=jnp.int8, tile=1 << 17),
        "rows_int8_t19": lambda d: enc_bits_tile(w_bits, d, k=K, m=M, dtype=jnp.int8, tile=1 << 19),
        "rows_int8_full": lambda d: enc_bits_tile(w_bits, d, k=K, m=M, dtype=jnp.int8, tile=0),
        "einsum_int8_full": lambda d: enc_einsum(w_bits, d, k=K, m=M, dtype=jnp.int8, tile=0),
        "einsum_int8_t256": lambda d: enc_einsum(w_bits, d, k=K, m=M, dtype=jnp.int8, tile=256),
        "einsum_bf16_full": lambda d: enc_einsum(w_bits, d, k=K, m=M, dtype=jnp.bfloat16, tile=0),
    }

    for name, fn in variants.items():
        try:
            out = np.asarray(fn(data[:4]))
            ok = np.array_equal(out, ref)

            def step(d, fn=fn):
                p = fn(d)
                return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

            t = chained_seconds_per_step(step, data)
            print(f"{name}: {'OK ' if ok else 'BAD'} {data_bytes / t / 1e9:8.2f} GB/s")
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
