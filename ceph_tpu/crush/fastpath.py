"""Fused fast path for the canonical CRUSH rules on two-level maps.

The generic batched mapper (mapper_jax) re-draws the whole batch every retry
ladder iteration and pads every bucket row to the global max bucket size.  For
the rule shapes that carry ~all real placement traffic —

    take root
    chooseleaf firstn N type-t     (replicated pools; mapper.c:460-648)
    emit
and
    take root
    choose firstn N osd            (flat maps)
    emit

over a *uniform two-level* straw2 hierarchy (root -> type-t buckets ->
devices), a better device schedule exists because the retry ladder's r values
are shared across replicas: replica ``rep`` draws with r = rep + ftotal, so
the whole ladder for all reps only ever consumes root/leaf winners at
r in [0, numrep + max_ftotal).  The fast path therefore:

  1. precomputes straw2 winners for a block of r values — a fori_loop
     producing one r column per step (root (N, H) draw -> winner; that
     host's item/weight rows, padded only to the max *leaf* size, -> (N, S)
     leaf draw -> device + its is_out verdict);
  2. consumes them with numrep cheap masked while_loops whose bodies are
     (N,)-sized gathers and compares — no redraws, and reps 1..n-1 reuse the
     winners rep 0 already paid for;
  3. if any lane's ftotal walks past the precomputed block (rare: needs many
     consecutive collisions/rejections), a lax.cond re-runs the same
     computation with the full r range R = tries + numrep, which by
     construction cannot overflow — bit-exactness is unconditional, the big
     recompute just never happens on healthy maps.

(A weight-class decomposition — draws are monotone in the 16-bit hash, so
only the max-u item per distinct weight can win — was evaluated and rejected:
truncated-quotient ties between items are common at realistic bucket weights
(quotient spacing ~ crush_ln slope / w approaches 1 for host-sized w), so an
exactness fallback triggers on virtually every bulk call.  The argmax over
full per-item draws handles ties for free.)

Bit-exactness: validated against the scalar oracle (crush.mapper_ref) in
tests/test_mapper_jax.py::test_fastpath_* across skewed weights, reweights,
out OSDs, uneven host sizes, and forced-fallback configurations.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.crush_kernel import is_out
from ceph_tpu.ops.straw2_u32 import (
    _ln_f32_error_bound, magic_tables, straw2_choose_index_approx)

from .types import (
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_EMIT,
    RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_TAKE,
    CrushMap,
)

NONE = jnp.int32(CRUSH_ITEM_NONE)

#: extra r-values beyond numrep precomputed in the first block.  6 covers
#: every lane on healthy maps (ftotal beyond 6 needs seven consecutive
#: collision/reject draws); the overflow cond recomputes with the full
#: range when it ever does not, so this is a latency knob, not a
#: correctness one.
DEFAULT_BLOCK = 6


@dataclass
class FastRule:
    """Host-side description of a fast-path-eligible rule."""

    kind: str                 # "chooseleaf" | "choose_flat"
    numrep_arg: int           # step arg1 (0 -> result_max)
    tries: int                # choose_total_tries + 1 (or SET override)
    vary_r: int
    root_ids: np.ndarray      # (H,) root bucket items
    root_w: np.ndarray        # (H,) int64 16.16 weights
    leaf_ids: np.ndarray | None   # (H, S) device ids, row per root item
    leaf_w: np.ndarray | None     # (H, S) int64, 0-padded
    max_devices: int


def detect(m: CrushMap, ruleno: int) -> FastRule | None:
    """Return a FastRule if ``ruleno`` on map ``m`` fits the fused kernel."""
    t = m.tunables
    if (t.choose_local_tries or t.choose_local_fallback_tries
            or t.chooseleaf_stable != 1):
        return None
    rule = m.rules[ruleno]
    if rule is None:
        return None
    tries = t.choose_total_tries + 1
    core: list = []
    for step in rule.steps:
        if step.op == RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                tries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0 and step.arg1 != 1:
                return None  # leaf retry loop not fused
        else:
            core.append(step)
    if len(core) != 3:
        return None
    take, choose, emit = core
    if take.op != RULE_TAKE or emit.op != RULE_EMIT:
        return None
    root = m.bucket(take.arg1)
    if root is None or root.alg != CRUSH_BUCKET_STRAW2 or root.size == 0:
        return None
    if root.size > 1024:
        return None  # (N, R, H) blocks would dwarf the iterative cost
    root_ids = np.asarray(root.items, dtype=np.int32)
    root_w = np.asarray(root.item_weights, dtype=np.int64)

    if choose.op == RULE_CHOOSE_FIRSTN and choose.arg2 == 0:
        # flat: every root item is a device
        if any(i < 0 or i >= m.max_devices for i in root.items):
            return None
        return FastRule(
            kind="choose_flat", numrep_arg=choose.arg1, tries=tries,
            vary_r=t.chooseleaf_vary_r, root_ids=root_ids, root_w=root_w,
            leaf_ids=None, leaf_w=None, max_devices=m.max_devices)

    if choose.op != RULE_CHOOSELEAF_FIRSTN:
        return None
    if not t.chooseleaf_descend_once:
        # without descend_once the leaf recursion retries inside the host
        # (recurse_tries = choose_tries, mapper.c:1041-1046); the fused
        # kernel only models the single-attempt (descend_once) semantics
        return None
    want_type = choose.arg2
    hosts = []
    for item in root.items:
        h = m.bucket(item)
        if (h is None or h.alg != CRUSH_BUCKET_STRAW2
                or h.type != want_type or h.size == 0):
            return None
        if any(i < 0 or i >= m.max_devices for i in h.items):
            return None
        hosts.append(h)
    s_max = max(h.size for h in hosts)
    leaf_ids = np.zeros((len(hosts), s_max), dtype=np.int32)
    leaf_w = np.zeros((len(hosts), s_max), dtype=np.int64)
    for row, h in enumerate(hosts):
        leaf_ids[row, :h.size] = h.items
        leaf_w[row, :h.size] = h.item_weights
    return FastRule(
        kind="chooseleaf", numrep_arg=choose.arg1, tries=tries,
        vary_r=t.chooseleaf_vary_r, root_ids=root_ids, root_w=root_w,
        leaf_ids=leaf_ids, leaf_w=leaf_w, max_devices=m.max_devices)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _draw_argmax(x, ids, weights, r, magic, off):
    """Straw2 winner position for one r value across the batch.

    x (N,) uint32; ids (S,) shared or (N, S) per-lane rows; weights /
    magic / off broadcastable to ids; r scalar uint32.  Returns (N,)
    positions.  Runs the u32 magic-division kernel (ops.straw2_u32) —
    bit-exact against the s64 kernel by exhaustive validation — whose
    argmin takes the first minimum, exactly the strict-``>`` scan of
    bucket_straw2_choose (mapper.c:374-380): truncation ties resolve to
    the lowest index for free.
    """
    idb = ids[None, :] if ids.ndim == 1 else ids
    wb = jnp.broadcast_to(
        weights[None, :] if weights.ndim == 1 else weights, idb.shape)
    mb = jnp.broadcast_to(
        magic[None, :, :] if magic.ndim == 2 else magic, (*idb.shape, 5))
    ob = jnp.broadcast_to(
        off[None, :] if off.ndim == 1 else off, idb.shape)
    return straw2_choose_index_approx(x, idb, r, wb, mb, ob)


def _consume(host_win, leaf_win, leaf_bad, numrep, tries, R, n):
    """Walk the firstn ladder over precomputed winners.

    host_win (N, R) int32: first-level item chosen at r (host id, or the
    device itself for flat rules).  leaf_win (N, R) int32: device at r.
    leaf_bad (N, R) bool: device rejected (is_out).  Returns
    (out_host, out_leaf, overflow): (N, numrep) selections with NONE holes
    and a per-lane flag for ftotal walking past R.
    """
    out_h = jnp.full((n, numrep), NONE, dtype=jnp.int32)
    out_l = jnp.full((n, numrep), NONE, dtype=jnp.int32)
    overflow = jnp.zeros((n,), dtype=bool)

    for rep in range(numrep):
        def cond(s):
            return jnp.any(s[3])

        def body(s, rep=rep, out_h=out_h, out_l=out_l):
            sel_h, sel_l, ft, act, ovf = s
            r = rep + ft
            within = r < R
            ridx = jnp.minimum(r, R - 1)[:, None]
            hb = jnp.take_along_axis(host_win, ridx, 1)[:, 0]
            lf = jnp.take_along_axis(leaf_win, ridx, 1)[:, 0]
            bad_l = jnp.take_along_axis(leaf_bad, ridx, 1)[:, 0]
            coll_h = jnp.any(out_h == hb[:, None], axis=1)
            coll_l = jnp.any(out_l == lf[:, None], axis=1)
            bad = coll_h | coll_l | bad_l
            place = act & within & ~bad
            sel_h = jnp.where(place, hb, sel_h)
            sel_l = jnp.where(place, lf, sel_l)
            ft = jnp.where(act & within & bad, ft + 1, ft)
            ovf = ovf | (act & ~within)
            act = act & within & bad & (ft < tries)
            return sel_h, sel_l, ft, act, ovf

        sel0 = jnp.full((n,), NONE, dtype=jnp.int32)
        sel_h, sel_l, _, _, overflow = jax.lax.while_loop(
            cond, body,
            (sel0, sel0, jnp.zeros((n,), jnp.int32),
             jnp.ones((n,), bool), overflow))
        out_h = out_h.at[:, rep].set(sel_h)
        out_l = out_l.at[:, rep].set(sel_l)
    return out_h, out_l, overflow


def _compact_rows(rows):
    order = jnp.argsort(rows == NONE, axis=1)
    return jnp.take_along_axis(rows, order, axis=1)


class FastMapper:
    """Compiled fast path for one (map, rule)."""

    def __init__(self, fr: FastRule):
        self.fr = fr
        _ln_f32_error_bound()   # measure eagerly: must be concrete by
        self.root_ids = jnp.asarray(fr.root_ids)   # the time jit traces
        self.root_w = jnp.asarray(fr.root_w)
        rm, ro = magic_tables(fr.root_w)
        self.root_magic = jnp.asarray(rm)
        self.root_off = jnp.asarray(ro)
        if fr.leaf_ids is not None:
            self.leaf_ids = jnp.asarray(fr.leaf_ids)
            self.leaf_w = jnp.asarray(fr.leaf_w)
            lm, lo = magic_tables(fr.leaf_w)
            self.leaf_magic = jnp.asarray(lm)
            self.leaf_off = jnp.asarray(lo)
        # the fused Pallas column kernels (2.5x the XLA path on this
        # backend); TPU-only — the CPU mesh tests keep the XLA path.
        # Mesh-sharded batches reach these kernels through the
        # shard_map wrapper in BatchMapper._fast_sharded_fn (a
        # pallas_call is an opaque custom call GSPMD cannot split, so
        # the batch splits BEFORE the kernel; run() itself is
        # row-independent along x by the oracle-equivalence contract).
        # The gate honors jax.default_device(<tpu>) too: a multi-
        # platform process (cpu default + tpu reachable) running under
        # that context IS on the tpu even though default_backend()
        # still says cpu
        self._pallas = None
        _dd = getattr(jax.config, "jax_default_device", None)
        if _dd is not None:
            # jax.default_device accepts a Device OR a platform string
            on_tpu = getattr(_dd, "platform", str(_dd)) == "tpu"
        else:
            on_tpu = jax.default_backend() == "tpu"
        if on_tpu:
            try:
                from ceph_tpu.ops.pallas_straw2 import PallasColumns
            except ImportError:   # pragma: no cover
                PallasColumns = None
            if PallasColumns is not None:
                # construction failures must surface, not silently
                # degrade to the slower XLA path
                self._pallas = PallasColumns(fr)

    def _winners(self, xs, reweight, R: int):
        """host_win/leaf_win/leaf_bad for r in [0, R): a fori_loop producing
        one r column per step (bounds the (N, H) ln-matmul intermediates to a
        single r; an unrolled R-wide block OOMs HBM at bulk batch sizes)."""
        fr = self.fr
        n = xs.shape[0]
        hw0 = jnp.full((n, R), NONE, dtype=jnp.int32)
        lw0 = jnp.full((n, R), NONE, dtype=jnp.int32)
        lb0 = jnp.zeros((n, R), dtype=bool)

        def body(i, bufs):
            hw, lw, lb = bufs
            r = i.astype(jnp.uint32)
            pos = _draw_argmax(xs, self.root_ids, self.root_w, r,
                               self.root_magic, self.root_off)
            first = self.root_ids[pos]                         # (N,)
            if fr.kind == "choose_flat":
                leaf = first
            else:
                # r_leaf = vary_r ? r >> (vary_r-1) : 0 (mapper.c:578)
                if fr.vary_r:
                    r_leaf = r >> jnp.uint32(fr.vary_r - 1)
                else:
                    r_leaf = jnp.uint32(0)
                ids = self.leaf_ids[pos]                       # (N, S)
                w = self.leaf_w[pos]                           # (N, S)
                lpos = _draw_argmax(xs, ids, w, r_leaf,
                                    self.leaf_magic[pos],
                                    self.leaf_off[pos])
                leaf = jnp.take_along_axis(ids, lpos[:, None], 1)[:, 0]
            bad = is_out(reweight, leaf, xs)
            hw = jax.lax.dynamic_update_slice(hw, first[:, None], (0, i))
            lw = jax.lax.dynamic_update_slice(lw, leaf[:, None], (0, i))
            lb = jax.lax.dynamic_update_slice(lb, bad[:, None], (0, i))
            return hw, lw, lb

        return jax.lax.fori_loop(0, R, body, (hw0, lw0, lb0))

    def _winners_cols(self, xs, reweight, R: int):
        """(host_win, leaf_win, leaf_bad) in the native (R, n_padded)
        column layout of the Pallas kernels (no transposes).

        Root columns go through the fused approx-filter kernel when the
        R columns' candidates fit one lane block; its certificate flag
        (any column with more than K items inside the measured f32
        error band) falls the whole batch back to the exact column
        kernel, so bit-exactness is unconditional."""
        pc = self._pallas
        from ceph_tpu.ops.pallas_straw2 import _KPACK
        if R * _KPACK <= 128 and 512 <= pc.S_root <= 1024:
            # the approx filter narrows each column from S items to K
            # candidates — a win only when S spans many slabs (big flat
            # buckets); at host-count-sized roots the packing machinery
            # costs more than the exact pipeline it saves (measured).
            # Upper bound: the extractor packs item positions into 10
            # bits (pallas_straw2._extract_candidates), so past 1024
            # items the certificate would fire on every batch and the
            # filter pass would be pure overhead
            pos, ids, ovf = pc.froot_columns(xs, reweight, R)
            pos, ids = jax.lax.cond(
                jnp.any(ovf != 0),
                lambda _: pc.root_columns(xs, reweight, R),
                lambda _: (pos, ids), None)
        else:
            pos, ids = pc.root_columns(xs, reweight, R)
        # the winner columns come back padded to the kernel block quantum
        n_pad = ids.shape[1]
        xs_pad = jnp.concatenate(
            [xs, jnp.zeros((n_pad - xs.shape[0],), dtype=xs.dtype)]) \
            if n_pad > xs.shape[0] else xs
        if self.fr.kind == "choose_flat":
            # is_out runs OUTSIDE the kernels: it is elementwise in
            # (winner, x), one cheap XLA op over the columns — and the
            # in-kernel variant hit a Mosaic miscompile (hash32_2 fed
            # from the winner gather/sum pipeline went wrong for ~0.03%
            # of lanes, compiled mode only; caught by TPU-vs-XLA
            # cross-validation in round 3)
            bad = is_out(reweight, ids, xs_pad[None, :])
            return ids, ids, bad
        lid = self._pallas.leaf_columns(xs, pos, R)
        lbad = is_out(reweight, lid, xs_pad[None, :])
        return ids, lid, lbad

    #: minimum batch for the two-stage schedule; below it one pass at R0
    #: is cheaper than the compaction plumbing
    TWO_STAGE_MIN = 32768
    #: stage-2 capacity: lanes whose ladder outran the stage-1 columns.
    #: At realistic reject/collision rates the expected count is a few
    #: hundred per 64Ki (p ~ fail^2 per lane); 4096 makes the capacity
    #: overflow a tail-of-tail event, and the guard recomputes the whole
    #: batch when it ever fires, so it costs latency, never correctness.
    STAGE2_CAP = 4096

    def _run_pallas(self, xs, reweight, result_max, numrep, R0, Rf):
        """Winner columns and the consume ladder both on-device in their
        native (R, N) layout — no transposes, no XLA while_loops.

        Bulk batches run a two-stage schedule: stage 1 computes only
        numrep+1 columns for every lane (covers lanes whose firstn
        ladder saw at most one failure in the last replica — ~99% at
        realistic maps), then gathers the overflowing lanes into one
        compact STAGE2_CAP batch that gets the full R0 treatment.  The
        placement for a given x is identical either way — the ladder is
        deterministic in (x, columns) — so this is pure scheduling, the
        oracle-equivalence property is untouched."""
        from ceph_tpu.ops.pallas_straw2 import consume_columns
        fr = self.fr
        n = xs.shape[0]
        interp = self._pallas.interpret

        def attempt(xv, R):
            m = xv.shape[0]
            hw, lw, lb = self._winners_cols(xv, reweight, R)
            oh, ol, ovf = consume_columns(
                hw, lw, lb, numrep=numrep, tries=fr.tries, interpret=interp)
            return oh[:, :m], ol[:, :m], ovf[:m]

        def attempt_full(xv, R):
            oh, ol, ovf = attempt(xv, R)
            return jax.lax.cond(
                jnp.any(ovf != 0),
                lambda _: attempt(xv, Rf)[:2],
                lambda _: (oh, ol), None)

        R1 = numrep + 1
        if n < self.TWO_STAGE_MIN or R1 >= R0:
            out_h, out_l = attempt_full(xs, R0)
        else:
            oh1, ol1, ovf1 = attempt(xs, R1)
            cap = self.STAGE2_CAP
            need = ovf1 != 0
            # overflowing lanes first, stable, then fillers
            order = jnp.argsort(jnp.where(need, 0, 1), stable=True)
            idx_c = order[:cap]
            xs2 = xs[idx_c]

            def merged(_):
                oh2, ol2 = attempt_full(xs2, R0)
                sel = need[idx_c][None, :]
                oh = oh1.at[:, idx_c].set(
                    jnp.where(sel, oh2, oh1[:, idx_c]))
                ol = ol1.at[:, idx_c].set(
                    jnp.where(sel, ol2, ol1[:, idx_c]))
                return oh, ol

            out_h, out_l = jax.lax.cond(
                jnp.sum(need) > cap,
                lambda _: attempt_full(xs, R0),
                merged, None)
        res = out_l if fr.kind == "chooseleaf" else out_h
        res = _compact_rows(res.T)
        if numrep < result_max:
            res = jnp.concatenate(
                [res, jnp.full((n, result_max - numrep), NONE,
                               dtype=jnp.int32)], axis=1)
        return res[:, :result_max]

    def run(self, xs, reweight, result_max: int,
            block: int = DEFAULT_BLOCK):
        """Full do_rule: returns (N, result_max) NONE-compacted placements."""
        fr = self.fr
        numrep = fr.numrep_arg
        if numrep <= 0:
            numrep += result_max
        n = xs.shape[0]
        if numrep <= 0:
            return jnp.full((n, result_max), NONE, dtype=jnp.int32)
        Rf = fr.tries + numrep
        R0 = min(numrep + block, Rf)

        if self._pallas is not None:
            return self._run_pallas(xs, reweight, result_max, numrep, R0, Rf)

        hw, lw, lb = self._winners(xs, reweight, R0)
        out_h, out_l, ovf = _consume(hw, lw, lb, numrep, fr.tries, R0, n)

        def slow(_):
            hw2, lw2, lb2 = self._winners(xs, reweight, Rf)
            oh, ol, _ = _consume(hw2, lw2, lb2, numrep, fr.tries, Rf, n)
            return oh, ol

        out_h, out_l = jax.lax.cond(
            jnp.any(ovf), slow, lambda _: (out_h, out_l), None)
        res = out_l if fr.kind == "chooseleaf" else out_h
        res = _compact_rows(res)
        if numrep < result_max:
            res = jnp.concatenate(
                [res, jnp.full((n, result_max - numrep), NONE,
                               dtype=jnp.int32)], axis=1)
        return res[:, :result_max]
