"""Fact extraction for the static checks (stdlib ``ast`` only).

One pass over every module under the analyzed package builds a
``TreeIndex``:

* every function/method (including nested defs) with its AST node;
* per-class lock attributes — ``self._lock = lockdep.make_lock("X")``
  resolves to the name ``X``; a bare ``threading.Lock()`` gets the
  synthesized name ``module.Class.attr`` (and is marked bare);
* per-function acquisition events and call sites, each annotated with
  the with-statement lock stack held at that point;
* a best-effort call graph: ``self.m()`` resolves within the class
  (and in-tree bases), bare names within the module and its
  from-imports, ``self.attr.m()`` through attribute types inferred
  from constructor calls and ``__init__`` parameter annotations, and
  — for otherwise-unresolvable attribute calls — a unique-method-name
  fallback (skipped when ambiguous).

Lock names are normalized so the per-instance suffix convention
(``OSD::osd_lock(0)``) collapses to one graph node per name family
(``OSD::osd_lock(*)``) — the same name-based merging runtime lockdep
does, extended over instances.
"""

from __future__ import annotations

import ast
import os
import re

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_ALLOW_RE = re.compile(
    r"#\s*analysis:\s*allow\[([\w*,-]+)\]\s*(?:--\s*(.*?))?\s*$")
_PAREN_RE = re.compile(r"\([^()]*\)")


def normalize_name(name: str) -> str:
    """Collapse per-instance suffixes: ``OSD::osd_lock(0)`` ->
    ``OSD::osd_lock(*)`` (one order-graph node per name family)."""
    return _PAREN_RE.sub("(*)", name)


def name_chain(node) -> tuple | None:
    """``a.b.c`` -> ("a","b","c"); ``self._lock`` -> ("self","_lock");
    None for anything that is not a pure Name/Attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _static_str(node) -> str | None:
    """A string literal or f-string with formatted parts as ``*``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant):
                out.append(str(part.value))
            else:
                out.append("*")
        return "".join(out)
    return None


class LockDef:
    __slots__ = ("name", "bare", "line")

    def __init__(self, name: str, bare: bool, line: int):
        self.name = normalize_name(name)
        self.bare = bare
        self.line = line


class AcqEvent:
    __slots__ = ("lock", "line", "held", "blocking")

    def __init__(self, lock: str, line: int, held: tuple,
                 blocking: bool = True):
        self.lock = lock
        self.line = line
        self.held = held
        self.blocking = blocking


class CallSite:
    __slots__ = ("spec", "line", "held", "node")

    def __init__(self, spec: tuple, line: int, held: tuple, node):
        self.spec = spec
        self.line = line
        self.held = held
        self.node = node


class FunctionInfo:
    def __init__(self, qualname: str, name: str, node, module,
                 cls=None, parent=None):
        self.qualname = qualname      # mod.Class.meth / mod.fn.<locals>.g
        self.name = name
        self.node = node
        self.module = module
        self.cls = cls                # ClassInfo or None
        self.parent = parent          # enclosing FunctionInfo
        self.nested: dict[str, FunctionInfo] = {}
        self.acq_events: list[AcqEvent] = []
        self.call_sites: list[CallSite] = []
        self.decorators: list = node.decorator_list if hasattr(
            node, "decorator_list") else []

    @property
    def line(self) -> int:
        return self.node.lineno

    def __repr__(self):
        return f"<fn {self.qualname}>"


class ClassInfo:
    def __init__(self, name: str, node, module):
        self.name = name
        self.node = node
        self.module = module
        self.bases: list[tuple] = [b for b in (
            name_chain(x) for x in node.bases) if b]
        self.attr_locks: dict[str, LockDef] = {}
        self.attr_types: dict[str, str] = {}   # attr -> class name
        self.methods: dict[str, FunctionInfo] = {}


class ModuleInfo:
    def __init__(self, path: str, relpath: str, modname: str, tree,
                 source: str):
        self.path = path
        self.relpath = relpath
        self.modname = modname
        self.tree = tree
        self.source = source
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}   # module-level
        self.module_locks: dict[str, LockDef] = {}
        #: alias -> ("module", dotted) | ("symbol", dotted, orig)
        self.imports: dict[str, tuple] = {}
        #: lineno -> [(check, reason)] suppression comments
        self.allows: dict[int, list] = {}
        for i, ln in enumerate(source.splitlines(), 1):
            m = _ALLOW_RE.search(ln)
            if m:
                checks = [c.strip() for c in m.group(1).split(",")]
                reason = (m.group(2) or "").strip()
                self.allows[i] = [(c, reason) for c in checks]


class TreeIndex:
    """All modules of one analyzed package + resolution helpers."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.base = os.path.dirname(self.root)
        self.modules: dict[str, ModuleInfo] = {}
        self.by_path: dict[str, ModuleInfo] = {}
        #: bare method name -> [FunctionInfo] across every class
        self.methods_by_name: dict[str, list] = {}
        #: class name -> [ClassInfo]
        self.classes_by_name: dict[str, list] = {}

    # -- construction ---------------------------------------------------------

    @classmethod
    def build(cls, root: str) -> "TreeIndex":
        idx = cls(root)
        for dirpath, dirnames, filenames in os.walk(idx.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    idx._load(os.path.join(dirpath, fn))
        for mod in idx.modules.values():
            idx._scan_module(mod)
        return idx

    def _load(self, path: str) -> None:
        rel = os.path.relpath(path, self.base).replace(os.sep, "/")
        modname = rel[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[:-len(".__init__")]
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return
        mod = ModuleInfo(path, rel, modname, tree, source)
        self.modules[modname] = mod
        self.by_path[rel] = mod
        self._index_module(mod)

    def _index_module(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        "module", a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:   # relative: resolve against package
                    base = mod.modname.split(".")
                    if not mod.path.endswith("__init__.py"):
                        base = base[:-1]
                    base = base[:len(base) - (node.level - 1)]
                    src = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    src = node.module
                if src:
                    for a in node.names:
                        mod.imports[a.asname or a.name] = (
                            "symbol", src, a.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = FunctionInfo(f"{mod.modname}.{node.name}",
                                 node.name, node, mod)
                mod.functions[node.name] = f
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(node.name, node, mod)
                mod.classes[node.name] = ci
                self.classes_by_name.setdefault(node.name, []).append(ci)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fi = FunctionInfo(
                            f"{mod.modname}.{node.name}.{sub.name}",
                            sub.name, sub, mod, cls=ci)
                        ci.methods[sub.name] = fi
                        self.methods_by_name.setdefault(
                            sub.name, []).append(fi)
                    elif isinstance(sub, ast.Assign):
                        self._note_attr_assign(mod, ci, sub,
                                               class_body=True)
            elif isinstance(node, ast.Assign):
                # owner = the module, so unrelated module-level _LOCKs
                # in different files stay distinct graph nodes
                ld = self._lock_def(mod, node.value,
                                    self._assign_name(node),
                                    owner=mod.modname)
                if ld:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mod.module_locks[t.id] = ld

    @staticmethod
    def _assign_name(node) -> str | None:
        t = node.targets[0] if node.targets else None
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return t.attr
        return None

    def _lock_def(self, mod: ModuleInfo, value, attr: str | None,
                  owner: str = "") -> LockDef | None:
        """Recognize a lock-constructing RHS; None otherwise."""
        if not isinstance(value, ast.Call):
            return None
        chain = name_chain(value.func)
        if not chain:
            return None
        tail = chain[-1]
        if tail in ("make_lock", "make_condition"):
            # make_condition(name, lock=self.X) shares ONE lock object
            # between a mutex and its condition — model it as an alias
            # of X, not a second node, or a real inversion through the
            # shared lock would split across two names and hide
            if tail == "make_condition":
                shared = value.args[1] if len(value.args) > 1 else None
                for kw in value.keywords:
                    if kw.arg == "lock":
                        shared = kw.value
                inner = name_chain(shared) if shared is not None \
                    else None
                if inner and inner[0] == "self" and len(inner) == 2:
                    return LockDef(f"@alias:{inner[1]}", False,
                                   value.lineno)
            nm = _static_str(value.args[0]) if value.args else None
            return LockDef(nm or f"{owner}.{attr}", False, value.lineno)
        if tail in _LOCK_CTORS and (
                chain[0] == "threading" or len(chain) == 1):
            # Condition(existing_lock) aliases the wrapped lock
            if tail == "Condition" and value.args:
                inner = name_chain(value.args[0])
                if inner and inner[0] == "self" and len(inner) == 2:
                    return LockDef(f"@alias:{inner[1]}", True,
                                   value.lineno)
            return LockDef(f"{owner}.{attr}", True, value.lineno)
        return None

    def _note_attr_assign(self, mod: ModuleInfo, ci: ClassInfo, node,
                          class_body: bool = False) -> None:
        owner = f"{mod.modname}.{ci.name}"
        attr = self._assign_name(node)
        if attr is None:
            return
        ld = self._lock_def(mod, node.value, attr, owner)
        targets_self = class_body or any(
            isinstance(t, ast.Attribute) and
            isinstance(t.value, ast.Name) and t.value.id in ("self", "cls")
            for t in node.targets)
        if not targets_self:
            return
        if ld:
            if ld.name.startswith("@alias:"):
                src = ci.attr_locks.get(ld.name[len("@alias:"):])
                if src is not None:
                    ci.attr_locks[attr] = src
                else:
                    ci.attr_locks[attr] = LockDef(
                        f"{owner}.{attr}", True, ld.line)
            else:
                ci.attr_locks[attr] = ld
            return
        # attribute types: self.x = ClassName(...) (annotated-param
        # assignments are typed by the pass in _collect_attrs)
        if isinstance(node.value, ast.Call):
            chain = name_chain(node.value.func)
            if chain and chain[-1][:1].isupper():
                ci.attr_types.setdefault(attr, chain[-1])

    # -- per-function scanning ------------------------------------------------

    def _scan_module(self, mod: ModuleInfo) -> None:
        for fi in list(mod.functions.values()):
            self._scan_function(fi)
        for ci in mod.classes.values():
            # attribute facts first (any method may assign self.x)
            for fi in ci.methods.values():
                self._collect_attrs(mod, ci, fi)
            for fi in ci.methods.values():
                self._scan_function(fi)

    def _collect_attrs(self, mod: ModuleInfo, ci: ClassInfo,
                       fi: FunctionInfo) -> None:
        ann: dict[str, str] = {}
        args = fi.node.args
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                t = None
                if isinstance(a.annotation, ast.Constant) and \
                        isinstance(a.annotation.value, str):
                    t = a.annotation.value.strip("'\"")
                else:
                    ch = name_chain(a.annotation)
                    if ch:
                        t = ch[-1]
                if t:
                    ann[a.arg] = t.split("[")[0].split(".")[-1]
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                self._note_attr_assign(mod, ci, node)
                # self.x = annotated_param
                attr = self._assign_name(node)
                if attr and isinstance(node.value, ast.Name) and \
                        node.value.id in ann:
                    ci.attr_types.setdefault(attr, ann[node.value.id])

    def _scan_function(self, fi: FunctionInfo) -> None:
        self._scan_block(fi, fi.node.body, [])

    def _scan_block(self, fi: FunctionInfo, stmts, held: list) -> None:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in st.items:
                    self._scan_expr(fi, item.context_expr, held)
                    lk = self.resolve_lock_expr(fi, item.context_expr)
                    if lk is not None:
                        fi.acq_events.append(AcqEvent(
                            lk, st.lineno, tuple(held)))
                        held.append(lk)
                        pushed += 1
                self._scan_block(fi, st.body, held)
                for _ in range(pushed):
                    held.pop()
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nf = FunctionInfo(
                    f"{fi.qualname}.<locals>.{st.name}", st.name,
                    st, fi.module, cls=fi.cls, parent=fi)
                fi.nested[st.name] = nf
                # a nested def runs later (often on another thread):
                # scan with an EMPTY held stack, but record the
                # definition as a call site so reachability flows
                self._scan_block(nf, st.body, [])
                fi.call_sites.append(CallSite(
                    ("nested", st.name), st.lineno, tuple(held), st))
            elif isinstance(st, ast.ClassDef):
                pass    # local classes: out of scope
            else:
                for _field, value in ast.iter_fields(st):
                    if isinstance(value, ast.expr):
                        self._scan_expr(fi, value, held)
                    elif isinstance(value, list):
                        if value and isinstance(value[0], ast.stmt):
                            self._scan_block(fi, value, held)
                        else:
                            for v in value:
                                if isinstance(v, ast.expr):
                                    self._scan_expr(fi, v, held)
                                elif isinstance(v, ast.ExceptHandler):
                                    self._scan_block(fi, v.body, held)

    def _scan_expr(self, fi: FunctionInfo, node, held: list) -> None:
        # collect Call nodes without descending into Lambda bodies —
        # a lambda runs later (usually on another thread/callback), so
        # its calls must not inherit the current held-lock stack
        calls, lambdas, stack = [], [], [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                lambdas.append(n)
                continue
            if isinstance(n, ast.Call):
                calls.append(n)
            stack.extend(ast.iter_child_nodes(n))
        for lam in lambdas:
            # lineno:col so two lambdas on one line get distinct nodes
            name = f"<lambda@{lam.lineno}:{lam.col_offset}>"
            nf = FunctionInfo(f"{fi.qualname}.<locals>.{name}", name,
                              lam, fi.module, cls=fi.cls, parent=fi)
            nf.decorators = []
            fi.nested[name] = nf
            self._scan_expr(nf, lam.body, [])
            fi.call_sites.append(CallSite(("nested", name), lam.lineno,
                                          tuple(held), lam))
        for call in calls:
            chain = name_chain(call.func)
            if not chain:
                continue
            line, snap = call.lineno, tuple(held)
            if chain[-1] == "acquire" and len(chain) > 1:
                lk = self.resolve_lock_expr(fi, call.func.value)
                if lk is not None:
                    blocking = True
                    if call.args and isinstance(call.args[0],
                                                ast.Constant):
                        blocking = bool(call.args[0].value)
                    for kw in call.keywords:
                        if kw.arg == "blocking" and isinstance(
                                kw.value, ast.Constant):
                            blocking = bool(kw.value.value)
                    fi.acq_events.append(AcqEvent(lk, line, snap,
                                                  blocking=blocking))
                    continue
            fi.call_sites.append(CallSite(
                self._call_spec(fi, chain), line, snap, call))

    @staticmethod
    def _call_spec(fi: FunctionInfo, chain: tuple) -> tuple:
        if len(chain) == 1:
            return ("name", chain[0])
        if chain[0] in ("self", "cls"):
            if len(chain) == 2:
                return ("self", chain[1])
            if len(chain) == 3:
                return ("selfattr", chain[1], chain[2])
        if len(chain) == 2:
            return ("dotted", chain[0], chain[1])
        return ("unique", chain[-1])

    # -- resolution -----------------------------------------------------------

    def find_class(self, name: str, mod: ModuleInfo) -> ClassInfo | None:
        if name in mod.classes:
            return mod.classes[name]
        imp = mod.imports.get(name)
        if imp and imp[0] == "symbol":
            m2 = self.modules.get(imp[1])
            if m2 and imp[2] in m2.classes:
                return m2.classes[imp[2]]
        cands = self.classes_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def _class_lock(self, ci: ClassInfo, attr: str,
                    seen=None) -> LockDef | None:
        if seen is None:
            seen = set()
        if id(ci) in seen:
            return None
        seen.add(id(ci))
        if attr in ci.attr_locks:
            return ci.attr_locks[attr]
        for b in ci.bases:
            bc = self.find_class(b[-1], ci.module)
            if bc is not None:
                ld = self._class_lock(bc, attr, seen)
                if ld is not None:
                    return ld
        return None

    def _class_attr_type(self, ci: ClassInfo, attr: str) -> str | None:
        if attr in ci.attr_types:
            return ci.attr_types[attr]
        for b in ci.bases:
            bc = self.find_class(b[-1], ci.module)
            if bc is not None:
                t = self._class_attr_type(bc, attr)
                if t:
                    return t
        return None

    def resolve_lock_expr(self, fi: FunctionInfo, expr) -> str | None:
        chain = name_chain(expr)
        if not chain:
            return None
        mod = fi.module
        if chain[0] in ("self", "cls") and fi.cls is not None:
            if len(chain) == 2:
                ld = self._class_lock(fi.cls, chain[1])
                return ld.name if ld else None
            if len(chain) == 3:
                t = self._class_attr_type(fi.cls, chain[1])
                if t:
                    c2 = self.find_class(t, mod)
                    if c2 is not None:
                        ld = self._class_lock(c2, chain[2])
                        if ld:
                            return ld.name
                return None
            return None
        if len(chain) == 1:
            ld = mod.module_locks.get(chain[0])
            return ld.name if ld else None
        if len(chain) == 2:
            ci = self.find_class(chain[0], mod)
            if ci is not None:
                ld = self._class_lock(ci, chain[1])
                return ld.name if ld else None
            imp = mod.imports.get(chain[0])
            if imp and imp[0] == "module":
                m2 = self.modules.get(imp[1])
                if m2:
                    ld = m2.module_locks.get(chain[1])
                    return ld.name if ld else None
        return None

    def resolve_call(self, fi: FunctionInfo,
                     spec: tuple) -> FunctionInfo | None:
        kind = spec[0]
        mod = fi.module
        if kind == "nested":
            return fi.nested.get(spec[1])
        if kind == "name":
            n = spec[1]
            cur = fi
            while cur is not None:
                if n in cur.nested:
                    return cur.nested[n]
                cur = cur.parent
            if n in mod.functions:
                return mod.functions[n]
            if n in mod.classes:
                return mod.classes[n].methods.get("__init__")
            imp = mod.imports.get(n)
            if imp and imp[0] == "symbol":
                m2 = self.modules.get(imp[1])
                if m2:
                    if imp[2] in m2.functions:
                        return m2.functions[imp[2]]
                    if imp[2] in m2.classes:
                        return m2.classes[imp[2]].methods.get(
                            "__init__")
            return None
        if kind == "self" and fi.cls is not None:
            m = self._class_method(fi.cls, spec[1])
            if m is not None:
                return m
            return self._unique_method(spec[1])
        if kind == "selfattr" and fi.cls is not None:
            t = self._class_attr_type(fi.cls, spec[1])
            if t:
                c2 = self.find_class(t, mod)
                if c2 is not None:
                    m = self._class_method(c2, spec[2])
                    if m is not None:
                        return m
            return self._unique_method(spec[2])
        if kind == "dotted":
            base, meth = spec[1], spec[2]
            ci = self.find_class(base, mod)
            if ci is not None:
                return self._class_method(ci, meth)
            imp = mod.imports.get(base)
            m2 = None
            if imp and imp[0] == "module":
                m2 = self.modules.get(imp[1])
            elif imp and imp[0] == "symbol":
                # `from . import x` / `from pkg import mod` where the
                # symbol IS a submodule
                m2 = self.modules.get(f"{imp[1]}.{imp[2]}")
            if imp and m2 is not None:
                if meth in m2.functions:
                    return m2.functions[meth]
                if meth in m2.classes:
                    return m2.classes[meth].methods.get("__init__")
            if imp and imp[0] == "module":
                return None
            return self._unique_method(meth)
        if kind == "unique":
            return self._unique_method(spec[1])
        return None

    def _class_method(self, ci: ClassInfo, name: str,
                      seen=None) -> FunctionInfo | None:
        if seen is None:
            seen = set()
        if id(ci) in seen:
            return None
        seen.add(id(ci))
        if name in ci.methods:
            return ci.methods[name]
        for b in ci.bases:
            bc = self.find_class(b[-1], ci.module)
            if bc is not None:
                m = self._class_method(bc, name, seen)
                if m is not None:
                    return m
        return None

    def _unique_method(self, name: str) -> FunctionInfo | None:
        cands = self.methods_by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    # -- iteration helpers ----------------------------------------------------

    def all_functions(self):
        for mod in self.modules.values():
            stack = list(mod.functions.values())
            for ci in mod.classes.values():
                stack.extend(ci.methods.values())
            while stack:
                fi = stack.pop()
                yield fi
                stack.extend(fi.nested.values())
