"""Event-driven RGW HTTP frontend (rgw_asio_frontend.cc analog): one
I/O loop + bounded handler pool instead of thread-per-connection —
keep-alive reuse, many concurrent connections, pipelined requests
sequenced per connection, and protocol edge refusals."""

from __future__ import annotations

import http.client
import socket
import threading

import pytest

from ceph_tpu.rgw_frontend import AsyncHttpFrontend, CIMap
from ceph_tpu.rgw_rest import RgwRestServer
from ceph_tpu.tools.vstart import MiniCluster


def test_cimap_case_insensitive():
    m = CIMap([("Content-Length", "5"), ("X-Amz-Date", "d")])
    assert m.get("content-length") == "5"
    assert m.get("X-AMZ-DATE") == "d"
    assert "x-amz-date" in m
    m["content-LENGTH"] = "9"
    assert m.get("Content-Length") == "9"
    assert len(m) == 2          # replaced, not duplicated


def test_frontend_echo_keepalive_and_concurrency():
    seen = []

    def handler(req):
        seen.append(req.method)
        return 200, {"X-Echo": req.headers.get("X-Ping", "")}, req.body

    f = AsyncHttpFrontend(handler, "127.0.0.1:0", workers=4).start()
    try:
        host, port = f.addr.rsplit(":", 1)
        # keep-alive: three requests over ONE connection
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        for i in range(3):
            conn.request("POST", "/x", body=f"b{i}".encode(),
                         headers={"X-Ping": str(i)})
            r = conn.getresponse()
            assert r.status == 200
            assert r.getheader("X-Echo") == str(i)
            assert r.read() == f"b{i}".encode()
        conn.close()
        # concurrency: 16 parallel connections through 4 workers
        errs = []

        def one(i):
            try:
                c = http.client.HTTPConnection(host, int(port),
                                               timeout=20)
                c.request("PUT", "/y", body=b"z" * 10000)
                r = c.getresponse()
                assert r.status == 200 and r.read() == b"z" * 10000
                c.close()
            except Exception as e:   # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=one, args=(i,))
              for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        assert not errs, errs
        # chunked transfer-encoding refused (SigV4 clients send lengths)
        raw = socket.create_connection((host, int(port)), timeout=10)
        raw.sendall(b"PUT /c HTTP/1.1\r\nHost: x\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n")
        assert b" 501 " in raw.recv(4096)
        raw.close()
        # garbage request line refused, connection closed
        raw = socket.create_connection((host, int(port)), timeout=10)
        raw.sendall(b"NONSENSE\r\n\r\n")
        assert b" 400 " in raw.recv(4096)
        raw.close()
    finally:
        f.stop()


def test_s3_over_async_frontend_e2e():
    """The full S3 dialect rides the async frontend (already covered
    broadly by the rgw suites; this pins HEAD semantics + keep-alive
    through the real gateway)."""
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        srv = RgwRestServer(client.open_ioctx(pool),
                            max_skew=None).start()
        try:
            from test_rgw_versioning import S3Client
            srv.add_key("k", "s")
            s3 = S3Client(srv.addr, "k", "s")
            assert s3.request("PUT", "/fb")[0] == 200
            st, _b, _h = s3.request("PUT", "/fb/o", body=b"0123456789")
            assert st == 200
            # HEAD: status 200, no body, real length advertised
            st, body, hdrs = s3.request("HEAD", "/fb/o")
            assert st == 200 and body == b""
            st, body, _ = s3.request("GET", "/fb/o")
            assert st == 200 and body == b"0123456789"
        finally:
            srv.shutdown()
    finally:
        c.stop()
