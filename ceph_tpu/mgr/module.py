"""Mgr module framework (src/pybind/mgr/mgr_module.py:205-1003 +
src/mgr/ActivePyModules.cc:44-120, redesigned host-side).

The reference's mgr is a MODULE HOST: a stable Python API every module
programs against — cluster-state snapshots via ``get()``, persisted
per-module config, a mon command channel, command registration, and
change notifications.  This module keeps that contract with a leaner
activation model:

  * modules are plain classes registered by name (entry in
    ``ceph_tpu.mgr.modules``), loaded by the active mgr from the
    mon-persisted enabled set (``config-key mgr/modules``) plus the
    always-on set — so a PROMOTED STANDBY loads the same modules the
    failed active ran;
  * instead of one thread per module (the reference's ``serve()``
    loops), modules get ``tick(now)`` on the host's timer and
    ``notify(what)`` on state changes — the single-threaded shape suits
    the host and keeps module re-entry trivial on failover.  A module
    that genuinely needs a thread may still override ``serve()`` and
    the host runs it (prometheus does, for its HTTP listener);
  * module config/state persists through the mon (``config-key``),
    never on the mgr — the mgr is stateless by design, which is what
    makes failover a pure promotion.
"""

from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING

from ceph_tpu.common.logging import dout

if TYPE_CHECKING:   # pragma: no cover
    from ceph_tpu.mgr.daemon import MgrDaemon


class MgrModule:
    """Base class every mgr module subclasses (MgrModule analog).

    Subclasses set NAME, optionally COMMANDS (list of
    ``{"prefix": ..., "help": ...}`` dispatched to handle_command) and
    MODULE_OPTIONS (``{"name": ..., "default": ...}`` served by
    get_module_option).
    """

    NAME = ""
    COMMANDS: list[dict] = []
    MODULE_OPTIONS: list[dict] = []

    def __init__(self, mgr: "MgrDaemon"):
        self.mgr = mgr

    # -- cluster state (ActivePyModules::get_python) --------------------------

    def get(self, data_name: str):
        """Snapshot of one named cluster-state view (see
        MgrDaemon.get for the catalog)."""
        return self.mgr.get(data_name)

    def get_osdmap(self):
        return self.mgr.osdmap

    # -- persisted config (get_module_option / set_module_option) -------------

    def _opt_default(self, key: str):
        for o in self.MODULE_OPTIONS:
            if o["name"] == key:
                return o.get("default")
        return None

    def get_module_option(self, key: str, default=None):
        v = self.mgr.get_store(f"mgr/{self.NAME}/{key}")
        if v is None:
            v = self._opt_default(key)
        return default if v is None else v

    def set_module_option(self, key: str, value) -> None:
        self.mgr.set_store(f"mgr/{self.NAME}/{key}", value)

    # -- KV store (get_store/set_store → mon config-key) ----------------------

    def get_store(self, key: str, default=None):
        v = self.mgr.get_store(f"mgr/{self.NAME}/{key}")
        return default if v is None else v

    def set_store(self, key: str, value) -> None:
        self.mgr.set_store(f"mgr/{self.NAME}/{key}", value)

    # -- mon channel ----------------------------------------------------------

    def mon_command(self, cmd: dict) -> tuple[int, str]:
        return self.mgr.mon_cmd.cmd(cmd)

    def log(self, level: int, fmt: str, *args) -> None:
        dout(f"mgr.{self.NAME}", level, fmt, *args)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Activation hook (module just loaded on the ACTIVE mgr)."""

    def stop(self) -> None:
        """Deactivation hook (failover demotion / disable / shutdown)."""

    def serve(self) -> None:
        """Optional long-running loop; when overridden the host runs it
        in a daemon thread after start().  Must exit promptly once
        self.mgr.module_should_stop(self) turns True."""

    def tick(self, now: float) -> None:
        """Periodic work on the host timer (~5 s)."""

    def notify(self, what: str, ident=None) -> None:
        """State-change callback: what in {"osd_map", "pg_stats"}."""

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        return f"module {self.NAME} has no commands", -22


class ModuleHost:
    """Loads/unloads modules on the active mgr and fans out events
    (ActivePyModules reduced).  Owned by MgrDaemon; all entry points
    are host-thread-safe and swallow per-module exceptions so one
    broken module never takes the mgr down (the reference marks such
    modules failed in health; we dout and carry on)."""

    #: modules every active mgr runs regardless of the enabled set
    #: (MgrMap always_on_modules)
    ALWAYS_ON = ("balancer", "iostat", "telemetry", "insights", "slo")

    def __init__(self, mgr: "MgrDaemon"):
        self.mgr = mgr
        self.modules: dict[str, MgrModule] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._stopping: set[str] = set()
        #: name -> repr(error) for modules whose load failed — feeds the
        #: MGR_MODULE_ERROR health check (the reference marks such
        #: modules failed in health the same way)
        self.failed: dict[str, str] = {}
        # analysis: allow[bare-lock] -- module-host RLock, mgr-local; held across module callbacks by design
        self._lock = threading.RLock()

    # -- registry -------------------------------------------------------------

    @staticmethod
    def resolve(name: str) -> type[MgrModule]:
        import importlib
        mod = importlib.import_module(f"ceph_tpu.mgr.modules.{name}")
        cls = getattr(mod, "Module", None)
        if cls is None or not issubclass(cls, MgrModule):
            raise ImportError(
                f"module {name!r} exports no MgrModule 'Module' class")
        return cls

    @staticmethod
    def available() -> list[str]:
        import pkgutil

        import ceph_tpu.mgr.modules as pkg
        return sorted(m.name for m in pkgutil.iter_modules(pkg.__path__))

    def enabled_set(self) -> list[str]:
        """always-on + the mon-persisted enabled list."""
        extra = self.mgr.get_store("mgr/modules")
        names = list(self.ALWAYS_ON)
        if extra:
            try:
                for n in json.loads(extra):
                    if n not in names:
                        names.append(n)
            except (ValueError, TypeError):
                pass
        return names

    # -- activation -----------------------------------------------------------

    def start_all(self) -> None:
        for name in self.enabled_set():
            self.load(name)

    def load(self, name: str) -> bool:
        with self._lock:
            if getattr(self.mgr, "_stopped", False):
                # a worker resuming a queued activation after shutdown
                # must not bind sockets/threads the teardown will never
                # reap
                return False
            if name in self.modules:
                return True
            try:
                inst = self.resolve(name)(self.mgr)
                inst.NAME = name
                inst.start()
            except Exception as e:
                dout("mgr", 0, "module %s failed to load: %r", name, e)
                self.failed[name] = repr(e)
                return False
            self.failed.pop(name, None)
            self.modules[name] = inst
            self._stopping.discard(name)
            if type(inst).serve is not MgrModule.serve:
                t = threading.Thread(target=self._serve_wrap,
                                     args=(name, inst),
                                     name=f"mgr-{name}", daemon=True)
                self._threads[name] = t
                t.start()
            dout("mgr", 2, "module %s loaded", name)
            return True

    def _serve_wrap(self, name: str, inst: MgrModule) -> None:
        try:
            inst.serve()
        except Exception as e:   # pragma: no cover
            dout("mgr", 0, "module %s serve() died: %r", name, e)

    def unload(self, name: str) -> None:
        with self._lock:
            inst = self.modules.pop(name, None)
            self._stopping.add(name)
            # disabling a module is the remediation for a failed load:
            # clear its health record or MGR_MODULE_ERROR would pin the
            # cluster in HEALTH_ERR with no operator path out
            self.failed.pop(name, None)
            t = self._threads.pop(name, None)
        if inst is not None:
            try:
                inst.stop()
            except Exception:
                pass
        if t is not None:
            t.join(timeout=2.0)

    def stop_all(self) -> None:
        for name in list(self.modules):
            self.unload(name)

    def should_stop(self, inst: MgrModule) -> bool:
        return inst.NAME in self._stopping \
            or self.modules.get(inst.NAME) is not inst

    def failed_modules(self) -> dict[str, str]:
        """Modules whose load failed (health MGR_MODULE_ERROR feed)."""
        with self._lock:
            return dict(self.failed)

    # -- fan-out --------------------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        for name, inst in list(self.modules.items()):
            try:
                inst.tick(now)
            except Exception as e:
                dout("mgr", 0, "module %s tick failed: %r", name, e)

    def notify_all(self, what: str, ident=None) -> None:
        for name, inst in list(self.modules.items()):
            try:
                inst.notify(what, ident)
            except Exception as e:
                dout("mgr", 0, "module %s notify(%s) failed: %r",
                     name, what, e)

    def handle_command(self, cmd: dict) -> tuple[str, int] | None:
        """Route to the module whose registered prefix matches; None if
        no module claims it."""
        prefix = cmd.get("prefix", "")
        for name, inst in list(self.modules.items()):
            for c in inst.COMMANDS:
                if c["prefix"] == prefix:
                    return inst.handle_command(cmd)
        return None
