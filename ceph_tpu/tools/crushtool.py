"""crushtool analog (src/tools/crushtool.cc): compile / decompile /
inspect CRUSH maps.

    python -m ceph_tpu.tools.crushtool -c map.txt -o map.bin
    python -m ceph_tpu.tools.crushtool -d map.bin [-o map.txt]
    python -m ceph_tpu.tools.crushtool --tree map.bin
    python -m ceph_tpu.tools.crushtool --build --num-osds N \
        node straw2 <per-node> root straw2 0 -o map.bin

The binary format is our crush codec (map_codec.encode_crush) framed
with a JSON name-table section — the reference's binary likewise
carries type/name/rule name maps next to the algorithmic struct.

--test is served by ceph_tpu.tools.crush_test (crushtool --test's
flags live there); --build mirrors the reference's layered builder.
"""

from __future__ import annotations

import argparse
import json
import struct
import sys

from ceph_tpu.crush.text import (
    _ALG_IDS, CrushNames, compile_text, decompile, item_name, type_name)
from ceph_tpu.crush.types import CRUSH_BUCKET_UNIFORM
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.osd.map_codec import decode_crush, encode_crush

_MAGIC = b"CTPUCRSH"


def write_binary(path: str, m, names: CrushNames) -> None:
    e = Encoder()
    encode_crush(m, e)
    write_binary_blob(path, e.tobytes(), {
        "types": names.types, "items": names.items,
        "rules": names.rules, "classes": names.classes})


def write_binary_blob(path: str, blob: bytes, names_dict: dict) -> None:
    """Frame an already-encoded crush blob (as fetched from the mon)
    without a redundant decode/re-encode round."""
    names_dict = {"types": names_dict.get("types") or {},
                  "items": names_dict.get("items") or {},
                  "rules": names_dict.get("rules") or {},
                  "classes": names_dict.get("classes") or {}}
    nj = json.dumps(names_dict).encode()
    with open(path, "wb") as f:
        f.write(_MAGIC + struct.pack("<II", len(blob), len(nj))
                + blob + nj)


def read_binary(path: str):
    raw = open(path, "rb").read()
    if not raw.startswith(_MAGIC):
        raise SystemExit(f"{path}: not a crush map (bad magic)")
    bl, nl = struct.unpack_from("<II", raw, len(_MAGIC))
    off = len(_MAGIC) + 8
    m = decode_crush(Decoder(raw[off:off + bl]))
    nd = json.loads(raw[off + bl:off + bl + nl].decode())
    names = CrushNames(
        types={int(k): v for k, v in nd["types"].items()},
        items={int(k): v for k, v in nd["items"].items()},
        rules={int(k): v for k, v in nd["rules"].items()},
        classes={int(k): v for k, v in nd["classes"].items()})
    return m, names


def tree_lines(m, names: CrushNames) -> list[str]:
    """`crushtool --tree` / `ceph osd tree` rendering."""
    def iname(i):
        return item_name(names, i)

    def tname(t):
        return type_name(names, t)

    referenced = {it for b in m.buckets if b is not None
                  for it in b.items}
    roots = [b for b in m.buckets
             if b is not None and b.id not in referenced]
    out = ["ID\tWEIGHT\tTYPE NAME"]

    def walk(bid, depth):
        b = m.bucket(bid)
        if b is None:   # device
            out.append(f"{bid}\t-\t{'  ' * depth}{iname(bid)}")
            return
        out.append(f"{b.id}\t{b.weight / 0x10000:.5f}\t"
                   f"{'  ' * depth}{tname(b.type)} {iname(b.id)}")
        for k, it in enumerate(b.items):
            if it >= 0:
                w = (b.item_weight if b.alg == CRUSH_BUCKET_UNIFORM
                     else (b.item_weights[k]
                           if k < len(b.item_weights) else 0))
                out.append(f"{it}\t{w / 0x10000:.5f}\t"
                           f"{'  ' * (depth + 1)}{iname(it)}")
            else:
                walk(it, depth + 1)

    for r in roots:
        walk(r.id, 0)
    return out


def build_layered(num_osds: int, layers: list[tuple[str, str, int]]):
    """crushtool --build: stack layers bottom-up; size 0 means one
    bucket holding everything (crushtool.cc build mode)."""
    from ceph_tpu.crush.builder import add_simple_rule, make_bucket
    from ceph_tpu.crush.types import CrushMap
    m = CrushMap()
    names = CrushNames(types={0: "osd"})
    prev = list(range(num_osds))
    prev_w = [0x10000] * num_osds
    names.items.update({i: f"osd.{i}" for i in prev})
    tid = 0
    # a multi-bucket top layer would leave subtrees unreachable by the
    # generated rule: close the map with an implicit root over them
    if not layers or layers[-1][2] != 0:
        layers = list(layers) + [("root", "straw2", 0)]
    for tname, alg, size in layers:
        tid += 1
        names.types[tid] = tname
        group = len(prev) if size == 0 else size
        nxt, nxt_w = [], []
        for i in range(0, len(prev), group):
            items = prev[i:i + group]
            ws = prev_w[i:i + group]
            b = make_bucket(m.next_bucket_id(), _ALG_IDS[alg], tid,
                            items, ws)
            m.add_bucket(b)
            names.items[b.id] = f"{tname}{len(nxt)}"
            nxt.append(b.id)
            nxt_w.append(b.weight)
        prev, prev_w = nxt, nxt_w
    m.max_devices = num_osds
    rule = add_simple_rule(m, prev[0], tid - 1)
    names.rules[rule] = "replicated_rule"
    return m, names


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-c", "--compile", metavar="TXT")
    p.add_argument("-d", "--decompile", metavar="BIN")
    p.add_argument("--tree", metavar="BIN")
    p.add_argument("--build", action="store_true")
    p.add_argument("--num-osds", type=int, default=0)
    p.add_argument("-o", "--outfn")
    p.add_argument("layers", nargs="*",
                   help="--build: name alg size triples")
    a = p.parse_args(argv)
    if a.compile:
        m, names = compile_text(open(a.compile).read())
        write_binary(a.outfn or a.compile + ".bin", m, names)
        return 0
    if a.decompile:
        m, names = read_binary(a.decompile)
        text = decompile(m, names)
        if a.outfn:
            open(a.outfn, "w").write(text)
        else:
            sys.stdout.write(text)
        return 0
    if a.tree:
        m, names = read_binary(a.tree)
        print("\n".join(tree_lines(m, names)))
        return 0
    if a.build:
        if not a.num_osds or len(a.layers) % 3:
            p.error("--build needs --num-osds and name alg size triples")
        layers = [(a.layers[i], a.layers[i + 1], int(a.layers[i + 2]))
                  for i in range(0, len(a.layers), 3)]
        m, names = build_layered(a.num_osds, layers)
        write_binary(a.outfn or "crush.bin", m, names)
        return 0
    p.error("one of -c / -d / --tree / --build required")


if __name__ == "__main__":
    sys.exit(main())
