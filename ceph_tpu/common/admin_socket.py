"""Admin-socket introspection (src/common/admin_socket.h:41,71 analog).

Every daemon registers named commands ("perf dump", "config show",
"dump_ops_in_flight", ...) that return JSON.  The reference serves them over a
unix socket; here the registry is in-process with an optional unix-socket
server for the vstart-style harness, same command surface either way.
"""

from __future__ import annotations

import json
import os
import socket
import threading


class AdminSocket:
    def __init__(self, path: str | None = None):
        # analysis: allow[bare-lock] -- command-table leaf lock, held only around dict ops
        self._lock = threading.Lock()
        self._commands: dict[str, tuple] = {}
        self._path = path
        self._server: socket.socket | None = None
        self._thread: threading.Thread | None = None

    def register_command(self, command: str, handler,
                         help: str = "", aliases: tuple = ()) -> None:
        """handler(**kwargs) -> JSON-serializable (admin_socket.h:71).
        aliases register additional spellings of the same command; help
        output marks them as such instead of duplicating the text."""
        with self._lock:
            for name in (command, *aliases):
                if name in self._commands:
                    raise ValueError(
                        f"admin command {name!r} already registered")
            self._commands[command] = (handler, help)
            for alias in aliases:
                self._commands[alias] = (handler,
                                         f"alias for {command!r}")

    def unregister_command(self, command: str) -> None:
        with self._lock:
            self._commands.pop(command, None)

    def execute(self, command: str, **kwargs):
        with self._lock:
            entry = self._commands.get(command)
        if entry is None:
            if command == "help":
                with self._lock:
                    return {c: h for c, (_f, h) in sorted(self._commands.items())}
            raise KeyError(f"unknown admin command {command!r}")
        return entry[0](**kwargs)

    # -- unix-socket server (vstart harness surface) --------------------------

    def serve(self) -> str:
        """Start serving on the configured unix path; returns the path.
        Protocol: one JSON request {"prefix": cmd, ...args} per connection,
        one JSON reply (the `ceph daemon <name> <cmd>` shape)."""
        assert self._path, "AdminSocket built without a path"
        if os.path.exists(self._path):
            os.unlink(self._path)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self._path)
        srv.listen(8)
        self._server = srv

        def loop():
            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                with conn:
                    try:
                        req = json.loads(conn.recv(1 << 16).decode())
                        cmd = req.pop("prefix")
                        out = self.execute(cmd, **req)
                        conn.sendall(json.dumps(out).encode())
                    except Exception as e:  # reported to the caller, not fatal
                        conn.sendall(json.dumps({"error": str(e)}).encode())

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self._path

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._path and os.path.exists(self._path):
            os.unlink(self._path)


def admin_request(path: str, prefix: str, **kwargs):
    """Client side of the unix-socket protocol (`ceph daemon` analog)."""
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(path)
    c.sendall(json.dumps({"prefix": prefix, **kwargs}).encode())
    c.shutdown(socket.SHUT_WR)
    buf = b""
    while True:
        chunk = c.recv(1 << 16)
        if not chunk:
            break
        buf += chunk
    c.close()
    return json.loads(buf.decode())
