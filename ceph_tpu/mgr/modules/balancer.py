"""Balancer module (src/pybind/mgr/balancer/module.py analog, upmap
mode): plans mon upmap commands that flatten the per-OSD PG histogram
of the mgr's current osdmap."""

from __future__ import annotations

import json
import time

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "balancer"
    COMMANDS = [
        {"prefix": "balancer status",
         "help": "mode + last optimize outcome + pool spread scores"},
        {"prefix": "balancer optimize",
         "help": "plan upmap commands flattening the PG histogram"},
    ]

    def __init__(self, mgr):
        super().__init__(mgr)
        self._last: dict = {}

    def plan(self, **kw) -> list[dict]:
        from ceph_tpu.balancer import plan_commands
        cmds = plan_commands(self.get_osdmap(), **kw)
        self._last = {"time": time.time(), "commands": len(cmds),
                      "pool_spread": self._spread_scores()}
        return cmds

    def _spread_scores(self) -> dict:
        from ceph_tpu.balancer import spread
        m = self.get_osdmap()    # snapshot: dispatch may swap the map
        return {pid: dict(zip(("min", "max"), spread(m, pid)))
                for pid in list(m.pools)}

    def status(self) -> dict:
        return {"mode": "upmap", "active": True,
                "last_optimize": dict(self._last),
                "pool_spread": self._spread_scores()}

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        if cmd.get("prefix") == "balancer status":
            return json.dumps(self.status()), 0
        if cmd.get("prefix") == "balancer optimize":
            return json.dumps({"commands": self.plan()}), 0
        return f"unknown balancer command {cmd.get('prefix')!r}", -22
