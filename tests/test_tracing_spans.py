"""Span-structured tracing: hierarchy, wire extension v2, head
sampling + tail retention (exact counts), thread-safe OpTracker
timelines, MMgrReport v4, and the mgr insights/prometheus surface."""

from __future__ import annotations

import json
import threading
import time

import pytest

from ceph_tpu.common import tracing
from ceph_tpu.common.op_tracker import OpTracker


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset()
    yield
    tracing.reset()


# -- span model ---------------------------------------------------------------

def test_span_hierarchy_and_attrs():
    with tracing.trace_ctx(name="write", daemon="client.1") as tid:
        root_sid = tracing.current_span()
        with tracing.span("dispatch", daemon="osd.0", pool=3,
                          op_size=4096) as sp:
            assert sp.parent_span_id == root_sid
            with tracing.span("encode", daemon="osd.0") as inner:
                assert inner.parent_span_id == sp.span_id
            tracing.record("osd.0", "sub_op_commit")
    rows = tracing.dump(tid)
    spans = {r["span_id"]: r for r in rows if r["kind"] == "span"}
    assert len(spans) == 3
    roots = [r for r in spans.values() if not r["parent_span_id"]]
    assert len(roots) == 1 and roots[0]["event"] == "write"
    disp = next(r for r in spans.values() if r["event"] == "dispatch")
    assert disp["attrs"] == {"pool": 3, "op_size": 4096}
    assert disp["dur"] is not None and disp["dur"] >= 0
    # the point event attached to the span current when it fired
    ev = next(r for r in rows if r["kind"] == "event"
              and r["event"] == "sub_op_commit")
    assert ev["span_id"] == disp["span_id"]
    # nested tree view agrees
    tree = tracing.span_tree(tid)
    assert len(tree["spans"]) == 1
    top = tree["spans"][0]
    assert top["name"] == "write"
    assert [c["name"] for c in top["children"]] == ["dispatch"]
    assert [c["name"] for c in top["children"][0]["children"]] \
        == ["encode"]


def test_untraced_span_is_noop():
    assert tracing.current() == 0
    with tracing.span("nothing", daemon="x") as sp:
        assert sp is None
    assert tracing.trace_ids() == []


def test_frame_v2_span_extension_roundtrip():
    from ceph_tpu.messages import MOSDOp
    from ceph_tpu.msg.message import Message

    m = MOSDOp(client_id=7, tid=1, oid="spanned")
    m.trace_id = 0xBEEF
    m.parent_span_id = 0xCAFE
    back = Message.decode(m.encode())
    assert back.trace_id == 0xBEEF
    assert back.parent_span_id == 0xCAFE
    # no parent -> v1 bare-u64 extension (8 bytes shorter), old layout
    v1 = MOSDOp(client_id=7, tid=1, oid="spanned")
    v1.trace_id = 0xBEEF
    assert len(v1.encode()) == len(m.encode()) - 8
    b1 = Message.decode(v1.encode())
    assert b1.trace_id == 0xBEEF and b1.parent_span_id == 0
    # untraced stays byte-identical to the pre-tracing format
    plain = MOSDOp(client_id=7, tid=1, oid="spanned")
    assert Message.decode(plain.encode()).trace_id == 0


# -- sampling policy ----------------------------------------------------------

def test_head_sampling_exact_counts():
    tracing.set_sample_rate(0.0)
    for _ in range(20):
        with tracing.maybe_sampled("op", "client.9") as tid:
            assert tid == 0
    assert tracing.trace_ids() == []
    tracing.set_sample_rate(1.0)
    for _ in range(5):
        with tracing.maybe_sampled("op", "client.9") as tid:
            assert tid != 0
    assert len(tracing.trace_ids()) == 5
    # joining an explicit trace never opens a second one
    with tracing.trace_ctx() as outer:
        with tracing.maybe_sampled("op", "client.9") as tid:
            assert tid == outer
    assert len(tracing.trace_ids()) == 6


def test_tail_retention_slow_survives_fast_dropped():
    tracing.set_slow_threshold(0.05)
    tracing.set_active_cap(8)
    slow_ids = []
    for _ in range(2):
        with tracing.trace_ctx(name="slow write", daemon="t") as tid:
            time.sleep(0.06)
            slow_ids.append(tid)
    fast_ids = []
    for _ in range(32):
        with tracing.trace_ctx(name="fast", daemon="t") as tid:
            fast_ids.append(tid)
    # EXACTLY the slow traces were promoted, in completion order
    ring = tracing.slow_traces()
    assert [s["trace_id"] for s in ring] == slow_ids
    assert all(s["duration"] >= 0.05 and s["root"] == "slow write"
               for s in ring)
    # fast traces aged out of the bounded active table
    remaining = set(tracing.trace_ids())
    assert set(slow_ids) <= remaining
    assert sum(1 for t in fast_ids if t in remaining) <= 8
    # an evicted slow trace still renders (served from the ring)
    assert tracing.dump(slow_ids[0]), "slow trace lost its rows"
    # the ring itself is bounded
    tracing.set_slow_ring(1)
    assert [s["trace_id"] for s in tracing.slow_traces()] \
        == [slow_ids[1]]
    s = tracing.slow_summary()
    assert s["count"] == 1 and s["p99_root_ms"] >= 50


def test_evicted_slow_trace_not_shadowed_by_stragglers():
    """A straggler event after promotion+eviction must not resurrect
    an empty ghost that shadows the archived snapshot; the unfiltered
    dump keeps showing ring-only traces."""
    tracing.set_slow_threshold(0.0)
    tracing.set_active_cap(4)
    with tracing.trace_ctx(name="archived", daemon="t") as slow_tid:
        tracing.record("t", "real work")
    for _ in range(16):   # push the archived trace out of the table
        with tracing.trace_ctx(name="churn", daemon="t"):
            pass
    full = tracing.dump(slow_tid)
    assert any(r["event"] == "real work" for r in full)
    # straggler from a thread that still holds the id
    tracing.record("t", "late straggler", trace_id=slow_tid)
    after = tracing.dump(slow_tid)
    assert after == full, "ghost trace shadowed the archived snapshot"
    # the unfiltered view includes ring-only traces too
    assert any(r["trace_id"] == slow_tid for r in tracing.dump())


def test_root_attached_events_render_in_tree():
    with tracing.trace_ctx(name="rooted", daemon="t") as tid:
        pass
    # an event recorded OFF-THREAD (explicit trace id, current() != tid)
    # attaches to the trace root rather than vanishing from the tree
    assert tracing.current() == 0
    tracing.record("other", "off-thread", trace_id=tid)
    tree = tracing.span_tree(tid)
    all_events = []

    def walk(n):
        all_events.extend(e["event"] for e in n["events"])
        for ch in n["children"]:
            walk(ch)
    for root in tree["spans"]:
        walk(root)
    assert "off-thread" in all_events, tree


def test_inflight_trace_survives_churn_and_promotes():
    """Eviction under head-sampling load must prefer COMPLETED traces:
    an in-flight trace may still turn out slow, and dropping it would
    defeat tail retention exactly when it matters."""
    tracing.set_slow_threshold(0.05)
    tracing.set_active_cap(8)
    with tracing.trace_ctx(name="inflight slow", daemon="t") as slow_tid:
        time.sleep(0.06)
        for _ in range(64):   # way past the cap while we're open
            with tracing.trace_ctx(name="churn", daemon="t"):
                pass
    assert any(s["trace_id"] == slow_tid
               for s in tracing.slow_traces()), \
        "in-flight slow trace was evicted before completion"


def test_sampling_knobs_are_config_options():
    from ceph_tpu.common.context import CephTpuContext
    ctx = CephTpuContext("client.sampling")
    ctx.conf.set("tracing_sample_rate", "1.0")
    with tracing.maybe_sampled("op", "c") as tid:
        assert tid != 0
    ctx.conf.set("tracing_sample_rate", "0.0")
    with tracing.maybe_sampled("op", "c") as tid:
        assert tid == 0
    ctx.conf.set("tracing_slow_threshold", "0.0")
    with tracing.trace_ctx(name="instant", daemon="c"):
        pass
    assert any(s["root"] == "instant" for s in tracing.slow_traces())


# -- satellite: OpTracker event-list thread safety ----------------------------

def test_tracked_op_events_thread_safe():
    trk = OpTracker(complaint_time=0.001, history_slow_threshold=0.0)
    op = trk.create_request("hammered op")
    errs: list[Exception] = []
    stop = threading.Event()

    def writer():
        try:
            while not stop.is_set():
                op.mark_event("tick")
        except Exception as e:   # pragma: no cover
            errs.append(e)

    def reader():
        try:
            while not stop.is_set():
                d = op.dump()
                evs = d["type_data"]["events"]
                assert evs[0]["event"] == "initiated"
                trk.dump_ops_in_flight()
                trk.check_ops_in_flight()
        except Exception as e:   # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=writer),
               threading.Thread(target=reader),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errs, errs[0]
    op.finish()
    assert trk.slow_digests()
    d = trk.slow_digests()[0]
    assert d["description"] == "hammered op"
    assert d["last_event"] == "done"


# -- satellite: admin-socket consolidation ------------------------------------

def test_dump_tracing_alias_and_payload():
    from ceph_tpu.common.context import CephTpuContext
    ctx = CephTpuContext("osd.42")
    with tracing.trace_ctx(name="aliased", daemon="osd.42") as tid:
        tracing.record("osd.42", "probe")
    a = ctx.admin.execute("dump_tracing", trace_id=str(tid))
    b = ctx.admin.execute("dump_traces", trace_id=str(tid))
    assert a == b and a, "alias must serve the identical payload"
    assert all("span_id" in r for r in a), "span-structured rows"
    helps = ctx.admin.execute("help")
    assert "span-structured" in helps["dump_tracing"]
    assert helps["dump_traces"] == "alias for 'dump_tracing'"


# -- MMgrReport v4 ------------------------------------------------------------

def test_mgr_report_v4_roundtrip_and_defaults():
    from ceph_tpu.mgr import MMgrReport
    from ceph_tpu.msg.message import Message

    digest = [{"trace_id": 7, "root": "write", "daemon": "osd.0",
               "duration": 1.25, "completed_at": 123.0, "n_spans": 4,
               "rows": [{"trace_id": 7, "daemon": "osd.0",
                         "event": "write", "t": 121.75, "kind": "span",
                         "span_id": 9, "parent_span_id": 0,
                         "dur": 1.25}]}]
    ops = [{"daemon": "osd.0", "description": "osd_op(...)",
            "initiated_at": 120.0, "duration": 2.0,
            "last_event": "done"}]
    rep = MMgrReport(osd_id=3, counters={"op_w": 5},
                     slow_traces=digest, slow_ops=ops)
    back = Message.decode(rep.encode())
    assert back.osd_id == 3
    assert back.slow_traces == digest
    assert back.slow_ops == ops
    # a report without the tail decodes to empty defaults
    bare = Message.decode(MMgrReport(osd_id=1).encode())
    assert bare.slow_traces == [] and bare.slow_ops == []


# -- mgr health severities ----------------------------------------------------

def _bare_mgr():
    from ceph_tpu.mgr import MgrDaemon
    return MgrDaemon(mon_addr="", ms_type="loopback")


def test_mgr_health_err_on_majority_down_and_failed_module():
    mgr = _bare_mgr()
    m = mgr.osdmap
    m.set_max_osd(4)
    for o in range(4):
        m.mark_up(o)
    assert mgr.health()["status"] == "HEALTH_OK"
    m.mark_down(3)
    h = mgr.health()
    assert h["status"] == "HEALTH_WARN"
    osd_down = next(c for c in h["checks"] if c["check"] == "OSD_DOWN")
    assert osd_down["severity"] == "warn" and osd_down["osds"] == [3]
    m.mark_down(2)   # exactly half down is still WARN (strict majority)
    assert mgr.health()["status"] == "HEALTH_WARN"
    m.mark_down(1)   # 3 of 4: the majority is down
    h = mgr.health()
    assert h["status"] == "HEALTH_ERR"
    assert next(c for c in h["checks"]
                if c["check"] == "OSD_DOWN")["severity"] == "error"
    for o in (1, 2, 3):
        m.mark_up(o)
    mgr.host.failed["badmod"] = "ImportError('nope')"
    h = mgr.health()
    assert h["status"] == "HEALTH_ERR"
    assert next(c for c in h["checks"]
                if c["check"] == "MGR_MODULE_ERROR")["modules"] \
        == {"badmod": "ImportError('nope')"}
    # disabling the broken module is the remediation: unload clears
    # the record, health returns to OK
    mgr.host.unload("badmod")
    assert mgr.health()["status"] == "HEALTH_OK"


def test_prometheus_health_value_mapping():
    from ceph_tpu.mgr.modules.prometheus import Module
    assert Module.HEALTH_VALUES == {"HEALTH_OK": 0, "HEALTH_WARN": 1,
                                    "HEALTH_ERR": 2}


# -- cluster-wide aggregation through the mgr ---------------------------------

def test_insights_module_aggregates_slow_traces_and_ops():
    from ceph_tpu.tools.vstart import MiniCluster

    tracing.set_slow_threshold(0.0)   # every completed trace retained
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.run_mgr()
        for oid in list(c.osds):       # osds re-report to the mgr
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(3)
        for d in c.osds.values():      # every completed op is "slow"
            d.op_tracker.history_slow_threshold = 0.0
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=1, size=3)
        io = client.open_ioctx(pool)
        io.write_full("warm", b"w" * 512)
        with tracing.trace_ctx(name="traced write",
                               daemon="client") as tid:
            io.write_full("slow-traced", b"S" * 4096)

        deadline = time.time() + 20
        mgr = c.mgr
        while time.time() < deadline:
            feed = mgr.insights_feed()
            if feed and any(e["slow_traces"] for e in feed.values()) \
                    and any(e["slow_ops"] for e in feed.values()):
                break
            time.sleep(0.2)

        out, rc = mgr._handle_command({"prefix": "tracing ls"})
        assert rc == 0, out
        ls = json.loads(out)["traces"]
        assert any(tr["trace_id"] == tid for tr in ls), ls
        out, rc = mgr._handle_command({"prefix": "tracing show",
                                       "trace_id": str(tid)})
        assert rc == 0, out
        shown = json.loads(out)
        assert shown["trace_id"] == tid
        names = set()

        def walk(nodes):
            for n in nodes:
                names.add(n["name"])
                walk(n["children"])
        walk(shown["tree"])
        assert "traced write" in names
        assert any(n.startswith("rx MOSDOp") for n in names), names
        out, rc = mgr._handle_command({"prefix": "slow_ops"})
        assert rc == 0, out
        ops = json.loads(out)["ops"]
        assert ops and all("duration" in o and "daemon" in o
                           for o in ops)
        # an unknown trace id is refused, not crashed on
        _out, rc = mgr._handle_command({"prefix": "tracing show",
                                        "trace_id": "12345"})
        assert rc == -2
        # prometheus exports the per-daemon slow-op counts
        body = mgr.prometheus_text()
        assert "ceph_daemon_slow_ops{" in body
        assert "ceph_daemon_slow_traces{" in body
    finally:
        c.stop()


# -- bench digest -------------------------------------------------------------

def test_slow_summary_shape():
    tracing.set_slow_threshold(0.0)
    with tracing.trace_ctx(name="b", daemon="bench"):
        time.sleep(0.01)
    s = tracing.slow_summary()
    assert s["count"] == 1
    assert s["p99_root_ms"] >= 10
