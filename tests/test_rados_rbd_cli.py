"""`rados` / `rbd` CLI tools (src/tools/rados, src/tools/rbd analogs)
and the PGLS op behind `rados ls` (librados nobjects iteration: one
pg-targeted op per PG, clone/shard store names reduced to client
names)."""

from __future__ import annotations

import io as _io
import json
import sys

import pytest

from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=4, ms_type="loopback").start()
    c.wait_for_osd_count(4)
    yield c
    c.stop()


def test_pgls_lists_logical_objects(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    io = client.open_ioctx(pool)
    names = {f"obj-{i:02d}" for i in range(17)}
    for n in names:
        io.write_full(n, b"payload")
    assert set(io.list_objects()) == names
    # snap CLONES stay hidden: overwrite after a pool snapshot
    rc, out = client.mon_command({"prefix": "osd pool mksnap",
                                  "pool": pool, "snap": "s1"})
    assert rc == 0
    client.wait_for_epoch(json.loads(out)["epoch"])
    io.write_full("obj-00", b"rewritten")
    assert set(io.list_objects()) == names
    io.remove("obj-16")
    assert "obj-16" not in set(io.list_objects())


def test_pgls_on_ec_pool_strips_shards(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=2, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    for i in range(5):
        io.write_full(f"ec-{i}", bytes(range(256)) * 16)
    assert set(io.list_objects()) == {f"ec-{i}" for i in range(5)}


def test_rados_cli_roundtrip(cluster, tmp_path):
    from ceph_tpu.tools import rados_cli
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=2, size=2)
    src = tmp_path / "in.bin"
    src.write_bytes(b"cli-payload" * 100)
    base = ["--mon", cluster.mon_host, "-p", str(pool),
            "--ms-type", "loopback"]
    assert rados_cli.main(base + ["put", "o1", str(src)]) == 0
    dst = tmp_path / "out.bin"
    assert rados_cli.main(base + ["get", "o1", str(dst)]) == 0
    assert dst.read_bytes() == src.read_bytes()
    out = _io.StringIO()
    real = sys.stdout
    sys.stdout = out
    try:
        assert rados_cli.main(base + ["ls"]) == 0
        assert rados_cli.main(base + ["stat", "o1"]) == 0
    finally:
        sys.stdout = real
    assert "o1" in out.getvalue()
    assert f"size {len(src.read_bytes())}" in out.getvalue()
    assert rados_cli.main(base + ["rm", "o1"]) == 0
    assert rados_cli.main(base + ["stat", "o1"]) == 1   # gone


def test_rbd_cli_lifecycle(cluster, tmp_path):
    from ceph_tpu.tools import rbd_cli
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=2, size=2)
    base = ["--mon", cluster.mon_host, "-p", str(pool),
            "--ms-type", "loopback"]
    MiB = 1 << 20
    assert rbd_cli.main(base + ["create", "vm0", "--size",
                                str(4 * MiB), "--order", "20"]) == 0
    # write through the library, manage through the CLI
    from ceph_tpu.rbd import Image
    io = client.open_ioctx(pool)
    img = Image(io, "vm0")
    img.write(b"golden" * 1000, 0)
    out = _io.StringIO()
    real = sys.stdout
    sys.stdout = out
    try:
        assert rbd_cli.main(base + ["ls"]) == 0
        assert rbd_cli.main(base + ["info", "vm0"]) == 0
        assert rbd_cli.main(base + ["snap", "create", "vm0@base"]) == 0
        assert rbd_cli.main(base + ["snap", "protect",
                                    "vm0@base"]) == 0
        assert rbd_cli.main(base + ["clone", "vm0@base",
                                    "vm1"]) == 0
        assert rbd_cli.main(base + ["children", "vm0@base"]) == 0
        assert rbd_cli.main(base + ["snap", "ls", "vm0"]) == 0
    finally:
        sys.stdout = real
    text = out.getvalue()
    assert "vm0" in text and "vm1" in text
    assert "protected" in text
    # the CLI-made clone reads the parent's bytes
    assert Image(io, "vm1").read(0, 6) == b"golden"
    # rollback via the CLI restores the snapshot's content
    img.write(b"SCRIBBLED-OVER", 0)
    assert rbd_cli.main(base + ["snap", "rollback", "vm0@base"]) == 0
    assert Image(io, "vm0").read(0, 6) == b"golden"
    # flatten + unprotect + rm via the CLI
    out2 = _io.StringIO()
    sys.stdout = out2
    try:
        assert rbd_cli.main(base + ["flatten", "vm1"]) == 0
        assert rbd_cli.main(base + ["snap", "unprotect",
                                    "vm0@base"]) == 0
        assert rbd_cli.main(base + ["snap", "rm", "vm0@base"]) == 0
        assert rbd_cli.main(base + ["rm", "vm1"]) == 0
        # export round-trips the image bytes
        dump = tmp_path / "vm0.img"
        assert rbd_cli.main(base + ["export", "vm0",
                                    str(dump)]) == 0
    finally:
        sys.stdout = real
    assert dump.read_bytes()[:6] == b"golden"
