"""Batched GF(2^8) erasure-code kernels.

The reference's hot loop is ``ec_encode_data(blocksize, k, m, tbls, data, coding)``
(ISA-L, called from src/erasure-code/isa/ErasureCodeIsa.cc:118-130) — a GF(2^8)
matrix-vector product applied independently to every byte column of a stripe, which the
OSD invokes per 4-64 KiB stripe in a loop (src/osd/ECUtil.cc:120-159).  Here that whole
loop is one batched device call.

TPU-first design (not a translation).  GF(2^8) multiplication by a constant is linear
over GF(2) in the bits of the input, so the coding matrix becomes a 0/1 matrix W of
shape (k*8, m*8) (ceph_tpu.gf.tables.bit_matrix) and encoding is

    parity_bits = bits(data) @ W   (mod 2)

an integer matrix multiply on the MXU whose ``& 1`` epilogue is the XOR reduction.
Two executors share that formulation:

* **Fused Pallas kernel** (TPU): per grid step, a block of stripes is loaded to VMEM,
  bit-expanded on sublanes, lane-split into G=4 groups stacked on the contraction
  axis, and multiplied against a block-diagonal W (G*k*8, G*m*8) int8 operand.  The
  block-diagonal packing is the core trick: a plain (k*8, m*8) matmul uses m*8 = 32 of
  the MXU's 128 output lanes (1/8 utilization — the measured ceiling of the previous
  nibble one-hot kernel); four independent lane-groups sharing one matmul fill all 128.
  Expansion, matmul and bit-pack all stay VMEM-resident — no HBM intermediates.
  Measured (v5e-1, k=8 m=4, 4 KiB chunks, batch 2048): ~2.8 TB/s KERNEL time
  (device-resident, jit-warm, sb=16); the repo bench's ~70 GB/s headline is the
  CHAINED end-to-end rate through the remote-dispatch tunnel, whose ~0.9 ms
  per-step latency dominates — on directly-attached chips the kernel number is
  the ceiling that matters.

* **XLA path** (any backend; also the CPU-mesh test fallback): the same bits @ W
  product tiled with lax.map so the 8x bit expansion stays in VMEM-scale working sets.

Decode is the same kernel with a host-side inverted sub-matrix (tiny, k x k), exactly
mirroring the reference's decode structure (ErasureCodeIsa.cc:150-310).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.gf.tables import bit_matrix, mul_table
from ceph_tpu.ops import telemetry


# ---------------------------------------------------------------------------
# numpy oracle — ground truth for bit-exactness tests and the CPU plugin
# ---------------------------------------------------------------------------

def ec_encode_ref(coeff: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Reference GF(2^8) encode on host.

    coeff : (m, k) uint8 coding matrix
    data  : (..., k, B) uint8 data chunks
    returns (..., m, B) uint8 parity chunks
    """
    # analysis: allow[blocking] -- host oracle: inputs are host numpy by contract (fallback/verification path)
    coeff = np.asarray(coeff, dtype=np.uint8)
    # analysis: allow[blocking] -- host oracle: inputs are host numpy by contract (fallback/verification path)
    data = np.asarray(data, dtype=np.uint8)
    mt = mul_table()
    # prods[..., i, j, b] = coeff[i, j] * data[..., j, b]
    prods = mt[coeff[..., :, :, None], data[..., None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=-2)


def ec_decode_ref(tables: np.ndarray, pidx: np.ndarray,
                  data: np.ndarray) -> np.ndarray:
    """Reference heterogeneous-matrix decode on host.

    tables : (P, t, k) uint8 stacked recovery matrices
    pidx   : (S,) integer pattern index per stripe
    data   : (S, k, B) uint8 surviving chunks
    returns (S, t, B) uint8 — stripe i rebuilt with tables[pidx[i]]
    """
    tables = np.asarray(tables, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    mats = tables[np.asarray(pidx)]            # (S, t, k)
    mt = mul_table()
    prods = mt[mats[:, :, :, None], data[:, None, :, :]]  # (S, t, k, B)
    return np.bitwise_xor.reduce(prods, axis=2)


# ---------------------------------------------------------------------------
# shared table prep
# ---------------------------------------------------------------------------

_BITW = np.arange(8, dtype=np.int32)

#: lane groups sharing one block-diagonal matmul in the Pallas kernel (fills
#: the 128 MXU output lanes at m*8 = 32 outputs per group)
_G = 4

#: stripes per Pallas grid step (amortizes per-step pipeline overhead;
#: measured on v5e at the bench shape (k=8,m=4,4KiB,batch=2048):
#: sb=8 -> 1.89 TB/s, sb=16 -> 2.84 TB/s kernel time, sb=32 regresses
#: (VMEM pressure); g sweeps {2,8,16} all lose to 4)
_SB = 16

#: byte-rows per XLA-path tile.  The bit expansion is k*8 int8 per source
#: byte; tiling keeps it in VMEM-scale working sets while the batch streams
#: (an untiled call materializes the expansion in HBM and halves throughput).
_TILE_ROWS = 1 << 17


def _blockdiag(wb: np.ndarray, g: int) -> np.ndarray:
    """Block-diagonal stack of g copies of the (k*8, m*8) bit matrix."""
    r, c = wb.shape
    out = np.zeros((g * r, g * c), dtype=np.int8)
    for i in range(g):
        out[i * r:(i + 1) * r, i * c:(i + 1) * c] = wb
    return out


# ---------------------------------------------------------------------------
# XLA executor (any backend)
# ---------------------------------------------------------------------------

def _xla_tile(w_bits: jax.Array, x: jax.Array, k: int, m: int,
              dot_dtype) -> jax.Array:
    """x: (T, k) uint8 byte rows -> (T, m) uint8 parity bytes."""
    t = x.shape[0]
    bits = ((x[:, :, None].astype(jnp.int32) >> _BITW) & 1)
    bits = bits.reshape(t, k * 8).astype(dot_dtype)
    acc = jax.lax.dot_general(
        bits, w_bits.astype(dot_dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32 if dot_dtype == jnp.bfloat16 else jnp.int32,
    )
    pb = acc.astype(jnp.int32) & 1  # (T, m*8)
    return jnp.sum(pb.reshape(t, m, 8) << _BITW, axis=-1,
                   dtype=jnp.int32).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "dot_dtype"))
def _encode_xla(w_bits: jax.Array, data: jax.Array, *, k: int, m: int,
                dot_dtype=jnp.int8) -> jax.Array:
    """data: (S, k, B) uint8 -> parity (S, m, B) uint8 via tiled bits @ W."""
    s, _, b = data.shape
    x = jnp.transpose(data, (0, 2, 1)).reshape(s * b, k)  # (SB, k)
    rows = s * b
    if rows <= _TILE_ROWS:
        packed = _xla_tile(w_bits, x, k, m, dot_dtype)
    else:
        pad = (-rows) % _TILE_ROWS
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad, k), dtype=x.dtype)])
        tiles = x.reshape(-1, _TILE_ROWS, k)
        packed = jax.lax.map(
            lambda xt: _xla_tile(w_bits, xt, k, m, dot_dtype), tiles
        ).reshape(-1, m)[:rows]
    return jnp.transpose(packed.reshape(s, b, m), (0, 2, 1)).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# heterogeneous-matrix batched decode (XLA, any backend)
# ---------------------------------------------------------------------------
#
# Encode coalesces trivially: every stripe multiplies the SAME coding
# matrix, so concurrent ops stack on the batch axis of one matmul.
# Decode could not — the recovery matrix depends on WHICH chunks
# survived, so each erasure pattern used to be its own device call
# (and its own jit entry).  Here the per-pattern bit matrices live
# stacked in one (P, k*8, t*8) table operand; each stripe carries a
# pattern index, the matrix is gathered on-device, and the product is
# one batched dot_general over all stripes of all patterns: the MXU
# sees a single (S, B, k8) x (S, k8, t8) batched matmul regardless of
# how many distinct erasure patterns the batch mixes.  The jit cache
# is bounded by buckets on BOTH data axes: the dispatch engine pow-2
# buckets the stripe axis, the codec pow-2 pads the table axis, and t
# is padded to a per-codec constant (zero matrix rows decode to zero
# rows, sliced off by the submitter).

#: stripes per decode tile: bounds the (ts, B, k*8) bit-expansion and
#: the gathered (ts, k*8, t*8) matrix stack to VMEM-scale working sets
#: while the batch streams through lax.map
_DEC_TILE_S = 256


def _decode_tile(w_tab: jax.Array, pidx: jax.Array, x: jax.Array,
                 k: int, t: int, dot_dtype) -> jax.Array:
    """x: (TS, k, B) uint8, pidx: (TS,) int32 -> (TS, t, B) uint8."""
    ts, _, b = x.shape
    bits = ((x[:, :, :, None].astype(jnp.int32) >> _BITW) & 1)  # (TS,k,B,8)
    bits = jnp.transpose(bits, (0, 2, 1, 3)).reshape(ts, b, k * 8)
    w = w_tab[pidx].astype(dot_dtype)                  # (TS, k8, t8) gather
    acc = jax.lax.dot_general(
        bits.astype(dot_dtype), w,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32 if dot_dtype == jnp.bfloat16
        else jnp.int32,
    )
    pb = acc.astype(jnp.int32) & 1                     # (TS, B, t*8)
    out = jnp.sum(pb.reshape(ts, b, t, 8) << _BITW, axis=-1,
                  dtype=jnp.int32)
    return jnp.transpose(out, (0, 2, 1)).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "t", "dot_dtype"))
def _decode_xla(w_tab: jax.Array, pidx: jax.Array, data: jax.Array, *,
                k: int, t: int, dot_dtype=jnp.int8) -> jax.Array:
    """data: (S, k, B) uint8 + per-stripe pattern index -> (S, t, B)."""
    s = data.shape[0]
    if s <= _DEC_TILE_S:
        return _decode_tile(w_tab, pidx, data, k, t, dot_dtype)
    pad = (-s) % _DEC_TILE_S
    if pad:
        data = jnp.concatenate(
            [data, jnp.zeros((pad,) + data.shape[1:], dtype=data.dtype)])
        pidx = jnp.concatenate(
            [pidx, jnp.zeros((pad,), dtype=pidx.dtype)])
    tiles = (data.reshape(-1, _DEC_TILE_S, *data.shape[1:]),
             pidx.reshape(-1, _DEC_TILE_S))
    out = jax.lax.map(
        lambda xp: _decode_tile(w_tab, xp[1], xp[0], k, t, dot_dtype),
        tiles)
    return out.reshape(-1, t, out.shape[-1])[:s]


def _decode_jit_entries() -> int:
    """Compile-cache entry count for the batched decode entry point
    (kept separate from _jit_entries so encode-side retrace accounting
    is untouched)."""
    return _decode_xla._cache_size()


def ec_decode_batched(tables_bits: np.ndarray, pidx, data, *,
                      k: int, t: int, dot_dtype=jnp.int8) -> jax.Array:
    """Heterogeneous-matrix batched decode: one device call for stripes
    spanning MIXED erasure patterns.

    tables_bits : (P, k*8, t*8) int8 — stacked bit matrices
                  (decode_bit_table), P power-of-two padded by the
                  caller so the jit cache stays bounded by the table
                  bucket, not the pattern population
    pidx        : (S,) int — pattern index per stripe
    data        : (S, k, B) uint8 surviving chunks
    returns (S, t, B) uint8 (padded target rows are zeros).
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    pidx = jnp.asarray(pidx, dtype=jnp.int32)
    tables_bits = jnp.asarray(tables_bits, dtype=jnp.int8)
    s, _, b = data.shape
    return telemetry.timed_kernel(
        "ec_decode",
        lambda: _decode_xla(tables_bits, pidx, data, k=k, t=t,
                            dot_dtype=dot_dtype),
        # the table operand is device-resident across calls (the codec
        # caches its device_put per snapshot), so only the per-call
        # operands count as h2d traffic
        batch=s, bytes_in=s * k * b + pidx.nbytes,
        bytes_out=s * t * b,
        cache_entries=_decode_jit_entries,
        signature=("ec_decode", k, t, s, b, tables_bits.shape[0],
                   str(dot_dtype)))


def decode_bit_table(mats) -> np.ndarray:
    """Stack per-pattern recovery matrices into the kernel's table
    operand: [(t, k) uint8, ...] -> (len(mats), k*8, t*8) int8."""
    return np.stack([bit_matrix(np.asarray(m, dtype=np.uint8))
                     for m in mats])


# ---------------------------------------------------------------------------
# fused Pallas executor (TPU)
# ---------------------------------------------------------------------------

def _expand_bits(d: jax.Array, k: int) -> jax.Array:
    """(k, B) uint8 -> (k*8, B) int8 bit planes: row j*8+t = bit t of chunk j."""
    d32 = d.astype(jnp.int32)
    rep = jnp.repeat(d32, 8, axis=0)
    shifts = jnp.tile(jnp.arange(8, dtype=jnp.int32), k)[:, None]
    return ((rep >> shifts) & 1).astype(jnp.int8)


def _pallas_kernel(d_ref, w_ref, out_ref, *, k, m, g, bc, sb):
    """One grid step: (sb, k, bc) uint8 -> (sb, m, bc) uint8 parity."""
    bg = bc // g
    outs = []
    for s in range(sb):
        bits = _expand_bits(d_ref[s], k)                     # (k8, bc) int8
        bits4 = jnp.concatenate(
            [bits[:, i * bg:(i + 1) * bg] for i in range(g)], axis=0)
        acc = jax.lax.dot_general(
            w_ref[...].T, bits4, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)                # (g*m8, bg)
        pb = (acc.astype(jnp.int32) & 1).reshape(g, m, 8, bg)
        bw = jnp.arange(8, dtype=jnp.int32)[None, None, :, None]
        packed = jnp.sum(pb << bw, axis=2, dtype=jnp.int32)  # (g, m, bg)
        outs.append(jnp.concatenate([packed[i] for i in range(g)], axis=1))
    out_ref[...] = jnp.stack(outs).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("k", "m", "bc", "interpret"))
def _encode_pallas(w_blk: jax.Array, data: jax.Array, *, k: int, m: int,
                   bc: int, interpret: bool = False) -> jax.Array:
    """data: (S, k, B) uint8 with S % _SB == 0 and B % bc == 0."""
    s, _, b = data.shape
    z = np.int32(0)  # concrete + 32-bit: neither a captured tracer under an
    return pl.pallas_call(  # outer jit nor an i64 index under x64
        functools.partial(_pallas_kernel, k=k, m=m, g=_G, bc=bc, sb=_SB),
        grid=(s // _SB, b // bc),
        in_specs=[
            pl.BlockSpec((_SB, k, bc), lambda i, j: (i, z, j)),
            pl.BlockSpec(w_blk.shape, lambda i, j: (z, z),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_SB, m, bc), lambda i, j: (i, z, j)),
        out_shape=jax.ShapeDtypeStruct((s, m, b), jnp.uint8),
        interpret=interpret,
    )(data, w_blk)


def _pick_bc(b: int) -> int | None:
    """Lane-block width for the Pallas kernel: a divisor of B that is a
    multiple of _G * 128 (each lane group needs >= one full vreg) and small
    enough that per-stripe VMEM temporaries stay modest."""
    for c in (4096, 2048, 1024, 512):
        if b % c == 0:
            return c
    return None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _jit_entries() -> int:
    """Compile-cache entry count across the jitted entry points — the
    telemetry retrace counter differences this around each call."""
    return _encode_xla._cache_size() + _encode_pallas._cache_size()


def _multi_device(x) -> bool:
    """True when x is committed/sharded across more than one device
    (a mesh-sharded engine batch).  numpy inputs have no sharding;
    tracers (outer-jit composition) conservatively count as single."""
    try:
        return len(x.sharding.device_set) > 1
    except Exception:
        return False


def _row_sharding(x):
    """x's NamedSharding when it splits ONLY the leading (stripe)
    axis — the dispatch engine's placement contract — else None."""
    try:
        sh = x.sharding
        spec = sh.spec
    except Exception:
        return None
    if getattr(sh, "mesh", None) is None or len(spec) == 0:
        return None
    if spec[0] is None or any(s is not None for s in spec[1:]):
        return None
    return sh


def build_sharded_rows_fn(fn, sh, n_replicated: int = 0):
    """jit(shard_map(fn)) over a committed row sharding ``sh`` — the
    ONE construction site for the wrappers that let an opaque
    ``pallas_call`` (a custom call GSPMD cannot split) ride a
    mesh-sharded engine batch: the batch splits BEFORE the kernel, one
    program per device, output re-assembled under the same sharding.
    ``fn(data_shard, *replicated)`` must be row-independent along the
    leading axis (every kernel in this repo's dispatch channels is —
    the crush_kernel mesh contract); the ``n_replicated`` trailing
    operands broadcast whole to every shard.  Callers cache the
    returned callable per (sharding, static-args) — a fresh wrapper
    per flush would re-trace on the hot dispatch path."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec

    rep_specs = tuple(PartitionSpec() for _ in range(n_replicated))
    # check_rep=False: pallas_call has no shard_map replication rule
    # (jax raises NotImplementedError otherwise); replication here is
    # by construction — every replicated operand is broadcast whole
    return jax.jit(shard_map(
        fn, mesh=sh.mesh, in_specs=(sh.spec,) + rep_specs,
        out_specs=sh.spec, check_rep=False))


def shard_map_rows(fn, data, *replicated):
    """One-shot convenience over build_sharded_rows_fn: run
    ``fn(data_shard, *replicated)`` over ``data``'s committed row
    sharding.  Uncached — use build_sharded_rows_fn (and cache the
    result) on hot paths."""
    return build_sharded_rows_fn(
        fn, data.sharding, len(replicated))(data, *replicated)


def _pallas_rows(w_blk, data, *, k, m, bc):
    """The fused Pallas encode over one (local) row block, padding the
    stripe axis to the grid quantum."""
    s = data.shape[0]
    pad = (-s) % _SB
    if pad:
        data = jnp.concatenate(
            [data, jnp.zeros((pad, k, data.shape[2]), dtype=data.dtype)])
    out = _encode_pallas(w_blk, data, k=k, m=m, bc=bc)
    return out[:s] if pad else out


def _pallas_rows_shard(d, w, *, k, m, bc):
    """_pallas_rows with shard_map's (data, replicated...) arg order."""
    return _pallas_rows(w, d, k=k, m=m, bc=bc)


@functools.lru_cache(maxsize=32)
def _pallas_sharded_fn(sh, k: int, m: int, bc: int):
    """Cached sharded Pallas encode per (sharding, k, m, bc) —
    NamedShardings are hashable, so the cache key is exact."""
    return build_sharded_rows_fn(
        functools.partial(_pallas_rows_shard, k=k, m=m, bc=bc), sh,
        n_replicated=1)


def _encode_dispatch_impl(w_bits, w_blk, data, *, k, m, dot_dtype):
    s, _, b = data.shape
    bc = _pick_bc(b)
    # batches below one grid step would pad up to _SB-1 all-zero
    # stripes through the Pallas path; the XLA path wastes nothing
    if (w_blk is not None and bc is not None and s >= _SB
            and jax.default_backend() == "tpu"):
        if not _multi_device(data):
            return _pallas_rows(w_blk, data, k=k, m=m, bc=bc)
        # mesh-sharded batch: pallas_call is an opaque custom call
        # GSPMD cannot split, so wrap it in shard_map — the stripe
        # axis splits BEFORE the kernel and each device runs its own
        # fused program (PR 7's XLA-only routing guard, lifted).
        # Tables committed to a different mesh than the batch (knob
        # hot-reload race) fall back to the XLA path, which jit
        # re-places freely.
        sh = _row_sharding(data)
        blk_mesh = getattr(getattr(w_blk, "sharding", None), "mesh",
                           None)
        if (sh is not None
                and s // len(data.sharding.device_set) >= _SB
                and (blk_mesh is None or blk_mesh == sh.mesh)):
            return _pallas_sharded_fn(sh, k, m, bc)(data, w_blk)
    return _encode_xla(w_bits, data, k=k, m=m, dot_dtype=dot_dtype)


def _encode_dispatch(w_bits, w_blk, data, *, k, m, dot_dtype):
    s, _, b = data.shape
    return telemetry.timed_kernel(
        "ec_encode",
        lambda: _encode_dispatch_impl(w_bits, w_blk, data,
                                      k=k, m=m, dot_dtype=dot_dtype),
        batch=s, bytes_in=s * k * b, bytes_out=s * m * b,
        cache_entries=_jit_entries,
        signature=("ec", k, m, s, b, str(dot_dtype)))


def ec_encode_jax(coeff: np.ndarray, data, dot_dtype=jnp.int8) -> jax.Array:
    """One-shot encode (builds the bit tables each call; use make_encoder for reuse)."""
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    wb = bit_matrix(coeff)
    w_bits = jnp.asarray(wb)
    data = jnp.asarray(data, dtype=jnp.uint8)
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    # only pay the block-diagonal build + upload when the Pallas path can run
    w_blk = (jnp.asarray(_blockdiag(wb, _G))
             if jax.default_backend() == "tpu" and _pick_bc(data.shape[2])
             else None)
    out = _encode_dispatch(w_bits, w_blk, data, k=k, m=m, dot_dtype=dot_dtype)
    return out[0] if squeeze else out


def make_encoder(coeff: np.ndarray, dot_dtype=jnp.int8, mesh=None):
    """Return a jitted encode(data (S,k,B) uint8) -> (S,m,B) with tables resident.

    ``mesh``: optional jax.sharding.Mesh — the bit tables are placed
    REPLICATED over it, so encode() accepts batches a mesh-sharded
    dispatch engine split across those devices without re-broadcasting
    the tables on every flush (and without tripping jax's mixed
    committed-device check)."""
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    wb = bit_matrix(coeff)
    wb_host = jnp.asarray(wb)               # uncommitted: follows any batch
    blk_host = (jnp.asarray(_blockdiag(wb, _G))
                if jax.default_backend() == "tpu" else None)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        rep = NamedSharding(mesh, PartitionSpec())
        w_bits = jax.device_put(wb_host, rep)
        w_blk = (jax.device_put(blk_host, rep) if blk_host is not None
                 else None)
    else:
        w_bits = jax.device_put(wb_host)
        w_blk = (jax.device_put(blk_host) if blk_host is not None
                 else None)

    def encode(data):
        data = jnp.asarray(data, dtype=jnp.uint8)
        wb_use, blk_use = w_bits, w_blk
        # VALUE equality, not identity: a knob hot-reload rebuilds an
        # EQUAL Mesh object (jax Mesh __eq__ is value-based, same
        # devices/layout), and tables committed to the equal mesh are
        # fully compatible — an identity check would silently take the
        # re-broadcast fallback on every flush forever after a rebuild
        if mesh is not None and getattr(
                getattr(data, "sharding", None), "mesh", None) != mesh:
            # the batch arrived committed to a DIFFERENT mesh (knob
            # hot-reload between submit and flush) or unplaced (engine
            # stopped, inline run): mesh-committed tables would trip
            # jax's mixed-committed-devices check, so fall back to the
            # uncommitted copies — jit re-places them to match the
            # batch, trading one broadcast for correctness
            wb_use, blk_use = wb_host, blk_host
        return _encode_dispatch(wb_use, blk_use, data,
                                k=k, m=m, dot_dtype=dot_dtype)

    return encode
