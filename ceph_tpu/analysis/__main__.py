"""CLI: ``python -m ceph_tpu.analysis [root] [options]``.

Exit status is 0 when no findings are NEW relative to the checked-in
baseline (``ceph_tpu/analysis/baseline.txt``), 1 otherwise — wired as
the fast pre-test step of the tier-1 command in ROADMAP.md, so every
PR is gated on a clean run.  The analysis itself is pure-AST stdlib
work (the only jax cost is the parent package's import-time x64
config; no kernels, no devices).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ceph_tpu import analysis


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description="whole-tree concurrency + jit-boundary static "
                    "analyzer (see docs/STATIC_ANALYSIS.md)")
    p.add_argument("root", nargs="?", default=None,
                   help="package directory to analyze (default: the "
                        "installed ceph_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: the checked-in "
                        "ceph_tpu/analysis/baseline.txt)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept the current findings as the baseline")
    p.add_argument("--runtime-graph", default=None, metavar="FILE",
                   help="lockdep.export_graph() JSON to union into "
                        "the static lock-order graph")
    p.add_argument("--checks", default=",".join(analysis.CHECKS),
                   help="comma-separated subset of: "
                        + ", ".join(analysis.CHECKS))
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list inline-suppressed findings")
    args = p.parse_args(argv)

    root = args.root
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    checks = tuple(c.strip() for c in args.checks.split(",")
                   if c.strip())
    unknown = [c for c in checks if c not in analysis.CHECKS]
    if unknown:
        p.error(f"unknown checks: {unknown}")
    runtime_graph = None
    if args.runtime_graph:
        with open(args.runtime_graph, encoding="utf-8") as f:
            runtime_graph = json.load(f)

    report = analysis.run(root, checks=checks,
                          runtime_graph=runtime_graph)
    baseline_path = args.baseline or analysis.default_baseline_path()
    baseline = analysis.load_baseline(baseline_path)
    new, stale = analysis.diff_baseline(report, baseline)

    if args.write_baseline:
        analysis.save_baseline(baseline_path, report.findings)
        print(f"baseline written: {len(report.findings)} finding(s) "
              f"-> {baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps({
            "root": root,
            "checks": list(checks),
            "findings": [vars(f) | {"new": f.key() not in baseline}
                         for f in report.findings],
            "suppressed": [vars(f) | {"reason": r}
                           for f, r in report.suppressed],
            "stale_baseline": stale,
            "exit": 1 if new else 0,
        }, indent=2, sort_keys=True))
    else:
        for f in report.findings:
            tag = "NEW " if f.key() not in baseline else "base"
            print(f"{tag} {f.render()}")
        if args.show_suppressed:
            for f, reason in report.suppressed:
                print(f"supp {f.render()}  [allowed: {reason}]")
        for k in stale:
            print(f"stale baseline entry (fixed — remove it): {k}")
        n_s = len(report.suppressed)
        print(f"{len(report.findings)} finding(s) "
              f"({len(new)} new, {n_s} suppressed inline, "
              f"{len(stale)} stale baseline) across "
              f"{len(checks)} check(s)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
