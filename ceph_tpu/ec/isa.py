"""isa-family plugin (Intel ISA-L semantics, TPU execution).

The reference's isa plugin (src/erasure-code/isa/ErasureCodeIsa.{h,cc}) wraps
ISA-L's `ec_encode_data` with two matrix flavours and caches decode tables.
Here the matrices come from ceph_tpu.gf.matrix (same constructions ISA-L's
gf_gen_rs_matrix / gf_gen_cauchy1_matrix publish) and encode/decode lower to
the batched MXU kernel via the ErasureCode base, whose recovery-matrix cache
plays the role of ErasureCodeIsaTableCache (327 LoC of mutex-guarded LRU in
the reference).

Matrix guard: the reference restricts Vandermonde to k <= 32 and m <= 4, where
that construction is known MDS, and silently switches m > 4 requests to Cauchy
(ErasureCodeIsa.cc:330-361); mirrored here.
"""

from __future__ import annotations

from ceph_tpu.gf.matrix import gen_cauchy1_matrix, gen_rs_vandermonde_matrix

from .base import ErasureCode
from .registry import register


class ErasureCodeIsaDefault(ErasureCode):
    """technique= reed_sol_van (default) or cauchy."""

    def _default_k(self) -> int:
        return 7

    def _default_m(self) -> int:
        return 3

    def parse(self, profile):
        super().parse(profile)
        self.technique = profile.get("technique", "reed_sol_van")
        if self.technique not in ("reed_sol_van", "cauchy"):
            raise ValueError(
                f"isa technique {self.technique!r} unknown; "
                f"known: ['reed_sol_van', 'cauchy']")
        if self.technique == "reed_sol_van":
            if self.m > 4:
                # reference behaviour: fall back to cauchy beyond the proven-
                # MDS region rather than erroring (ErasureCodeIsa.cc:330-361)
                self.technique = "cauchy"
            elif self.k > 32:
                raise ValueError(
                    f"isa reed_sol_van requires k <= 32, got k={self.k}")

    def _build_generator(self):
        if self.technique == "cauchy":
            return gen_cauchy1_matrix(self.k, self.m)
        return gen_rs_vandermonde_matrix(self.k, self.m)


register("isa", lambda profile: ErasureCodeIsaDefault())
