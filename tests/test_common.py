"""Foundation-runtime tests: config registry + observers, perf counters,
admin socket (in-process and over the unix socket), throttle, logging."""

import os
import tempfile
import threading

import pytest

from ceph_tpu.common import (
    CephTpuContext, Config, Option, OPT_INT, PerfCountersBuilder, Throttle,
    dout, set_subsys_level)
from ceph_tpu.common.admin_socket import admin_request
from ceph_tpu.common.config import register_options


def test_config_defaults_and_layers():
    c = Config()
    assert c.get("osd_pool_default_size") == 3
    c.set("osd_pool_default_size", "5", source="file")
    assert c.get("osd_pool_default_size") == 5        # cast to int
    c.set("osd_pool_default_size", 4, source="runtime")
    assert c.get("osd_pool_default_size") == 4        # runtime wins over file
    c.set("osd_pool_default_size", 7, source="file")
    assert c.get("osd_pool_default_size") == 4        # still runtime
    assert c.diff() == {"osd_pool_default_size": 4}


def test_config_validation():
    c = Config()
    with pytest.raises(KeyError):
        c.get("no_such_option")
    with pytest.raises(ValueError):
        c.set("osd_pool_default_size", "abc")
    with pytest.raises(ValueError):
        c.set("osd_pool_default_size", 3, source="bogus")


def test_config_observer_fires_on_change():
    c = Config()
    seen = []
    c.add_observer("log_level", lambda n, v: seen.append((n, v)))
    c.set("log_level", 5)
    c.set("log_level", 5)   # no change, no callback
    assert seen == [("log_level", 5)]


def test_register_options_conflict():
    register_options([Option("test_option_xyz", OPT_INT, 1)])
    register_options([Option("test_option_xyz", OPT_INT, 1)])  # same: ok
    with pytest.raises(ValueError):
        register_options([Option("test_option_xyz", OPT_INT, 2)])


def test_perf_counters():
    pc = (PerfCountersBuilder("osd")
          .add_u64("op_w", "writes")
          .add_time_avg("op_w_latency", "write latency")
          .add_histogram("op_size", [1024, 4096, 65536])
          .create_perf_counters())
    pc.inc("op_w")
    pc.inc("op_w", 2)
    pc.tinc("op_w_latency", 0.5)
    pc.tinc("op_w_latency", 1.5)
    pc.hinc("op_size", 2000)
    pc.hinc("op_size", 100000)
    d = pc.dump()
    assert d["op_w"] == 3
    assert d["op_w_latency"] == {"avgcount": 2, "sum": 2.0}
    assert d["op_size"]["buckets"] == [0, 1, 0, 1]
    assert pc.avg("op_w_latency") == 1.0


def test_context_admin_commands():
    ctx = CephTpuContext("osd.0")
    pc = PerfCountersBuilder("osd").add_u64("ops").create_perf_counters()
    ctx.perf.add(pc)
    pc.inc("ops", 7)
    assert ctx.admin.execute("perf dump")["osd"]["ops"] == 7
    ctx.admin.execute("config set", name="log_level", value=3)
    assert ctx.admin.execute("config get", name="log_level") == {"log_level": 3}
    assert "perf dump" in ctx.admin.execute("help")
    with pytest.raises(KeyError):
        ctx.admin.execute("no such command")


def test_admin_socket_over_unix_socket():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "osd.asok")
        ctx = CephTpuContext("osd.1", admin_path=path)
        ctx.admin.serve()
        out = admin_request(path, "config get", name="osd_pool_default_size")
        assert out == {"osd_pool_default_size": 3}
        out = admin_request(path, "bogus")
        assert "error" in out
        ctx.admin.shutdown()


def test_throttle_blocks_and_releases():
    t = Throttle("bytes", 100)
    assert t.get_or_fail(80)
    assert not t.get_or_fail(30)
    done = []

    def waiter():
        t.get(30)
        done.append(True)

    th = threading.Thread(target=waiter)
    th.start()
    th.join(0.05)
    assert not done            # still blocked
    t.put(80)
    th.join(2)
    assert done
    assert t.current == 30


def test_dout_gating():
    import logging

    class Capture(logging.Handler):
        def __init__(self):
            super().__init__()
            self.msgs = []

        def emit(self, record):
            self.msgs.append(record.getMessage())

    cap = Capture()
    logging.getLogger("ceph_tpu").addHandler(cap)
    try:
        set_subsys_level("crush", 1)
        dout("crush", 1, "visible %d", 1)
        dout("crush", 10, "hidden")
        set_subsys_level("crush", 10)
        dout("crush", 10, "now visible")
    finally:
        logging.getLogger("ceph_tpu").removeHandler(cap)
    assert "visible 1" in cap.msgs
    assert "hidden" not in cap.msgs
    assert "now visible" in cap.msgs
