"""Upmap balancer — evens per-OSD PG counts with pg_upmap_items
overrides (mgr balancer module in upmap mode +
OSDMap::calc_pg_upmaps, OSDMap.cc:4420-4743).

The optimizer is a pure function over an OSDMap: per pool, it measures
the per-OSD placement histogram, then greedily relocates single
replicas from the most-overfull OSD to the most-underfull one by
emitting (from, to) exception pairs — the same mechanism the
reference's `ceph osd pg-upmap-items` plumbs through
OSDMap::_apply_upmap.  Failure-domain safety is preserved
structurally: a move is only legal if the destination's CRUSH parent
bucket is not already represented in the PG's mapping (unless the
mapping never separated parents to begin with, i.e. a flat
osd-failure-domain rule).

The output is a plan: a list of mon commands ("osd pg-upmap-items" /
"osd rm-pg-upmap-items") that the caller applies through the normal
command path, mirroring how the mgr module executes its plans.
"""

from __future__ import annotations

from .osd.osdmap import CEPH_NOSD, CRUSH_ITEM_NONE, OSDMap


def _shared_service(osdmap: OSDMap):
    """The default context's shared mapping cache, warmed to this map
    (osd.mapping.SharedPGMappingService) — None when the
    osdmap_mapping_shared knob is off or warming fails.  The balancer
    reads the same epoch-keyed tables every other consumer does; every
    read still falls back to the scalar oracle on a cache miss."""
    try:
        from .common.context import default_context
        ctx = default_context()
        if not ctx.conf.get("osdmap_mapping_shared"):
            return None
        svc = ctx.mapping_service()
        svc.warm(osdmap)
        return svc
    except Exception:
        return None


def crush_parent(osdmap: OSDMap, osd: int) -> int | None:
    """The id of the bucket directly containing this osd (CrushWrapper
    get_immediate_parent_id)."""
    for b in osdmap.crush.buckets:
        if b is not None and osd in b.items:
            return b.id
    return None


def _candidate_osds(osdmap: OSDMap) -> list[int]:
    """OSDs eligible to receive PGs: exist, up, in."""
    return [o for o in range(osdmap.max_osd)
            if osdmap.exists(o) and osdmap.is_up(o)
            and not osdmap._is_out(o)]


def pool_pg_histogram(osdmap: OSDMap, pool_id: int, service=None
                      ) -> dict[int, list[tuple[int, int]]]:
    """osd -> [(pgid_ps, position)] placements for one pool, read from
    the shared mapping cache (scalar per-PG pipeline when disabled)."""
    pool = osdmap.pools[pool_id]
    svc = service if service is not None else _shared_service(osdmap)
    out: dict[int, list[tuple[int, int]]] = {}
    for ps in range(pool.pg_num):
        up, _p, _a, _ap = (svc.lookup(osdmap, pool_id, ps) if svc
                           else osdmap.pg_to_up_acting_osds(pool_id, ps))
        for pos, o in enumerate(up):
            if o not in (CEPH_NOSD, CRUSH_ITEM_NONE):
                out.setdefault(o, []).append((ps, pos))
    return out


def _move_is_safe(osdmap: OSDMap, up: list[int], frm: int,
                  to: int) -> bool:
    """Structural failure-domain check: the mapping after frm->to must
    not co-locate two members under one CRUSH parent, unless the
    current mapping already does (flat map / osd failure domain)."""
    if to in up:
        return False
    others = [o for o in up
              if o not in (frm, CEPH_NOSD, CRUSH_ITEM_NONE)]
    parents = [crush_parent(osdmap, o) for o in others]
    separated = len(set(parents + [crush_parent(osdmap, frm)])) \
        == len(others) + 1
    if not separated:
        return True          # rule never isolated parents; osd-distinct ok
    return crush_parent(osdmap, to) not in parents


def calc_pg_upmaps(osdmap: OSDMap, pool_ids: list[int] | None = None,
                   max_deviation: int = 1,
                   max_optimizations: int = 256
                   ) -> dict[tuple[int, int], list[tuple[int, int]]]:
    """Compute pg_upmap_items changes that flatten per-pool PG counts
    to within max_deviation of the mean (OSDMap::calc_pg_upmaps).

    Returns {pgid: pairs}; an empty pairs list means "remove the
    existing entry".  The osdmap is not modified.
    """
    m = osdmap
    changes: dict[tuple[int, int], list[tuple[int, int]]] = {}
    cands = _candidate_osds(m)
    if len(cands) < 2:
        return changes
    svc = _shared_service(m)
    budget = max_optimizations
    for pool_id in (pool_ids if pool_ids is not None
                    else sorted(m.pools)):
        pool = m.pools[pool_id]
        hist = pool_pg_histogram(m, pool_id, service=svc)
        counts = {o: len(hist.get(o, [])) for o in cands}
        total = sum(counts.values())
        mean = total / len(cands)
        # pairs we've planned this run, composed over what's in the map
        planned: dict[int, list[tuple[int, int]]] = {
            ps: list(m.pg_upmap_items.get((pool_id, ps), []))
            for ps in range(pool.pg_num)}

        def up_of(ps: int) -> list[int]:
            raw = svc.raw_row(m, pool_id, ps) if svc else None
            if raw is None:
                raw = list(m._pg_to_raw_osds(pool, ps))
            for frm, to in planned[ps]:
                if frm in raw and to not in raw and m.exists(to) \
                        and not m._is_out(to):
                    raw[raw.index(frm)] = to
            up, _ = m._raw_to_up_osds(pool, raw)
            return up

        # ps -> up under the CURRENT planned pairs (the map itself
        # never changes inside this optimization), batch-filled
        # through the fused ladder and invalidated per moved PG — so
        # the whole over-full OSD's candidate set costs ONE device
        # call up front and each later iteration re-evaluates only
        # what a move actually changed (host up_of stays the fallback
        # and the oracle: bit-identical by the ladder contract)
        ups_cache: dict[int, list[int]] = {}

        def fill_ups(cand_list):
            missing = [ps for ps, _pos in cand_list
                       if ps not in ups_cache]
            if svc is None or not missing:
                return
            got = svc.what_if_up(
                m, pool_id, [(ps, planned[ps]) for ps in missing])
            if got is not None:
                ups_cache.update(zip(missing, got))

        while budget > 0:
            over = max(cands, key=lambda o: counts[o])
            under = min(cands, key=lambda o: counts[o])
            # iterate until BOTH tails are inside the deviation target
            # (OSDMap::calc_pg_upmaps loops on max deviation, with
            # retries; stopping when either side looked fine left the
            # other tail unbalanced)
            if counts[over] - mean <= max_deviation \
                    and mean - counts[under] <= max_deviation:
                break
            moved = False
            over_cands = sorted(hist.get(over, []))
            fill_ups(over_cands)
            for ps, _pos in over_cands:
                up = ups_cache.get(ps)
                if up is None:
                    up = up_of(ps)
                if over not in up:
                    continue
                # prefer the most-underfull legal destination
                for to in sorted(cands, key=lambda o: counts[o]):
                    if counts[to] >= mean or to == over:
                        continue
                    if not _move_is_safe(m, up, over, to):
                        continue
                    # compose: if `over` itself arrived via an earlier
                    # pair (x -> over), rewrite that pair to (x -> to);
                    # otherwise add a fresh (over -> to) pair
                    src = next((f for (f, t) in planned[ps]
                                if t == over), None)
                    pairs = [p for p in planned[ps] if p[1] != over]
                    pairs.append((src if src is not None else over, to))
                    pairs = [p for p in pairs if p[0] != p[1]]
                    planned[ps] = pairs
                    ups_cache.pop(ps, None)   # pairs moved: re-score
                    changes[(pool_id, ps)] = pairs
                    counts[over] -= 1
                    counts[to] += 1
                    hist[over] = [e for e in hist.get(over, [])
                                  if e[0] != ps]
                    hist.setdefault(to, []).append((ps, _pos))
                    moved = True
                    budget -= 1
                    break
                if moved:
                    break
            if not moved:
                break
    # drop no-op changes (identical to what the map already has)
    return {pgid: pairs for pgid, pairs in changes.items()
            if pairs != m.pg_upmap_items.get(pgid, [])}


def plan_commands(osdmap: OSDMap, **kw) -> list[dict]:
    """Render calc_pg_upmaps output as mon commands (the balancer
    module's execute() shape)."""
    cmds = []
    for (pool_id, ps), pairs in sorted(calc_pg_upmaps(osdmap,
                                                      **kw).items()):
        if pairs:
            flat: list[int] = []
            for f, t in pairs:
                flat += [f, t]
            cmds.append({"prefix": "osd pg-upmap-items",
                         "pgid": f"{pool_id}.{ps}", "id_pairs": flat})
        else:
            cmds.append({"prefix": "osd rm-pg-upmap-items",
                         "pgid": f"{pool_id}.{ps}"})
    return cmds


def reweight_by_utilization(osdmap: OSDMap, oload: int = 120,
                            max_change: float = 0.05,
                            max_osds: int = 4) -> list[tuple[int, float]]:
    """The classic alternative to upmap: nudge the reweight of the most
    overloaded OSDs down (mon `osd reweight-by-utilization`,
    OSDMonitor::reweight_by_utilization semantics with PG count standing
    in for byte utilization).

    Only OSDs loaded above oload% of the mean are touched, each by at
    most max_change of full weight, at most max_osds per invocation —
    the reference's gradual, bounded adjustment so one run can never
    destabilize the cluster.  Returns [(osd, new_weight_float)] with
    weights in [0, 1] (16.16-scaled by the caller / mon command).
    """
    cands = _candidate_osds(osdmap)
    if len(cands) < 2:
        return []
    counts: dict[int, int] = {o: 0 for o in cands}
    for pool_id in osdmap.pools:
        for o, placements in pool_pg_histogram(osdmap, pool_id).items():
            if o in counts:
                counts[o] += len(placements)
    mean = sum(counts.values()) / len(cands)
    if mean <= 0:
        return []
    threshold = mean * oload / 100.0
    over = sorted((o for o in cands if counts[o] > threshold),
                  key=lambda o: -counts[o])[:max_osds]
    out = []
    for o in over:
        cur = osdmap.osd_weight[o] / 0x10000
        target = cur * mean / counts[o]
        new = max(cur - max_change, target, 0.0)
        if new < cur:
            out.append((o, round(new, 4)))
    return out


def spread(osdmap: OSDMap, pool_id: int) -> tuple[int, int]:
    """(min, max) per-OSD PG count over candidate osds — the balancer
    score."""
    hist = pool_pg_histogram(osdmap, pool_id)
    counts = [len(hist.get(o, [])) for o in _candidate_osds(osdmap)]
    return (min(counts), max(counts)) if counts else (0, 0)
