"""Flagship benchmark: erasure encode + 2-erasure recovery throughput.

Mirrors the reference's `ceph_erasure_code_benchmark` workload (BASELINE.json
north-star config: k=8 m=4 cauchy, 4 KiB chunks) — the reference harness reports
elapsed seconds and KiB processed (src/test/erasure-code/
ceph_erasure_code_benchmark.cc:188,326); here the same quantity is reported as
MB/s directly, batched over many stripes per device call instead of one stripe
per call (the ECUtil stripe-loop batch point, src/osd/ECUtil.cc:136).

Timing: the device runtime acks dispatch before execution completes (remote
tunnel), so naive block_until_ready under-measures.  Each measurement runs the
kernel N times inside one jitted lax.scan with a forced data dependency between
iterations, fetches a scalar (which cannot resolve until everything executed),
and differences two iteration counts to cancel dispatch/transfer overhead.

vs_baseline: ratio against a single-core CPU GF(2^8) table encode measured in
the same process (numpy oracle — the same math jerasure computes without SIMD
hand-tuning).  The reference publishes no numbers in-tree (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def chained_seconds_per_step(step_fn, carry, n_lo: int = 4, n_hi: int = 12,
                             reps: int = 3) -> float:
    """Seconds per step_fn call, measured as d(time)/d(iterations)."""
    import jax

    @functools.partial(jax.jit, static_argnames="n")
    def loop(c, n):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), ()), c, None, length=n)
        leaf = jax.tree_util.tree_leaves(c)[0]
        return leaf.ravel()[0]

    def run(n):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.device_get(loop(carry, n))
            best = min(best, time.perf_counter() - t0)
        return best

    jax.device_get(loop(carry, n_lo))  # compile
    jax.device_get(loop(carry, n_hi))
    t_lo, t_hi = run(n_lo), run(n_hi)
    return max(t_hi - t_lo, 1e-9) / (n_hi - n_lo)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf.matrix import gen_cauchy1_matrix, recovery_matrix
    from ceph_tpu.gf.tables import nibble_bit_table
    from ceph_tpu.ops.gf_kernel import _encode_impl, ec_encode_ref

    k, m = 8, 4
    chunk = 4096          # 4 KiB chunks — BASELINE.json config
    stripes = 2048        # 64 MiB of data per device call
    erasures = [1, k + 1]  # one data + one parity chunk lost

    gen = gen_cauchy1_matrix(k, m)
    coding = gen[k:]
    chosen = [i for i in range(k + m) if i not in set(erasures)][:k]
    rmat = recovery_matrix(gen, chosen, erasures)
    w_enc = jnp.asarray(nibble_bit_table(coding))
    w_rec = jnp.asarray(nibble_bit_table(rmat))

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))
    data_bytes = stripes * k * chunk

    def enc_step(d):
        p = _encode_impl(w_enc, d, k=k, m=m, dot_dtype=jnp.bfloat16)
        return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

    t_enc = chained_seconds_per_step(enc_step, data)
    enc_mbps = data_bytes / t_enc / 1e6

    surv = jnp.asarray(
        rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))

    def dec_step(s):
        r = _encode_impl(w_rec, s, k=k, m=len(erasures), dot_dtype=jnp.bfloat16)
        return s.at[0, 0, 0].set(r[0, 0, 0] ^ jnp.uint8(1))

    t_dec = chained_seconds_per_step(dec_step, surv)
    dec_mbps = data_bytes / t_dec / 1e6

    combined = 2 * data_bytes / (t_enc + t_dec) / 1e6

    # CRUSH bulk placement (BASELINE config #5 shape): 10k-OSD two-level map
    # (250 hosts x 40 osds), chooseleaf firstn 3, 64k PGs per device call
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.crush.mapper_jax import BatchMapper

    crush_map, _root, rid = build_two_level_map(250, 40)
    bm = BatchMapper(crush_map)
    n_pgs, numrep = 65536, 3
    rw = jnp.full((10000,), 0x10000, dtype=jnp.int64)
    xs = jnp.asarray(rng.integers(0, 2**32, (n_pgs,), dtype=np.uint32))
    bm.do_rule(rid, xs, numrep, rw)  # compile

    def crush_step(x):
        p = bm.do_rule(rid, x, numrep, rw)
        return x ^ p[:, 0].astype(jnp.uint32)

    t_crush = chained_seconds_per_step(crush_step, xs, n_lo=2, n_hi=6)
    crush_mpps = n_pgs / t_crush / 1e6

    # single-core CPU baseline: same math via the numpy table oracle on a slice
    cpu_stripes = max(stripes // 32, 1)
    cpu_data = np.asarray(data[:cpu_stripes])
    t0 = time.perf_counter()
    ec_encode_ref(coding, cpu_data)
    t_cpu = time.perf_counter() - t0
    cpu_mbps = cpu_stripes * k * chunk / t_cpu / 1e6

    print(json.dumps({
        "metric": "ec encode+recover MB/s (k=8,m=4,4KiB chunks, batch=2048)",
        "value": round(combined, 1),
        "unit": "MB/s",
        "vs_baseline": round(combined / cpu_mbps, 2),
        "encode_mbps": round(enc_mbps, 1),
        "recover_mbps": round(dec_mbps, 1),
        "cpu_oracle_mbps": round(cpu_mbps, 1),
        "crush_mpps": round(crush_mpps, 2),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
