"""RGW S3 REST frontend — an authenticated HTTP gateway over rgw_lite.

The reference's radosgw is an HTTP server (civetweb/asio frontends,
src/rgw/rgw_asio_frontend.cc) that parses S3's REST dialect
(src/rgw/rgw_rest_s3.cc), authenticates AWS signatures
(src/rgw/rgw_auth_s3.cc), and maps operations onto the RADOS layout
(src/rgw/rgw_rados.cc).  This module is that surface over the rgw_lite
storage mapping, sized to the repo:

* event-driven HTTP frontend (rgw_frontend.AsyncHttpFrontend — the
  asio/beast analog: one I/O loop owning the sockets, a bounded
  handler pool doing the RADOS work)
* AWS Signature V4: full canonical-request -> string-to-sign -> derived
  signing key verification (UNSIGNED-PAYLOAD and sha256 payloads), with
  access keys provisioned against the cluster's auth key material
* bucket ops: PUT/DELETE/GET(list) with ListObjectsV2 pagination
  (max-keys / continuation-token / IsTruncated)
* object ops: PUT (with x-amz-meta-*), GET, HEAD, DELETE
* multipart upload: initiate (POST ?uploads), UploadPart
  (PUT ?partNumber&uploadId), complete (POST ?uploadId), abort
  (DELETE ?uploadId) — parts staged as rgw_lite objects and
  concatenated on complete (rgw_rest_s3.cc multipart flow)

Error responses use the S3 XML error envelope with the usual codes
(NoSuchBucket, NoSuchKey, SignatureDoesNotMatch, BucketNotEmpty...).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import re
import threading
import time
import urllib.parse
from ceph_tpu.rgw_frontend import AsyncHttpFrontend

from ceph_tpu.rgw_lite import Bucket

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


# ---------------------------------------------------------------------------
# AWS Signature V4
# ---------------------------------------------------------------------------

def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_signing_key(secret: str, date: str, region: str,
                      service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(query: str) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    enc = [(urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~")) for k, v in pairs]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def sign_request(method: str, path: str, query: str, headers: dict,
                 payload_sha: str, access: str, secret: str,
                 region: str = "default") -> str:
    """Produce the Authorization header value for a request (used by the
    server to verify and by test clients to sign)."""
    amzdate = headers["x-amz-date"]
    date = amzdate[:8]
    signed = sorted(h.lower() for h in ("host", "x-amz-content-sha256",
                                        "x-amz-date") if h in
                    {k.lower() for k in headers})
    canon_headers = "".join(
        f"{h}:{_header(headers, h).strip()}\n" for h in signed)
    # S3's no-double-encode rule: the canonical URI is the path exactly
    # as sent on the wire (already percent-encoded by the client); both
    # signer and verifier must use it verbatim or encoded keys 403
    creq = "\n".join([
        method, path,
        canonical_query(query), canon_headers, ";".join(signed),
        payload_sha])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(sigv4_signing_key(secret, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    return (f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")


def _header(headers: dict, name: str) -> str:
    for k, v in headers.items():
        if k.lower() == name.lower():
            return v
    return ""


_AUTH_RE = re.compile(
    r"AWS4-HMAC-SHA256 Credential=(?P<access>[^/]+)/(?P<date>\d{8})/"
    r"(?P<region>[^/]+)/s3/aws4_request,\s*"
    r"SignedHeaders=(?P<signed>[^,]+),\s*Signature=(?P<sig>[0-9a-f]+)")


# ---------------------------------------------------------------------------
# XML helpers (no external deps; S3's dialect is shallow)
# ---------------------------------------------------------------------------

def _x(tag: str, body: str) -> str:
    return f"<{tag}>{body}</{tag}>"


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def _error_xml(code: str, message: str) -> bytes:
    return (f'<?xml version="1.0" encoding="UTF-8"?>'
            f'<Error>{_x("Code", code)}{_x("Message", _esc(message))}'
            f"</Error>").encode()


_ERR_STATUS = {"NoSuchBucket": 404, "NoSuchKey": 404, "NoSuchUpload": 404,
               "BucketNotEmpty": 409, "BucketAlreadyExists": 409,
               "SignatureDoesNotMatch": 403, "AccessDenied": 403,
               "InvalidPart": 400, "MalformedXML": 400,
               "InvalidArgument": 400, "RequestTimeTooSkewed": 403,
               "NoSuchLifecycleConfiguration": 404,
               "NoSuchBucketPolicy": 404,
               "NoSuchCORSConfiguration": 404,
               "MalformedPolicy": 400, "MalformedACLError": 400,
               "AccessForbidden": 403}


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code
        self.status = _ERR_STATUS.get(code, 400)


# ---------------------------------------------------------------------------
# gateway
# ---------------------------------------------------------------------------

class S3Gateway:
    """The op layer: S3 verbs -> rgw_lite buckets over one ioctx."""

    MP_PREFIX = ".mp"
    #: all bucket names live in one registry omap (the rgw metadata-pool
    #: bucket listing, rgw_metadata.cc reduced) so service-level ops and
    #: the lifecycle agent can enumerate buckets
    REGISTRY = ".buckets.registry"

    def __init__(self, ioctx, compression: str = "none", clock=time.time):
        self.io = ioctx
        self.compression = compression
        self.clock = clock
        #: multisite: when True, mutations append bucket datalog records
        #: a ZoneSyncAgent replays on the secondary (rgw_datalog analog)
        self.datalog_enabled = False
        # analysis: allow[bare-lock] -- rgw store leaf lock guarding the per-bucket lock table
        self._lock = threading.Lock()
        self._bucket_locks: dict[str, threading.Lock] = {}

    def _block(self, bucket: str) -> threading.Lock:
        """Per-bucket mutation lock: apply+datalog ordering is a
        PER-BUCKET invariant — one global lock would serialize every
        object write across all buckets."""
        with self._lock:
            return self._bucket_locks.setdefault(bucket,
                                                 # analysis: allow[bare-lock] -- per-bucket mutation locks, leaf by construction (taken after _lock released)
                                                 threading.Lock())

    def _datalog(self, bucket: str, op: str, key: str) -> None:
        if self.datalog_enabled:
            from ceph_tpu.rgw_sync import datalog_append
            datalog_append(self, bucket, op, key, clock=self.clock)

    @staticmethod
    def _check_name(s: str, what: str) -> None:
        if any(ord(c) < 0x20 for c in s):
            raise S3Error("InvalidArgument",
                          f"control character in {what}")

    def _bucket(self, name: str, must_exist: bool = True) -> Bucket:
        b = Bucket(self.io, name, compression=self.compression)
        if must_exist and not b.exists():
            raise S3Error("NoSuchBucket", name)
        return b

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, name: str, owner: str = "",
                      acl: str = "private") -> None:
        self._check_name(name, "bucket name")
        b = Bucket(self.io, name, compression=self.compression)
        if b.exists():
            raise S3Error("BucketAlreadyExists", name)
        b.create(owner=owner)
        if acl != "private":
            b.set_meta("acl", acl)
        self.io.set_omap(self.REGISTRY, {name: (owner or "-").encode()})

    # -- versioning / lifecycle / acl ----------------------------------------

    def get_versioning(self, name: str) -> str:
        return self._bucket(name).versioning()

    def set_versioning(self, name: str, status: str) -> None:
        if status not in ("Enabled", "Suspended"):
            raise S3Error("IllegalVersioningConfigurationException", status)
        self._bucket(name).set_versioning(status)

    def get_lifecycle(self, name: str) -> list[dict]:
        lc = self._bucket(name).get_meta("lifecycle")
        if not lc:
            raise S3Error("NoSuchLifecycleConfiguration", name)
        return lc

    def set_lifecycle(self, name: str, rules: list[dict]) -> None:
        for r in rules:
            if not (r.get("expiration_days") or
                    r.get("noncurrent_days")):
                raise S3Error("MalformedXML", "rule without an action")
        self._bucket(name).set_meta("lifecycle", rules)

    def delete_lifecycle(self, name: str) -> None:
        self._bucket(name).set_meta("lifecycle", None)

    def get_acl(self, name: str) -> tuple[str, str]:
        meta = self._bucket(name).meta_all()
        return (meta.get("acl") or "private", meta.get("owner") or "")

    def set_acl(self, name: str, acl: str) -> None:
        if acl not in ("private", "public-read", "public-read-write",
                       "authenticated-read"):
            raise S3Error("InvalidArgument", f"unsupported canned acl {acl}")
        b = self._bucket(name)
        b.set_meta("acl", acl)
        # a canned reset REPLACES any explicit grant list — leaving
        # stale grants behind would let `x-amz-acl: private` silently
        # keep the bucket public
        b.set_meta("grants", None)

    def _bucket_grants(self, meta: dict) -> list[dict]:
        """The bucket's effective grant list: explicit grants when set,
        else the canned ACL expanded (rgw_acl.h ACLGrant table)."""
        from ceph_tpu import rgw_auth
        blob = meta.get("grants")
        if blob:
            return json.loads(blob)
        return rgw_auth.canned_grants(meta.get("acl") or "private",
                                      meta.get("owner") or "")

    def get_policy(self, name: str) -> str | None:
        return self._bucket(name).meta_all().get("policy")

    def set_policy(self, name: str, doc: str) -> None:
        from ceph_tpu import rgw_auth
        try:
            rgw_auth.BucketPolicy.parse(doc)     # validate up front
        except rgw_auth.PolicyError as e:
            raise S3Error("MalformedPolicy", str(e))
        self._bucket(name).set_meta("policy", doc)

    def delete_policy(self, name: str) -> None:
        self._bucket(name).set_meta("policy", None)

    def set_bucket_grants(self, name: str, grants: list[dict]) -> None:
        from ceph_tpu import rgw_auth
        try:
            grants = rgw_auth.validate_grants(grants)
        except ValueError as e:
            raise S3Error("InvalidArgument", str(e))
        self._bucket(name).set_meta("grants", json.dumps(grants))

    def get_bucket_grants(self, name: str) -> list[dict]:
        return self._bucket_grants(self._bucket(name).meta_all())

    def set_object_grants(self, bucket: str, key: str,
                          grants: list[dict],
                          vid: str | None = None) -> None:
        from ceph_tpu import rgw_auth
        try:
            grants = rgw_auth.validate_grants(grants)
        except ValueError as e:
            raise S3Error("InvalidArgument", str(e))
        try:
            self._bucket(bucket).update_entry(
                key, {"acl_grants": grants}, vid=vid)
        except KeyError:
            raise S3Error("NoSuchKey", key)

    def get_cors(self, name: str) -> list[dict]:
        blob = self._bucket(name).meta_all().get("cors")
        return json.loads(blob) if blob else []

    def set_cors(self, name: str, rules: list[dict]) -> None:
        from ceph_tpu import rgw_auth
        try:
            rgw_auth.CorsConfig.from_rules(rules)    # validate
        except ValueError as e:
            raise S3Error("InvalidArgument", str(e))
        self._bucket(name).set_meta("cors", json.dumps(rules))

    def delete_cors(self, name: str) -> None:
        self._bucket(name).set_meta("cors", None)

    def cors_match(self, name: str, origin: str, method: str,
                   req_headers: list[str] | None = None):
        from ceph_tpu import rgw_auth
        rules = self.get_cors(name)
        if not rules:
            return None
        return rgw_auth.CorsConfig.from_rules(rules).match(
            origin, method, req_headers)

    def authorize(self, name: str, principal: str | None,
                  write: bool, key: str | None = None,
                  action: str | None = None,
                  vid: str | None = None) -> None:
        """Full data-path authorization (rgw_op.cc verify_permission):
        bucket POLICY first (explicit Deny ends it, Allow grants), then
        the ACL grant table — the OBJECT's own grants for object reads
        when it has them (of the ADDRESSED version, so per-version ACLs
        enforce), else the bucket's (canned ACLs expand into the same
        table).  An EMPTY owner matches nobody: a bucket whose
        ownership is unknown must not become world-owned."""
        from ceph_tpu import rgw_auth
        b = self._bucket(name)
        try:
            idx = b._index()       # ONE omap fetch serves meta + entry
        except OSError:
            idx = {}
        meta = b.meta_all(idx=idx)
        owner = meta.get("owner") or ""
        if action is None:
            if key is not None:
                action = "s3:PutObject" if write else "s3:GetObject"
            else:
                action = "s3:PutObject" if write else "s3:ListBucket"
        policy = None
        if meta.get("policy"):
            try:
                policy = rgw_auth.BucketPolicy.parse(meta["policy"])
            except rgw_auth.PolicyError:
                policy = None   # unparseable stored policy: ACLs rule
        grants = self._bucket_grants(meta)
        obj_owner = owner
        if key is not None:
            try:
                ent = b.head(key, vid, idx=idx)
            except (KeyError, S3Error):
                ent = None
            if ent:
                if ent.get("acl_grants"):
                    grants = ent["acl_grants"]
                if ent.get("owner"):
                    obj_owner = ent["owner"]
        perm = {"s3:GetObjectAcl": rgw_auth.READ_ACP,
                "s3:PutObjectAcl": rgw_auth.WRITE_ACP}.get(
            action, rgw_auth.WRITE if write else rgw_auth.READ)
        if not rgw_auth.evaluate(policy, grants,
                                 obj_owner if key is not None
                                 else owner,
                                 principal, perm, action, name,
                                 key=key):
            raise S3Error("AccessDenied", f"{action} {name}"
                          + (f"/{key}" if key else ""))

    def authorize_owner(self, name: str, principal: str | None) -> None:
        """Bucket-configuration ops (versioning/lifecycle/acl/delete):
        owner only — canned ACLs never delegate these."""
        owner = self._bucket(name).meta_all().get("owner") or ""
        if principal is None or not owner or principal != owner:
            raise S3Error("AccessDenied", "bucket owner only")

    def delete_bucket(self, name: str) -> None:
        b = self._bucket(name)
        try:
            b.delete()
        except OSError:
            raise S3Error("BucketNotEmpty", name)
        try:
            self.io.rm_omap_keys(self.REGISTRY, [name])
        except OSError:
            pass

    def list_objects(self, name: str, prefix: str, max_keys: int,
                     token: str) -> tuple[list[tuple[str, dict]], str]:
        """ListObjectsV2: (entries, next_token); '' token = done."""
        b = self._bucket(name)
        keys = [k for k in b.list(prefix=prefix)
                if not k.startswith(self.MP_PREFIX + ".")]
        if token:
            keys = [k for k in keys if k > token]
        page = keys[:max_keys]
        next_token = page[-1] if len(keys) > max_keys else ""
        out = []
        for k in page:
            try:
                out.append((k, b.head(k)))
            except KeyError:
                continue
        return out, next_token

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   metadata: dict,
                   owner: str | None = None) -> tuple[str, str | None]:
        """Returns (etag, version_id-or-None)."""
        self._check_name(key, "object key")
        if key.startswith(self.MP_PREFIX + "."):
            raise S3Error("InvalidArgument",
                          f"key prefix {self.MP_PREFIX!r}. is reserved "
                          "for multipart staging")
        b = self._bucket(bucket)
        etag = hashlib.md5(data).hexdigest()
        if self.datalog_enabled:
            # apply + log under the BUCKET's lock: a racing put/delete
            # pair on one key must log in the order it applied, or
            # replay diverges the peer
            with self._block(bucket):
                entry = b.put(key, data, metadata=metadata,
                              clock=self.clock, etag=etag, owner=owner)
                self._datalog(bucket, "put", key)
        else:
            entry = b.put(key, data, metadata=metadata,
                          clock=self.clock, etag=etag, owner=owner)
        return etag, entry.get("version_id")

    def get_object(self, bucket: str, key: str,
                   vid: str | None = None) -> tuple[bytes, dict]:
        b = self._bucket(bucket)
        try:
            head = b.head(key, vid)
            return b.get(key, vid), head
        except KeyError:
            raise S3Error("NoSuchKey", key)

    def head_object(self, bucket: str, key: str,
                    vid: str | None = None) -> dict:
        try:
            return self._bucket(bucket).head(key, vid)
        except KeyError:
            raise S3Error("NoSuchKey", key)

    def copy_object(self, src_bucket: str, src_key: str,
                    dst_bucket: str, dst_key: str,
                    src_vid: str | None = None,
                    metadata: dict | None = None,
                    owner: str | None = None) -> tuple[str, str | None]:
        """S3 CopyObject (rgw_op.cc RGWCopyObj reduced): server-side
        read + re-put, so the datalog/versioning/compression semantics
        are exactly a put's.  metadata None = COPY the source's
        (x-amz-metadata-directive: COPY); a dict = REPLACE."""
        data, head = self.get_object(src_bucket, src_key, src_vid)
        if metadata is None:      # x-amz-metadata-directive: COPY
            meta = dict(head.get("meta") or {})
        else:
            meta = metadata
        return self.put_object(dst_bucket, dst_key, data, meta,
                               owner=owner)

    def delete_object(self, bucket: str, key: str,
                      vid: str | None = None) -> dict:
        try:
            if self.datalog_enabled:
                with self._block(bucket):
                    b = self._bucket(bucket)
                    out = b.delete_object(key, vid, clock=self.clock)
                    # the peer mirrors CURRENT objects only: log what
                    # happened to the current object, not the verb.  A
                    # version-targeted delete can repoint the current
                    # (including an undelete when a marker is removed)
                    # or leave it untouched — replay by re-copy then;
                    # only a key whose current is gone/marked replays
                    # as a delete
                    cur = b.current_entry(key)
                    present = (cur is not None
                               and not cur.get("delete_marker"))
                    if present and vid is not None:
                        self._datalog(bucket, "put", key)
                    elif not present:
                        self._datalog(bucket, "delete", key)
            else:
                out = self._bucket(bucket).delete_object(
                    key, vid, clock=self.clock)
        except KeyError:
            # S3 DELETE is idempotent
            return {"delete_marker": False, "version_id": None}
        return out

    def list_versions(self, name: str, prefix: str, max_keys: int,
                      key_marker: str = "",
                      vid_marker: str = "") -> tuple[list, bool]:
        """ListObjectVersions: ([(key, entry, is_latest)], truncated).
        Rows order (key asc, version newest-first); resume after the
        (key-marker, version-id-marker) pair like S3."""
        b = self._bucket(name)
        rows = [r for r in b.list_versions(prefix=prefix)
                if not r[0].startswith(self.MP_PREFIX + ".")]
        if key_marker:
            # resume POSITIONALLY after the marker row: versions order
            # within a key is by mtime, so a lexicographic version-id
            # comparison would skip "null" ids across page boundaries
            idx = next((i for i, (k, e, _l) in enumerate(rows)
                        if k == key_marker
                        and e.get("version_id", "") == vid_marker), None)
            if idx is not None:
                rows = rows[idx + 1:]
            else:
                # the marker row was deleted between pages.  Timestamp
                # version ids (20-digit time_ns) order with mtime, so
                # "after the marker" = a numerically-smaller id in the
                # newest-first stream; a "null" marker/row defeats that
                # comparison, so those keep the whole key — possibly
                # re-serving a version, never silently dropping one
                def _after(k, e):
                    if k != key_marker:
                        return k > key_marker
                    vid = e.get("version_id", "")
                    if vid_marker.isdigit() and vid.isdigit():
                        return vid < vid_marker
                    return True
                rows = [r for r in rows if _after(r[0], r[1])]
        return rows[:max_keys], len(rows) > max_keys

    # -- lifecycle agent (rgw_lc.cc RGWLC::process reduced) -------------------

    def lifecycle_pass(self, bucket_names: list[str] | None = None) -> dict:
        """One expiration sweep over buckets carrying lifecycle config.
        Current objects past expiration_days expire the S3 way (delete
        marker under versioning, hard delete otherwise); noncurrent
        versions past noncurrent_days are permanently removed.  Returns
        counters for observability/tests."""
        stats = {"expired": 0, "noncurrent_removed": 0, "buckets": 0}
        names = (bucket_names if bucket_names is not None
                 else self._buckets_with_lc())
        now = self.clock()
        for name in names:
            try:
                b = self._bucket(name)
            except S3Error:
                continue
            rules = b.get_meta("lifecycle") or []
            if not rules:
                continue
            stats["buckets"] += 1
            with self._block(name):
                for rule in rules:
                    if rule.get("status", "Enabled") != "Enabled":
                        continue
                    self._apply_lc_rule(b, rule, now, stats)
        return stats

    def _buckets_with_lc(self) -> list[str]:
        try:
            return sorted(self.io.get_omap(self.REGISTRY))
        except OSError:
            return []

    def _apply_lc_rule(self, b: Bucket, rule: dict, now: float,
                       stats: dict) -> None:
        prefix = rule.get("prefix", "")
        exp_days = rule.get("expiration_days")
        nc_days = rule.get("noncurrent_days")
        day = 86400.0
        if exp_days:
            for key in b.list(prefix=prefix):
                if key.startswith(self.MP_PREFIX + "."):
                    continue
                try:
                    entry = b.head(key)
                except KeyError:
                    continue
                if now - entry.get("mtime", now) >= exp_days * day:
                    b.delete_object(key, clock=self.clock)
                    self._datalog(b.name, "delete", key)
                    stats["expired"] += 1
        if nc_days:
            # NoncurrentDays counts from the moment a version BECAME
            # noncurrent — the write time of its successor — not from
            # its own mtime (S3 semantics, rgw_lc.cc pass through
            # next_mtime)
            by_key: dict[str, list[dict]] = {}
            for key, entry, _latest in b.list_versions(prefix=prefix):
                if not key.startswith(self.MP_PREFIX + "."):
                    by_key.setdefault(key, []).append(entry)
            for key, rows in by_key.items():     # rows newest-first
                succ_mtime = None
                for entry in rows:
                    if succ_mtime is not None \
                            and now - succ_mtime >= nc_days * day:
                        b.delete_object(key, entry.get("version_id"),
                                        clock=self.clock)
                        stats["noncurrent_removed"] += 1
                    succ_mtime = entry.get("mtime", now)

    # -- multipart -----------------------------------------------------------

    def _mp_key(self, upload_id: str, part: int | None = None) -> str:
        base = f"{self.MP_PREFIX}.{upload_id}"
        return base if part is None else f"{base}.{part:05d}"

    def initiate_multipart(self, bucket: str, key: str,
                           metadata: dict) -> str:
        self._check_name(key, "object key")
        with self._lock:
            b = self._bucket(bucket)
            upload_id = hashlib.sha1(
                f"{bucket}/{key}/{time.time_ns()}".encode()).hexdigest()[:16]
            b.put(self._mp_key(upload_id), json.dumps(
                {"key": key, "meta": metadata}).encode(),
                  unversioned=True)
            return upload_id

    def _mp_manifest(self, b: Bucket, upload_id: str) -> dict:
        try:
            return json.loads(b.get(self._mp_key(upload_id)).decode())
        except KeyError:
            raise S3Error("NoSuchUpload", upload_id)

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part: int, data: bytes) -> str:
        b = self._bucket(bucket)
        self._mp_manifest(b, upload_id)
        b.put(self._mp_key(upload_id, part), data, unversioned=True)
        return hashlib.md5(data).hexdigest()

    def upload_part_copy(self, bucket: str, key: str, upload_id: str,
                         part: int, src_bucket: str, src_key: str,
                         src_vid: str | None = None,
                         byte_range: tuple[int, int] | None = None
                         ) -> str:
        """S3 UploadPartCopy (RGWCopyObj's multipart shape): the part's
        bytes come from an existing object, optionally a byte range
        (x-amz-copy-source-range, inclusive ends like HTTP ranges)."""
        # upload validity FIRST (S3's NoSuchUpload beats range errors,
        # and a dead upload must not cost a full source read)
        self._mp_manifest(self._bucket(bucket), upload_id)
        data, _head = self.get_object(src_bucket, src_key, src_vid)
        if byte_range is not None:
            first, last = byte_range
            if not (0 <= first <= last < len(data)):
                raise S3Error("InvalidArgument",
                              f"range {first}-{last} outside object "
                              f"of {len(data)} bytes")
            data = data[first:last + 1]
        return self.upload_part(bucket, key, upload_id, part, data)

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           parts: list[tuple[int, str]]) -> str:
        # serialized: complete reads parts then deletes them; two racing
        # completes (or a racing abort) must not interleave
        with self._lock:
            return self._complete_locked(bucket, key, upload_id, parts)

    def _complete_locked(self, bucket: str, key: str, upload_id: str,
                         parts: list[tuple[int, str]]) -> str:
        b = self._bucket(bucket)
        manifest = self._mp_manifest(b, upload_id)
        chunks = []
        for num, etag in parts:
            try:
                data = b.get(self._mp_key(upload_id, num))
            except KeyError:
                raise S3Error("InvalidPart", f"part {num} missing")
            if etag and hashlib.md5(data).hexdigest() != etag.strip('"'):
                raise S3Error("InvalidPart", f"part {num} etag mismatch")
            chunks.append(data)
        whole = b"".join(chunks)
        b.put(key, whole, metadata=manifest.get("meta") or {},
              clock=self.clock,
              etag=hashlib.md5(whole).hexdigest())
        self._datalog(bucket, "put", key)
        self._abort_locked(b, upload_id)
        return hashlib.md5(whole).hexdigest()

    def abort_multipart(self, bucket: str, key: str,
                        upload_id: str) -> None:
        with self._lock:
            self._abort_locked(self._bucket(bucket), upload_id)

    def _abort_locked(self, b: Bucket, upload_id: str) -> None:
        for k in b.list(prefix=f"{self.MP_PREFIX}.{upload_id}"):
            try:
                b.delete_object(k, unversioned=True)
            except KeyError:
                pass


class _S3Request:
    """One request's routing context, transport-neutral: the async
    frontend (rgw_frontend) hands it an HttpRequest on a worker thread
    and takes back (status, headers, body).  The surface the routing
    methods use — command/path/headers/rfile/_respond — matches the
    old BaseHTTPRequestHandler shape, so the S3 dialect is unchanged."""

    def __init__(self, server: "RgwRestServer", req) -> None:
        import io
        import types
        self.server = types.SimpleNamespace(rgw=server)
        self.command = req.method
        self.path = req.target
        self.headers = req.headers
        self.rfile = io.BytesIO(req.body)
        self._out: tuple[int, dict, bytes] | None = None

    def handle(self) -> tuple[int, dict, bytes]:
        self._dispatch()
        if self._out is None:   # a route returned without responding
            self._out = (500, {"Content-Type": "application/xml"},
                         _error_xml("InternalError", "no response"))
        return self._out

    # -- auth ----------------------------------------------------------------

    def _authenticate(self, body: bytes) -> str | None:
        """Verify SigV4 and return the principal (access key id), or
        None for an anonymous request — per-bucket ACLs decide what an
        anonymous principal may do (rgw allows unsigned requests through
        to policy evaluation the same way)."""
        srv: "RgwRestServer" = self.server.rgw     # type: ignore
        auth = self.headers.get("Authorization", "")
        if not auth:
            return None
        m = _AUTH_RE.match(auth)
        if not m:
            raise S3Error("AccessDenied", "malformed auth")
        secret = srv.lookup_key(m.group("access"))
        if secret is None:
            raise S3Error("AccessDenied", "unknown access key")
        payload_sha = self.headers.get("x-amz-content-sha256",
                                       "UNSIGNED-PAYLOAD")
        if payload_sha != "UNSIGNED-PAYLOAD":
            # the signature only binds the HEADER value; the body must
            # match it or a captured signature could carry any payload
            if hashlib.sha256(body).hexdigest() != payload_sha:
                raise S3Error("SignatureDoesNotMatch",
                              "payload hash mismatch")
        amzdate = self.headers.get("x-amz-date", "")
        if not re.match(r"\d{8}T\d{6}Z$", amzdate):
            raise S3Error("AccessDenied", "missing or malformed x-amz-date")
        # freshness: AWS rejects requests outside a ~15-minute skew
        # window — without it any captured signature replays forever
        skew = getattr(srv, "max_skew", 900.0)
        if skew is not None:
            try:
                ts = datetime.datetime.strptime(
                    amzdate, "%Y%m%dT%H%M%SZ").replace(
                    tzinfo=datetime.timezone.utc).timestamp()
            except ValueError:   # 8+6 digits but not a real timestamp
                raise S3Error("AccessDenied", "malformed x-amz-date")
            if abs(srv.clock() - ts) > skew:
                raise S3Error("RequestTimeTooSkewed",
                              "request time too skewed")
        parsed = urllib.parse.urlsplit(self.path)
        hdrs = {"host": self.headers.get("Host", ""),
                "x-amz-date": amzdate,
                "x-amz-content-sha256": payload_sha}
        expect = sign_request(self.command, parsed.path, parsed.query,
                              hdrs, payload_sha, m.group("access"),
                              secret, m.group("region"))
        want_sig = _AUTH_RE.match(expect).group("sig")
        if not hmac.compare_digest(want_sig, m.group("sig")):
            raise S3Error("SignatureDoesNotMatch", "bad signature")
        return m.group("access")

    # -- plumbing ------------------------------------------------------------

    _cors_hdrs: dict | None = None

    def _respond(self, status: int, body: bytes = b"",
                 headers: dict | None = None) -> None:
        merged = dict(self._cors_hdrs or {})
        merged.update(headers or {})
        # HEAD: length of the real body, no bytes (RFC 9110)
        merged["Content-Length"] = str(len(body))
        self._out = (status, merged,
                     b"" if self.command == "HEAD" else body)

    def _dispatch(self) -> None:
        gw: S3Gateway = self.server.rgw.gateway     # type: ignore
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        self._cors_hdrs = None   # per-request (keep-alive reuses us)
        try:
            principal = self._authenticate(body)
            parsed = urllib.parse.urlsplit(self.path)
            q = dict(urllib.parse.parse_qsl(parsed.query,
                                            keep_blank_values=True))
            parts = parsed.path.lstrip("/").split("/", 1)
            bucket = urllib.parse.unquote(parts[0])
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            # tenant QoS lane: every rados op this request issues —
            # index omap, striped data, multipart staging — bills to
            # the authenticated user's tenant, so the OSDs' dmclock
            # schedulers arbitrate S3 traffic per tenant end-to-end
            with self.server.rgw.rados_lane(principal):
                self._route(gw, self.command, bucket, key, q, body,
                            principal)
        except S3Error as e:
            self._respond(e.status, _error_xml(e.code, str(e)),
                          {"Content-Type": "application/xml"})
        except Exception as e:   # pragma: no cover
            self._respond(500, _error_xml("InternalError", repr(e)),
                          {"Content-Type": "application/xml"})

    # -- routing -------------------------------------------------------------

    def _route(self, gw: S3Gateway, method: str, bucket: str, key: str,
               q: dict, body: bytes, principal: str | None) -> None:
        if not bucket:
            raise S3Error("InvalidArgument", "service-level ops: none")
        if method == "OPTIONS":
            # CORS preflight (rgw_cors: unauthenticated by design)
            return self._preflight(gw, bucket)
        # simple CORS: a matching rule decorates the ACTUAL response
        origin = self.headers.get("Origin")
        if origin:
            try:
                if gw.cors_match(bucket, origin, method) is not None:
                    self._cors_hdrs = {
                        "Access-Control-Allow-Origin": origin,
                        "Vary": "Origin"}
            except S3Error:
                pass
        if not key:
            return self._route_bucket(gw, method, bucket, q, body,
                                      principal)
        if "acl" in q:
            return self._route_object_acl(gw, method, bucket, key, q,
                                          body, principal)
        # grant-table gate (policy evaluated inside): reads need READ,
        # everything else WRITE — on the ADDRESSED version's grants
        # when the object carries its own
        avid = q.get("versionId") or None
        if method in ("GET", "HEAD"):
            gw.authorize(bucket, principal, write=False, key=key,
                         vid=avid)
        elif method == "DELETE":
            gw.authorize(bucket, principal, write=True, key=key,
                         action="s3:DeleteObject", vid=avid)
        else:
            gw.authorize(bucket, principal, write=True, key=key)
        if method == "POST" and "uploads" in q:
            meta = self._meta_headers()
            uid = gw.initiate_multipart(bucket, key, meta)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<InitiateMultipartUploadResult>"
                   + _x("Bucket", _esc(bucket)) + _x("Key", _esc(key))
                   + _x("UploadId", uid)
                   + "</InitiateMultipartUploadResult>").encode()
            return self._respond(200, xml)
        if method == "PUT" and "uploadId" in q and "partNumber" in q:
            copy_src = self.headers.get("x-amz-copy-source", "")
            if copy_src:
                # UploadPartCopy: the part's bytes come from an
                # existing (READ-authorized) object, optionally ranged
                sbucket, skey, svid = self._copy_source(gw, copy_src,
                                                        principal)
                rng = None
                rh = self.headers.get("x-amz-copy-source-range", "")
                if rh:
                    m2 = re.match(r"bytes=(\d+)-(\d+)$", rh)
                    if not m2:
                        raise S3Error("InvalidArgument",
                                      f"bad range {rh!r}")
                    rng = (int(m2.group(1)), int(m2.group(2)))
                etag = gw.upload_part_copy(
                    bucket, key, q["uploadId"], int(q["partNumber"]),
                    sbucket, skey, src_vid=svid, byte_range=rng)
                xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                       "<CopyPartResult>"
                       + _x("ETag", f'"{etag}"')
                       + "</CopyPartResult>").encode()
                return self._respond(200, xml)
            etag = gw.upload_part(bucket, key, q["uploadId"],
                                  int(q["partNumber"]), body)
            return self._respond(200, b"", {"ETag": f'"{etag}"'})
        if method == "POST" and "uploadId" in q:
            text = body.decode(errors="replace")
            parts = []
            for block in re.findall(r"<Part>(.*?)</Part>", text, re.S):
                num = re.search(r"<PartNumber>\s*(\d+)\s*</PartNumber>",
                                block)
                if num is None:
                    raise S3Error("MalformedXML", "part without number")
                et = re.search(
                    r"<ETag>\s*(?:&quot;|\")?([0-9a-f]+)", block)
                parts.append((int(num.group(1)),
                              et.group(1) if et else ""))
            if not parts:
                raise S3Error("MalformedXML", "no parts")
            etag = gw.complete_multipart(bucket, key, q["uploadId"],
                                         parts)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<CompleteMultipartUploadResult>"
                   + _x("Key", _esc(key)) + _x("ETag", f'"{etag}"')
                   + "</CompleteMultipartUploadResult>").encode()
            return self._respond(200, xml)
        if method == "DELETE" and "uploadId" in q:
            gw.abort_multipart(bucket, key, q["uploadId"])
            return self._respond(204)
        vid = q.get("versionId") or None
        if method == "PUT":
            copy_src = self.headers.get("x-amz-copy-source", "")
            if copy_src:
                # CopyObject: authorize READ on the SOURCE too, then
                # server-side copy (rgw_op.cc RGWCopyObj)
                sbucket, skey, svid = self._copy_source(gw, copy_src,
                                                        principal)
                directive = self.headers.get(
                    "x-amz-metadata-directive", "COPY").upper()
                if directive not in ("COPY", "REPLACE"):
                    raise S3Error("InvalidArgument",
                                  f"bad metadata directive "
                                  f"{directive!r}")
                meta = (self._meta_headers()
                        if directive == "REPLACE" else None)
                etag, put_vid = gw.copy_object(
                    sbucket, skey, bucket, key, src_vid=svid,
                    metadata=meta, owner=principal)
                hdrs = {}
                if put_vid:
                    hdrs["x-amz-version-id"] = put_vid
                xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                       "<CopyObjectResult>"
                       + _x("ETag", f'"{etag}"')
                       + "</CopyObjectResult>").encode()
                return self._respond(200, xml, hdrs)
            etag, put_vid = gw.put_object(bucket, key, body,
                                          self._meta_headers(),
                                          owner=principal)
            hdrs = {"ETag": f'"{etag}"'}
            if put_vid:
                hdrs["x-amz-version-id"] = put_vid
            return self._respond(200, b"", hdrs)
        if method == "GET":
            data, head = gw.get_object(bucket, key, vid)
            hdrs = {"Content-Type": "application/octet-stream",
                    "ETag": f'"{hashlib.md5(data).hexdigest()}"'}
            if head.get("version_id"):
                hdrs["x-amz-version-id"] = head["version_id"]
            for mk, mv in (head.get("meta") or {}).items():
                hdrs[f"x-amz-meta-{mk}"] = mv
            return self._respond(200, data, hdrs)
        if method == "HEAD":
            head = gw.head_object(bucket, key, vid)
            return self._respond(200, b"", {
                "Content-Length-Hint": str(head["size"])})
        if method == "DELETE":
            res = gw.delete_object(bucket, key, vid)
            hdrs = {}
            if res.get("delete_marker"):
                hdrs["x-amz-delete-marker"] = "true"
            if res.get("version_id"):
                hdrs["x-amz-version-id"] = res["version_id"]
            return self._respond(204, b"", hdrs)
        raise S3Error("InvalidArgument", f"unsupported {method}")

    _LC_RULE_RE = re.compile(r"<Rule>(.*?)</Rule>", re.S)

    def _route_bucket(self, gw: S3Gateway, method: str, bucket: str,
                      q: dict, body: bytes,
                      principal: str | None) -> None:
        if "versioning" in q:
            if method == "PUT":
                gw.authorize_owner(bucket, principal)
                m = re.search(r"<Status>\s*(\w+)\s*</Status>",
                              body.decode(errors="replace"))
                if not m:
                    raise S3Error("MalformedXML", "no Status")
                gw.set_versioning(bucket, m.group(1))
                return self._respond(200)
            if method == "GET":
                gw.authorize(bucket, principal, write=False)
                status = gw.get_versioning(bucket)
                xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                       "<VersioningConfiguration>"
                       + (_x("Status", status) if status else "")
                       + "</VersioningConfiguration>").encode()
                return self._respond(200, xml,
                                     {"Content-Type": "application/xml"})
            raise S3Error("InvalidArgument",
                          f"unsupported {method} on ?versioning")
        if "lifecycle" in q:
            gw.authorize_owner(bucket, principal)
            if method == "PUT":
                gw.set_lifecycle(bucket, self._parse_lc(body))
                return self._respond(200)
            if method == "GET":
                rules = gw.get_lifecycle(bucket)
                return self._respond(200, self._lc_xml(rules),
                                     {"Content-Type": "application/xml"})
            if method == "DELETE":
                gw.delete_lifecycle(bucket)
                return self._respond(204)
            raise S3Error("InvalidArgument",
                          f"unsupported {method} on ?lifecycle")
        if "acl" in q:
            if method == "PUT":
                gw.authorize_owner(bucket, principal)
                grants = self._parse_grants(body)
                if grants is not None:
                    gw.set_bucket_grants(bucket, grants)
                    return self._respond(200)
                canned = self.headers.get("x-amz-acl", "")
                if not canned:
                    raise S3Error("InvalidArgument",
                                  "need grants or canned x-amz-acl")
                gw.set_acl(bucket, canned)
                return self._respond(200)
            if method == "GET":
                gw.authorize_owner(bucket, principal)
                _acl, owner = gw.get_acl(bucket)
                grants = gw.get_bucket_grants(bucket)
                return self._respond(
                    200, self._grants_xml(grants, owner),
                    {"Content-Type": "application/xml"})
            raise S3Error("InvalidArgument",
                          f"unsupported {method} on ?acl")
        if "policy" in q:
            gw.authorize_owner(bucket, principal)
            if method == "PUT":
                gw.set_policy(bucket, body.decode(errors="replace"))
                return self._respond(204)
            if method == "GET":
                doc = gw.get_policy(bucket)
                if not doc:
                    raise S3Error("NoSuchBucketPolicy", bucket)
                return self._respond(200, doc.encode(),
                                     {"Content-Type":
                                      "application/json"})
            if method == "DELETE":
                gw.delete_policy(bucket)
                return self._respond(204)
            raise S3Error("InvalidArgument",
                          f"unsupported {method} on ?policy")
        if "cors" in q:
            gw.authorize_owner(bucket, principal)
            if method == "PUT":
                gw.set_cors(bucket, self._parse_cors(body))
                return self._respond(200)
            if method == "GET":
                rules = gw.get_cors(bucket)
                if not rules:
                    raise S3Error("NoSuchCORSConfiguration", bucket)
                items = "".join(
                    "<CORSRule>"
                    + "".join(_x("AllowedOrigin", _esc(o))
                              for o in r["origins"])
                    + "".join(_x("AllowedMethod", m)
                              for m in r["methods"])
                    + "".join(_x("AllowedHeader", _esc(h))
                              for h in r.get("headers", []))
                    + (_x("MaxAgeSeconds", str(r["max_age"]))
                       if r.get("max_age") else "")
                    + "</CORSRule>" for r in rules)
                xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                       "<CORSConfiguration>" + items
                       + "</CORSConfiguration>").encode()
                return self._respond(200, xml,
                                     {"Content-Type":
                                      "application/xml"})
            if method == "DELETE":
                gw.delete_cors(bucket)
                return self._respond(204)
            raise S3Error("InvalidArgument",
                          f"unsupported {method} on ?cors")
        if method == "GET" and "versions" in q:
            gw.authorize(bucket, principal, write=False)
            return self._respond_versions(gw, bucket, q)
        if method == "PUT":
            if principal is None:
                raise S3Error("AccessDenied",
                              "anonymous bucket creation")
            gw.create_bucket(bucket, owner=principal,
                             acl=self.headers.get("x-amz-acl", "private"))
            return self._respond(200)
        if method == "DELETE":
            gw.authorize_owner(bucket, principal)
            gw.delete_bucket(bucket)
            return self._respond(204)
        if method == "GET":
            gw.authorize(bucket, principal, write=False)
            max_keys = max(1, min(int(q.get("max-keys", 1000)), 1000))
            entries, next_token = gw.list_objects(
                bucket, q.get("prefix", ""), max_keys,
                q.get("continuation-token", ""))
            items = "".join(
                "<Contents>" + _x("Key", _esc(k))
                + _x("Size", str(h.get("size", 0)))
                + _x("LastModified", datetime.datetime.fromtimestamp(
                    h.get("mtime", 0),
                    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"))
                + "</Contents>"
                for k, h in entries)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<ListBucketResult>"
                   + _x("Name", _esc(bucket))
                   + _x("KeyCount", str(len(entries)))
                   + _x("IsTruncated", "true" if next_token else "false")
                   + (_x("NextContinuationToken", _esc(next_token))
                      if next_token else "")
                   + items + "</ListBucketResult>").encode()
            return self._respond(200, xml,
                                 {"Content-Type": "application/xml"})
        raise S3Error("InvalidArgument", f"unsupported {method} on bucket")

    def _copy_source(self, gw: S3Gateway, copy_src: str,
                     principal: str | None) -> tuple[str, str,
                                                     str | None]:
        """Parse + READ-authorize an x-amz-copy-source value (shared
        by CopyObject and UploadPartCopy): (bucket, key, versionId)."""
        srcq = urllib.parse.urlsplit(copy_src)
        sparts = urllib.parse.unquote(
            srcq.path).lstrip("/").split("/", 1)
        if len(sparts) != 2 or not sparts[1]:
            raise S3Error("InvalidArgument",
                          "copy source must be /bucket/key")
        sbucket, skey = sparts
        svid = dict(urllib.parse.parse_qsl(srcq.query)).get("versionId")
        gw.authorize(sbucket, principal, write=False, key=skey,
                     vid=svid)
        return sbucket, skey, svid

    # -- CORS (rgw_cors.cc) ---------------------------------------------------

    def _preflight(self, gw: S3Gateway, bucket: str) -> None:
        origin = self.headers.get("Origin", "")
        want_method = self.headers.get("Access-Control-Request-Method",
                                       "")
        want_headers = [h.strip() for h in
                        (self.headers.get(
                            "Access-Control-Request-Headers") or ""
                         ).split(",") if h.strip()]
        if not origin or not want_method:
            raise S3Error("InvalidArgument",
                          "preflight needs Origin + "
                          "Access-Control-Request-Method")
        rule = gw.cors_match(bucket, origin, want_method, want_headers)
        if rule is None:
            return self._respond(
                403, _error_xml("AccessForbidden",
                                "CORSResponse: no matching rule"),
                {"Content-Type": "application/xml"})
        hdrs = {"Access-Control-Allow-Origin": origin,
                "Access-Control-Allow-Methods": ", ".join(rule.methods),
                "Vary": "Origin"}
        if want_headers:
            hdrs["Access-Control-Allow-Headers"] = ", ".join(
                want_headers)
        if rule.max_age:
            hdrs["Access-Control-Max-Age"] = str(rule.max_age)
        return self._respond(200, b"", hdrs)

    _CORS_RULE_RE = re.compile(r"<CORSRule>(.*?)</CORSRule>", re.S)

    def _parse_cors(self, body: bytes) -> list[dict]:
        txt = body.decode(errors="replace")
        rules = []
        for block in self._CORS_RULE_RE.findall(txt):
            age = re.search(r"<MaxAgeSeconds>\s*(\d+)", block)
            rules.append({
                "origins": re.findall(
                    r"<AllowedOrigin>\s*([^<]+?)\s*</AllowedOrigin>",
                    block),
                "methods": re.findall(
                    r"<AllowedMethod>\s*([^<]+?)\s*</AllowedMethod>",
                    block),
                "headers": re.findall(
                    r"<AllowedHeader>\s*([^<]+?)\s*</AllowedHeader>",
                    block),
                "max_age": int(age.group(1)) if age else 0,
            })
        if not rules:
            raise S3Error("MalformedXML", "no CORSRule")
        return rules

    # -- ACL grants (rgw_acl_s3.cc parsing, reduced) --------------------------

    _GRANT_HDRS = {"x-amz-grant-read": "READ",
                   "x-amz-grant-write": "WRITE",
                   "x-amz-grant-read-acp": "READ_ACP",
                   "x-amz-grant-write-acp": "WRITE_ACP",
                   "x-amz-grant-full-control": "FULL_CONTROL"}

    @staticmethod
    def _group_grantee(uri: str) -> str:
        """Map a group URI to its grantee — ONLY the two groups we
        implement; an unknown group must be refused, never silently
        widened to AllUsers."""
        if uri.endswith("/AuthenticatedUsers"):
            return "authenticated"
        if uri.endswith("/AllUsers"):
            return "*"
        raise S3Error("InvalidArgument",
                      f"unsupported grantee group {uri!r}")

    @classmethod
    def _grantee_of(cls, token: str) -> str:
        token = token.strip().strip('"')
        if token.startswith("id="):
            return token[3:].strip('"')
        if token.startswith("uri="):
            return cls._group_grantee(token[4:])
        return token

    def _parse_grants(self, body: bytes) -> list[dict] | None:
        """Grant list from an XML AccessControlPolicy body or the
        x-amz-grant-* headers; None when neither is present (caller
        falls back to the canned x-amz-acl header)."""
        txt = body.decode(errors="replace")
        if "<Grant>" in txt:
            grants = []
            for block in re.findall(r"<Grant>(.*?)</Grant>", txt, re.S):
                perm = re.search(
                    r"<Permission>\s*([A-Z_]+)\s*</Permission>", block)
                idm = re.search(r"<ID>\s*([^<]+?)\s*</ID>", block)
                uri = re.search(r"<URI>\s*([^<]+?)\s*</URI>", block)
                if perm is None or (idm is None and uri is None):
                    raise S3Error("MalformedACLError",
                                  "grant needs Permission + grantee")
                if uri is not None:
                    grantee = self._group_grantee(uri.group(1))
                else:
                    grantee = idm.group(1)
                grants.append({"grantee": grantee,
                               "permission": perm.group(1)})
            return grants
        grants = []
        for hdr, perm in self._GRANT_HDRS.items():
            v = self.headers.get(hdr)
            if not v:
                continue
            for token in v.split(","):
                if token.strip():
                    grants.append({"grantee": self._grantee_of(token),
                                   "permission": perm})
        return grants or None

    @staticmethod
    def _grants_xml(grants: list[dict], owner: str) -> bytes:
        items = "".join(
            "<Grant><Grantee>"
            + (_x("URI", "http://acs.amazonaws.com/groups/global/"
                  + ("AllUsers" if g["grantee"] == "*"
                     else "AuthenticatedUsers"))
               if g["grantee"] in ("*", "authenticated")
               else _x("ID", _esc(g["grantee"])))
            + "</Grantee>" + _x("Permission", g["permission"])
            + "</Grant>"
            for g in grants)
        return ('<?xml version="1.0" encoding="UTF-8"?>'
                "<AccessControlPolicy>"
                + _x("Owner", _x("ID", _esc(owner)))
                + _x("AccessControlList", items)
                + "</AccessControlPolicy>").encode()

    def _route_object_acl(self, gw: S3Gateway, method: str,
                          bucket: str, key: str, q: dict, body: bytes,
                          principal: str | None) -> None:
        """GET/PUT /bucket/key?acl — per-OBJECT grant lists
        (rgw_acl.h: a second user gets access to one object without
        the bucket going public)."""
        vid = q.get("versionId") or None
        if method == "GET":
            gw.authorize(bucket, principal, write=False, key=key,
                         action="s3:GetObjectAcl", vid=vid)
            ent = gw.head_object(bucket, key, vid)
            owner = ent.get("owner") \
                or gw._bucket(bucket).meta_all().get("owner") or ""
            grants = ent.get("acl_grants") \
                or [{"grantee": owner, "permission": "FULL_CONTROL"}]
            return self._respond(200, self._grants_xml(grants, owner),
                                 {"Content-Type": "application/xml"})
        if method == "PUT":
            gw.authorize(bucket, principal, write=True, key=key,
                         action="s3:PutObjectAcl", vid=vid)
            grants = self._parse_grants(body)
            if grants is None:
                canned = self.headers.get("x-amz-acl", "")
                if not canned:
                    raise S3Error("InvalidArgument",
                                  "no grants and no canned acl")
                from ceph_tpu import rgw_auth
                ent = gw.head_object(bucket, key, vid)
                owner = ent.get("owner") \
                    or gw._bucket(bucket).meta_all().get("owner") or ""
                grants = rgw_auth.canned_grants(canned, owner)
            gw.set_object_grants(bucket, key, grants, vid=vid)
            return self._respond(200)
        raise S3Error("InvalidArgument", f"unsupported {method} on ?acl")

    def _meta_headers(self) -> dict:
        return {k[len("x-amz-meta-"):]: v for k, v in self.headers.items()
                if k.lower().startswith("x-amz-meta-")}

    def _respond_versions(self, gw: S3Gateway, bucket: str,
                          q: dict) -> None:
        max_keys = max(1, min(int(q.get("max-keys", 1000)), 1000))
        rows, truncated = gw.list_versions(
            bucket, q.get("prefix", ""), max_keys,
            q.get("key-marker", ""), q.get("version-id-marker", ""))
        items = []
        for key, e, latest in rows:
            tag = "DeleteMarker" if e.get("delete_marker") else "Version"
            items.append(
                f"<{tag}>" + _x("Key", _esc(key))
                + _x("VersionId", _esc(e.get("version_id", "null")))
                + _x("IsLatest", "true" if latest else "false")
                + _x("Size", str(e.get("size", 0)))
                + _x("LastModified", datetime.datetime.fromtimestamp(
                    e.get("mtime", 0), datetime.timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%SZ"))
                + f"</{tag}>")
        nxt = ""
        if truncated and rows:
            lk, le, _ = rows[-1]
            nxt = (_x("NextKeyMarker", _esc(lk))
                   + _x("NextVersionIdMarker",
                        _esc(le.get("version_id", ""))))
        xml = ('<?xml version="1.0" encoding="UTF-8"?>'
               "<ListVersionsResult>"
               + _x("Name", _esc(bucket))
               + _x("IsTruncated", "true" if truncated else "false")
               + nxt
               + "".join(items) + "</ListVersionsResult>").encode()
        self._respond(200, xml, {"Content-Type": "application/xml"})

    def _parse_lc(self, body: bytes) -> list[dict]:
        """Reduced lifecycle XML: Rule{ID, Prefix|Filter/Prefix, Status,
        Expiration/Days, NoncurrentVersionExpiration/NoncurrentDays}."""
        text = body.decode(errors="replace")
        rules = []
        for block in self._LC_RULE_RE.findall(text):
            rule: dict = {}
            m = re.search(r"<ID>\s*(.*?)\s*</ID>", block, re.S)
            if m:
                rule["id"] = m.group(1)
            m = re.search(r"<Prefix>\s*(.*?)\s*</Prefix>", block, re.S)
            rule["prefix"] = m.group(1) if m else ""
            m = re.search(r"<Status>\s*(\w+)\s*</Status>", block)
            rule["status"] = m.group(1) if m else "Enabled"
            m = re.search(r"<Expiration>.*?<Days>\s*(\d+)\s*</Days>.*?"
                          r"</Expiration>", block, re.S)
            if m:
                rule["expiration_days"] = int(m.group(1))
            m = re.search(r"<NoncurrentVersionExpiration>.*?"
                          r"<NoncurrentDays>\s*(\d+)\s*</NoncurrentDays>"
                          r".*?</NoncurrentVersionExpiration>", block, re.S)
            if m:
                rule["noncurrent_days"] = int(m.group(1))
            rules.append(rule)
        if not rules:
            raise S3Error("MalformedXML", "no lifecycle rules")
        return rules

    @staticmethod
    def _lc_xml(rules: list[dict]) -> bytes:
        blocks = []
        for r in rules:
            b = "<Rule>"
            if r.get("id"):
                b += _x("ID", _esc(r["id"]))
            b += _x("Prefix", _esc(r.get("prefix", "")))
            b += _x("Status", r.get("status", "Enabled"))
            if r.get("expiration_days"):
                b += _x("Expiration", _x("Days",
                                         str(r["expiration_days"])))
            if r.get("noncurrent_days"):
                b += _x("NoncurrentVersionExpiration",
                        _x("NoncurrentDays", str(r["noncurrent_days"])))
            blocks.append(b + "</Rule>")
        return ('<?xml version="1.0" encoding="UTF-8"?>'
                "<LifecycleConfiguration>" + "".join(blocks)
                + "</LifecycleConfiguration>").encode()


#: pool-resident user registry (the reference stores RGW users as
#: rados objects, src/rgw/rgw_user.cc): access-key -> json record
USERS_OID = ".users.registry"


def load_pool_users(ioctx) -> dict[str, dict]:
    """access -> {"secret", "uid", "created"} from the pool registry."""
    try:
        omap = ioctx.get_omap(USERS_OID)
    except OSError:
        return {}
    out = {}
    for k, v in omap.items():
        try:
            out[k] = json.loads(v.decode())
        except ValueError:
            continue
    return out


def save_pool_user(ioctx, access: str, secret: str, uid: str,
                   tenant: str | None = None) -> None:
    """tenant names the user's QoS lane (rgw_user tenant field); it
    defaults to the uid so every user is its own lane until an
    operator groups users under a shared tenant."""
    ioctx.set_omap(USERS_OID, {access: json.dumps(
        {"secret": secret, "uid": uid, "tenant": tenant or uid,
         "created": time.time()}).encode()})


def remove_pool_user(ioctx, access: str) -> None:
    ioctx.rm_omap_keys(USERS_OID, [access])


def derive_s3_credentials(cluster_key: bytes | str) -> tuple[str, str]:
    """Deterministic S3 credential pair from cluster auth material (the
    AuthMonitor-issues-rgw-credentials analog) — ONE definition shared
    by the server's provisioning and by operators deriving the same
    pair out-of-band."""
    if isinstance(cluster_key, str):
        cluster_key = cluster_key.encode()
    access = "AK" + hashlib.sha256(b"rgw-access" + cluster_key
                                   ).hexdigest()[:18].upper()
    secret = hashlib.sha256(b"rgw-secret" + cluster_key).hexdigest()
    return access, secret


class RgwRestServer:
    """The radosgw daemon shell: HTTP frontend + gateway + key table.

    Access keys are provisioned from cluster auth material:
    ``add_key(access, secret)``; with a cephx-lite cluster key,
    ``provision_from_cephx(key)`` derives a deterministic S3 credential
    pair from it (the AuthMonitor-issues-rgw-credentials analog).
    """

    def __init__(self, ioctx, addr: str = "127.0.0.1:0",
                 compression: str = "none",
                 max_skew: float | None = 900.0, clock=time.time,
                 lc_interval: float | None = None, ctx=None,
                 frontend_workers: int = 8):
        self.gateway = S3Gateway(ioctx, compression=compression,
                                 clock=clock)
        # gateway perf set (rgw's l_rgw_* counters): op counts by verb,
        # bytes in/out, request latency — registered into the context's
        # collection so `perf dump` and the prometheus scrape see it
        from ceph_tpu.common.context import default_context
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("rgw")
                     .add_u64("req").add_u64("failed_req")
                     .add_u64("get").add_u64("put").add_u64("delete")
                     .add_u64("head").add_u64("post")
                     .add_u64("bytes_recv").add_u64("bytes_sent")
                     .add_time_avg("req_lat")
                     .create_perf_counters())
        self._perf_coll = (ctx or default_context()).perf
        self._perf_coll.add(self.perf)
        self.keys: dict[str, str] = {}
        #: access key -> QoS tenant lane for in-memory keys (pool
        #: users carry their tenant in the registry record)
        self.key_tenants: dict[str, str] = {}
        #: SigV4 freshness window in seconds (AWS: 15 min); None
        #: disables the check.  clock is injectable for tests.
        self.max_skew = max_skew
        self.clock = clock
        #: lifecycle agent cadence (rgw_lc.cc lc_thread); None = manual
        #: (call gateway.lifecycle_pass() — what the tests do with a
        #: fake clock)
        self.lc_interval = lc_interval
        self._lc_stop = threading.Event()
        self._lc_thread: threading.Thread | None = None
        #: event-driven frontend (rgw_asio_frontend analog): one I/O
        #: loop owning the sockets + a bounded handler pool, replacing
        #: the old thread-per-connection stdlib server.  The pool must
        #: exceed the expected concurrent-request fan-in or tenants
        #: head-of-line block each other at HTTP before the OSDs'
        #: dmclock lanes ever see their ops (rgw_thread_pool_size)
        self._frontend = AsyncHttpFrontend(
            lambda req: self._handle_counted(req), addr,
            workers=frontend_workers)

    def _handle_counted(self, req) -> tuple[int, dict, bytes]:
        """Request entry: route through _S3Request under the perf set.
        An escaping exception (the frontend serves it as a 500) still
        records latency and failed_req — req and req_lat avgcount must
        never diverge."""
        t0 = time.perf_counter()
        self.perf.inc("req")
        self.perf.inc("bytes_recv", len(req.body or b""))
        verb = req.method.lower()
        if verb in ("get", "put", "delete", "head", "post"):
            self.perf.inc(verb)
        status, body = 500, b""
        try:
            status, headers, body = _S3Request(self, req).handle()
            return status, headers, body
        finally:
            if status >= 500:
                self.perf.inc("failed_req")
            self.perf.inc("bytes_sent", len(body or b""))
            self.perf.tinc("req_lat", time.perf_counter() - t0)

    @property
    def addr(self) -> str:
        return self._frontend.addr

    def add_key(self, access: str, secret: str,
                tenant: str | None = None) -> None:
        self.keys[access] = secret
        if tenant:
            self.key_tenants[access] = tenant

    #: pool-user cache TTL: radosgw-admin created users become usable
    #: within this window without a gateway restart
    USER_CACHE_TTL = 2.0

    def _pool_user_table(self) -> dict:
        """The pool user registry behind ONE shared TTL read-through
        cache (lookup_key and tenant_of both consult it — without the
        sharing every authenticated request would pay a rados round
        trip for its tenant lookup)."""
        now = self.clock()
        cached = getattr(self, "_user_cache", None)
        if cached is None or now - cached[0] > self.USER_CACHE_TTL:
            cached = (now, load_pool_users(self.gateway.io))
            self._user_cache = cached
        return cached[1]

    def lookup_key(self, access: str) -> str | None:
        """Secret for an access key: the in-memory table first, then
        the POOL user registry (radosgw-admin's store) with a short
        read-through cache."""
        secret = self.keys.get(access)
        if secret is not None:
            return secret
        rec = self._pool_user_table().get(access)
        return rec["secret"] if rec else None

    def tenant_of(self, access: str | None) -> str | None:
        """QoS tenant lane for an authenticated principal: the
        explicit add_key tenant, then the pool user record's tenant
        (defaulting to its uid), then the access key itself — every
        authenticated identity lands in SOME lane.  In-memory keys
        without a tenant short-circuit before the pool table: their
        lane is the access key, no registry read needed."""
        if not access:
            return None
        tenant = self.key_tenants.get(access)
        if tenant:
            return tenant
        if access in self.keys:
            return access
        rec = self._pool_user_table().get(access)
        if rec:
            return rec.get("tenant") or rec.get("uid") or access
        return access

    def rados_lane(self, principal: str | None):
        """Context manager billing the calling thread's rados ops to
        the principal's tenant lane (no-op for anonymous requests or
        non-rados io handles — unit tests run the gateway over plain
        dict-backed stubs)."""
        import contextlib
        client = getattr(self.gateway.io, "client", None)
        tenant = self.tenant_of(principal)
        if tenant is None or client is None \
                or not hasattr(client, "qos_tenant"):
            return contextlib.nullcontext()
        return client.qos_tenant(tenant)

    def provision_from_cephx(self, cluster_key: bytes | str
                             ) -> tuple[str, str]:
        access, secret = derive_s3_credentials(cluster_key)
        self.add_key(access, secret)
        return access, secret

    def start(self) -> "RgwRestServer":
        self._frontend.start()
        if self.lc_interval:
            self._lc_thread = threading.Thread(
                target=self._lc_loop, name="rgw-lc", daemon=True)
            self._lc_thread.start()
        return self

    def _lc_loop(self) -> None:
        while not self._lc_stop.wait(self.lc_interval):
            try:
                self.gateway.lifecycle_pass()
            except Exception:   # agent must survive transient pool errors
                pass

    def shutdown(self) -> None:
        self._lc_stop.set()
        if self._lc_thread is not None:
            self._lc_thread.join(timeout=5)
        self._frontend.stop()
        # deregister only if the collection still holds OUR set (a
        # later gateway instance may have replaced it)
        if self._perf_coll.get(self.perf.name) is self.perf:
            self._perf_coll.remove(self.perf.name)
