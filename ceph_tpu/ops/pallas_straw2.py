"""Fused Pallas TPU kernels for the CRUSH straw2 column draws.

The XLA path (ops.straw2_u32 driven by crush.fastpath) is bit-exact but
this backend leaves long u32 elementwise chains unfused: a single
(65536, 256) draw column costs ~25 ms against a ~0.5 ms roofline, with
hundreds of materialized (N, S) intermediates.  These kernels fuse one
whole column — rjenkins hash, crush_ln limb pipeline, magic division,
first-min winner select, and the is_out verdict — into one VMEM-resident
Pallas program per (r, block) grid step:

  root kernel:  xs block -> winner position/id per r  (+ is_out for flat
                rules, whose first level already lands on devices)
  leaf kernel:  root winner position -> the winning host's device row
                (fetched with an exact f32 one-hot MXU dot — a vectorized
                row gather the VPU cannot do) -> device winner + is_out

Bit-exactness contract: identical output to ops.straw2_u32 (itself
validated exhaustively against the s64 kernel and the scalar C-semantics
oracle).  tests/test_pallas_straw2.py compares both, exhaustively over
the 16-bit hash domain for the ln/divide pipeline and end-to-end on
random maps, in interpret mode on CPU and compiled on TPU.

Table lookups ride the MXU as exact one-hot matmuls (8-bit limbs in
bf16, one-hot 0/1 exact; f32 accumulator sums < 2^15).  The count-
leading-zeros of the ln normalization uses the f32 exponent field
(exact: inputs < 2^17 convert exactly).  All element math is u32/i32 —
no 64-bit emulation anywhere.
"""

from __future__ import annotations

import functools
import sys

# the unrolled R-column kernels build deep expression trees; default
# CPython recursion limits trip inside jax lowering
if sys.getrecursionlimit() < 20000:
    sys.setrecursionlimit(20000)

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.ops.crush_kernel import (
    _ln_limb_operands_np, hash32_2, hash32_3)

_U32 = jnp.uint32
_I32 = jnp.int32

#: rows per grid step (TPU blocks need a 128-divisible last dim).  512
#: measures fastest on v5e for the bulk-mapping shapes: fewer grid steps
#: amortize the per-step block/table traffic, and the (512, 128) slab
#: temporaries still fit VMEM comfortably.
BLOCK = 512

#: batch rows per grid step for the candidate-filter kernels: their
#: working set (approx bands + keys + 9 gathered operand planes) tops
#: 16 MB VMEM at 512 rows
CAND_BLOCK = 128


def _bitlen_f32(v):
    """bit length of v (uint32, v < 2^17) via the f32 exponent field —
    Mosaic-safe replacement for lax.clz; exact because the convert is."""
    # Mosaic has no u32->f32 cast; go through i32 (values < 2^17, safe)
    f = (v | _U32(1)).astype(_I32).astype(jnp.float32)
    e = (jax.lax.bitcast_convert_type(f, _U32) >> 23) - _U32(127)
    return e + _U32(1)


def _row_lookup(idx, row):
    """Per-lane table lookup: idx (B, S) i32 with values < S; row (S,)
    shared table — or (B, S) per-row tables.  Lowers to Mosaic's
    tpu.dynamic_gather (take_along_axis on same-shaped 2-D operands) —
    a lane shuffle, with none of the one-hot matmul's VMEM or reshape
    trouble."""
    x = (jnp.broadcast_to(row[None, :], idx.shape) if row.ndim == 1
         else row)
    # raw lax.gather with i32 indices: jnp.take_along_axis promotes its
    # indices to i64 under x64, which Mosaic cannot lower.  These
    # dimension numbers are exactly the per-lane tpu.dynamic_gather
    # pattern Mosaic's gather rule recognizes.
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(1,), start_index_map=(1,),
        operand_batching_dims=(0,), start_indices_batching_dims=(0,))
    return jax.lax.gather(
        x, idx[..., None], dnums, slice_sizes=(1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def _ln_p48_pl(u, rhlh_ref, ll_lo_ref, ll_hi_ref, rh128):
    """P = 2^48 - crush_ln(u) as (p_hi17, p_lo32) u32 — the Pallas twin
    of straw2_u32._crush_ln_p48.

    rhlh_ref (13, S): limb j's table for k in [0, 127]; rh128 is the
    k == 128 row as python constants (tables must fit the S-lane gather
    width, and the leaf kernel runs at S = 128).  ll_lo/ll_hi (6, S):
    the 256-entry LL table split at row 128 the same way.
    """
    x = u.astype(_U32) + _U32(1)
    low17 = x & _U32(0x1FFFF)
    bits = _U32(16) - _bitlen_f32(low17)
    needs_norm = (x & _U32(0x18000)) == 0
    xnorm = jnp.where(needs_norm, x << bits, x).astype(_I32)
    iexpon = jnp.where(needs_norm, _U32(15) - bits, _U32(15)).astype(_I32)
    idx1 = (xnorm.astype(_U32) >> 8) << 1
    k = ((idx1 - _U32(256)) >> 1).astype(_I32)
    k_cap = jnp.minimum(k, _I32(127))
    is128 = k == _I32(128)
    rhlh = [jnp.where(is128, _I32(rh128[j]),
                      _row_lookup(k_cap, rhlh_ref[j, :]))
            for j in range(13)]
    acc = jnp.zeros_like(xnorm)
    for j in range(7):
        acc = (acc >> 8) + xnorm * rhlh[j]
    idx2 = acc & _I32(0xFF)
    lo7 = idx2 & _I32(127)
    hi_half = idx2 >= _I32(128)
    ll = [jnp.where(hi_half, _row_lookup(lo7, ll_hi_ref[j, :]),
                    _row_lookup(lo7, ll_lo_ref[j, :]))
          for j in range(6)]
    bj = []
    carry = jnp.zeros_like(xnorm)
    for j in range(6):
        t = rhlh[7 + j] + ll[j] + carry
        bj.append(t & _I32(0xFF))
        carry = t >> 8
    bj.append(carry)
    v = [((bj[j] >> 4) | ((bj[j + 1] & _I32(0xF)) << 4)) for j in range(6)]
    v[5] = v[5] + ((iexpon & _I32(0xF)) << 4)
    ln_lo = (v[0] | (v[1] << 8) | (v[2] << 16)).astype(_U32) \
        | (v[3].astype(_U32) << 24)
    ln_hi = (v[4] | (v[5] << 8)).astype(_U32)
    is_zero = (ln_lo == 0) & (ln_hi == 0)
    p_lo = (~ln_lo) + _U32(1)
    carry_in = jnp.where(ln_lo == 0, _U32(1), _U32(0))
    p_hi = (((~ln_hi) & _U32(0xFFFF)) + carry_in) & _U32(0x1FFFF)
    p_lo = jnp.where(is_zero, _U32(0), p_lo)
    p_hi = jnp.where(is_zero, _U32(0x10000), p_hi)
    return p_hi, p_lo


def _magic_div_pl(p_hi, p_lo, magic, off):
    """floor(P/w): the shared magic-multiply (straw2_u32) with magic as
    a list of 5 (B, S) limb planes — one implementation for both the
    XLA path and these kernels (pure jnp, Mosaic-safe)."""
    from ceph_tpu.ops.straw2_u32 import magic_divide_planes
    return magic_divide_planes(p_hi, p_lo, magic, off)


def _umin(v, axis, keepdims):
    """u32 min via the order-preserving signed bias (Mosaic has no
    unsigned reductions)."""
    s = (v ^ _U32(0x80000000)).astype(_I32)
    m = jnp.min(s, axis=axis, keepdims=keepdims)
    return m.astype(_U32) ^ _U32(0x80000000)


def _ult(a, b):
    """unsigned < via the sign bias (Mosaic lacks unsigned compares)."""
    return ((a ^ _U32(0x80000000)).astype(_I32)
            < (b ^ _U32(0x80000000)).astype(_I32))


def _first_min(q_hi, q_lo, ids):
    """Lexicographic first minimum along axis 1: winner q pair, position,
    id, and the winner one-hot mask (for gathering sibling values)."""
    b, s = q_hi.shape
    min_hi = _umin(q_hi, 1, True)
    on_h = q_hi == min_hi
    lo_m = jnp.where(on_h, q_lo, _U32(0xFFFFFFFF))
    min_lo = _umin(lo_m, 1, True)
    on = on_h & (lo_m == min_lo)
    # "first index wins": the smallest position among the tied minima
    # (no cumsum in Mosaic — a masked min over iota does the same)
    iota = jax.lax.broadcasted_iota(_I32, (b, s), 1)
    pos_m = jnp.where(on, iota, _I32(2 ** 31 - 1))
    minpos = jnp.min(pos_m, axis=1, keepdims=True)
    first = on & (iota == minpos)
    pos = minpos[:, 0]
    # dtype pinned: with x64 enabled jnp.sum promotes i32 -> i64,
    # which Mosaic cannot lower
    wid = jnp.sum(jnp.where(first, ids, _I32(0)), axis=1, dtype=_I32)
    return min_hi[:, 0], min_lo[:, 0], pos, wid, first


def _draw_slab(x, ids, wz, magic_planes, off, tabs, r):
    """One 128-lane slab of a straw2 column: (B,) x, (B, 128) item
    operands -> winner (q_hi, q_lo, pos, wid, first).  Slabs are 128 wide
    because tpu.dynamic_gather shuffles within a single vreg."""
    rhlh_ref, ll_lo_ref, ll_hi_ref, rh128 = tabs
    u = hash32_3(x[:, None], ids, r) & _U32(0xFFFF)
    p_hi, p_lo = _ln_p48_pl(u, rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)
    q_hi, q_lo = _magic_div_pl(p_hi, p_lo, magic_planes, off)
    bad = wz != 0
    q_hi = jnp.where(bad, _U32(0xFFFFFFFF), q_hi)
    q_lo = jnp.where(bad, _U32(0xFFFFFFFF), q_lo)
    return _first_min(q_hi, q_lo, ids)


def _merge_slabs(best, new):
    """Merge a later slab's winner into the running best: strictly
    smaller (q_hi, q_lo) wins — ties stay with the earlier slab, whose
    positions are lower (the first-index rule)."""
    if best is None:
        return new
    bqh, bql, bpos, bwid, brw = best
    nqh, nql, npos, nwid, nrw = new
    better = _ult(nqh, bqh) | ((nqh == bqh) & _ult(nql, bql))
    return (jnp.where(better, nqh, bqh), jnp.where(better, nql, bql),
            jnp.where(better, npos, bpos), jnp.where(better, nwid, bwid),
            jnp.where(better, nrw, brw))


def _column_over_slabs(x, S, tabs, r, slab_operands, rw_of_slab):
    """Full-bucket column: iterate 128-wide slabs, merge winners.
    slab_operands(slab) -> (ids, wz, magic[5], off) as (B, 128) values;
    rw_of_slab(slab, first) -> (B,) winner reweight (or zeros)."""
    best = None
    for slab in range(S // 128):
        ids, wz, magic, off = slab_operands(slab)
        qh, ql, pos, wid, first = _draw_slab(x, ids, wz, magic, off,
                                             tabs, r)
        rwv = rw_of_slab(slab, first)
        pos = pos + _I32(slab * 128)
        best = _merge_slabs(best, (qh, ql, pos, wid, rwv))
    return best


def _store_row(ref, r, value):
    """Write one (B,) row at dynamic sublane index r of an (R, B) ref."""
    ref[pl.dslice(r, 1), :] = value[None, :]


def _root_kernel(xs_ref, ids_ref, wz_ref, magic_ref, off_ref,
                 rhlh_ref, ll_lo_ref, ll_hi_ref,
                 pos_ref, id_ref, *, S, rh128):
    """Grid (n//B, R): one (block, r) column per step — r rides the grid
    so the kernel stays small enough for Mosaic to compile quickly.

    is_out verdicts are NOT computed here: they are elementwise in
    (winner, x) and run as one cheap XLA op over the output columns
    (crush_kernel.is_out).  Keeping them out of the kernel also dodged a
    real Mosaic miscompile: hash32_2 fed from the gather/sum winner
    pipeline produced wrong values for ~0.03% of lanes (see r03 notes in
    fastpath._winners_cols)."""
    r = pl.program_id(1)
    x = xs_ref[0, :]
    tabs = (rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)

    def operands(slab):
        sl = slice(slab * 128, (slab + 1) * 128)
        return (ids_ref[0, sl][None, :], wz_ref[0, sl][None, :],
                [magic_ref[j, sl][None, :].astype(_U32) for j in range(5)],
                off_ref[0, sl][None, :])

    def rw_of(slab, first):
        return jnp.zeros((x.shape[0],), dtype=_I32)

    _qh, _ql, pos, wid, _rwv = _column_over_slabs(
        x, S, tabs, r.astype(_U32), operands, rw_of)
    _store_row(pos_ref, r, pos)
    _store_row(id_ref, r, wid)


def _leaf_kernel(xs_ref, pos_ref, static_ref,
                 rhlh_ref, ll_lo_ref, ll_hi_ref,
                 id_ref, *, H, S, vary_r, rh128):
    r = pl.program_id(1)
    if vary_r:
        r_leaf = (r >> (vary_r - 1)).astype(_U32)
    else:
        r_leaf = _U32(0)
    x = xs_ref[0, :]
    iota = jax.lax.broadcasted_iota(_I32, (1, H), 1)
    tabs = (rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)
    pos = pos_ref[pl.dslice(r, 1), :][0, :]   # this r's root winners
    # exact f32 one-hot row gather of the winning host's packed
    # fields: [ids | wz | off | magic0..magic4] (each S wide) — a
    # vectorized row gather on the MXU
    oh = jnp.where(pos[:, None] == iota, jnp.float32(1.0),
                   jnp.float32(0.0))
    # HIGHEST precision: the default TPU matmul truncates f32 operands
    # to bf16, mangling ids and 16-bit magic limbs
    rows = jnp.dot(oh, static_ref[...],
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)   # (B, 8*S)

    def operands(slab):
        sl = slice(slab * 128, (slab + 1) * 128)
        # f32 -> u32 is an unhandled Mosaic cast; go via i32 (limb
        # values < 2^16, so fptosi is exact)
        return (rows[:, sl].astype(_I32),
                rows[:, S + slab * 128:S + (slab + 1) * 128]
                .astype(_I32),
                [rows[:, (3 + j) * S + slab * 128:
                      (3 + j) * S + (slab + 1) * 128]
                 .astype(_I32).astype(_U32) for j in range(5)],
                rows[:, 2 * S + slab * 128:2 * S + (slab + 1) * 128]
                .astype(_I32))

    def rw_of(slab, first):
        return jnp.zeros((x.shape[0],), dtype=_I32)

    _qh, _ql, _pos_l, wid, _rwv = _column_over_slabs(
        x, S, tabs, r_leaf, operands, rw_of)
    _store_row(id_ref, r, wid)


# ---------------------------------------------------------------------------
# approx-filter + packed-candidate exact verify (the fast path's fast path)
# ---------------------------------------------------------------------------
#
# The exact column kernels above price every (x, item, r) triple at the
# full ~200-op u32 pipeline.  The same certified-filter idea as
# straw2_u32.straw2_choose_index_approx — a cheap f32 draw approximation
# with a *measured* error bound narrows each (x, r) column to K candidate
# items — but packed across r: all R columns' candidates (R*K <= ~40
# rows) run through ONE exact sublane-oriented slab instead of R full
# lane slabs.  Exactness is unconditional: any (x, r) with more than K
# items inside the error band raises a flag and the caller re-runs the
# exact column kernels (measured: does not fire at realistic weights).
#
# The ln error bound is measured against the integer crush_ln over the
# full 16-bit domain USING THIS BACKEND'S OWN f32 log2 lowering (Mosaic's
# approximation differs from XLA's), so the certificate holds for the
# exact code path that runs.

_K = 4


def _ln_f32_pl(u):
    xf = u.astype(_I32).astype(jnp.float32) + jnp.float32(1.0)
    return jnp.log2(xf) * jnp.float32(2.0 ** 44)


def _ln_bound_kernel(u_ref, out_ref):
    out_ref[...] = _ln_f32_pl(u_ref[...].astype(_U32))


@functools.lru_cache(maxsize=None)
def _ln_f32_bound(interpret: bool) -> float:
    """max |f32_ln(u) - crush_ln(u)| over every 16-bit u, with the f32
    evaluated by the same Pallas lowering the filter kernel uses."""
    from ceph_tpu.ops.crush_kernel import crush_ln
    u = jnp.arange(65536, dtype=jnp.int32).reshape(128, 512)
    approx = pl.pallas_call(
        _ln_bound_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 512), jnp.float32),
        interpret=interpret,
    )(u)
    exact = crush_ln(u.ravel().astype(jnp.uint32)).astype(jnp.float32)
    return float(jnp.max(jnp.abs(approx.ravel() - exact)))


def _approx_column(x, r, slab_ops, n_slabs, D):
    """One cheap f32 column: per-slab (q_lo, q_hi) bands.  slab_ops(s) ->
    (ids, wf, wz) with wf (B, 128) f32 weights, wz bool."""
    bands = []
    for s in range(n_slabs):
        ids, wf, wz = slab_ops(s)
        u = hash32_3(x[:, None], ids, r) & _U32(0xFFFF)
        q = (jnp.float32(2.0 ** 48) - _ln_f32_pl(u)) / wf
        # margin: measured ln bound + f32 representation of P (<= 2^25)
        # + f32 division/weight-rounding relative error + floor-tie
        # quantization
        m = ((jnp.float32(D) + jnp.float32(2 ** 25)) / wf
             + q * jnp.float32(2.0 ** -20) + jnp.float32(4.0))
        big = jnp.float32(3.0e38)
        q = jnp.where(wz, big, q)
        m = jnp.where(wz, jnp.float32(0.0), m)
        bands.append((q - m, q + m))
    return bands


def _sortable_f32(v):
    """Monotone u32 key for f32 (standard float-sort transform)."""
    bits = jax.lax.bitcast_convert_type(v, _U32)
    neg = (bits >> 31) == _U32(1)
    return jnp.where(neg, ~bits, bits | _U32(0x80000000))


def _extract_candidates(bands, K):
    """K candidate positions per row + the exactness certificate.

    Selection: K rounds of a packed-key argmin (the key truncates the
    f32 lower-bound's low 10 bits and carries the global position, so
    one unsigned min per round yields value AND position).  The
    certificate does not trust the selection order: after K rounds it
    checks directly that every lane inside the error band of the
    minimum upper bound was chosen — any miss raises the flag and the
    caller re-runs the exact kernels.  Returns ([(B,) pos] * K, flag).
    """
    n_slabs = len(bands)
    min_hi = None
    for _lo, hi in bands:
        h = jnp.min(hi, axis=1, keepdims=True)
        min_hi = h if min_hi is None else jnp.minimum(min_hi, h)
    los = [lo for lo, _ in bands]
    orig_in_band = [lo <= min_hi for lo in los]
    keys = []
    for s, lo in enumerate(los):
        b, width = lo.shape
        gpos = (jax.lax.broadcasted_iota(_I32, (b, width), 1)
                + _I32(s * 128)).astype(_U32)
        keys.append((_sortable_f32(lo) & _U32(0xFFFFFC00)) | gpos)
    chosen = [jnp.zeros_like(k, dtype=jnp.bool_) for k in keys]
    big_key = _U32(0xFFFFFFFF)
    positions = []
    for _k in range(K):
        best = None
        for s in range(n_slabs):
            m = _umin(keys[s], 1, False)
            best = m if best is None else \
                jnp.where(_ult(m, best), m, best)
        pos = (best & _U32(0x3FF)).astype(_I32)          # (B,)
        positions.append(pos)
        for s in range(n_slabs):
            b, width = keys[s].shape
            gpos = (jax.lax.broadcasted_iota(_I32, (b, width), 1)
                    + _I32(s * 128))
            hit = gpos == pos[:, None]
            keys[s] = jnp.where(hit, big_key, keys[s])
            chosen[s] = chosen[s] | hit
    missed = None
    for s in range(n_slabs):
        v = jnp.max(jnp.where(orig_in_band[s] & ~chosen[s], _I32(1),
                              _I32(0)), axis=1)
        missed = v if missed is None else jnp.maximum(missed, v)
    return positions, missed


#: candidate field order shared by the phase-1 and phase-2 kernels
_FIELDS = ("pos", "ids", "wz", "off", "m0", "m1", "m2", "m3", "m4")

#: candidate rows per column in the packed lane layout: K real
#: candidates padded to the 8-lane segment quantum with dummies
_KPACK = 8


def _gather_packed(positions, row_of_slab, n_slabs):
    """Gather one operand at all K candidate positions with ONE
    dynamic_gather per slab: lane k of the result holds candidate k's
    value (lanes >= K are garbage, masked later)."""
    b = positions[0].shape[0]
    lane = jax.lax.broadcasted_iota(_I32, (b, 128), 1)
    gpos = jnp.zeros((b, 128), dtype=_I32)
    for k, p in enumerate(positions):
        gpos = jnp.where(lane == _I32(k), p[:, None], gpos)
    out = None
    for s in range(n_slabs):
        local = jnp.clip(gpos - _I32(s * 128), _I32(0), _I32(127))
        g = _row_lookup(local, row_of_slab(s))
        in_slab = (gpos >= _I32(s * 128)) & (gpos < _I32((s + 1) * 128))
        out = g if out is None else jnp.where(in_slab, g, out)
    return out


def _shift_to_segment(packed, r):
    """Move lanes [0, KPACK) to lanes [r*KPACK, (r+1)*KPACK): a per-row
    gather with a shifted index (garbage outside the segment, masked by
    the caller's segment write)."""
    b = packed.shape[0]
    lane = jax.lax.broadcasted_iota(_I32, (b, 128), 1)
    idx = jnp.clip(lane - (r * _I32(_KPACK))[None, None], _I32(0),
                   _I32(127))
    return _row_lookup(jnp.broadcast_to(idx, (b, 128)), packed)


def _verify_packed(x, pos_p, ids_p, wz_p, off_p, magic_p, tabs,
                   *, R, vary_r):
    """The exact pipeline over a lane-packed candidate block (lane
    r*KPACK+k = candidate k of column r), then per-r segment winners.
    Returns two per-r lists of (B,) vectors: (wpos, wid)."""
    B = x.shape[0]
    lane = jax.lax.broadcasted_iota(_I32, (B, 128), 1)
    valid = lane < _I32(R * _KPACK)
    seg_r = lane // _I32(_KPACK)
    if vary_r is None:
        r_vec = jnp.where(valid, seg_r, _I32(0)).astype(_U32)
    elif vary_r:
        r_vec = jnp.where(valid, seg_r >> _I32(vary_r - 1),
                          _I32(0)).astype(_U32)
    else:
        r_vec = jnp.zeros((B, 128), dtype=_U32)
    u = hash32_3(x[:, None], ids_p, r_vec) & _U32(0xFFFF)
    p_hi, p_lo = _ln_p48_pl(u, *tabs[:3], tabs[3])
    q_hi, q_lo = _magic_div_pl(p_hi, p_lo, magic_p, off_p)
    bad = (wz_p != 0) | ~valid
    q_hi = jnp.where(bad, _U32(0xFFFFFFFF), q_hi)
    q_lo = jnp.where(bad, _U32(0xFFFFFFFF), q_lo)
    wposs, wids = [], []
    for r in range(R):
        m = (seg_r == _I32(r)) & valid
        qh = jnp.where(m, q_hi, _U32(0xFFFFFFFF))
        mh = _umin(qh, 1, True)
        on_h = m & (qh == mh)
        ql_m = jnp.where(on_h, q_lo, _U32(0xFFFFFFFF))
        ml = _umin(ql_m, 1, True)
        on = on_h & (ql_m == ml)
        # ties resolve to the smallest ORIGINAL item position
        pos_m = jnp.where(on, pos_p, _I32(2 ** 31 - 1))
        minpos = jnp.min(pos_m, axis=1, keepdims=True)
        first = on & (pos_p == minpos) & m
        wid = jnp.sum(jnp.where(first, ids_p, _I32(0)), axis=1,
                      dtype=_I32)
        wposs.append(minpos[:, 0])
        wids.append(wid)
    return wposs, wids


def _froot_kernel(xs_ref, ids_ref, wz_ref, wf_ref, magic_ref, off_ref,
                  rhlh_ref, ll_lo_ref, ll_hi_ref,
                  pos_ref, id_ref, ovf_ref,
                  *, S, R, rh128, D):
    """Fused single-phase root columns: approx-filter every r column,
    pack the K candidates of all R columns into one (B, 128) lane block
    IN VMEM, run the exact pipeline once, emit per-r winners.

    This replaces the two-phase root_columns_fast whose staged candidate
    fields round-tripped ~10 (n, 128) i32 arrays through HBM between two
    pallas_calls — the layout the AOT toolchain compiled pathologically.
    One kernel, no staged state, same certificate: any (x, r) column
    with more than K items inside the measured f32 error band raises the
    overflow flag and the caller re-runs the exact column kernels."""
    x = xs_ref[0, :]
    B = x.shape[0]
    n_slabs = S // 128
    lane = jax.lax.broadcasted_iota(_I32, (B, 128), 1)
    tabs = (rhlh_ref, ll_lo_ref, ll_hi_ref, rh128)

    def slab_ops(s):
        sl = slice(s * 128, (s + 1) * 128)
        return (ids_ref[0, sl][None, :],
                wf_ref[0, sl][None, :],
                wz_ref[0, sl][None, :] != 0)

    def row_of(name):
        def rows(s):
            sl = slice(s * 128, (s + 1) * 128)
            if name == "ids":
                return ids_ref[0, sl]
            if name == "wz":
                return wz_ref[0, sl]
            if name == "off":
                return off_ref[0, sl]
            j = int(name[1])
            return magic_ref[j, sl].astype(_I32)
        return rows

    packed = {name: jnp.full((B, 128), _I32(2 ** 31 - 1)) if name == "pos"
              else jnp.zeros((B, 128), dtype=_I32) for name in _FIELDS}
    missed_all = jnp.zeros((B,), dtype=_I32)
    for r in range(R):
        bands = _approx_column(x, _U32(r), slab_ops, n_slabs, D)
        positions, missed = _extract_candidates(bands, _K)
        missed_all = jnp.maximum(missed_all, missed)
        in_seg = (lane >= _I32(r * _KPACK)) & (lane < _I32((r + 1) * _KPACK))
        for name in _FIELDS:
            if name == "pos":
                pk = jnp.full((B, 128), _I32(2 ** 31 - 1))
                for k, p in enumerate(positions):
                    pk = jnp.where(lane == _I32(k), p[:, None], pk)
            else:
                pk = _gather_packed(positions, row_of(name), n_slabs)
                pk = jnp.where(
                    (lane >= _I32(len(positions))) & (lane < _I32(_KPACK)),
                    _I32(1) if name == "wz" else _I32(0), pk)
            shifted = _shift_to_segment(pk, _I32(r))
            packed[name] = jnp.where(in_seg, shifted, packed[name])
    magic_p = [packed[f"m{j}"].astype(_U32) for j in range(5)]
    wposs, wids = _verify_packed(
        x, packed["pos"], packed["ids"], packed["wz"], packed["off"],
        magic_p, tabs, R=R, vary_r=None)
    for r in range(R):
        _store_row(pos_ref, r, wposs[r])
        _store_row(id_ref, r, wids[r])
    _store_row(ovf_ref, 0, missed_all)


def _consume_kernel(hw_ref, lw_ref, lb_ref, outh_ref, outl_ref, ovf_ref,
                    *, R, numrep, tries):
    """The firstn ladder over precomputed winner columns, fully unrolled.

    crush_choose_firstn (mapper.c:460-648) resets ftotal per replica and
    draws with r = rep + ftotal; within one replica every attempt either
    places (done) or fails (ftotal + 1), so an active lane at unroll step
    i of replica rep has ftotal == i exactly — r = rep + i is a STATIC
    row index into the winner columns.  That turns the XLA while_loop
    ladder (46 ms at the 64Ki bulk shape — as expensive as the draws it
    consumes) into ~numrep*R unrolled vector ops with no dynamic gathers.

    Collision semantics: a candidate collides if its host or device id
    equals ANY already-placed slot (earlier replicas only — the current
    replica has not placed yet), matching _consume/mapper.c; NONE slots
    (exhausted replicas) never match a real id.  Lanes that walk past the
    last precomputed column while still active raise the overflow flag,
    upon which the caller re-runs with the full r range."""
    b = hw_ref.shape[1]
    none_v = jnp.full((b,), _I32(0x7FFFFFFF))  # CRUSH_ITEM_NONE
    sel_h = [none_v for _ in range(numrep)]
    sel_l = [none_v for _ in range(numrep)]
    ovf = jnp.zeros((b,), dtype=jnp.bool_)
    for rep in range(numrep):
        done = jnp.zeros((b,), dtype=jnp.bool_)
        steps = min(tries, R - rep)
        for i in range(steps):
            r = rep + i
            hb = hw_ref[r, :]
            lf = lw_ref[r, :]
            bad = lb_ref[r, :] != 0
            for j in range(numrep):
                bad = bad | (sel_h[j] == hb) | (sel_l[j] == lf)
            place = ~done & ~bad
            sel_h[rep] = jnp.where(place, hb, sel_h[rep])
            sel_l[rep] = jnp.where(place, lf, sel_l[rep])
            done = done | place
            if i + 1 >= tries:
                done = jnp.ones((b,), dtype=jnp.bool_)
        # active lanes that ran out of columns (ft < tries): overflow
        ovf = ovf | (~done if steps < tries else jnp.zeros((b,), jnp.bool_))
    for rep in range(numrep):
        _store_row(outh_ref, rep, sel_h[rep])
        _store_row(outl_ref, rep, sel_l[rep])
    _store_row(ovf_ref, 0, ovf.astype(jnp.int32))


def consume_columns(hw, lw, lb, *, numrep: int, tries: int,
                    interpret: bool = False):
    """(R, N) winner columns -> (out_h, out_l, ovf): (numrep, N) int32
    selections with NONE holes and an (N,) overflow flag."""
    R, n = hw.shape
    B = min(BLOCK, n)
    z = np.int32(0)
    col = lambda: pl.BlockSpec((R, B), lambda i: (z, i))
    outs = [jax.ShapeDtypeStruct((numrep, n), jnp.int32),
            jax.ShapeDtypeStruct((numrep, n), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32)]
    out_specs = [pl.BlockSpec((numrep, B), lambda i: (z, i)),
                 pl.BlockSpec((numrep, B), lambda i: (z, i)),
                 pl.BlockSpec((1, B), lambda i: (z, i))]
    oh, ol, ovf = pl.pallas_call(
        functools.partial(_consume_kernel, R=R, numrep=numrep, tries=tries),
        grid=(n // B,),
        out_shape=outs,
        in_specs=[col(), col(), col()],
        out_specs=out_specs,
        interpret=interpret,
    )(hw, lw, lb.astype(jnp.int32))
    return oh, ol, ovf[0]


def _pad_lanes(n: int) -> int:
    return max(128, -(-n // 128) * 128)


def _pad_block(xs, *more):
    """Pad 1-D xs (and the last axis of any extra arrays) to a multiple
    of the batch block; returns (xs, padded_n, B, *more).  Small batches
    use a lane-quantum block so tests and trickle calls don't pay the
    bulk block's padding."""
    n = xs.shape[0]
    B = min(BLOCK, _pad_lanes(n))
    pad = (-n) % B
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,), dtype=xs.dtype)])
        more = tuple(
            jnp.concatenate(
                [a, jnp.zeros((*a.shape[:-1], pad), dtype=a.dtype)],
            axis=-1) for a in more)
    out = (xs, n + pad, B)
    return out + more if more else out


@functools.lru_cache(maxsize=None)
def _ln_tables_rows():
    """Gather-layout ln tables, one vreg (128 lanes) wide: rhlh rows
    (13, 128) for k in [0,127] + the k==128 row as python constants; the
    256-entry LL table split at row 128 into (6, 128) halves."""
    rhlh, ll = _ln_limb_operands_np()          # (129, 13), (256, 6) bytes
    rhlh = rhlh.astype(np.int32)
    ll = ll.astype(np.int32)
    rh_rows = np.ascontiguousarray(rhlh[:128].T)
    rh128 = tuple(int(v) for v in rhlh[128])
    ll_lo = np.ascontiguousarray(ll[:128].T)
    ll_hi = np.ascontiguousarray(ll[128:].T)
    return rh_rows, rh128, ll_lo, ll_hi


class PallasColumns:
    """Compiled winner-precompute for one FastRule on the TPU backend.

    Produces (host_win_ids, host_pos, leaf_win, leaf_bad) arrays shaped
    (R, N) for r in [0, R): drop-in data for fastpath._consume.
    """

    def __init__(self, fr, interpret: bool = False):
        from ceph_tpu.ops.straw2_u32 import magic_tables
        self.fr = fr
        self.interpret = interpret
        S = _pad_lanes(len(fr.root_ids))
        self.S_root = S
        ids = np.zeros(S, dtype=np.int32)
        ids[:len(fr.root_ids)] = fr.root_ids
        w = np.zeros(S, dtype=np.int64)
        w[:len(fr.root_w)] = fr.root_w
        limbs, off = magic_tables(w)
        self.root_ids = jnp.asarray(ids[None, :])
        self.root_wz = jnp.asarray((w <= 0).astype(np.int32)[None, :])
        self.root_magic = jnp.asarray(
            np.ascontiguousarray(limbs.T))            # (5, S)
        self.root_off = jnp.asarray(off.astype(np.int32)[None, :])
        rh, self.rh128, ll_lo, ll_hi = _ln_tables_rows()
        self.tabs = (jnp.asarray(rh), jnp.asarray(ll_lo),
                     jnp.asarray(ll_hi))

        self.root_wf = jnp.asarray(
            np.maximum(w, 1).astype(np.float32)[None, :])
        if fr.leaf_ids is not None:
            H, S_l = fr.leaf_ids.shape
            Sp = _pad_lanes(S_l)
            Hp = _pad_lanes(H)      # the one-hot dot wants 128-multiples
            self.H = Hp
            self.S_leaf = Sp
            lids = np.zeros((Hp, Sp), dtype=np.int64)
            lids[:H, :S_l] = fr.leaf_ids
            lw = np.zeros((Hp, Sp), dtype=np.int64)
            lw[:H, :S_l] = fr.leaf_w
            l_limbs, l_off = magic_tables(lw)
            # packed static per-host fields, all exact in f32 except the
            # raw weight column (col 8), whose f32 rounding the approx
            # filter's margin absorbs
            packed = np.concatenate([
                lids.astype(np.float32),
                (lw <= 0).astype(np.float32),
                l_off.astype(np.float32),
            ] + [l_limbs[..., j].astype(np.float32) for j in range(5)]
              + [lw.astype(np.float32)],
                axis=1)                                # (Hp, 9*Sp)
            self.leaf_static = jnp.asarray(packed)
            self.leaf_ids_np = lids                    # for reweight rows

    @property
    def D(self) -> float:
        """Certified ln error bound for the approx filter — measured
        lazily (a kernel compile + launch) since the filter is opt-in;
        lru-cached per backend mode, and a python constant by the time
        jit traces the filter kernels (property access runs eagerly in
        the wrappers before pallas_call)."""
        return _ln_f32_bound(self.interpret)

    @staticmethod
    def _fullspec(shape):
        return pl.BlockSpec(shape,
                            lambda i, r: (jnp.int32(0), jnp.int32(0)),
                            memory_space=pltpu.VMEM)

    def root_columns(self, xs, reweight, R: int):
        """xs (N,) uint32 -> (pos, ids) each (R, N) int32.  is_out
        verdicts are computed by the caller in XLA (elementwise).
        Batches that are not a BLOCK multiple are zero-padded here."""
        del reweight
        S = self.S_root
        xs, n, B = _pad_block(xs)
        grid = (n // B, R)     # r innermost: output blocks revisited
        outs = [jax.ShapeDtypeStruct((R, n), jnp.int32) for _ in range(2)]
        out_specs = [pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i))
                     for _ in range(2)]
        fs = self._fullspec
        rh, ll_lo, ll_hi = self.tabs
        pos, ids = pl.pallas_call(
            functools.partial(_root_kernel, S=S, rh128=self.rh128),
            grid=grid,
            out_shape=outs,
            in_specs=[pl.BlockSpec((1, B), lambda i, r: (jnp.int32(0), i)),
                      fs((1, S)), fs((1, S)), fs((5, S)), fs((1, S)),
                      fs(rh.shape), fs(ll_lo.shape), fs(ll_hi.shape)],
            out_specs=out_specs,
            interpret=self.interpret,
        )(xs[None, :], self.root_ids, self.root_wz, self.root_magic,
          self.root_off, rh, ll_lo, ll_hi)
        return pos, ids

    def froot_columns(self, xs, reweight, R: int):
        """Fused single-phase filtered root columns: (pos, ids, ovf) —
        one pallas_call, candidates packed in VMEM, is_out left to the
        caller.  Requires R * _KPACK <= 128."""
        del reweight
        if R * _KPACK > 128:
            raise ValueError(f"froot_columns: R={R} exceeds the lane pack")
        S = self.S_root
        D = self.D   # concrete before tracing
        xs, n, B = _pad_block(xs)
        Bc = 128   # 256 tops the 16M scoped-vmem limit (measured 16.22M)
        z = np.int32(0)
        fs1 = lambda shape: pl.BlockSpec(
            shape, lambda i: tuple(z for _ in shape),
            memory_space=pltpu.VMEM)
        rh, ll_lo, ll_hi = self.tabs
        outs = [jax.ShapeDtypeStruct((R, n), jnp.int32) for _ in range(2)]
        outs.append(jax.ShapeDtypeStruct((1, n), jnp.int32))
        out_specs = [pl.BlockSpec((R, Bc), lambda i: (z, i))
                     for _ in range(2)]
        out_specs.append(pl.BlockSpec((1, Bc), lambda i: (z, i)))
        pos, ids, ovf = pl.pallas_call(
            functools.partial(_froot_kernel, S=S, R=R,
                              rh128=self.rh128, D=D),
            grid=(n // Bc,),
            out_shape=outs,
            in_specs=[pl.BlockSpec((1, Bc), lambda i: (z, i)),
                      fs1((1, S)), fs1((1, S)), fs1((1, S)), fs1((5, S)),
                      fs1((1, S)),
                      fs1(rh.shape), fs1(ll_lo.shape), fs1(ll_hi.shape)],
            out_specs=out_specs,
            interpret=self.interpret,
        )(xs[None, :], self.root_ids, self.root_wz, self.root_wf,
          self.root_magic, self.root_off, rh, ll_lo, ll_hi)
        return pos, ids, ovf[0]

    def leaf_columns(self, xs, root_pos, R: int):
        """root winner positions -> leaf_id (R, N).  is_out verdicts are
        computed by the caller in XLA (elementwise)."""
        # root_pos comes back padded from root_columns; re-pad from the
        # caller's batch width so both land on the same quantum
        root_pos = root_pos[:, :xs.shape[0]]
        xs, n, B, root_pos = _pad_block(xs, root_pos)
        grid = (n // B, R)
        outs = [jax.ShapeDtypeStruct((R, n), jnp.int32)]
        out_specs = [pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i))]
        fs = self._fullspec
        rh, ll_lo, ll_hi = self.tabs
        (lid,) = pl.pallas_call(
            functools.partial(_leaf_kernel, H=self.H, S=self.S_leaf,
                              vary_r=self.fr.vary_r,
                              rh128=self.rh128),
            grid=grid,
            out_shape=outs,
            in_specs=[pl.BlockSpec((1, B), lambda i, r: (jnp.int32(0), i)),
                      pl.BlockSpec((R, B), lambda i, r: (jnp.int32(0), i)),
                      fs(self.leaf_static.shape),
                      fs(rh.shape), fs(ll_lo.shape), fs(ll_hi.shape)],
            out_specs=out_specs,
            interpret=self.interpret,
        )(xs[None, :], root_pos, self.leaf_static,
          rh, ll_lo, ll_hi)
        return lid
