"""librbd object-map / fast-diff (src/librbd/object_map/ analog).

A per-image allocation bitmap with TWO BITS per backing data object:

  0 NONEXISTENT   object has never been written (or was discarded)
  1 EXISTS        object holds data written since the last snapshot
  2 PENDING       discard in flight (kept for state-model parity)
  3 EXISTS_CLEAN  object holds data, unchanged since the last snapshot

The head map lives in ``rbd_object_map.<image>``; every snapshot
freezes a copy at ``rbd_object_map.<image>.<snapid>`` (the reference
keys per-snap maps the same way, object_map::ObjectMap<I>::object_map_name).
Maintained write-ahead under the image's exclusive-lock discipline:
the map marks EXISTS before data lands, so a crash can only ever
over-report (diff/du then over-copy, never lose extents).

Fast-diff derives changed extents from two maps without touching a
single data object: O(map width) bit compares instead of O(objects)
stats — diff/du/export-diff on a lightly-written multi-TiB image cost
what its WRITTEN objects cost, not its size.

Blob layout: 1 byte flags (bit 0 = invalid, set by a detected
inconsistency, cleared by rebuild) + 8 bytes LE object count + packed
2-bit states.
"""

from __future__ import annotations

OBJECT_NONEXISTENT = 0
OBJECT_EXISTS = 1
OBJECT_PENDING = 2
OBJECT_EXISTS_CLEAN = 3

FLAG_INVALID = 1

_PRESENT = (OBJECT_EXISTS, OBJECT_PENDING, OBJECT_EXISTS_CLEAN)


class ObjectMap:
    """The bitmap itself + its RADOS persistence."""

    FMT = "rbd_object_map.{name}"

    def __init__(self, ioctx, image_name: str, snapid: int = 0):
        self.io = ioctx
        self.image_name = image_name
        self.snapid = snapid
        self.flags = 0
        self._bits = bytearray()
        self.n_objs = 0

    # -- persistence ----------------------------------------------------------

    def oid(self) -> str:
        base = self.FMT.format(name=self.image_name)
        return base if not self.snapid else f"{base}.{self.snapid}"

    @classmethod
    def load(cls, ioctx, image_name: str, snapid: int = 0) -> "ObjectMap":
        om = cls(ioctx, image_name, snapid)
        blob = ioctx.read(om.oid())     # OSError -> caller decides
        if len(blob) < 9:
            raise ValueError("truncated object map")
        om.flags = blob[0]
        om.n_objs = int.from_bytes(blob[1:9], "little")
        om._bits = bytearray(blob[9:])
        want = (om.n_objs * 2 + 7) // 8
        if len(om._bits) < want:
            raise ValueError("truncated object map bitmap")
        return om

    def save(self) -> None:
        self.io.write_full(
            self.oid(),
            bytes([self.flags]) + self.n_objs.to_bytes(8, "little")
            + bytes(self._bits))

    def remove(self) -> None:
        try:
            self.io.remove(self.oid())
        except OSError:
            pass

    # -- bit plumbing ---------------------------------------------------------

    def get(self, objno: int) -> int:
        if objno >= self.n_objs:
            return OBJECT_NONEXISTENT
        byte, shift = divmod(objno * 2, 8)
        return (self._bits[byte] >> shift) & 0b11

    def set(self, objno: int, state: int) -> None:
        if objno >= self.n_objs:
            self.resize(objno + 1)
        byte, shift = divmod(objno * 2, 8)
        self._bits[byte] = ((self._bits[byte] & ~(0b11 << shift))
                            | ((state & 0b11) << shift))

    def resize(self, n_objs: int) -> None:
        want = (n_objs * 2 + 7) // 8
        if want > len(self._bits):
            self._bits.extend(bytes(want - len(self._bits)))
        elif want < len(self._bits):
            del self._bits[want:]
        if n_objs < self.n_objs:
            # clear the partial byte's tail bits beyond the new width
            for objno in range(n_objs, min(self.n_objs, want * 4)):
                byte, shift = divmod(objno * 2, 8)
                if byte < len(self._bits):
                    self._bits[byte] &= ~(0b11 << shift)
        self.n_objs = n_objs

    def count(self, *states: int) -> int:
        wanted = set(states or _PRESENT)
        return sum(1 for i in range(self.n_objs)
                   if self.get(i) in wanted)

    def present_objnos(self) -> list[int]:
        return [i for i in range(self.n_objs) if self.get(i) in _PRESENT]

    def snapshot_copy(self, snapid: int) -> "ObjectMap":
        """Freeze the current states under a snapshot id (snap_create),
        then the HEAD's EXISTS demote to EXISTS_CLEAN — 'clean' always
        means 'unchanged since the latest snapshot' (fast-diff)."""
        snap = ObjectMap(self.io, self.image_name, snapid)
        snap.flags = self.flags
        snap.n_objs = self.n_objs
        snap._bits = bytearray(self._bits)
        snap.save()
        for i in range(self.n_objs):
            if self.get(i) == OBJECT_EXISTS:
                self.set(i, OBJECT_EXISTS_CLEAN)
        self.save()
        return snap


def diff_objnos(from_map: ObjectMap | None,
                chain: list[ObjectMap]) -> dict:
    """{objno: exists_bool} of objects that changed from `from_map`
    through `chain` — the fast-diff kernel (object_map::DiffRequest).

    `chain` is every object map STRICTLY AFTER from_map up to and
    including the diff target (ordered oldest→newest, head last when
    diffing to head).  EXISTS in any step means "dirty since the
    previous snapshot", so OR-ing the steps catches an object rewritten
    between two intermediate snapshots even though the target map shows
    it EXISTS_CLEAN.  With no from_map, every present target object
    differs (diff since the beginning)."""
    out: dict[int, bool] = {}
    to_map = chain[-1]
    width = max((m.n_objs for m in chain), default=0)
    if from_map is not None:
        width = max(width, from_map.n_objs)
    for objno in range(width):
        t_present = to_map.get(objno) in _PRESENT
        if from_map is None:
            if t_present:
                out[objno] = True
            continue
        f_present = from_map.get(objno) in _PRESENT
        dirty = any(m.get(objno) in (OBJECT_EXISTS, OBJECT_PENDING)
                    for m in chain)
        if dirty or t_present != f_present:
            out[objno] = t_present
    return out
