"""Pallas straw2 kernels vs the XLA u32 kernel (itself exhaustively
validated against the s64 kernel and the scalar C-semantics oracle).

Runs in interpret mode on the CPU mesh — the TPU compile path is
exercised by the benchmark and by the fastpath bit-exactness tests when
a TPU backend is present (fastpath auto-selects PallasColumns there).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.crush.fastpath import detect
from ceph_tpu.ops.crush_kernel import is_out
from ceph_tpu.ops.pallas_straw2 import PallasColumns
from ceph_tpu.ops.straw2_u32 import magic_tables, straw2_choose_index_u32


@pytest.fixture(scope="module")
def skewed_map():
    # 200 hosts -> two 128-lane root slabs; 6 osds/host -> padded leaf
    crush_map, _root, rid = build_two_level_map(200, 6)
    wrng = np.random.default_rng(42)
    for b in crush_map.buckets:
        if b is not None and b.type == 1:
            b.item_weights = [int(w) for w in
                              wrng.integers(0x8000, 0x20000, b.size)]
            b.weight = sum(b.item_weights)
    root = crush_map.bucket(-1)
    root.item_weights = [crush_map.bucket(h).weight for h in root.items]
    root.weight = sum(root.item_weights)
    return crush_map, rid


def test_pallas_columns_match_u32_kernel(skewed_map):
    crush_map, rid = skewed_map
    fr = detect(crush_map, rid)
    assert fr is not None
    pc = PallasColumns(fr, interpret=True)
    N, R = 256, 5
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.integers(0, 2 ** 32, (N,), dtype=np.uint32))
    reweight = np.full(1200, 0x10000, dtype=np.int64)
    reweight[3] = 0           # an out osd
    reweight[7] = 0x8000      # a half-reweighted osd
    rw = jnp.asarray(reweight)

    pos, ids = pc.root_columns(xs, rw, R)
    lid = pc.leaf_columns(xs, pos, R)
    lbad = np.asarray(is_out(rw, lid, jnp.asarray(
        np.pad(np.asarray(xs), (0, lid.shape[1] - N)))[None, :])
    ).astype(np.int32)

    Sr = len(fr.root_ids)
    rm, ro = magic_tables(fr.root_w)
    lm, lo = magic_tables(fr.leaf_w)
    for r in range(R):
        ref = np.asarray(straw2_choose_index_u32(
            xs, jnp.asarray(fr.root_ids)[None, :], jnp.uint32(r),
            jnp.asarray(fr.root_w)[None, :],
            jnp.asarray(np.broadcast_to(rm[None], (N, Sr, 5)).copy()),
            jnp.asarray(np.broadcast_to(ro[None], (N, Sr)).copy())))
        assert (ref == np.asarray(pos[r])).all(), f"root col r={r}"
        assert (np.asarray(ids[r])
                == np.asarray(fr.root_ids)[ref]).all()

        posr = np.asarray(pos[r])
        lids = fr.leaf_ids[posr]
        lws = fr.leaf_w[posr]
        r_leaf = (r >> (fr.vary_r - 1)) if fr.vary_r else 0
        ref_l = np.asarray(straw2_choose_index_u32(
            xs, jnp.asarray(lids), jnp.uint32(r_leaf), jnp.asarray(lws),
            jnp.asarray(lm[posr]), jnp.asarray(lo[posr])))
        ref_id = lids[np.arange(N), ref_l]
        assert (ref_id == np.asarray(lid[r])).all(), f"leaf col r={r}"
        ref_bad = np.asarray(
            is_out(rw, jnp.asarray(ref_id), xs)).astype(np.int32)
        assert (ref_bad == np.asarray(lbad[r])).all(), f"leaf bad r={r}"


def test_pallas_flat_rule(skewed_map):
    from ceph_tpu.crush import build_flat_map
    crush_map, _root, rid = build_flat_map(300)
    fr = detect(crush_map, rid)
    assert fr is not None and fr.kind == "choose_flat"
    pc = PallasColumns(fr, interpret=True)
    N, R = 128, 3
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.integers(0, 2 ** 32, (N,), dtype=np.uint32))
    reweight = np.full(300, 0x10000, dtype=np.int64)
    reweight[5] = 0
    rw = jnp.asarray(reweight)
    pos, ids = pc.root_columns(xs, rw, R)
    bad = np.asarray(is_out(rw, ids, jnp.asarray(
        np.pad(np.asarray(xs), (0, ids.shape[1] - N)))[None, :])
    ).astype(np.int32)
    Sr = len(fr.root_ids)
    rm, ro = magic_tables(fr.root_w)
    for r in range(R):
        ref = np.asarray(straw2_choose_index_u32(
            xs, jnp.asarray(fr.root_ids)[None, :], jnp.uint32(r),
            jnp.asarray(fr.root_w)[None, :],
            jnp.asarray(np.broadcast_to(rm[None], (N, Sr, 5)).copy()),
            jnp.asarray(np.broadcast_to(ro[None], (N, Sr)).copy())))
        assert (ref == np.asarray(pos[r])).all()
        ref_id = np.asarray(fr.root_ids)[ref]
        ref_bad = np.asarray(
            is_out(rw, jnp.asarray(ref_id), xs)).astype(np.int32)
        assert (ref_bad == np.asarray(bad[r])).all()


def test_consume_columns_matches_xla_ladder(skewed_map):
    """The unrolled Pallas firstn ladder == fastpath._consume on random
    winner columns, including collision, reject, tries-exhaustion and
    overflow lanes."""
    from ceph_tpu.crush.fastpath import _consume
    from ceph_tpu.ops.pallas_straw2 import consume_columns

    rng = np.random.default_rng(3)
    n, R, numrep = 256, 7, 3
    for tries, seed in ((51, 0), (2, 1), (5, 2)):
        r2 = np.random.default_rng(seed)
        # few distinct ids -> plenty of collisions; bad ~ 1/4 of draws
        hw = r2.integers(-6, -1, (R, n)).astype(np.int32)
        lw = r2.integers(0, 8, (R, n)).astype(np.int32)
        lb = (r2.random((R, n)) < 0.25)
        oh, ol, ovf = consume_columns(
            jnp.asarray(hw), jnp.asarray(lw), jnp.asarray(lb),
            numrep=numrep, tries=tries, interpret=True)
        ref_h, ref_l, ref_ovf = _consume(
            jnp.asarray(hw.T), jnp.asarray(lw.T), jnp.asarray(lb.T),
            numrep, tries, R, n)
        np.testing.assert_array_equal(np.asarray(oh).T, np.asarray(ref_h))
        np.testing.assert_array_equal(np.asarray(ol).T, np.asarray(ref_l))
        np.testing.assert_array_equal(np.asarray(ovf) != 0,
                                      np.asarray(ref_ovf))


def test_froot_columns_match_exact(skewed_map):
    """Fused single-phase filter kernel == exact root columns, with the
    certificate clean on realistic weights."""
    crush_map, rid = skewed_map
    fr = detect(crush_map, rid)
    pc = PallasColumns(fr, interpret=True)
    N, R = 256, 5
    rng = np.random.default_rng(1)
    xs = jnp.asarray(rng.integers(0, 2 ** 32, (N,), dtype=np.uint32))
    reweight = np.full(1200, 0x10000, dtype=np.int64)
    reweight[3] = 0
    reweight[7] = 0x8000
    rw = jnp.asarray(reweight)

    pos, ids = pc.root_columns(xs, rw, R)
    fpos, fids, ovf = pc.froot_columns(xs, rw, R)
    assert int(np.asarray(ovf).max()) == 0, "certificate fired on clean map"
    np.testing.assert_array_equal(np.asarray(fpos), np.asarray(pos))
    np.testing.assert_array_equal(np.asarray(fids), np.asarray(ids))
