"""rgw-lite — object-gateway semantics over RADOS (src/rgw/ analog,
collapsed to the storage mapping: buckets are omap index objects,
gateway objects stripe over RADOS objects, metadata rides omap — the
same rgw_rados.cc layout idea without the HTTP frontends).

Surface: create/delete bucket, put/get/delete/list/head object, with
optional transparent compression via the compressor registry; S3 object
versioning (rgw_rados versioned-object semantics: per-version omap
entries + a current pointer, delete markers, null versions while
suspended — src/rgw/rgw_rados.cc RGWRados::Object versioning paths).
"""

from __future__ import annotations

import json
import time

from ceph_tpu import compressor as _compressor
from ceph_tpu.osdc.striper import StripeLayout, StripedObject

#: ONE layout for both put and get — a mismatch would remap logical
#: offsets to different objects between write and read
_LAYOUT = StripeLayout(stripe_unit=1 << 16, stripe_count=2,
                       object_size=1 << 22)


class Bucket:
    INDEX_FMT = ".bucket.index.{name}"

    def __init__(self, ioctx, name: str, compression: str = "none",
                 tenant: str | None = None):
        #: tenant scopes every rados op of this bucket handle to the
        #: tenant's QoS lane (rgw_user tenant -> dmclock class on the
        #: OSDs); plain dict-backed test ioctxs lack with_tenant and
        #: pass through unscoped
        if tenant and hasattr(ioctx, "with_tenant"):
            ioctx = ioctx.with_tenant(tenant)
        self.io = ioctx
        self.name = name
        self.tenant = tenant
        self.comp = _compressor.create(compression)
        self.compression = compression

    # -- bucket lifecycle -----------------------------------------------------

    def create(self, owner: str = "") -> "Bucket":
        self.io.set_omap(self.INDEX_FMT.format(name=self.name),
                         {".bucket.meta": json.dumps(
                             {"created": time.time(),
                              "owner": owner,
                              "compression": self.compression}).encode()})
        return self

    def meta_all(self, idx: dict | None = None) -> dict:
        """The parsed bucket metadata record ({} when absent) — ONE
        omap fetch; callers needing several fields use this instead of
        repeated get_meta round trips.  idx reuses a caller's index
        snapshot (authorize fetches it once per request)."""
        if idx is None:
            try:
                idx = self.io.get_omap(
                    self.INDEX_FMT.format(name=self.name))
            except OSError:
                return {}
        blob = idx.get(".bucket.meta")
        return json.loads(blob.decode()) if blob else {}

    def get_meta(self, key: str, default=None):
        """One field of the bucket metadata record."""
        return self.meta_all().get(key, default)

    def set_meta(self, key: str, value) -> None:
        omap = self.io.get_omap(self.INDEX_FMT.format(name=self.name))
        meta = json.loads(omap[".bucket.meta"].decode())
        if value is None:
            meta.pop(key, None)
        else:
            meta[key] = value
        self.io.set_omap(self.INDEX_FMT.format(name=self.name),
                         {".bucket.meta": json.dumps(meta).encode()})

    def exists(self) -> bool:
        try:
            self.io.stat(self.INDEX_FMT.format(name=self.name))
            return True
        except OSError:
            return False

    def delete(self) -> None:
        if self.list() or any(True for _ in self.list_versions()):
            raise OSError(39, "bucket not empty")   # ENOTEMPTY
        self.io.remove(self.INDEX_FMT.format(name=self.name))

    # -- versioning state -----------------------------------------------------

    #: "" (never enabled) | "Enabled" | "Suspended" — S3's three states
    def versioning(self) -> str:
        return self.get_meta("versioning", "") or ""

    def set_versioning(self, status: str) -> None:
        self.set_meta("versioning", status)

    # -- objects --------------------------------------------------------------

    VSEP = "\x00"   # key/version separator in omap index keys
    DSEP = "\x1e"   # key/version separator in data object names: a
    #                 client key may contain "@" freely; RECORD SEPARATOR
    #                 cannot appear in keys (rejected at the gateway)

    def _data_name(self, key: str, vid: str | None = None) -> str:
        base = f".bucket.data.{self.name}.{key}"
        return base if not vid else f"{base}{self.DSEP}{vid}"

    def _data_so(self, key: str, entry: dict) -> StripedObject:
        """The striped data object an index entry points at.  data_vid
        tracks where the BYTES live: a pre-versioning object promoted to
        the null version keeps its bytes at the base name (data_vid
        None) even though its version_id is "null"."""
        vid = entry.get("data_vid", entry.get("version_id"))
        return StripedObject(self.io, self._data_name(key, vid), _LAYOUT)

    def _vkey(self, key: str, vid: str) -> str:
        return f"ver.{key}{self.VSEP}{vid}"

    def _index(self) -> dict:
        return self.io.get_omap(self.INDEX_FMT.format(name=self.name))

    def _preserve_preversioning(self, key: str, updates: dict) -> None:
        """S3 keeps an object written BEFORE versioning was ever enabled
        as the addressable null version: promote it into the version
        index on the first versioned op touching its key."""
        cur = self.current_entry(key)
        if cur is not None and "version_id" not in cur:
            cur["version_id"] = "null"
            cur["data_vid"] = None      # bytes stay at the base name
            updates[self._vkey(key, "null")] = json.dumps(cur).encode()

    def _drop_null_version(self, key: str, updates: dict) -> None:
        """Replacing THE null version (suspended put / null marker):
        its data — wherever it lives — goes away.  A pre-versioning
        object that was never promoted into the version index IS the
        null version; its base-name data goes too."""
        old = self._index().get(self._vkey(key, "null"))
        if old:
            e = json.loads(old.decode())
            if not e.get("delete_marker"):
                self._data_so(key, e).remove()
            return
        cur = self.current_entry(key)
        if cur is not None and "version_id" not in cur:
            StripedObject(self.io, self._data_name(key), _LAYOUT).remove()

    def put(self, key: str, data: bytes, metadata: dict | None = None,
            clock=time.time, unversioned: bool = False,
            etag: str | None = None, owner: str | None = None) -> dict:
        """Write an object; under versioning each put lands as a NEW
        version (a unique id, Enabled) or as THE null version
        (Suspended).  unversioned=True forces the classic single-slot
        path (internal staging like multipart parts must never grow
        version chains).  Returns the index entry written."""
        status = "" if unversioned else self.versioning()
        vid = None
        updates: dict = {}
        if status == "Enabled":
            vid = f"{time.time_ns():020d}"
            self._preserve_preversioning(key, updates)
        elif status == "Suspended":
            vid = "null"
            self._drop_null_version(key, updates)
        blob = self.comp.compress(data)
        so = StripedObject(self.io, self._data_name(key, vid), _LAYOUT)
        so.remove()   # null-version rewrite (or unversioned overwrite)
        so.write(blob)
        entry = {"size": len(data), "stored": len(blob),
                 "mtime": clock(), "meta": metadata or {},
                 "compression": self.comp.name}
        if etag is not None:
            entry["etag"] = etag
        if owner is not None:
            # the uploader (rgw_acl object owner): object-ACL ops are
            # gated on it, not on the bucket owner
            entry["owner"] = owner
        if vid is not None:
            entry["version_id"] = vid
            updates[self._vkey(key, vid)] = json.dumps(entry).encode()
        updates[f"obj.{key}"] = json.dumps(entry).encode()
        self.io.set_omap(self.INDEX_FMT.format(name=self.name), updates)
        return entry

    def update_entry(self, key: str, fields: dict,
                     vid: str | None = None) -> dict:
        """Merge fields into an index entry (object-ACL writes).  The
        versioned row and — when it IS the current — the obj.<key> row
        update together, so listings and direct reads agree."""
        idx = self._index()
        cur_blob = idx.get(f"obj.{key}")
        cur = json.loads(cur_blob.decode()) if cur_blob else None
        if vid is None:
            if cur is None or cur.get("delete_marker"):
                raise KeyError(key)
            ent, is_current = cur, True
            vid = cur.get("version_id")
        else:
            blob = idx.get(self._vkey(key, vid))
            if blob is None and vid == "null" and cur is not None \
                    and "version_id" not in cur:
                # un-promoted pre-versioning object IS the null
                # version (same fallback head() applies)
                ent, is_current = cur, True
                vid = None
            elif blob is None:
                raise KeyError(f"{key}@{vid}")
            else:
                ent = json.loads(blob.decode())
                is_current = (cur is not None
                              and cur.get("version_id") == vid)
        ent.update(fields)
        updates = {}
        if vid is not None:
            updates[self._vkey(key, vid)] = json.dumps(ent).encode()
        if is_current:
            updates[f"obj.{key}"] = json.dumps(ent).encode()
        self.io.set_omap(self.INDEX_FMT.format(name=self.name), updates)
        return ent

    def current_entry(self, key: str,
                      idx: dict | None = None) -> dict | None:
        """The current index entry — may be a delete marker — or None."""
        blob = (idx if idx is not None
                else self._index()).get(f"obj.{key}")
        if not blob:
            return None
        return json.loads(blob.decode())

    def head(self, key: str, vid: str | None = None,
             idx: dict | None = None) -> dict:
        if vid is None:
            entry = self.current_entry(key, idx=idx)
        else:
            blob = (idx if idx is not None
                    else self._index()).get(self._vkey(key, vid))
            entry = json.loads(blob.decode()) if blob else None
            if entry is None and vid == "null":
                # un-promoted pre-versioning object IS the null version
                cur = self.current_entry(key, idx=idx)
                if cur is not None and "version_id" not in cur:
                    entry = cur
        if entry is None or entry.get("delete_marker"):
            raise KeyError(key)
        return entry

    def get(self, key: str, vid: str | None = None) -> bytes:
        entry = self.head(key, vid)
        raw = self._data_so(key, entry).read(0, entry["stored"])
        comp = _compressor.create(entry.get("compression", "none"))
        return comp.decompress(raw[:entry["stored"]])

    def delete_object(self, key: str, vid: str | None = None,
                      clock=time.time, unversioned: bool = False) -> dict:
        """S3 delete semantics.  Unversioned: drop data, tombstone the
        index entry.  Versioned without a version id: lay down a delete
        marker (data untouched).  With a version id: permanently remove
        exactly that version and recompute the current pointer.
        unversioned=True hard-deletes regardless of bucket state (for
        internal staging objects).  Returns {"delete_marker": bool,
        "version_id": str|None}."""
        index_oid = self.INDEX_FMT.format(name=self.name)
        if vid is not None:
            # ONE index snapshot serves the whole removal (lookup,
            # current-pointer check, repoint) instead of three fetches
            idx = self._index()
            blob = idx.get(self._vkey(key, vid))
            if not blob:
                cur_blob = idx.get(f"obj.{key}")
                cur = json.loads(cur_blob.decode()) if cur_blob else None
                if vid == "null" and cur is not None \
                        and "version_id" not in cur:
                    # un-promoted pre-versioning object IS the null
                    # version: deleting it by id hard-deletes it
                    StripedObject(self.io, self._data_name(key),
                                  _LAYOUT).remove()
                    self.io.set_omap(index_oid, {f"obj.{key}": b""})
                    return {"delete_marker": False, "version_id": vid}
                return {"delete_marker": False, "version_id": vid}
            entry = json.loads(blob.decode())
            if not entry.get("delete_marker"):
                self._data_so(key, entry).remove()
            self.io.rm_omap_keys(index_oid, [self._vkey(key, vid)])
            del idx[self._vkey(key, vid)]
            cur_blob = idx.get(f"obj.{key}")
            cur = json.loads(cur_blob.decode()) if cur_blob else None
            if cur is not None and cur.get("version_id") == vid:
                self._repoint_current(key, idx)
            return {"delete_marker": bool(entry.get("delete_marker")),
                    "version_id": vid}
        status = "" if unversioned else self.versioning()
        if status in ("Enabled", "Suspended"):
            updates: dict = {}
            if status == "Enabled":
                mvid = f"{time.time_ns():020d}"
                # a marker over a pre-versioning object preserves it as
                # the addressable null version (S3 semantics)
                self._preserve_preversioning(key, updates)
            else:
                mvid = "null"
                # a null delete marker REPLACES the null version
                self._drop_null_version(key, updates)
            marker = {"delete_marker": True, "version_id": mvid,
                      "mtime": clock(), "size": 0, "meta": {}}
            updates[self._vkey(key, mvid)] = json.dumps(marker).encode()
            updates[f"obj.{key}"] = json.dumps(marker).encode()
            self.io.set_omap(index_oid, updates)
            return {"delete_marker": True, "version_id": mvid}
        self.head(key)   # KeyError if absent
        StripedObject(self.io, self._data_name(key), _LAYOUT).remove()
        # tombstone (b"") rather than key removal: a reader paging the
        # index mid-delete sees a consistent "absent" value
        self.io.set_omap(index_oid, {f"obj.{key}": b""})
        return {"delete_marker": False, "version_id": None}

    def _repoint_current(self, key: str, idx: dict | None = None) -> None:
        """The current version was permanently removed: newest surviving
        version (by id; marker or not) becomes current, else tombstone."""
        vers = self.versions_of(key, idx=idx)
        index_oid = self.INDEX_FMT.format(name=self.name)
        if vers:
            newest = vers[0]
            self.io.set_omap(index_oid, {
                f"obj.{key}": json.dumps(newest).encode()})
        else:
            self.io.set_omap(index_oid, {f"obj.{key}": b""})

    def versions_of(self, key: str, idx: dict | None = None) -> list[dict]:
        """All surviving versions of one key, newest first ("null" sorts
        by its mtime against the timestamp ids).  idx reuses a caller's
        index snapshot."""
        prefix = f"ver.{key}{self.VSEP}"
        out = []
        for k, v in (idx if idx is not None else self._index()).items():
            if k.startswith(prefix) and v:
                out.append(json.loads(v.decode()))
        out.sort(key=lambda e: (e.get("mtime", 0),
                                e.get("version_id", "")), reverse=True)
        return out

    def list_versions(self, prefix: str = ""):
        """Iterate (key, entry, is_latest) over every surviving version,
        keys ascending, versions newest-first within a key."""
        try:
            omap = self._index()
        except OSError:
            return
        by_key: dict[str, list[dict]] = {}
        for k, v in omap.items():
            if not k.startswith("ver.") or not v:
                continue
            key = k[4:].split(self.VSEP, 1)[0]
            if key.startswith(prefix):
                by_key.setdefault(key, []).append(json.loads(v.decode()))
        for key in sorted(by_key):
            vers = sorted(by_key[key],
                          key=lambda e: (e.get("mtime", 0),
                                         e.get("version_id", "")),
                          reverse=True)
            # current pointer from the SAME omap snapshot (one fetch
            # for the whole listing, not one per key)
            cur_blob = omap.get(f"obj.{key}")
            cur = json.loads(cur_blob.decode()) if cur_blob else None
            cur_vid = cur.get("version_id") if cur else None
            for e in vers:
                yield key, e, e.get("version_id") == cur_vid

    def list(self, prefix: str = "") -> list[str]:
        try:
            omap = self._index()
        except OSError:
            return []
        out = []
        for k, v in omap.items():
            if not k.startswith("obj.") or not v:
                continue
            key = k[4:]
            if key.startswith(prefix) \
                    and not json.loads(v.decode()).get("delete_marker"):
                out.append(key)
        return sorted(out)
