"""Flagship benchmark: erasure encode + 2-erasure recovery throughput.

Mirrors the reference's `ceph_erasure_code_benchmark` workload (BASELINE.json
north-star config: k=8 m=4 cauchy, 4 KiB chunks) — the reference harness reports
elapsed seconds and KiB processed (src/test/erasure-code/
ceph_erasure_code_benchmark.cc:188,326); here the same quantity is reported as
MB/s directly, batched over many stripes per device call instead of one stripe
per call (the ECUtil stripe-loop batch point, src/osd/ECUtil.cc:136).

Timing: the device runtime acks dispatch before execution completes (remote
tunnel), so naive block_until_ready under-measures.  Each measurement runs the
kernel N times inside one jitted lax.scan with a forced data dependency between
iterations, fetches a scalar (which cannot resolve until everything executed),
and differences two iteration counts to cancel dispatch/transfer overhead.
Tunnel variance is large (r01 vs r02 disagreed 3x), so every rate reported is
the MEDIAN of `reps` independent chained-scan differences and the min..max band
rides along in the JSON (keys *_band) — a single lucky or unlucky run can no
longer move the headline.

vs_baseline: ratio against the single-core C baseline compiled from
ceph_tpu/native/baseline.c — an ISA-L-class split-nibble SIMD GF(2^8) encode
and a scalar straw2 crush_do_rule, both bit-validated against the same oracles
the TPU kernels are (tests/test_native.py) — measured in the same run, on this
host, never carried across sessions.

CRUSH runs with non-uniform bucket weights, a skewed reweight vector, and out
OSDs — the retry-ladder-heavy case, not the easy uniform one.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Sections: the run is split into named sweeps selectable with
``--sections`` (comma list) so any ONE section completes well inside a
590 s harness timeout on slow hosts:

  ec              device EC encode/recover rates + C baseline + the
                  fenced kernel-telemetry digest
  crush           device bulk CRUSH placement rate + C baseline
  dispatch_sweep  encode-side cross-op coalescing concurrency sweep
  recovery_sweep  decode-side (heterogeneous-pattern) concurrency sweep
  map_churn       map-epoch consumption storm: scalar full-scan vs the
                  shared PG mapping service (epochs/s, per-epoch scan
                  time, changed-PG counts), bit-verified vs the oracle
  profile         pipeline-profile micro-section: a short concurrent
                  encode/decode burst + a few mapping epochs, emitting
                  the where-did-the-time-go digest (phase shares,
                  compile seconds, utilization) into the JSON
  objectstore     device-resident objectstore write path: on-disk
                  bluestore write/read MB/s scalar vs the
                  bluestore_data checksum channel, the isolated
                  csum-settle micro, and the tpu_bitplane compression
                  leg — bit-verified against the host oracles

Default (no flag) runs every section EXCEPT map_churn and profile —
byte-compatible with the historical flagship JSON; ``--sections all``
adds the opt-ins.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def chained_rates(step_fn, carry, n_lo: int = 8, n_hi: int = 48,
                  reps: int = 5, inner: int = 5) -> list[float]:
    """Per-step seconds samples, robust against tunnel stalls.

    The tunnel's noise is ADDITIVE-POSITIVE (ack stalls, transfer
    hiccups), so each sample differences the MIN over `inner` timed
    runs of each iteration count — min-filtering converges on the true
    time where a single-pair difference can be dominated by one stall
    (round 3's band spanned 6x; a stall pair can even produce a
    near-zero difference, i.e. an absurd rate).  lo/hi runs alternate
    so a stall burst hits both counts, not just one side, and the wide
    n_hi - n_lo spread divides whatever residue remains."""
    import jax

    @functools.partial(jax.jit, static_argnames="n")
    def loop(c, n):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), ()), c, None, length=n)
        leaf = jax.tree_util.tree_leaves(c)[0]
        return leaf.ravel()[0]

    def timed(n):
        t0 = time.perf_counter()
        jax.device_get(loop(carry, n))
        return time.perf_counter() - t0

    jax.device_get(loop(carry, n_lo))  # compile
    jax.device_get(loop(carry, n_hi))
    for _ in range(2):                 # clock/thermal warm-up
        timed(n_hi)
    out = []
    for _ in range(reps):
        ts_lo, ts_hi = [], []
        for _ in range(inner):
            ts_lo.append(timed(n_lo))
            ts_hi.append(timed(n_hi))
        d = (min(ts_hi) - min(ts_lo)) / (n_hi - n_lo)
        # a non-positive difference is clock noise; fall back to the full
        # n_hi run amortized per step — that INCLUDES dispatch overhead, so
        # it can only understate the rate, never inflate the headline
        out.append(d if d > 2e-9 else min(ts_hi) / n_hi)
    return out


def median_band(samples: list[float]):
    """(median, lo, hi): the band is TRIMMED when there are >= 5
    samples (drop the single best and worst) — with a heavy-tailed
    tunnel, min/max report one outlier stall or one fluke near-zero
    difference, not the kernel.  The trim is symmetric, so it cannot
    bias the band in the flattering direction only."""
    s = sorted(samples)
    if len(s) >= 5:
        return s[len(s) // 2], s[1], s[-2]
    return s[len(s) // 2], s[0], s[-1]


def chained_seconds_per_step(step_fn, carry, n_lo: int = 8, n_hi: int = 48,
                             reps: int = 5) -> float:
    return median_band(chained_rates(step_fn, carry, n_lo, n_hi, reps))[0]


def _closed_loop_sweep(levels, total_ops: int, stats, make_submit,
                       name: str, op_bytes: int, actor_key: str,
                       snapshot=None, extra_row=None, mesh=None) -> dict:
    """Shared closed-loop concurrency harness for the dispatch sweeps
    (encode-side dispatch_sweep and decode-side recovery_sweep evolve
    in lockstep): per level, N barrier-started actors each keep ONE op
    in flight (submit, wait, repeat), and the row reports wall-clock
    MB/s, op-latency percentiles, and before/after differencing of the
    engine's scalar counters.  ``make_submit(engine)`` returns
    ``submit(actor_id, i) -> future``; ``snapshot(stats)``/
    ``extra_row(before, stats, calls, n_ops)`` add sweep-specific
    columns."""
    import threading

    from ceph_tpu.ops.dispatch import DeviceDispatchEngine

    out = {}
    for conc in levels:
        ops_per_actor = max(3, total_ops // conc)
        eng = DeviceDispatchEngine(name=f"{name}-c{conc}", stats=stats,
                                   mesh=mesh)
        submit = make_submit(eng)
        lats: list[float] = []
        lat_lock = threading.Lock()
        start = threading.Barrier(conc + 1)

        def actor(aid):
            start.wait()
            mine = []
            for i in range(ops_per_actor):
                t0 = time.perf_counter()
                submit(aid, i).result(timeout=120)
                mine.append(time.perf_counter() - t0)
            with lat_lock:
                lats.extend(mine)

        threads = [threading.Thread(target=actor, args=(a,),
                                    daemon=True)
                   for a in range(conc)]
        for t in threads:
            t.start()
        sub0, bat0 = stats.submits, stats.batches
        before = snapshot(stats) if snapshot is not None else None
        start.wait()           # release every actor at once
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        eng.stop()
        n_ops = conc * ops_per_actor
        calls = stats.batches - bat0
        row = {
            actor_key: conc,
            "ops": n_ops,
            "mbps": round(n_ops * op_bytes / wall / 1e6, 1),
            "p99_op_ms": round(
                float(np.percentile(lats, 99)) * 1e3, 3),
            "median_op_ms": round(
                float(np.percentile(lats, 50)) * 1e3, 3),
            "mean_coalesce": (round((stats.submits - sub0) / calls, 2)
                              if calls else 0.0),
            "device_calls_per_1k_ops": (round(1000.0 * calls / n_ops, 1)
                                        if n_ops else 0.0),
        }
        if extra_row is not None:
            row.update(extra_row(before, stats, calls, n_ops))
        out[str(conc)] = row
    return out


def dispatch_sweep(encode, k: int, chunk: int,
                   levels=(1, 4, 16, 64), op_stripes: int = 32,
                   total_ops: int = 96, coding=None) -> dict:
    """Offered-concurrency sweep through the cross-op coalescing
    engine (ops.dispatch): N closed-loop writers each submit one
    op-sized encode at a time and wait for its parity, exactly the OSD
    EC write path's submit-and-continue shape.  Reports end-to-end
    MB/s and p99 op latency per level plus the engine's own coalesce
    metrics — the amortization story is "MB/s climbs with writers
    while device calls per op falls".  All levels feed the global
    DispatchStats sink, so the process-wide `dispatch` digest in the
    JSON covers the whole sweep; per-level factors difference the
    scalar counters around each level.

    Mesh column: the per-level rows above run single-device engines
    (the ``kernel_mesh_devices=1`` number); with ``coding`` and a
    multi-device backend, ONE extra run at the top writer level uses a
    MESH-sharded engine (batch fans out across every local device) and
    lands in ``mesh_devices`` / ``encode_mbps_mesh`` /
    ``mesh_sharded_flushes``."""
    from ceph_tpu.ops import telemetry

    rng = np.random.default_rng(7)
    op = rng.integers(0, 256, (op_stripes, k, chunk), dtype=np.uint8)
    key = ("bench_ec", k, chunk)

    def make_submit(eng):
        return lambda _aid, _i: eng.submit(key, encode, op)

    out = _closed_loop_sweep(levels, total_ops,
                             telemetry.dispatch_stats(), make_submit,
                             "bench", op.nbytes, "writers")
    import jax
    n_dev = len(jax.devices())
    out["mesh_devices"] = n_dev
    if coding is not None and n_dev > 1:
        from ceph_tpu.ops.gf_kernel import make_encoder
        from ceph_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(n_dev)
        mesh_encode = make_encoder(coding, mesh=mesh)
        mesh_stats = telemetry.DispatchStats()   # private sink: the
        # global digest stays the single-device sweep's story
        conc = max(levels)
        row = _closed_loop_sweep(
            (conc,), total_ops, mesh_stats,
            lambda eng: (lambda _aid, _i: eng.submit(
                key, mesh_encode, op)),
            "bench-mesh", op.nbytes, "writers", mesh=mesh)[str(conc)]
        out["encode_mbps_mesh"] = row["mbps"]
        out["mesh_sharded_flushes"] = mesh_stats.sharded_flushes
        out["mesh_mean_devices"] = mesh_stats.summary()["mean_devices"]
    return out


def recovery_sweep(k: int, m: int, chunk: int, levels=(1, 4, 16),
                   op_stripes: int = 32, total_ops: int = 48) -> dict:
    """Degraded-read/recovery concurrency sweep through the DECODE
    dispatch engine: N closed-loop readers each submit one op-sized
    reconstruction at a time — every op missing 2 chunks, with the
    erasure PATTERN rotating per reader and per op — exactly the OSD
    degraded-read/recovery-pull shape.  The point over the encode-side
    dispatch_sweep: decodes with DIFFERENT recovery matrices still
    coalesce (heterogeneous-matrix batched kernel, pattern index per
    stripe), so MB/s climbs with readers while device calls per op and
    single-pattern batches both fall.  All levels feed the global
    DecodeDispatchStats sink; per-level factors difference the scalar
    counters around each level."""
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.ops import telemetry

    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m)})
    # 2-erasure patterns over the data chunks (the recovery case that
    # exercises distinct matrices): rotate through a handful
    patterns = []
    for e0 in range(min(k, 4)):
        e1 = (e0 + 1 + e0 % 2) % k
        erased = tuple(sorted({e0, e1}))
        if len(erased) < 2:
            continue
        chosen = [c for c in range(k + m) if c not in erased][:k]
        patterns.append((tuple(chosen), erased))
    rng = np.random.default_rng(11)
    op = rng.integers(0, 256, (op_stripes, k, chunk), dtype=np.uint8)

    def make_submit(eng):
        def submit(rid, i):
            chosen, targets = patterns[(rid + i) % len(patterns)]
            return codec.submit_decode_chunks(eng, chosen, op, targets)
        return submit

    def snapshot(st):
        return (st.patterns.count, st.patterns.sum)

    def extra_row(before, st, _calls, _n_ops):
        pat_n = st.patterns.count - before[0]
        return {"erasures": 2,
                "mean_patterns_per_call": (
                    round((st.patterns.sum - before[1]) / pat_n, 2)
                    if pat_n else 0.0)}

    return _closed_loop_sweep(levels, total_ops,
                              telemetry.decode_dispatch_stats(),
                              make_submit, "bench-rec", op.nbytes,
                              "readers", snapshot=snapshot,
                              extra_row=extra_row)


def map_churn(pools: int = 6, pg_num: int = 1024, hosts: int = 16,
              per_host: int = 4, epochs: int = 10) -> dict:
    """Map-epoch consumption sweep: a reweight/mark-down/override storm
    over many pools, comparing the seed's scalar full scan (every PG
    through pg_to_up_acting_osds on every epoch) against the shared
    mapping service (incremental pool recompute + on-device diff +
    O(changed) reads).  Every epoch's shared-cache reads are verified
    bit-identical to the scalar oracle across ALL PGs — the timing rows
    only count the work each consumption strategy actually does.

    Fused column: the primary ``shared_epoch_s`` row now runs the
    FUSED device ladder (PR 10 — packed up/acting tables, fused-output
    epoch diff, row-slice reads); an extra replay with
    ``osdmap_mapping_fused`` off reports the PR 5 host-tail cost as
    ``shared_epoch_s_unfused`` and the ``fused_speedup`` ratio — the
    ISSUE 10 acceptance number.  The default scale moved 1536 -> 6144
    PGs with this PR (ROADMAP item 3 direction): at toy scale the
    per-candidate host tail was already cheap; the fused ladder's win
    is that epoch cost stays flat while changed-PG counts grow.

    Mesh column: a THIRD consumption strategy rides a context-backed
    service whose pool remaps submit through the (mesh-sharded when the
    backend is multi-device) dispatch engine and whose on-device epoch
    diff shards over the kernel mesh — ``shared_epoch_s_mesh`` /
    ``mesh_devices``; ``mesh_devices`` 1 means one device and the row
    measures the engine path alone.  The mesh pass runs as a SEPARATE
    replay over the same recorded epoch sequence, after the ``mapping``
    digest is captured, so both the plain ``shared_epoch_s`` row and
    the digest stay comparable with the historical JSON."""
    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.osd import OSDMap, PGPool, SharedPGMappingService

    crush, _root, rule = build_two_level_map(hosts, per_host)
    n = hosts * per_host
    m = OSDMap(crush=crush, epoch=2)
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    for p in range(1, pools + 1):
        m.pools[p] = PGPool(pool_id=p, size=3, crush_rule=rule,
                            pg_num=pg_num)
    base = m
    svc = SharedPGMappingService()
    svc.update_to(m)    # epoch 0->2: full build (+ kernel compile)
    rng = np.random.default_rng(5)
    t_shared: list[float] = []
    t_scalar: list[float] = []
    changed_counts: list[int] = []
    verified = True
    epoch_log: list[tuple[int, object, dict]] = []  # (from, map, oracle)
    for i in range(epochs):
        new = m.copy()
        new.epoch = m.epoch + 1
        kind = i % 5
        osd = int(rng.integers(0, n))
        if kind == 0:      # reweight storm step (pools recompute)
            for o in rng.integers(0, n, 4):
                new.osd_weight[int(o)] = int(rng.choice(
                    (0x4000, 0x8000, 0xC000, 0x10000)))
        elif kind == 1:    # host failure: a whole failure domain goes
            host = int(rng.integers(0, hosts))   # down (state-only:
            for o in range(host * per_host,      # tables reuse, many
                           (host + 1) * per_host):   # PGs remap)
                new.osd_state[o] = new.osd_state[o] & ~2
        elif kind == 2:    # a host comes back
            host = int(rng.integers(0, hosts))
            for o in range(host * per_host, (host + 1) * per_host):
                new.osd_state[o] = new.osd_state[o] | 3
        elif kind == 3:    # pg_temp inject/clear burst (override-only)
            for _ in range(4):
                pgid = (1 + int(rng.integers(0, pools)),
                        int(rng.integers(0, pg_num)))
                if pgid in new.pg_temp:
                    del new.pg_temp[pgid]
                else:
                    new.pg_temp[pgid] = [osd, (osd + 1) % n]
        else:              # mark out / back in (weight edge)
            for o in rng.integers(0, n, 2):
                new.osd_weight[int(o)] = (
                    0x10000 if new.osd_weight[int(o)] == 0 else 0)
        # shared-cache consumption: epoch update + reading every
        # changed PG (what _scan_pgs does beyond its local PGs)
        t0 = time.perf_counter()
        upd = svc.update_to(new, from_epoch=m.epoch)
        reads = (upd.changed if not upd.full
                 else [(pid, pg) for pid, pool in new.pools.items()
                       for pg in range(pool.pg_num)])
        for pid, pg in reads:
            svc.lookup(new, pid, pg)
        t_shared.append(time.perf_counter() - t0)
        changed_counts.append(len(reads))
        # scalar baseline: the seed's full per-epoch scan
        t0 = time.perf_counter()
        oracle = {(pid, pg): new.pg_to_up_acting_osds(pid, pg)
                  for pid, pool in new.pools.items()
                  for pg in range(pool.pg_num)}
        t_scalar.append(time.perf_counter() - t0)
        # bit-identical acceptance gate, over EVERY pg
        for (pid, pg), want in oracle.items():
            if svc.lookup(new, pid, pg) != want:
                verified = False
        epoch_log.append((m.epoch, new, oracle))
        m = new
    from ceph_tpu.ops import telemetry
    # capture the digest BEFORE the mesh replay: it then describes
    # exactly the engine-less service's work, byte-comparable with
    # pre-mesh runs (the global mapping stats sink is shared)
    digest = telemetry.mapping_summary()
    # the bit-verify gate above reads EVERY pg per epoch through the
    # same global stats — those lookup counters describe the gate, not
    # the timed consumption loop, so report the timed reads instead
    digest.pop("lookups", None)
    digest.pop("lookup_fallbacks", None)
    digest["timed_reads"] = int(sum(changed_counts))
    # unfused replay of the SAME epoch sequence (the PR 5 host-tail
    # consumption path): same cache machinery, per-candidate
    # _finish_from delta + host-tail lookups — the A/B for the fused
    # ladder the primary rows above ran.  Timing only: the fused run
    # already bit-verified every epoch against the oracle.
    svc_uf = SharedPGMappingService(fused=False)
    svc_uf.update_to(base)
    t_unfused: list[float] = []
    for frm, new, _oracle in epoch_log:
        t0 = time.perf_counter()
        upd_u = svc_uf.update_to(new, from_epoch=frm)
        reads_u = (upd_u.changed if not upd_u.full
                   else [(pid, pg) for pid, pool in new.pools.items()
                         for pg in range(pool.pg_num)])
        for pid, pg in reads_u:
            svc_uf.lookup(new, pid, pg)
        t_unfused.append(time.perf_counter() - t0)
    # mesh/engine-backed replay of the SAME epoch sequence.  The
    # min-pgs floor would route this workload's pool sizes to the
    # scalar rebuild path (engine never touched — the column would
    # measure nothing); zero it so recomputed pools really submit
    # through the (mesh-sharded when multi-device) dispatch engine.
    mesh_ctx = CephTpuContext("bench-map-mesh")
    mesh_ctx.conf.set("osdmap_mapping_min_pgs", 0)
    svc_mesh = SharedPGMappingService(mesh_ctx)
    svc_mesh.update_to(base)
    t_mesh: list[float] = []
    for frm, new, oracle in epoch_log:
        t0 = time.perf_counter()
        upd_m = svc_mesh.update_to(new, from_epoch=frm)
        reads_m = (upd_m.changed if not upd_m.full
                   else [(pid, pg) for pid, pool in new.pools.items()
                         for pg in range(pool.pg_num)])
        for pid, pg in reads_m:
            svc_mesh.lookup(new, pid, pg)
        t_mesh.append(time.perf_counter() - t0)
        for (pid, pg), want in oracle.items():
            if svc_mesh.lookup(new, pid, pg) != want:
                verified = False
    # mesh_devices is EVIDENCE, not aspiration: read the placement the
    # replay's engine actually used (1 = the engine path ran without a
    # mesh — single-device backend or mesh build failure)
    mesh_devices = 1
    if mesh_ctx._dispatch is not None:
        pm = mesh_ctx._dispatch.placement_mesh()
        if pm is not None:
            mesh_devices = int(pm.size)
    # the mesh context's engines were lazily built for this section
    # only: drain and stop their threads instead of leaking them for
    # the rest of the bench process
    for eng in (mesh_ctx._dispatch, mesh_ctx._decode_dispatch):
        if eng is not None:
            eng.stop()
    med = (lambda xs: sorted(xs)[len(xs) // 2])
    sh, sc = med(t_shared), med(t_scalar)
    shm = med(t_mesh)
    shu = med(t_unfused)
    return {
        "pgs": pools * pg_num,
        "osds": n,
        "epochs": epochs,
        "scalar_epoch_s": round(sc, 4),
        "shared_epoch_s": round(sh, 4),
        "shared_epoch_s_unfused": round(shu, 4),
        "shared_epoch_s_mesh": round(shm, 4),
        "mesh_devices": mesh_devices,
        "speedup": round(sc / sh, 1) if sh > 0 else 0.0,
        "fused_speedup": round(shu / sh, 2) if sh > 0 else 0.0,
        "speedup_mesh": round(sc / shm, 1) if shm > 0 else 0.0,
        "scalar_epochs_per_s": round(1.0 / sc, 2) if sc > 0 else 0.0,
        "shared_epochs_per_s": round(1.0 / sh, 2) if sh > 0 else 0.0,
        "mean_changed_pgs": round(sum(changed_counts)
                                  / len(changed_counts), 1),
        "verified": verified,
        "mapping": digest,
    }


def profile_section(k: int = 8, m: int = 4, chunk: int = 1024,
                    writers: int = 4, ops_each: int = 10,
                    epochs: int = 4) -> dict:
    """Pipeline-profile micro-section: a short burst of concurrent
    encodes + heterogeneous decodes through context-backed dispatch
    engines and a few mapping epochs, then the profiler digest — the
    bench JSON gains the same where-did-the-time-go attribution
    (phase shares, compile seconds, utilization, mapping phase split)
    an operator reads from ``dump_pipeline_profile`` on a live
    daemon.  Deliberately tiny: it exists to capture phase SHARES per
    bench round, not to be a throughput sweep."""
    import threading

    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.ops import telemetry
    from ceph_tpu.osd import OSDMap, PGPool, SharedPGMappingService

    # the phase ledgers are process-global and earlier sections'
    # engines feed them: clear so the digest describes THIS section's
    # burst (shares, first-call compile events, utilization window),
    # not the whole run.  Runs last in main(), after every other
    # section's digest is already captured into the JSON.
    telemetry.dispatch_stats().phases.clear()
    telemetry.decode_dispatch_stats().phases.clear()
    telemetry.mapping_stats().clear()
    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m)})
    ctx = CephTpuContext("bench-profile")
    eng = ctx.dispatch_engine()
    deng = ctx.decode_dispatch_engine()
    rng = np.random.default_rng(13)
    op = rng.integers(0, 256, (32, k, chunk), dtype=np.uint8)
    patterns = []
    for e0 in range(min(k, 3)):
        erased = (e0, (e0 + 2) % k)
        erased = tuple(sorted(set(erased)))
        chosen = [c for c in range(k + m) if c not in erased][:k]
        patterns.append((tuple(chosen), erased))
    start = threading.Barrier(writers + 1)

    def actor(aid):
        start.wait()
        for i in range(ops_each):
            codec.submit_chunks(eng, op).result(timeout=120)
            if i % 2 == 0:
                chosen, targets = patterns[(aid + i) % len(patterns)]
                codec.submit_decode_chunks(
                    deng, chosen, op, targets).result(timeout=120)

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(writers)]
    for t in threads:
        t.start()
    start.wait()
    for t in threads:
        t.join()
    eng.flush()
    deng.flush()
    # a few mapping epochs so the digest's mapping phase split is live
    crush, _root, rule = build_two_level_map(4, 2)
    mp = OSDMap(crush=crush, epoch=2)
    mp.set_max_osd(8)
    for o in range(8):
        mp.mark_up(o)
    for p in (1, 2):
        mp.pools[p] = PGPool(pool_id=p, size=3, crush_rule=rule,
                             pg_num=64)
    svc = SharedPGMappingService()
    svc.update_to(mp)
    for i in range(epochs):
        new = mp.copy()
        new.epoch = mp.epoch + 1
        new.osd_weight[i % 8] = 0x8000 if i % 2 else 0x10000
        svc.update_to(new)
        mp = new
    for e in (eng, deng):
        e.stop()
    return telemetry.pipeline_profile_digest()


def placement_digest(crush_map, rid: int, bm, reweight: np.ndarray,
                     t_crush: float, n_pgs: int, numrep: int = 3,
                     sample: int = 2048) -> dict:
    """Fused-pipeline placement digest for the crush section: the full
    raw→up→acting ladder (ops.placement_kernel) over all ``n_pgs`` PGs
    of a 10k-OSD map in one device call — affinity skew, temps and
    upmap pairs injected so every ladder stage does real work — vs the
    per-PG host pipeline tail it replaces.  ``pipeline_mpps`` composes
    the measured raw rate (``t_crush`` per batch) with the ladder;
    a ``sample`` of rows is bit-verified against the host tail."""
    import jax.numpy as jnp

    from ceph_tpu.ops import placement_kernel as pk
    from ceph_tpu.osd import OSDMap, PGPool
    from ceph_tpu.osd.mapping import _finish_from, pps_batch

    n_osds = len(reweight)
    m = OSDMap(crush=crush_map, epoch=2)
    m.set_max_osd(n_osds)
    for o in range(n_osds):
        m.osd_state[o] = 3                      # exists | up
        m.osd_weight[o] = int(reweight[o])
    orng = np.random.default_rng(9)
    for o in orng.integers(0, n_osds, 500):     # 5%-ish affinity skew
        m.osd_primary_affinity[int(o)] = 0x8000
    pool = PGPool(pool_id=1, size=numrep, crush_rule=rid, pg_num=n_pgs)
    m.pools[1] = pool
    for pg in orng.integers(0, n_pgs, 512):
        m.pg_temp[(1, int(pg))] = [int(x) for x in
                                   orng.integers(0, n_osds, numrep)]
    for pg in orng.integers(0, n_pgs, 512):
        frm = int(orng.integers(0, n_osds))
        m.pg_upmap_items[(1, int(pg))] = [(frm, (frm + 7) % n_osds)]
    for pg in orng.integers(0, n_pgs, 256):
        m.primary_temp[(1, int(pg))] = int(orng.integers(0, n_osds))

    pgids = np.arange(n_pgs, dtype=np.uint32)
    pps = np.asarray(pps_batch(pool, pgids))
    raw = np.asarray(bm.do_rule(rid, jnp.asarray(pps), numrep,
                                jnp.asarray(reweight)), dtype=np.int32)
    width, pairs = pk.pool_widths(m)
    ops_ = pk.build_operands(m, 1, pool, raw, pps, width=width,
                             pairs=pairs)

    def make_step():
        from ceph_tpu.ops.placement_kernel import _ladder_jit
        fn = _ladder_jit(ops_.erasure)
        aux = tuple(jnp.asarray(a) for a in ops_.aux())
        vecs = (jnp.asarray(ops_.state), jnp.asarray(ops_.weight),
                jnp.asarray(ops_.affinity))

        def step(r):
            packed = fn(r, *aux, *vecs)
            return r.at[0, 0].set(packed[0, 0] ^ r[0, 0])
        return step

    # lean counts: the ladder is one fused call per step and the crush
    # section is already the longest on slow hosts
    t_ladder, _lo, _hi = median_band(chained_rates(
        make_step(), jnp.asarray(raw), n_lo=2, n_hi=12, reps=3,
        inner=3))

    # host-tail baseline on a sample (the per-PG _finish_from the
    # ladder replaces), and the bit-exactness gate on the same rows
    packed = pk.run_ladder(ops_)
    raw_tab, pps_tab = {1: raw}, {1: pps}
    idx = orng.integers(0, n_pgs, sample)
    t0 = time.perf_counter()
    wants = [_finish_from(m, pool, 1, int(pg), raw_tab, pps_tab)
             for pg in idx]
    t_tail = (time.perf_counter() - t0) / sample
    verified = all(
        pk.unpack_row(packed[int(pg)], width) == want
        for pg, want in zip(idx, wants))
    ladder_mpps = n_pgs / t_ladder / 1e6
    return {
        "pgs": n_pgs,
        "osds": n_osds,
        "ladder_mpps": round(ladder_mpps, 3),
        "pipeline_mpps": round(n_pgs / (t_crush + t_ladder) / 1e6, 3),
        "host_tail_mpps": round(1.0 / t_tail / 1e6, 4),
        "ladder_vs_host_tail": round(ladder_mpps * 1e6 * t_tail, 1),
        "verified": verified,
    }


SECTIONS = ("ec", "crush", "dispatch_sweep", "recovery_sweep",
            "map_churn", "profile", "qos", "scrub", "objectstore")
#: the historical flagship run (map_churn is opt-in: it is a
#: consumption-path sweep, not a device-kernel headline)
DEFAULT_SECTIONS = ("ec", "crush", "dispatch_sweep", "recovery_sweep")


def _tenant_queue_rates(profiles, pump_threads, *, service_s,
                        warmup_s, measure_s, qos_on=True,
                        extra_pumps=()):
    """Shared closed-loop tenant-pump harness for the queue-level QoS
    sweeps (qos_section and scrub_section both drive it — ONE copy,
    so the 4-tenant scenario cannot drift between them).  Pumps run
    closed-loop against one ShardedOpQueue whose handler has a FIXED
    per-op service time (capacity = 1/service_s with one shard
    worker); ``extra_pumps`` adds (name, klass, threads) pump sets
    (the scrub storm) on top of the tenant lanes.  Returns
    (rates, wait_p99) keyed by pump name."""
    import threading as _th

    from ceph_tpu.osd.op_queue import ClassInfo, ShardedOpQueue

    lock = _th.Lock()
    names = list(pump_threads) + [n for n, _k, _t in extra_pumps]
    counts = {n: 0 for n in names}
    waits: dict = {n: [] for n in names}

    def handler(klass, item, served=None):
        time.sleep(service_s)
        name, sem = item
        with lock:
            counts[name] += 1
            if served is not None:
                waits[name].append(served[1])
        sem.release()

    wq = ShardedOpQueue(
        handler, n_shards=1, name="bench-tenants",
        client_template=ClassInfo(weight=100.0),
        client_profiles={f"client.{t}": p
                         for t, p in profiles.items()}
        if qos_on else None)
    stop = _th.Event()

    def pump(name, klass):
        sem = _th.Semaphore(0)
        while not stop.is_set():
            wq.enqueue(name, klass, (name, sem))
            sem.acquire()

    specs = [(t, f"client.{t}" if qos_on else "client", n)
             for t, n in pump_threads.items()]
    specs += list(extra_pumps)
    threads = [_th.Thread(target=pump, args=(n, k), daemon=True)
               for n, k, cnt in specs for _ in range(cnt)]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    with lock:
        base = dict(counts)
        for v in waits.values():
            v.clear()
    t0 = time.perf_counter()
    time.sleep(measure_s)
    with lock:
        snap = {n: counts[n] - base[n] for n in names}
        wsnap = {n: sorted(waits[n]) for n in names}
    elapsed = time.perf_counter() - t0
    stop.set()
    wq.shutdown()
    rates = {n: c / elapsed for n, c in snap.items()}
    p99 = {n: (w[int(0.99 * (len(w) - 1))] if w else 0.0)
           for n, w in wsnap.items()}
    return rates, p99


def _tenant_device_burst(tenants, ops_each: int = 3, k: int = 4,
                         m: int = 2, chunk: int = 512) -> dict:
    """Tiny tagged-submit burst through a context-backed dispatch
    engine: each tenant's encode batches carry a ``cost_tag``, plus
    one scrub-style batch riding as background_best_effort, so the
    qos section's JSON gains the same tenant device-time ledger
    digest the mgr ships in the MMgrReport tail."""
    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.ops import telemetry
    from ceph_tpu.ops.dispatch import BACKGROUND_BEST_EFFORT

    # the ledger is process-global and earlier sections' engines feed
    # it untagged: clear so the digest attributes THIS burst
    telemetry.tenant_stats().clear()
    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m)})
    ctx = CephTpuContext("bench-qos-tenants")
    eng = ctx.dispatch_engine()
    rng = np.random.default_rng(7)
    op = rng.integers(0, 256, (8, k, chunk), dtype=np.uint8)
    futs = []
    for tenant in tenants:
        futs.extend(codec.submit_chunks(eng, op,
                                        cost_tag=(tenant, "client"))
                    for _ in range(ops_each))
    futs.append(codec.submit_chunks(
        eng, op,
        cost_tag=(BACKGROUND_BEST_EFFORT, BACKGROUND_BEST_EFFORT)))
    for f in futs:
        f.result(timeout=120)
    eng.flush()
    eng.stop()
    return telemetry.tenant_usage_digest()


def qos_section(measure_s: float = 2.5, warmup_s: float = 0.8,
                service_s: float = 0.002) -> dict:
    """Multi-tenant dmClock fairness sweep (--sections qos; validated
    standalone — the full bench exceeds the 590 s budget on this host).

    Four tenants drive one sharded op queue whose handler has a FIXED
    per-op service time (capacity = 1/service_s with one shard
    worker): a hog (weight 8) floods, gold holds a 100 ops/s
    reservation, silver (weight 2) shares the excess, bronze is capped
    at 50 ops/s.  The sweep runs twice — dmclock lanes with profiles
    vs one aggregate FIFO class (QoS off = the seed's arbitration) —
    and reports per-tenant throughput + queue-wait p99, the
    reservation attainment, the limit overshoot, and the hog:silver
    excess ratio vs the configured 4.0.  A tagged device burst then
    captures the tenant device-time ledger digest under
    ``tenant_usage`` (renderable by tools/profile_report.py)."""
    from ceph_tpu.osd.op_queue import ClassInfo

    profiles = {
        "hog": ClassInfo(weight=8.0),
        "gold": ClassInfo(reservation=100.0, weight=0.01),
        "silver": ClassInfo(weight=2.0),
        "bronze": ClassInfo(weight=8.0, limit=50.0),
    }
    pumps = {"hog": 8, "gold": 3, "silver": 4, "bronze": 4}

    def run(qos_on: bool) -> dict:
        rates, p99 = _tenant_queue_rates(
            profiles, pumps, service_s=service_s, warmup_s=warmup_s,
            measure_s=measure_s, qos_on=qos_on)
        return {"tenant_ops_s": {t: round(r, 1)
                                 for t, r in rates.items()},
                "tenant_wait_p99_s": {t: round(v, 4)
                                      for t, v in p99.items()},
                "_rates": rates}

    qos = run(qos_on=True)
    fifo = run(qos_on=False)
    r = qos.pop("_rates")
    rf = fifo.pop("_rates")
    hog_silver = r["hog"] / max(r["silver"], 1e-9)
    return {
        "capacity_ops_s": round(1.0 / service_s, 1),
        "profiles": {t: {"reservation": p.reservation,
                         "weight": p.weight, "limit": p.limit}
                     for t, p in profiles.items()},
        "qos": qos,
        "fifo": fifo,
        "reservation_attainment": round(r["gold"] / 100.0, 3),
        "reservation_attainment_fifo": round(rf["gold"] / 100.0, 3),
        "limit_overshoot": round(r["bronze"] / 50.0, 3),
        "excess_ratio_hog_silver": round(hog_silver, 2),
        "excess_ratio_configured": 4.0,
        "tenant_usage": _tenant_device_burst(list(profiles)),
    }


def scrub_section(n_objects: int = 384, obj_bytes: int = 8192,
                  measure_s: float = 2.0, warmup_s: float = 0.6,
                  service_s: float = 0.002) -> dict:
    """Background-integrity sweep (--sections scrub; validated
    standalone — the full bench exceeds the 590 s budget on this
    host).  Two sub-sweeps:

    (a) digest throughput: a PG-sized object population digested by
        the seed's scalar shard_crc loop vs the batched scrub_digest
        channel through a private dispatch engine (objects/s + MB/s,
        bit-verified against each other);

    (b) tenant reservation attainment with and without the background
        class: the qos_section's 4-tenant queue with a continuous
        scrub pump added — scrub ops riding background_best_effort vs
        jammed into the aggregate client class vs scrub off — so the
        number the fairness gate watches (gold's attainment under a
        scrub storm, relative to the scrub-off baseline) prices the
        QoS lane directly."""
    from ceph_tpu.ops.dispatch import (
        DeviceDispatchEngine, submit_scrub_digest)
    from ceph_tpu.ops.telemetry import DispatchStats
    from ceph_tpu.osd.ec_util import shard_crc
    from ceph_tpu.osd.op_queue import ClassInfo

    rng = np.random.default_rng(11)
    sizes = rng.integers(obj_bytes // 2, obj_bytes, n_objects)
    blobs = [rng.integers(0, 256, int(s), dtype=np.uint8).tobytes()
             for s in sizes]
    total_bytes = int(sizes.sum())

    # scalar: the seed's per-object host loop
    t_scalar = float("inf")
    scalar_crcs = None
    for _ in range(3):
        t0 = time.perf_counter()
        scalar_crcs = [shard_crc(b) for b in blobs]
        t_scalar = min(t_scalar, time.perf_counter() - t0)

    # batched: PG-sized groups through one private engine (the groups
    # coalesce on the shared width bucket, exactly like concurrent
    # PG scrubs in the OSD)
    group = 64
    eng = DeviceDispatchEngine(name="bench-scrub",
                               stats=DispatchStats())
    try:
        futs = [submit_scrub_digest(
            eng, blobs[i:i + group])
            for i in range(0, len(blobs), group)]
        for f in futs:
            f.result(timeout=120.0)       # jit warmup outside timing
        t_batched = float("inf")
        digs = None
        for _ in range(3):
            t0 = time.perf_counter()
            futs = [submit_scrub_digest(eng, blobs[i:i + group])
                    for i in range(0, len(blobs), group)]
            digs = np.concatenate(
                [np.asarray(f.result(timeout=120.0)) for f in futs])
            t_batched = min(t_batched, time.perf_counter() - t0)
        verified = all(int(digs[i, 0]) == scalar_crcs[i]
                       for i in range(len(blobs)))
        eng_summary = eng.stats.summary()
    finally:
        eng.stop()

    digest = {
        "objects": n_objects,
        "mbytes": round(total_bytes / 1e6, 2),
        "scalar_objects_s": round(n_objects / t_scalar, 1),
        "scalar_mbps": round(total_bytes / t_scalar / 1e6, 1),
        "batched_objects_s": round(n_objects / t_batched, 1),
        "batched_mbps": round(total_bytes / t_batched / 1e6, 1),
        "batched_vs_scalar": round(t_scalar / t_batched, 2),
        "mean_coalesce": eng_summary["mean_coalesce"],
        "verified": verified,
    }

    # -- (b) reservation attainment with/without the background class
    profiles = {
        "hog": ClassInfo(weight=8.0),
        "gold": ClassInfo(reservation=100.0, weight=0.01),
        "silver": ClassInfo(weight=2.0),
        "bronze": ClassInfo(weight=8.0, limit=50.0),
    }
    pumps = {"hog": 8, "gold": 3, "silver": 4, "bronze": 4}

    def run(scrub_class: str | None) -> dict:
        extra = (() if scrub_class is None
                 else (("_scrub", scrub_class, 4),))
        rates, _p99 = _tenant_queue_rates(
            profiles, pumps, service_s=service_s, warmup_s=warmup_s,
            measure_s=measure_s, extra_pumps=extra)
        rates.setdefault("_scrub", 0.0)
        return rates

    off = run(None)
    bg = run("background_best_effort")
    fg = run("client")    # scrub jammed into the aggregate client lane
    fairness = {
        "capacity_ops_s": round(1.0 / service_s, 1),
        "gold_reservation": 100.0,
        "attainment_scrub_off": round(off["gold"] / 100.0, 3),
        "attainment_background": round(bg["gold"] / 100.0, 3),
        "attainment_client_class": round(fg["gold"] / 100.0, 3),
        "attainment_vs_off": round(
            bg["gold"] / max(off["gold"], 1e-9), 3),
        "scrub_ops_s_background": round(bg["_scrub"], 1),
        "scrub_ops_s_client_class": round(fg["_scrub"], 1),
    }
    return {"digest": digest, "fairness": fairness}


def objectstore_section(n_objects: int = 96,
                        obj_bytes: int = 65536) -> dict:
    """Device-resident objectstore write path (--sections
    objectstore; validated standalone).  Three sub-sweeps over a real
    on-disk BlueStoreLite:

    (a) write+read MB/s: the seed's scalar per-block ``zlib.crc32``
        store vs one whose commits settle checksums through the
        ``bluestore_data`` channel (batched reads verify through the
        same channel); every committed checksum in the batched store
        is re-verified against host zlib.crc32 of the stored bytes,
        and every read is byte-compared against the written payloads;

    (b) csum settle micro: the channel's digest call vs the host crc32
        loop over identical staged payloads — the isolated quantity
        the channel accelerates, free of fsync/KV noise;

    (c) compression-on head-to-head: the seed scalar path with the
        registry's host zlib plugin vs the device store with
        tpu_bitplane (plane extraction batched per commit), same
        6-bit payloads, ``compression_mode=force`` both sides —
        write+read MB/s, stored-byte ratios, round-trip and csum
        verification.  Read-side channel verification is priced by
        (a)/(b); here it is disabled so the leg isolates the
        compressor comparison."""
    import os as _os
    import shutil as _shutil
    import tempfile
    import zlib as _zlib

    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.objectstore.bluestore import (
        BLOCK, BlueStoreLite)
    from ceph_tpu.objectstore.transaction import Transaction

    rng = np.random.default_rng(23)
    # 6-bit payloads: two provably-zero bit planes, so the bitplane
    # leg clearly clears the required-ratio gate; the csum legs are
    # content-agnostic
    payloads = [rng.integers(0, 64, obj_bytes,
                             dtype=np.uint8).tobytes()
                for _ in range(n_objects)]
    total = n_objects * obj_bytes
    group = 8   # objects per transaction -> blocks per digest batch

    base = tempfile.mkdtemp(prefix="bench-objstore-")
    ctx = CephTpuContext("bench-objectstore")
    ctx.conf.set("bluestore_batched_csum_min", "1", source="cli")

    def mkstore(name, use_ctx):
        path = _os.path.join(base, name)
        s = BlueStoreLite(path, ctx=ctx if use_ctx else None)
        s.mkfs()
        s.mount()
        t = Transaction().create_collection("2.0")
        s.apply_transaction(t)
        return s

    def write_all(store):
        t0 = time.perf_counter()
        for i in range(0, n_objects, group):
            txn = Transaction()
            for j in range(i, min(i + group, n_objects)):
                txn.write("2.0", f"obj-{j}", 0, payloads[j])
            store.apply_transaction(txn)
        return time.perf_counter() - t0

    def read_all(store):
        best, got = float("inf"), None
        for _ in range(2):
            t0 = time.perf_counter()
            got = [store.read("2.0", f"obj-{j}")
                   for j in range(n_objects)]
            best = min(best, time.perf_counter() - t0)
        return best, got

    def verify_csums(store):
        """Every committed csum must equal host zlib.crc32 of the
        block's STORED bytes — the bit-exactness gate on the device
        digest."""
        for blob in store._db.get_range("obj").values():
            meta = json.loads(blob.decode())
            co = meta.get("comp") or []
            for bi, b in enumerate(meta["extents"]):
                if b < 0:
                    continue
                comp = co[bi] if bi < len(co) else None
                data = store._read_block(b)
                stored = data[:comp[1]] if comp else data
                if _zlib.crc32(stored) != meta["csum"][bi]:
                    return False
        return True

    out: dict = {}
    try:
        scalar = mkstore("scalar", use_ctx=False)
        batched = mkstore("batched", use_ctx=True)
        try:
            write_all(batched)        # jit warmup outside timing
            t_ws = min(write_all(scalar) for _ in range(2))
            t_wb = min(write_all(batched) for _ in range(2))
            t_rs, got_s = read_all(scalar)
            t_rb, got_b = read_all(batched)
            verified = (got_s == payloads and got_b == payloads
                        and verify_csums(batched))
            from ceph_tpu.ops import telemetry
            bstats = telemetry.bluestore_summary()
        finally:
            scalar.umount()
            batched.umount()

        # (b) the isolated csum-settle quantity: host crc loop vs one
        # channel digest over the same staged payloads
        blobs = [p[i:i + BLOCK]
                 for p in payloads[:16]
                 for i in range(0, obj_bytes, BLOCK)]
        t_host = float("inf")
        host_crcs = None
        for _ in range(3):
            t0 = time.perf_counter()
            host_crcs = [_zlib.crc32(b) for b in blobs]
            t_host = min(t_host, time.perf_counter() - t0)
        from ceph_tpu.ops.dispatch import (
            DeviceDispatchEngine, submit_bluestore_data)
        from ceph_tpu.ops.telemetry import DispatchStats
        eng = DeviceDispatchEngine(name="bench-objstore",
                                   stats=DispatchStats())
        try:
            submit_bluestore_data(eng, blobs).result(timeout=120.0)
            t_dev = float("inf")
            dig = None
            for _ in range(3):
                t0 = time.perf_counter()
                dig = np.asarray(submit_bluestore_data(
                    eng, blobs).result(timeout=120.0))
                t_dev = min(t_dev, time.perf_counter() - t0)
            micro_ok = all(int(dig[i, 0]) == host_crcs[i]
                           for i in range(len(blobs)))
        finally:
            eng.stop()

        # (c) compression-on head-to-head: seed scalar path + host
        # zlib vs the device store + tpu_bitplane, force mode both
        # sides.  Channel read-verify is priced by (a)/(b) — off here
        # so the leg isolates the compressor comparison.
        def stored_ratio(store):
            stored = logical = 0
            for blob in store._db.get_range("obj").values():
                meta = json.loads(blob.decode())
                for bi, b in enumerate(meta["extents"]):
                    if b < 0:
                        continue
                    ce = (meta.get("comp") or [None] * (bi + 1))[bi]
                    logical += BLOCK
                    stored += ce[1] if ce else BLOCK
            return stored / max(logical, 1)

        ctx.conf.set("bluestore_batched_read_verify", "false",
                     source="cli")
        comp_s = mkstore("comp-scalar", use_ctx=False)
        comp_b = mkstore("comp-batched", use_ctx=True)
        try:
            comp_s.set_pool_compression(2, "force", "zlib")
            comp_b.set_pool_compression(2, "force", "tpu_bitplane")
            write_all(comp_b)     # jit warmup outside timing
            t_cws = min(write_all(comp_s) for _ in range(2))
            t_cwb = min(write_all(comp_b) for _ in range(2))
            t_crs, got_cs = read_all(comp_s)
            t_crb, got_cb = read_all(comp_b)
            comp_ok = (got_cs == payloads and got_cb == payloads
                       and verify_csums(comp_s)
                       and verify_csums(comp_b))
            ratio_s = stored_ratio(comp_s)
            ratio_b = stored_ratio(comp_b)
        finally:
            comp_s.umount()
            comp_b.umount()

        out = {
            "objects": n_objects,
            "mbytes": round(total / 1e6, 2),
            "write_scalar_mbps": round(total / t_ws / 1e6, 1),
            "write_batched_mbps": round(total / t_wb / 1e6, 1),
            "write_batched_vs_scalar": round(t_ws / t_wb, 2),
            "read_scalar_mbps": round(total / t_rs / 1e6, 1),
            "read_batched_mbps": round(total / t_rb / 1e6, 1),
            "csum_settle_host_mbps": round(
                len(blobs) * BLOCK / t_host / 1e6, 1),
            "csum_settle_device_mbps": round(
                len(blobs) * BLOCK / t_dev / 1e6, 1),
            "csum_settle_batched_vs_scalar": round(t_host / t_dev, 2),
            "csum_batches": bstats.get("csum_batches", 0),
            "batched_csum_blocks": bstats.get("batched_csum_blocks", 0),
            "read_verify_batches": bstats.get("read_verify_batches", 0),
            "comp_write_scalar_zlib_mbps": round(
                total / t_cws / 1e6, 1),
            "comp_write_batched_bitplane_mbps": round(
                total / t_cwb / 1e6, 1),
            "comp_write_batched_vs_scalar": round(t_cws / t_cwb, 2),
            "comp_read_scalar_zlib_mbps": round(
                total / t_crs / 1e6, 1),
            "comp_read_batched_bitplane_mbps": round(
                total / t_crb / 1e6, 1),
            "comp_read_batched_vs_scalar": round(t_crs / t_crb, 2),
            "comp_stored_ratio_zlib": round(ratio_s, 3),
            "comp_stored_ratio_bitplane": round(ratio_b, 3),
            "compress_verified": comp_ok,
            "verified": verified and micro_ok,
        }
    finally:
        for eng_attr in ("_decode_dispatch", "_dispatch"):
            e = getattr(ctx, eng_attr, None)
            if e is not None:
                e.stop()
        _shutil.rmtree(base, ignore_errors=True)
    return out


def main(argv=None) -> None:
    import argparse

    import jax
    import jax.numpy as jnp

    ap = argparse.ArgumentParser(
        prog="bench",
        description="tpu-rados flagship benchmark; see module "
                    "docstring for the section list")
    ap.add_argument(
        "--sections", default=None, metavar="NAMES",
        help="comma list of sweeps to run (%s), or 'all'; default "
             "runs the flagship set (%s).  Any single section "
             "completes well inside a 590 s harness timeout."
             % (",".join(SECTIONS), ",".join(DEFAULT_SECTIONS)))
    args = ap.parse_args(argv)
    if args.sections is None:
        secs = set(DEFAULT_SECTIONS)
    elif args.sections.strip() == "all":
        secs = set(SECTIONS)
    else:
        secs = {s.strip() for s in args.sections.split(",") if s.strip()}
        unknown = secs - set(SECTIONS)
        if unknown:
            ap.error(f"unknown sections {sorted(unknown)}; "
                     f"choose from {SECTIONS}")

    k, m = 8, 4
    chunk = 4096          # 4 KiB chunks — BASELINE.json config
    stripes = 2048        # 64 MiB of data per device call
    erasures = [1, k + 1]  # one data + one parity chunk lost
    data_bytes = stripes * k * chunk
    rng = np.random.default_rng(0)
    out: dict = {}

    encode = None
    if secs & {"ec", "dispatch_sweep"}:
        from ceph_tpu.gf.matrix import gen_cauchy1_matrix, recovery_matrix
        from ceph_tpu.ops.gf_kernel import make_encoder

        gen = gen_cauchy1_matrix(k, m)
        coding = gen[k:]
        chosen = [i for i in range(k + m) if i not in set(erasures)][:k]
        rmat = recovery_matrix(gen, chosen, erasures)
        encode = make_encoder(coding)
        recover = make_encoder(rmat)

    data = None
    if "ec" in secs:
        data = jnp.asarray(
            rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))

        def enc_step(d):
            p = encode(d)
            return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

        t_enc, t_enc_min, t_enc_max = median_band(
            chained_rates(enc_step, data))
        enc_mbps = data_bytes / t_enc / 1e6

        surv = jnp.asarray(
            rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))

        def dec_step(s):
            r = recover(s)
            return s.at[0, 0, 0].set(r[0, 0, 0] ^ jnp.uint8(1))

        t_dec, t_dec_min, t_dec_max = median_band(
            chained_rates(dec_step, surv))
        dec_mbps = data_bytes / t_dec / 1e6

        combined = 2 * data_bytes / (t_enc + t_dec) / 1e6

        # single-core C baseline (ceph_tpu/native): ISA-L-class SIMD
        # encode, same inputs, same math
        from ceph_tpu.native import ec_encode_native

        cpu_data = np.asarray(data)
        t_c = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            ec_encode_native(coding, cpu_data)
            t_c = min(t_c, time.perf_counter() - t0)
        c_enc_mbps = data_bytes / t_c / 1e6
        t_c = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            ec_encode_native(rmat, cpu_data)
            t_c = min(t_c, time.perf_counter() - t0)
        c_dec_mbps = data_bytes / t_c / 1e6
        c_combined = 2 / (1 / c_enc_mbps + 1 / c_dec_mbps)

        out.update({
            "metric": "ec encode+recover MB/s "
                      "(k=8,m=4,4KiB chunks, batch=2048)",
            "value": round(combined, 1),
            "unit": "MB/s",
            "vs_baseline": round(combined / c_combined, 2),
            "encode_mbps": round(enc_mbps, 1),
            "encode_mbps_band": [round(data_bytes / t_enc_max / 1e6, 1),
                                 round(data_bytes / t_enc_min / 1e6, 1)],
            "recover_mbps": round(dec_mbps, 1),
            "recover_mbps_band": [round(data_bytes / t_dec_max / 1e6, 1),
                                  round(data_bytes / t_dec_min / 1e6, 1)],
            "c_encode_mbps": round(c_enc_mbps, 1),
            "c_recover_mbps": round(c_dec_mbps, 1),
            "encode_vs_c": round(enc_mbps / c_enc_mbps, 2),
        })

    bm = None
    if "crush" in secs:
        # CRUSH bulk placement (BASELINE config #5 shape): 10k-OSD
        # two-level map (250 hosts x 40 osds), chooseleaf firstn 3, 64k
        # PGs per device call.  Non-uniform: skewed per-osd bucket
        # weights, 10% reweighted to 0.5, 2% out — the retry ladder
        # actually fires.
        from ceph_tpu.crush import build_two_level_map
        from ceph_tpu.crush.mapper_jax import BatchMapper

        crush_map, _root, rid = build_two_level_map(250, 40)
        wrng = np.random.default_rng(42)
        for b in crush_map.buckets:
            if b is not None and b.type == 1:  # host level: skew weights
                b.item_weights = [int(w) for w in
                                  wrng.integers(0x8000, 0x20000, b.size)]
                b.weight = sum(b.item_weights)
        root = crush_map.bucket(-1)
        root.item_weights = [crush_map.bucket(h).weight
                             for h in root.items]
        root.weight = sum(root.item_weights)

        n_osds = 10000
        reweight = np.full(n_osds, 0x10000, dtype=np.int64)
        idx = wrng.permutation(n_osds)
        reweight[idx[:1000]] = 0x8000   # 10% half-weight
        reweight[idx[1000:1200]] = 0    # 2% out

        bm = BatchMapper(crush_map)
        n_pgs, numrep = 65536, 3
        rw = jnp.asarray(reweight)
        xs = jnp.asarray(rng.integers(0, 2**32, (n_pgs,),
                                      dtype=np.uint32))
        bm.do_rule(rid, xs, numrep, rw)  # compile

        def crush_step(x):
            p = bm.do_rule(rid, x, numrep, rw)
            return x ^ p[:, 0].astype(jnp.uint32)

        t_crush, t_crush_min, t_crush_max = median_band(
            chained_rates(crush_step, xs, n_lo=4, n_hi=24, reps=5,
                          inner=4))
        crush_mpps = n_pgs / t_crush / 1e6

        # single-core C baseline: scalar straw2 crush_do_rule
        from ceph_tpu.native import CrushBaseline

        cb = CrushBaseline(crush_map)
        c_xs = np.asarray(xs[:8192], dtype=np.uint32)
        cb.do_rule_batch(rid, c_xs[:256], numrep,
                         reweight.astype(np.uint32))
        t0 = time.perf_counter()
        cb.do_rule_batch(rid, c_xs, numrep, reweight.astype(np.uint32))
        c_crush_mpps = len(c_xs) / (time.perf_counter() - t0) / 1e6

        out.update({
            "crush_mpps": round(crush_mpps, 3),
            "crush_mpps_band": [round(n_pgs / t_crush_max / 1e6, 3),
                                round(n_pgs / t_crush_min / 1e6, 3)],
            "c_crush_mpps": round(c_crush_mpps, 3),
            "crush_vs_c": round(crush_mpps / c_crush_mpps, 2),
        })
        # fused raw→up→acting ladder over the same map: the
        # device-resident pipeline-tail story (ISSUE 10), bit-verified
        # against the host tail on a sample
        out["placement"] = placement_digest(
            crush_map, rid, bm, reweight, t_crush, n_pgs)

    from ceph_tpu.ops import telemetry
    if "ec" in secs and "crush" in secs:
        # kernel telemetry digest (retraces, p50/p99 latency,
        # occupancy): the timed loops above run inside jitted scans, so
        # close with a few FENCED standalone calls — real per-call
        # device residency samples — before summarizing.  A retrace
        # count above the handful of shapes this harness uses is the
        # regression tell.
        from ceph_tpu.common import tracing
        telemetry.set_fence_for_timing(True)
        # trace the fenced calls with a zero slow threshold: every one
        # lands in the slow ring, so the JSON records a tail-latency
        # digest (count + p99 root-span duration) next to the
        # throughput headline
        tracing.set_slow_threshold(0.0)
        for _ in range(3):
            with tracing.trace_ctx(name="bench ec_encode",
                                   daemon="bench"):
                encode(data)
            with tracing.trace_ctx(name="bench crush_map",
                                   daemon="bench"):
                bm.do_rule(rid, xs, numrep, rw)
        telemetry.set_fence_for_timing(False)
        out["kernel_telemetry"] = telemetry.registry().summary()
        out["slow_traces"] = tracing.slow_summary()

    if "dispatch_sweep" in secs:
        # cross-op coalescing: offered-concurrency sweep through the
        # dispatch engine (1/4/16/64 in-flight writers, OSD-write-sized
        # ops).  The headline EC numbers above are device-resident;
        # this is the END-TO-END rate a concurrent client population
        # sees, and the coalesce factor is the amortization making up
        # the gap.
        sweep = dispatch_sweep(encode, k, chunk, coding=coding)
        out["dispatch"] = telemetry.dispatch_summary()   # key order as
        out["dispatch_sweep"] = sweep                    # historically

    if "recovery_sweep" in secs:
        # decode-side twin: degraded-read/recovery concurrency sweep
        # with 2 erasures per op and MIXED recovery patterns across
        # readers — the heterogeneous-matrix batched decode's
        # amortization story
        rec = recovery_sweep(k, m, chunk)
        out["decode_dispatch"] = telemetry.decode_dispatch_summary()
        out["recovery_sweep"] = rec

    if "map_churn" in secs:
        # map-epoch consumption: scalar full scan vs the shared PG
        # mapping service, bit-verified against the oracle
        out["map_churn"] = map_churn()

    if "profile" in secs:
        # pipeline phase attribution: where a coalesced batch's
        # submit->delivery wall-clock goes (phase shares, compile
        # seconds, utilization, mapping phase split) — the
        # dump_pipeline_profile story embedded per bench round.
        # Render with: python -m ceph_tpu.tools.profile_report
        out["profile"] = profile_section()

    if "qos" in secs:
        # multi-tenant dmclock fairness: per-tenant throughput/p99
        # with vs without QoS lanes, reservation attainment, limit
        # overshoot, and the excess-sharing ratio against the
        # configured weights
        out["qos"] = qos_section()

    if "scrub" in secs:
        # background integrity: scalar vs batched digest throughput
        # and tenant reservation attainment under a scrub storm with
        # vs without the background_best_effort class
        out["scrub"] = scrub_section()

    if "objectstore" in secs:
        # device-resident objectstore write path: on-disk bluestore
        # write/read MB/s scalar vs the bluestore_data channel, the
        # isolated csum-settle micro, and the bitplane compression
        # leg — all bit-verified against the host oracles
        out["objectstore"] = objectstore_section()

    if "metric" not in out:
        out = {"metric": "sections " + "+".join(sorted(secs)),
               **out}
    out["device"] = str(jax.devices()[0])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
