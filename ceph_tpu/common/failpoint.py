"""Named failpoints — in-tree fault injection for the device runtime.

The reference earns its durability story by injecting faults under
load: the objectstore error-injection hooks, the heartbeat drop knobs
(``OSD.h debug_heartbeat_drops_remaining``), and the teuthology
thrasher all assume every boundary can fail and make it fail on
demand.  This module is that facility for the accelerator data path:
a process-global registry of NAMED failpoints that the device
boundaries in ``ops/dispatch.py`` consult (``device_put``, kernel
launch, completion ``block_until_ready``, thread run-loops), armed at
runtime via config (``kernel_failpoints``) or the ``failpoint
set/clear/ls`` admin commands, and fired deterministically under a
seedable RNG so chaos tests replay.

Modes (the ``freq``/``oneshot`` vocabulary of classic failpoint
frameworks):

* ``always``   — every hit fires
* ``prob:P``   — each hit fires with probability P (0..1)
* ``oneshot``  — the first hit fires, then the point disarms itself
* ``nth:K``    — exactly the K-th hit fires (1-based), then disarms
* ``off``      — disarmed (same as clearing)

A failpoint name may carry a channel qualifier: arming
``dispatch.launch:ec_encode`` fires only for hits tagged with the
``ec_encode`` kernel channel, while ``dispatch.launch`` fires for
every channel.  Hits are NOT errors when nothing is armed: the hot
path is one module-global counter check, no lock.

Injected errors: ``InjectedDeviceFault`` (an ``Exception`` — the
dispatch engine classifies it transient and retries/fails over) and
``InjectedThreadDeath`` (derives from ``BaseException`` like
``KeyboardInterrupt``, so it sails past ``except Exception`` handlers
and genuinely kills the run-loop — the thread-supervision test
vector).
"""

from __future__ import annotations

import random

from ceph_tpu.common import lockdep


class FailpointError(RuntimeError):
    """Base class for every injected failure."""


class InjectedDeviceFault(FailpointError):
    """A transient device fault (the retry/fallback classifier treats
    any Exception as potentially transient; this one always is)."""


class InjectedThreadDeath(BaseException):
    """Kills a run-loop outright: BaseException-derived so generic
    ``except Exception`` recovery cannot absorb it — only the engine's
    thread supervisor sees it."""


_MODES = ("off", "always", "prob", "oneshot", "nth")


class _Failpoint:
    __slots__ = ("name", "mode", "p", "n", "hits", "fires", "exc")

    def __init__(self, name: str, mode: str, p: float = 0.0,
                 n: int = 0, exc=InjectedDeviceFault):
        self.name = name
        self.mode = mode
        self.p = p
        self.n = n
        self.hits = 0
        self.fires = 0
        self.exc = exc

    def describe(self) -> str:
        if self.mode == "prob":
            return f"prob:{self.p:g}"
        if self.mode == "nth":
            return f"nth:{self.n}"
        return self.mode


#: name -> _Failpoint.  Guarded by _lock; _armed is a lock-free hot
#: path gate (reads of an int are atomic in CPython; a stale zero just
#: delays the first fire by one hit).
_points: dict[str, _Failpoint] = {}
_lock = lockdep.make_lock("failpoint::registry")
_armed = 0
_rng = random.Random()
#: name -> owner token for points armed by configure() (the
#: kernel_failpoints option).  The registry is process-global but
#: contexts come and go — and COEXIST: a revived OSD's CephTpuContext
#: re-applies its (default-empty) option spec, and a client context
#: constructing mid-test applies its own — each spec must replace only
#: the points ITS option armed, never the chaos storm's / an admin's
#: set() nor another context's option-armed points (guarded by _lock;
#: set()/clear() move ownership to the direct caller).
_conf_owned: dict[str, int] = {}


def seed(n: int) -> None:
    """Deterministic firing order for chaos tests."""
    _rng.seed(n)


def parse_mode(mode: str) -> tuple[str, float, int]:
    """'prob:0.1' -> ("prob", 0.1, 0); raises ValueError on nonsense."""
    mode = mode.strip()
    kind, _, arg = mode.partition(":")
    if kind not in _MODES:
        raise ValueError(f"unknown failpoint mode {mode!r}")
    p, n = 0.0, 0
    if kind == "prob":
        p = float(arg)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"failpoint probability {p} outside [0, 1]")
    elif kind == "nth":
        n = int(arg)
        if n < 1:
            raise ValueError(f"failpoint nth:{n} must be >= 1")
    elif arg:
        raise ValueError(f"mode {kind!r} takes no argument")
    return kind, p, n


def set(name: str, mode: str, exc=None) -> None:   # noqa: A001 — admin verb
    """Arm (or disarm, mode='off') one named failpoint."""
    global _armed
    kind, p, n = parse_mode(mode)
    with _lock:
        _conf_owned.pop(name, None)
        if kind == "off":
            _points.pop(name, None)
        else:
            fp = _Failpoint(name, kind, p, n)
            if exc is not None:
                fp.exc = exc
            elif "thread_death" in name:
                # thread-death sites model loop bugs, not batch
                # errors: BaseException-derived so only the thread
                # supervisor (never a batch handler) sees it
                fp.exc = InjectedThreadDeath
            _points[name] = fp
        _armed = len(_points)


def clear(name: str | None = None) -> None:
    """Disarm one failpoint, or every one (name None/'all')."""
    global _armed
    with _lock:
        if name is None or name == "all":
            _points.clear()
            _conf_owned.clear()
        else:
            _points.pop(name, None)
            _conf_owned.pop(name, None)
        _armed = len(_points)


def ls() -> dict:
    """{name: {mode, hits, fires}} for every armed point."""
    with _lock:
        return {fp.name: {"mode": fp.describe(), "hits": fp.hits,
                          "fires": fp.fires}
                for fp in sorted(_points.values(),
                                 key=lambda f: f.name)}


def hit(name: str, tag: str | None = None) -> None:
    """One pass through an instrumented boundary: raises the armed
    exception when the point (exact name, or ``name:tag``) decides to
    fire.  Free when nothing is armed anywhere."""
    global _armed
    if not _armed:
        return
    exc = None
    with _lock:
        for key in ((name,) if tag is None else (f"{name}:{tag}", name)):
            fp = _points.get(key)
            if fp is None:
                continue
            fp.hits += 1
            fire = False
            if fp.mode == "always":
                fire = True
            elif fp.mode == "prob":
                fire = _rng.random() < fp.p
            elif fp.mode == "oneshot":
                fire = True
                _points.pop(key, None)
            elif fp.mode == "nth":
                fire = fp.hits == fp.n
                if fire:
                    _points.pop(key, None)
            if fire:
                fp.fires += 1
                exc = fp.exc(f"failpoint {key} fired"
                             + (f" (channel {tag})" if tag else ""))
                break
        _armed = len(_points)
    if exc is not None:
        raise exc


def configure(spec: str, owner: int = 0) -> None:
    """Apply a config-option spec: ``name=mode[;name=mode...]``, e.g.
    ``dispatch.launch:ec_encode=prob:0.1;dispatch.device_put=oneshot``.
    The spec REPLACES the points THIS owner's option previously armed;
    points armed via set() (admin command, chaos mode) — or by ANOTHER
    context's option — are untouched.  Contexts coexist in one
    process: a daemon revived mid-storm applies its default-empty
    spec, and a client context constructing mid-test applies its own —
    neither may disarm injection someone else armed.  Two specs arming
    the SAME name: last writer wins and takes ownership."""
    entries = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, mode = part.partition("=")
        if not sep:
            raise ValueError(f"failpoint spec {part!r}: expected "
                             "name=mode")
        parse_mode(mode)          # validate before mutating anything
        entries.append((name.strip(), mode.strip()))
    with _lock:
        mine = sorted(n for n, o in _conf_owned.items() if o == owner)
    for name in mine:
        clear(name)
    for name, mode in entries:
        set(name, mode)
        with _lock:
            _conf_owned[name] = owner


def configure_from_conf(conf) -> None:
    """Wire the ``kernel_failpoints`` option: applied now and on every
    runtime change (the thrasher's chaos mode drives it this way).
    Ownership is keyed per config object, so each context's spec
    replaces only its own points."""
    try:
        configure(str(conf.get("kernel_failpoints")), owner=id(conf))
    except Exception:
        pass   # a bad baked-in spec must not kill context construction
    conf.add_observer("kernel_failpoints",
                      lambda _n, v, _o=id(conf): configure(str(v), _o))


def register_admin(admin) -> None:
    """``failpoint set/clear/ls`` admin commands (ceph daemon analog:
    the reference drives its injection knobs through config/admin
    socket the same way)."""
    admin.register_command(
        "failpoint set",
        lambda name, mode, **kw: (set(name, mode), "ok")[1],
        "arm a named failpoint: name=<site[:channel]> mode="
        "always|prob:P|oneshot|nth:K|off")
    admin.register_command(
        "failpoint clear",
        lambda name="all", **kw: (clear(name), "ok")[1],
        "disarm one failpoint (or all)")
    admin.register_command(
        "failpoint ls", lambda **kw: ls(),
        "armed failpoints with hit/fire counts")
