"""CRUSH map data model.

Mirrors the semantic content of src/crush/crush.h (crush_map, crush_bucket and its
five algorithm variants, crush_rule) as plain Python dataclasses.  Negative ids are
buckets (bucket id b lives at index -1-b), non-negative ids are devices, exactly as in
the reference.  Weights are 16.16 fixed point (0x10000 == weight 1.0).
"""

from __future__ import annotations

from dataclasses import dataclass, field

CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

CRUSH_ITEM_UNDEF = 0x7FFFFFFE
CRUSH_ITEM_NONE = 0x7FFFFFFF

# device classes: shadow-bucket table (CrushWrapper class_bucket) keyed
# (original bucket id, class name) -> shadow bucket id; see
# crush/classes.py

RULE_NOOP = 0
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9
RULE_SET_CHOOSE_LOCAL_TRIES = 10
RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
RULE_SET_CHOOSELEAF_VARY_R = 12
RULE_SET_CHOOSELEAF_STABLE = 13

S64_MIN = -(1 << 63)


@dataclass
class Tunables:
    """Default profile is "jewel" with straw_calc_version 1
    (CrushWrapper.h:186-211 set_tunables_default)."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1

    @classmethod
    def legacy(cls) -> "Tunables":
        """The pre-bobtail ("argonaut") profile (CrushWrapper.h set_tunables_legacy)."""
        return cls(choose_local_tries=2, choose_local_fallback_tries=5,
                   choose_total_tries=19, chooseleaf_descend_once=0,
                   chooseleaf_vary_r=0, chooseleaf_stable=0, straw_calc_version=0)


@dataclass
class Bucket:
    id: int                      # negative
    type: int                    # user-defined type id (0 = device)
    alg: int                     # CRUSH_BUCKET_*
    hash: int = 0                # CRUSH_HASH_RJENKINS1
    items: list[int] = field(default_factory=list)
    weight: int = 0              # 16.16 total
    # straw2 / list: per-item 16.16 weights
    item_weights: list[int] = field(default_factory=list)
    # uniform: single shared weight
    item_weight: int = 0
    # list: cumulative weights (sum_weights[i] = sum of item_weights[0..i])
    sum_weights: list[int] = field(default_factory=list)
    # straw (legacy): 16.16 straw lengths
    straws: list[int] = field(default_factory=list)
    # tree: node weights indexed by tree node id
    node_weights: list[int] = field(default_factory=list)

    @property
    def size(self) -> int:
        return len(self.items)


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    ruleset: int
    type: int
    min_size: int
    max_size: int
    steps: list[RuleStep] = field(default_factory=list)


@dataclass
class ChooseArg:
    """Per-bucket weight-set override (CrushWrapper choose_args machinery,
    consumed at mapper.c:309-326)."""

    ids: list[int] | None = None
    # weight_set[position][i] — per-result-position weight override
    weight_set: list[list[int]] | None = None


@dataclass
class CrushMap:
    buckets: list[Bucket | None] = field(default_factory=list)  # index -1-id
    rules: list[Rule | None] = field(default_factory=list)
    max_devices: int = 0
    tunables: Tunables = field(default_factory=Tunables)
    # choose_args: name -> {bucket_index: ChooseArg}
    choose_args: dict = field(default_factory=dict)
    #: device-class shadow buckets: (orig bucket id, class) -> shadow id
    #: (CrushWrapper class_bucket; built by crush.classes)
    class_bucket: dict = field(default_factory=dict)

    @property
    def max_buckets(self) -> int:
        return len(self.buckets)

    @property
    def max_rules(self) -> int:
        return len(self.rules)

    def bucket(self, id: int) -> Bucket | None:
        idx = -1 - id
        if idx < 0 or idx >= len(self.buckets):
            return None
        return self.buckets[idx]

    def add_bucket(self, bucket: Bucket) -> int:
        """Place bucket at index -1-id, growing the array (builder.c:138-188)."""
        if bucket.id == 0:
            bucket.id = self.next_bucket_id()
        pos = -1 - bucket.id
        while pos >= len(self.buckets):
            self.buckets.append(None)
        if self.buckets[pos] is not None:
            raise ValueError(f"bucket id {bucket.id} already in use")
        self.buckets[pos] = bucket
        return bucket.id

    def next_bucket_id(self) -> int:
        for pos, b in enumerate(self.buckets):
            if b is None:
                return -1 - pos
        return -1 - len(self.buckets)

    def add_rule(self, rule: Rule) -> int:
        self.rules.append(rule)
        return len(self.rules) - 1

    def find_rule(self, ruleset: int, type: int, size: int) -> int:
        """crush_find_rule (mapper.c:41-54)."""
        for i, r in enumerate(self.rules):
            if (r is not None and r.ruleset == ruleset and r.type == type
                    and r.min_size <= size <= r.max_size):
                return i
        return -1
