"""cephx ticket protocol unit tests (CephxProtocol.h observable
behaviour: mint/validate, forgery, expiry, rotation, keyring refresh)."""

import time

from ceph_tpu.auth.cephx import (
    LIVE_GENERATIONS, KeyServer, TicketKeyring, mint_ticket,
    validate_ticket)


def test_mint_validate_roundtrip():
    ks = KeyServer()
    t = ks.grant("osd", "client.admin")
    got = validate_ticket(t.blob(), "osd", ks.rotating_keys("osd"))
    assert got is not None
    entity, skey = got
    assert entity == "client.admin"
    assert skey == t.session_key       # both sides derive the same key


def test_wrong_service_and_tamper_rejected():
    ks = KeyServer()
    t = ks.grant("osd", "client.x")
    assert validate_ticket(t.blob(), "mds",
                           ks.rotating_keys("mds")) is None
    evil = t.blob().replace(b"client.x", b"client.root")
    assert validate_ticket(evil, "osd", ks.rotating_keys("osd")) is None
    assert validate_ticket(b"garbage", "osd",
                           ks.rotating_keys("osd")) is None


def test_forged_ticket_without_service_key():
    ks = KeyServer()
    ks.grant("osd", "x")                    # init the service
    forged = mint_ticket("osd", "client.evil", 1, "attackerkey")
    assert validate_ticket(forged.blob(), "osd",
                           ks.rotating_keys("osd")) is None


def test_expiry():
    ks = KeyServer()
    t = ks.grant("osd", "c", ttl=0.1)
    assert validate_ticket(t.blob(), "osd", ks.rotating_keys("osd"),
                           now=time.time() + 1) is None


def test_rotation_keeps_live_generations():
    ks = KeyServer(rotation_period=0.0)
    t1 = ks.grant("osd", "c")               # signed with gen 1
    # a service that fetched keys BEFORE any rotation already holds the
    # next generation — the property that makes rotation hitless
    pre_rotation_keys = ks.rotating_keys("osd")
    assert set(pre_rotation_keys) == {1, 2}
    ks.rotate_now("osd")                    # cur=2, keys {1,2,3}
    t2 = ks.grant("osd", "c")
    assert t2.gen == 2
    assert validate_ticket(t2.blob(), "osd",
                           pre_rotation_keys) is not None
    keys = ks.rotating_keys("osd")
    assert len(keys) == LIVE_GENERATIONS
    # the gen-1 ticket still validates for one period (prev is live)
    assert validate_ticket(t1.blob(), "osd", keys) is not None
    ks.rotate_now("osd")                    # cur=3, keys {2,3,4}
    # now gen 1 rotated out: the old ticket is dead
    assert validate_ticket(t1.blob(), "osd",
                           ks.rotating_keys("osd")) is None


def test_state_survives_restart():
    ks = KeyServer()
    t = ks.grant("mds", "c")
    ks2 = KeyServer(state=dict(ks.state))   # "restarted" mon
    assert validate_ticket(t.blob(), "mds",
                           ks2.rotating_keys("mds")) is not None


def test_keyring_refreshes_before_expiry():
    ks = KeyServer()
    calls = []

    def fetch(service):
        calls.append(service)
        return ks.grant(service, "c", ttl=100.0)

    kr = TicketKeyring(fetch)
    t0 = kr.get("osd", now=0.0)
    assert t0 is not None and calls == ["osd"]
    # well within ttl: cached
    assert kr.get("osd", now=10.0) is t0
    assert calls == ["osd"]
    # less than 25% of TICKET_TTL left: refreshed
    kr.get("osd", now=t0.expiry - 1.0)
    assert calls == ["osd", "osd"]


def test_keyring_survives_fetch_failure():
    ks = KeyServer()
    good = ks.grant("osd", "c", ttl=100.0)
    state = {"fail": False}

    def fetch(service):
        if state["fail"]:
            return None
        return good

    kr = TicketKeyring(fetch)
    assert kr.get("osd", now=0.0) is good
    state["fail"] = True
    # refresh fails but the old ticket is still valid: keep using it
    assert kr.get("osd", now=good.expiry - 1.0) is good
    # once truly expired and unfetchable: None
    assert kr.get("osd", now=good.expiry + 1.0) is None
