"""Batched bit-plane block compression — the device half of the
``tpu_bitplane`` compressor plugin (compressor.py).

Fixed-width entropy coding in the checksum-kernel mold: treat each
byte of a block as an 8-bit GF(2) vector and transpose the batch's
bit-matrix — plane j collects bit j of every byte (multiplication by
the j-th selector matrix), packed 8 bits per byte.  Structured data
(ASCII text, zero runs, small integers) concentrates its entropy in
the low planes; all-zero planes are dropped and a 1-byte mask records
which survive, so a 4 KiB block of 7-bit text stores in ~7/8 of the
space and a zero-heavy block in far less.  Random data keeps all 8
planes and the coding loses (header overhead) — the caller's
required-ratio check stores such blocks raw.

The transform is exactly invertible (a bit permutation plus drops of
provably-zero planes), so round-trips are byte-identical by
construction; the store verifies them anyway before committing a
compressed block.  Like every kernel module, jax enters only through
the jitted entry point: ``bitplane_planes_ref`` is the numpy host
oracle (bit-exact ground truth for the device path and its fallback),
and the decode side is numpy-only — reads never need the device.
"""

from __future__ import annotations

import functools
import struct

import numpy as np

from ceph_tpu.ops import telemetry

#: per-block body header: original length (u16 — blocks are <= 4 KiB),
#: plane-presence mask (bit j set = plane j follows)
_BP_HDR = struct.Struct("<HB")

#: largest buffer the u16 length header can describe
MAX_BLOCK = 0xFFFF


def _pad8(n: int) -> int:
    return max(8, ((n + 7) // 8) * 8)


def bitplane_planes_ref(batch: np.ndarray) -> np.ndarray:
    """Host oracle: (S, W) uint8 rows (W % 8 == 0) -> (S, 8, W//8)
    uint8 planes, plane j packing bit j of every byte LSB-first (the
    packing ``np.unpackbits(..., bitorder="little")`` inverts)."""
    # analysis: allow[blocking] -- host oracle: inputs are host numpy by contract
    batch = np.asarray(batch, dtype=np.uint8)
    s, w = batch.shape
    bits = (batch[:, None, :]
            >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1
    pows = (1 << np.arange(8, dtype=np.uint16))
    packed = (bits.reshape(s, 8, w // 8, 8).astype(np.uint16)
              * pows).sum(axis=3)
    return packed.astype(np.uint8)


@functools.lru_cache(maxsize=1)
def _jit_planes():
    """Jitted bit-plane transpose (jax imports live inside so the
    host/decode paths never pull the device stack)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def planes(batch):
        s, w = batch.shape
        u8 = jnp.uint8
        bits = (batch[:, None, :]
                >> jnp.arange(8, dtype=u8)[None, :, None]) & u8(1)
        pows = (jnp.uint16(1) << jnp.arange(8, dtype=jnp.uint16))
        packed = jnp.sum(
            bits.reshape(s, 8, w // 8, 8).astype(jnp.uint16) * pows,
            axis=3)
        return packed.astype(u8)

    return planes


def plane_jit_entries() -> int:
    try:
        return _jit_planes()._cache_size()
    except Exception:
        return 0


def bitplane_planes_batched(batch) -> np.ndarray:
    """One batched device plane-extraction call, accounted under the
    ``bitplane_pack`` telemetry family; bit-exact vs the ref."""
    import jax.numpy as jnp
    batch = jnp.asarray(np.asarray(batch, dtype=np.uint8))
    s, w = batch.shape
    out = telemetry.timed_kernel(
        "bitplane_pack",
        lambda: _jit_planes()(batch),
        batch=int(s), bytes_in=int(s) * int(w), bytes_out=int(s) * int(w),
        cache_entries=plane_jit_entries,
        signature=("bitplane_pack", int(s), int(w)))
    # analysis: allow[blocking] -- caller consumes host planes (encode is host-side slicing)
    return np.asarray(out)


def pack_planes(blobs, device: bool = True) -> list[np.ndarray]:
    """Planes for a batch of blobs in ONE kernel call: each result is
    (8, ceil(len/8)) uint8.  Rows zero-pad to a shared width; padding
    bits land as zeros in the plane tails, which ``encode_block``'s
    length header makes the decoder ignore.  The device path falls
    back to the numpy oracle on any failure — compression must never
    make a write path throw."""
    if not blobs:
        return []
    wmax = _pad8(max(len(b) for b in blobs))
    batch = np.zeros((len(blobs), wmax), dtype=np.uint8)
    for i, b in enumerate(blobs):
        if len(b):
            batch[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    if device:
        try:
            planes = bitplane_planes_batched(batch)
        except Exception:
            planes = bitplane_planes_ref(batch)
    else:
        planes = bitplane_planes_ref(batch)
    return [planes[i] for i in range(len(blobs))]


def encode_block(data: bytes, planes: np.ndarray) -> bytes:
    """Body bytes for one blob from its (8, >=ceil(len/8)) planes:
    length + plane mask header, then only the non-zero planes."""
    if len(data) > MAX_BLOCK:
        raise ValueError(f"bitplane block too large: {len(data)}")
    p = (len(data) + 7) // 8
    live = planes[:, :p]
    present = live.any(axis=1)
    mask = int(np.packbits(present, bitorder="little")[0])
    return (_BP_HDR.pack(len(data), mask)
            + np.ascontiguousarray(live[present]).tobytes())


def decode_block(body: bytes) -> bytes:
    """Invert ``encode_block`` (numpy-only; raises ValueError on a
    malformed body — the plugin maps that to CompressionError)."""
    if len(body) < _BP_HDR.size:
        raise ValueError("bitplane body shorter than its header")
    n, mask = _BP_HDR.unpack_from(body)
    p = (n + 7) // 8
    js = [j for j in range(8) if mask & (1 << j)]
    if len(body) != _BP_HDR.size + len(js) * p:
        raise ValueError("bitplane body length mismatch")
    if not js:
        return b"\x00" * n
    # the present planes are contiguous: ONE unpackbits over all of
    # them, then one weighted sum — per-plane loops are numpy-call
    # overhead-bound at 4 KiB block sizes
    planes = np.frombuffer(body, dtype=np.uint8, count=len(js) * p,
                           offset=_BP_HDR.size).reshape(len(js), p)
    bits = np.unpackbits(planes, axis=1, bitorder="little")
    out = (bits.astype(np.uint8)
           * (np.uint8(1) << np.array(js, dtype=np.uint8))[:, None]
           ).sum(axis=0, dtype=np.uint8)
    return out[:n].tobytes()
