"""Flagship benchmark: erasure encode + 2-erasure recovery throughput.

Mirrors the reference's `ceph_erasure_code_benchmark` workload (BASELINE.json
north-star config: k=8 m=4 cauchy, 4 KiB chunks) — the reference harness reports
elapsed seconds and KiB processed (src/test/erasure-code/
ceph_erasure_code_benchmark.cc:188,326); here the same quantity is reported as
MB/s directly, batched over many stripes per device call instead of one stripe
per call (the ECUtil stripe-loop batch point, src/osd/ECUtil.cc:136).

Timing: the device runtime acks dispatch before execution completes (remote
tunnel), so naive block_until_ready under-measures.  Each measurement runs the
kernel N times inside one jitted lax.scan with a forced data dependency between
iterations, fetches a scalar (which cannot resolve until everything executed),
and differences two iteration counts to cancel dispatch/transfer overhead.
Tunnel variance is large (r01 vs r02 disagreed 3x), so every rate reported is
the MEDIAN of `reps` independent chained-scan differences and the min..max band
rides along in the JSON (keys *_band) — a single lucky or unlucky run can no
longer move the headline.

vs_baseline: ratio against the single-core C baseline compiled from
ceph_tpu/native/baseline.c — an ISA-L-class split-nibble SIMD GF(2^8) encode
and a scalar straw2 crush_do_rule, both bit-validated against the same oracles
the TPU kernels are (tests/test_native.py) — measured in the same run, on this
host, never carried across sessions.

CRUSH runs with non-uniform bucket weights, a skewed reweight vector, and out
OSDs — the retry-ladder-heavy case, not the easy uniform one.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def chained_rates(step_fn, carry, n_lo: int = 8, n_hi: int = 48,
                  reps: int = 5, inner: int = 5) -> list[float]:
    """Per-step seconds samples, robust against tunnel stalls.

    The tunnel's noise is ADDITIVE-POSITIVE (ack stalls, transfer
    hiccups), so each sample differences the MIN over `inner` timed
    runs of each iteration count — min-filtering converges on the true
    time where a single-pair difference can be dominated by one stall
    (round 3's band spanned 6x; a stall pair can even produce a
    near-zero difference, i.e. an absurd rate).  lo/hi runs alternate
    so a stall burst hits both counts, not just one side, and the wide
    n_hi - n_lo spread divides whatever residue remains."""
    import jax

    @functools.partial(jax.jit, static_argnames="n")
    def loop(c, n):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), ()), c, None, length=n)
        leaf = jax.tree_util.tree_leaves(c)[0]
        return leaf.ravel()[0]

    def timed(n):
        t0 = time.perf_counter()
        jax.device_get(loop(carry, n))
        return time.perf_counter() - t0

    jax.device_get(loop(carry, n_lo))  # compile
    jax.device_get(loop(carry, n_hi))
    for _ in range(2):                 # clock/thermal warm-up
        timed(n_hi)
    out = []
    for _ in range(reps):
        ts_lo, ts_hi = [], []
        for _ in range(inner):
            ts_lo.append(timed(n_lo))
            ts_hi.append(timed(n_hi))
        d = (min(ts_hi) - min(ts_lo)) / (n_hi - n_lo)
        # a non-positive difference is clock noise; fall back to the full
        # n_hi run amortized per step — that INCLUDES dispatch overhead, so
        # it can only understate the rate, never inflate the headline
        out.append(d if d > 2e-9 else min(ts_hi) / n_hi)
    return out


def median_band(samples: list[float]):
    """(median, lo, hi): the band is TRIMMED when there are >= 5
    samples (drop the single best and worst) — with a heavy-tailed
    tunnel, min/max report one outlier stall or one fluke near-zero
    difference, not the kernel.  The trim is symmetric, so it cannot
    bias the band in the flattering direction only."""
    s = sorted(samples)
    if len(s) >= 5:
        return s[len(s) // 2], s[1], s[-2]
    return s[len(s) // 2], s[0], s[-1]


def chained_seconds_per_step(step_fn, carry, n_lo: int = 8, n_hi: int = 48,
                             reps: int = 5) -> float:
    return median_band(chained_rates(step_fn, carry, n_lo, n_hi, reps))[0]


def dispatch_sweep(encode, k: int, chunk: int,
                   levels=(1, 4, 16, 64), op_stripes: int = 32,
                   total_ops: int = 96) -> dict:
    """Offered-concurrency sweep through the cross-op coalescing
    engine (ops.dispatch): N closed-loop writers each submit one
    op-sized encode at a time and wait for its parity, exactly the OSD
    EC write path's submit-and-continue shape.  Reports end-to-end
    MB/s and p99 op latency per level plus the engine's own coalesce
    metrics — the amortization story is "MB/s climbs with writers
    while device calls per op falls".  All levels feed the global
    DispatchStats sink, so the process-wide `dispatch` digest in the
    JSON covers the whole sweep; per-level factors difference the
    scalar counters around each level."""
    import threading

    from ceph_tpu.ops import telemetry
    from ceph_tpu.ops.dispatch import DeviceDispatchEngine

    rng = np.random.default_rng(7)
    op = rng.integers(0, 256, (op_stripes, k, chunk), dtype=np.uint8)
    op_bytes = op.nbytes
    stats = telemetry.dispatch_stats()
    out = {}
    for conc in levels:
        ops_per_writer = max(3, total_ops // conc)
        eng = DeviceDispatchEngine(name=f"bench-c{conc}", stats=stats)
        key = ("bench_ec", k, chunk)
        lats: list[float] = []
        lat_lock = threading.Lock()
        start = threading.Barrier(conc + 1)

        def writer():
            start.wait()
            mine = []
            for _ in range(ops_per_writer):
                t0 = time.perf_counter()
                eng.submit(key, encode, op).result(timeout=120)
                mine.append(time.perf_counter() - t0)
            with lat_lock:
                lats.extend(mine)

        threads = [threading.Thread(target=writer, daemon=True)
                   for _ in range(conc)]
        for t in threads:
            t.start()
        sub0, bat0 = stats.submits, stats.batches
        start.wait()           # release every writer at once
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        eng.stop()
        n_ops = conc * ops_per_writer
        calls = stats.batches - bat0
        out[str(conc)] = {
            "writers": conc,
            "ops": n_ops,
            "mbps": round(n_ops * op_bytes / wall / 1e6, 1),
            "p99_op_ms": round(
                float(np.percentile(lats, 99)) * 1e3, 3),
            "median_op_ms": round(
                float(np.percentile(lats, 50)) * 1e3, 3),
            "mean_coalesce": (round((stats.submits - sub0) / calls, 2)
                              if calls else 0.0),
            "device_calls_per_1k_ops": (round(1000.0 * calls / n_ops, 1)
                                        if n_ops else 0.0),
        }
    return out


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf.matrix import gen_cauchy1_matrix, recovery_matrix
    from ceph_tpu.ops.gf_kernel import make_encoder

    k, m = 8, 4
    chunk = 4096          # 4 KiB chunks — BASELINE.json config
    stripes = 2048        # 64 MiB of data per device call
    erasures = [1, k + 1]  # one data + one parity chunk lost

    gen = gen_cauchy1_matrix(k, m)
    coding = gen[k:]
    chosen = [i for i in range(k + m) if i not in set(erasures)][:k]
    rmat = recovery_matrix(gen, chosen, erasures)
    encode = make_encoder(coding)
    recover = make_encoder(rmat)

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))
    data_bytes = stripes * k * chunk

    def enc_step(d):
        p = encode(d)
        return d.at[0, 0, 0].set(p[0, 0, 0] ^ jnp.uint8(1))

    t_enc, t_enc_min, t_enc_max = median_band(chained_rates(enc_step, data))
    enc_mbps = data_bytes / t_enc / 1e6

    surv = jnp.asarray(
        rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))

    def dec_step(s):
        r = recover(s)
        return s.at[0, 0, 0].set(r[0, 0, 0] ^ jnp.uint8(1))

    t_dec, t_dec_min, t_dec_max = median_band(chained_rates(dec_step, surv))
    dec_mbps = data_bytes / t_dec / 1e6

    combined = 2 * data_bytes / (t_enc + t_dec) / 1e6

    # CRUSH bulk placement (BASELINE config #5 shape): 10k-OSD two-level map
    # (250 hosts x 40 osds), chooseleaf firstn 3, 64k PGs per device call.
    # Non-uniform: skewed per-osd bucket weights, 10% reweighted to 0.5,
    # 2% out — the retry ladder actually fires.
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.crush.mapper_jax import BatchMapper

    crush_map, _root, rid = build_two_level_map(250, 40)
    wrng = np.random.default_rng(42)
    for b in crush_map.buckets:
        if b is not None and b.type == 1:  # host level: skew osd weights
            b.item_weights = [int(w) for w in
                              wrng.integers(0x8000, 0x20000, b.size)]
            b.weight = sum(b.item_weights)
    root = crush_map.bucket(-1)
    root.item_weights = [crush_map.bucket(h).weight for h in root.items]
    root.weight = sum(root.item_weights)

    n_osds = 10000
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    idx = wrng.permutation(n_osds)
    reweight[idx[:1000]] = 0x8000   # 10% half-weight
    reweight[idx[1000:1200]] = 0    # 2% out

    bm = BatchMapper(crush_map)
    n_pgs, numrep = 65536, 3
    rw = jnp.asarray(reweight)
    xs = jnp.asarray(rng.integers(0, 2**32, (n_pgs,), dtype=np.uint32))
    bm.do_rule(rid, xs, numrep, rw)  # compile

    def crush_step(x):
        p = bm.do_rule(rid, x, numrep, rw)
        return x ^ p[:, 0].astype(jnp.uint32)

    t_crush, t_crush_min, t_crush_max = median_band(
        chained_rates(crush_step, xs, n_lo=4, n_hi=24, reps=5,
                      inner=4))
    crush_mpps = n_pgs / t_crush / 1e6

    # single-core C baselines (ceph_tpu/native): ISA-L-class SIMD encode and
    # scalar crush_do_rule, same inputs, same math
    from ceph_tpu.native import CrushBaseline, ec_encode_native

    cpu_data = np.asarray(data)
    t_c = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ec_encode_native(coding, cpu_data)
        t_c = min(t_c, time.perf_counter() - t0)
    c_enc_mbps = data_bytes / t_c / 1e6
    t_c = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ec_encode_native(rmat, cpu_data)
        t_c = min(t_c, time.perf_counter() - t0)
    c_dec_mbps = data_bytes / t_c / 1e6
    c_combined = 2 / (1 / c_enc_mbps + 1 / c_dec_mbps)

    cb = CrushBaseline(crush_map)
    c_xs = np.asarray(xs[:8192], dtype=np.uint32)
    cb.do_rule_batch(rid, c_xs[:256], numrep, reweight.astype(np.uint32))
    t0 = time.perf_counter()
    cb.do_rule_batch(rid, c_xs, numrep, reweight.astype(np.uint32))
    c_crush_mpps = len(c_xs) / (time.perf_counter() - t0) / 1e6

    # kernel telemetry digest (retraces, p50/p99 latency, occupancy):
    # the timed loops above run inside jitted scans, so close with a few
    # FENCED standalone calls — real per-call device residency samples —
    # before summarizing.  A retrace count above the handful of shapes
    # this harness uses is the regression tell.
    from ceph_tpu.common import tracing
    from ceph_tpu.ops import telemetry
    telemetry.set_fence_for_timing(True)
    # trace the fenced calls with a zero slow threshold: every one
    # lands in the slow ring, so the JSON records a tail-latency digest
    # (count + p99 root-span duration) next to the throughput headline
    tracing.set_slow_threshold(0.0)
    for _ in range(3):
        with tracing.trace_ctx(name="bench ec_encode", daemon="bench"):
            encode(data)
        with tracing.trace_ctx(name="bench crush_map", daemon="bench"):
            bm.do_rule(rid, xs, numrep, rw)
    telemetry.set_fence_for_timing(False)
    kernel_summary = telemetry.registry().summary()
    slow_traces = tracing.slow_summary()

    # cross-op coalescing: offered-concurrency sweep through the
    # dispatch engine (1/4/16/64 in-flight writers, OSD-write-sized
    # ops).  The headline EC numbers above are device-resident; this
    # is the END-TO-END rate a concurrent client population sees, and
    # the coalesce factor is the amortization making up the gap.
    sweep = dispatch_sweep(encode, k, chunk)
    dispatch_digest = telemetry.dispatch_summary()

    print(json.dumps({
        "metric": "ec encode+recover MB/s (k=8,m=4,4KiB chunks, batch=2048)",
        "value": round(combined, 1),
        "unit": "MB/s",
        "vs_baseline": round(combined / c_combined, 2),
        "encode_mbps": round(enc_mbps, 1),
        "encode_mbps_band": [round(data_bytes / t_enc_max / 1e6, 1),
                             round(data_bytes / t_enc_min / 1e6, 1)],
        "recover_mbps": round(dec_mbps, 1),
        "recover_mbps_band": [round(data_bytes / t_dec_max / 1e6, 1),
                              round(data_bytes / t_dec_min / 1e6, 1)],
        "c_encode_mbps": round(c_enc_mbps, 1),
        "c_recover_mbps": round(c_dec_mbps, 1),
        "encode_vs_c": round(enc_mbps / c_enc_mbps, 2),
        "crush_mpps": round(crush_mpps, 3),
        "crush_mpps_band": [round(n_pgs / t_crush_max / 1e6, 3),
                            round(n_pgs / t_crush_min / 1e6, 3)],
        "c_crush_mpps": round(c_crush_mpps, 3),
        "crush_vs_c": round(crush_mpps / c_crush_mpps, 2),
        "kernel_telemetry": kernel_summary,
        "slow_traces": slow_traces,
        "dispatch": dispatch_digest,
        "dispatch_sweep": sweep,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
