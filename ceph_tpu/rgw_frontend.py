"""Event-driven HTTP frontend for the object gateway
(src/rgw/rgw_asio_frontend.cc analog).

The reference's beast frontend is an async I/O loop feeding a bounded
executor pool; the stdlib ThreadingHTTPServer it replaces here is
thread-per-connection.  Same split, same discipline as the repo's
event-driven messenger (msg/event_tcp):

* ONE event-loop thread owns every socket: accepts, reads, parses
  HTTP/1.1 frames (request line + headers + Content-Length body), and
  writes responses — sockets are single-threaded by construction;
* a BOUNDED worker pool runs the request handlers (they do RADOS I/O
  and must never block the loop); finished responses return to the
  loop over a wakeup pipe;
* keep-alive by default; one request in flight per connection (a
  pipelined second request waits buffered until the response flushes,
  which is how the reference's beast sessions sequence too).
"""

from __future__ import annotations

import os
import queue
import selectors
import socket
import threading
from dataclasses import dataclass, field


class CIMap(dict):
    """Case-insensitive header map (stores the wire casing, matches
    any)."""

    def __init__(self, items=()):
        super().__init__()
        self._lower: dict[str, str] = {}
        for k, v in items:
            self[k] = v

    def __setitem__(self, k, v):
        low = k.lower()
        old = self._lower.get(low)
        if old is not None:
            super().__delitem__(old)
        self._lower[low] = k
        super().__setitem__(k, v)

    def get(self, k, default=None):
        real = self._lower.get(k.lower())
        return super().get(real, default) if real is not None else default

    def __contains__(self, k):
        return k.lower() in self._lower


@dataclass
class HttpRequest:
    method: str
    target: str            # path?query, as received
    headers: CIMap
    body: bytes


@dataclass
class _ConnState:
    sock: socket.socket
    inbuf: bytearray = field(default_factory=bytearray)
    outbuf: bytearray = field(default_factory=bytearray)
    busy: bool = False     # a request is with the workers
    close_after: bool = False
    dead: bool = False
    read_eof: bool = False   # client half-closed (SHUT_WR): finish
    #                          the in-flight response, then close
    sent_100: bool = False   # interim 100 Continue emitted


_MAX_HEADER = 64 << 10
_MAX_BODY = 512 << 20


class AsyncHttpFrontend:
    """handler(req: HttpRequest) -> (status:int, headers:dict,
    body:bytes), run on a worker thread."""

    REASONS = {200: "OK", 204: "No Content", 400: "Bad Request",
               403: "Forbidden", 404: "Not Found", 409: "Conflict",
               411: "Length Required", 500: "Internal Server Error",
               501: "Not Implemented"}

    def __init__(self, handler, addr: str = "127.0.0.1:0",
                 workers: int = 8):
        self.handler = handler
        host, port = addr.rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(128)
        self._listener.setblocking(False)
        self.sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._work_q: queue.Queue = queue.Queue()
        self._done_q: queue.Queue = queue.Queue()
        self._stop = False
        self._threads: list[threading.Thread] = []
        self.n_workers = workers

    @property
    def addr(self) -> str:
        h, p = self._listener.getsockname()[:2]
        return f"{h}:{p}"

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "AsyncHttpFrontend":
        t = threading.Thread(target=self._loop, name="rgw-http-loop",
                             daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self.n_workers):
            w = threading.Thread(target=self._worker,
                                 name=f"rgw-http-w{i}", daemon=True)
            w.start()
            self._threads.append(w)
        return self

    def stop(self) -> None:
        self._stop = True
        for _ in range(self.n_workers):
            self._work_q.put(None)
        os.write(self._wake_w, b"x")
        for t in self._threads:
            t.join(timeout=5)
        try:
            self._listener.close()
        finally:
            self.sel.close()
            os.close(self._wake_r)
            os.close(self._wake_w)

    # -- event loop (single thread owns every socket) -------------------------

    def _loop(self) -> None:
        sel = self.sel
        sel.register(self._listener, selectors.EVENT_READ, "accept")
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        while not self._stop:
            for key, events in sel.select(timeout=0.5):
                if key.data == "accept":
                    self._accept()
                elif key.data == "wake":
                    try:
                        os.read(self._wake_r, 4096)
                    except OSError:
                        pass
                    self._drain_done()
                else:
                    self._service(key.data, key.fileobj, events)
        # teardown: close every connection socket
        for key in list(self.sel.get_map().values()):
            if isinstance(key.data, _ConnState):
                try:
                    key.fileobj.close()
                except OSError:
                    pass

    def _accept(self) -> None:
        while True:
            try:
                sock, _peer = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            st = _ConnState(sock)
            self.sel.register(sock, selectors.EVENT_READ, st)

    def _close(self, st: _ConnState) -> None:
        if st.dead:
            return
        st.dead = True
        try:
            self.sel.unregister(st.sock)
        except (KeyError, ValueError):
            pass
        try:
            st.sock.close()
        except OSError:
            pass

    def _service(self, st: _ConnState, sock, events) -> None:
        if events & selectors.EVENT_READ:
            try:
                while True:
                    chunk = sock.recv(64 << 10)
                    if chunk == b"":
                        # half-close: a legal HTTP pattern — the client
                        # sent its request and shut down its write
                        # side; serve the in-flight response first
                        st.read_eof = True
                        if not (st.busy or st.outbuf or st.inbuf):
                            self._close(st)
                            return
                        st.close_after = True
                        break
                    st.inbuf += chunk
                    if len(st.inbuf) > _MAX_HEADER + _MAX_BODY:
                        # bytes buffered past any legal frame (incl.
                        # data streamed while a request is in flight):
                        # memory-exhaustion guard
                        self._close(st)
                        return
                    if len(chunk) < (64 << 10):
                        break
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                self._close(st)
                return
            self._maybe_parse(st)
        if events & selectors.EVENT_WRITE:
            self._flush(st)

    def _maybe_parse(self, st: _ConnState) -> None:
        """Frame one request off the input buffer and hand it to the
        workers; one in flight per connection."""
        if st.busy or st.dead:
            return
        head_end = st.inbuf.find(b"\r\n\r\n")
        if head_end < 0:
            if len(st.inbuf) > _MAX_HEADER:
                self._bad(st, 400, close=True)
            return
        head = bytes(st.inbuf[:head_end]).decode("latin-1")
        lines = head.split("\r\n")
        try:
            method, target, _ver = lines[0].split(" ", 2)
        except ValueError:
            self._bad(st, 400, close=True)
            return
        headers = CIMap()
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip()] = v.strip()
        if headers.get("Transfer-Encoding"):
            self._bad(st, 501, close=True)   # no chunked TE (SigV4
            return                           # clients send lengths)
        try:
            length = int(headers.get("Content-Length") or 0)
        except ValueError:
            self._bad(st, 400, close=True)   # malformed, not missing
            return
        if length > _MAX_BODY:
            self._bad(st, 400, close=True)
            return
        total = head_end + 4 + length
        if len(st.inbuf) < total:
            if "100-continue" in (headers.get("Expect", "")
                                  .lower()) and not st.sent_100:
                # the client waits for the interim before sending the
                # body (boto3/curl PUTs) — BaseHTTPRequestHandler sent
                # this automatically and so must we
                st.sent_100 = True
                st.outbuf += b"HTTP/1.1 100 Continue\r\n\r\n"
                self._want_write(st)
            return    # body still arriving
        body = bytes(st.inbuf[head_end + 4:total])
        del st.inbuf[:total]
        st.busy = True
        st.close_after = (headers.get("Connection", "")
                          .lower() == "close")
        self._work_q.put((st, HttpRequest(method, target, headers,
                                          body)))

    def _bad(self, st: _ConnState, status: int,
             close: bool = False) -> None:
        st.outbuf += self._render(status, {}, b"")
        st.close_after = st.close_after or close
        st.inbuf.clear()
        self._want_write(st)

    # -- workers --------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._work_q.get()
            if item is None:
                return
            st, req = item
            try:
                status, headers, body = self.handler(req)
            except Exception:   # the handler layer catches its own;
                status, headers, body = 500, {}, b""   # belt only
            self._done_q.put((st, req, status, headers, body))
            os.write(self._wake_w, b"x")

    def _drain_done(self) -> None:
        while True:
            try:
                st, req, status, headers, body = \
                    self._done_q.get_nowait()
            except queue.Empty:
                return
            if st.dead:
                continue
            st.outbuf += self._render(status, headers, body)
            st.busy = False
            st.sent_100 = False
            self._want_write(st)
            # a pipelined next request may already be buffered
            self._maybe_parse(st)

    # -- writes (loop thread only) --------------------------------------------

    def _render(self, status: int, headers: dict,
                body: bytes) -> bytes:
        reason = self.REASONS.get(status, "OK")
        out = [f"HTTP/1.1 {status} {reason}"]
        hdrs = dict(headers)
        hdrs.setdefault("Content-Length", str(len(body)))
        for k, v in hdrs.items():
            out.append(f"{k}: {v}")
        return ("\r\n".join(out) + "\r\n\r\n").encode("latin-1") + body

    def _want_write(self, st: _ConnState) -> None:
        self._flush(st)
        if st.dead:
            return
        want = selectors.EVENT_READ
        if st.outbuf:
            want |= selectors.EVENT_WRITE
        try:
            self.sel.modify(st.sock, want, st)
        except (KeyError, ValueError):
            pass

    def _flush(self, st: _ConnState) -> None:
        while st.outbuf:
            try:
                n = st.sock.send(bytes(st.outbuf[:256 << 10]))
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close(st)
                return
            del st.outbuf[:n]
        if not st.outbuf:
            if st.close_after:
                self._close(st)
                return
            try:
                self.sel.modify(st.sock, selectors.EVENT_READ, st)
            except (KeyError, ValueError):
                pass
