"""Peering message types (messages/MOSDPGQuery.h, MOSDPGNotify.h,
MOSDPGLog.h analogs).  Type ids follow the reference's include/msgr.h
numbering (MSG_OSD_PG_NOTIFY=80, MSG_OSD_PG_QUERY=81, MSG_OSD_PG_LOG=83).
"""

from __future__ import annotations

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.osd.pg import LogEntry, PGInfo


def _enc_pgid(e: Encoder, pgid) -> None:
    e.s64(pgid[0]).u32(pgid[1])


def _dec_pgid(d: Decoder):
    return (d.s64(), d.u32())


@register_message
class MOSDPGQuery(Message):
    """primary -> peer: tell me about this PG (pg_query_t INFO / LOG)."""

    TYPE = 81  # MSG_OSD_PG_QUERY
    INFO = 1
    LOG = 2

    def __init__(self, pgid=(0, 0), qtype: int = 1,
                 since=(0, 0), epoch: int = 0, from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.qtype = qtype
        self.since = since
        self.epoch = epoch      # peering round (interval) guard
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            _enc_pgid(e, self.pgid), e.u8(self.qtype),
            e.u32(self.since[0]), e.u64(self.since[1]),
            e.u32(self.epoch), e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = _dec_pgid(d)
            self.qtype = d.u8()
            self.since = (d.u32(), d.u64())
            self.epoch = d.u32()
            self.from_osd = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDPGNotify(Message):
    """peer -> primary: my pg_info_t (reply to an INFO query)."""

    TYPE = 80  # MSG_OSD_PG_NOTIFY

    def __init__(self, pgid=(0, 0), info: PGInfo | None = None,
                 epoch: int = 0, from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.info = info or PGInfo()
        self.epoch = epoch
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            _enc_pgid(e, self.pgid), self.info.encode(e),
            e.u32(self.epoch), e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = _dec_pgid(d)
            self.info = PGInfo.decode(d)
            self.epoch = d.u32()
            self.from_osd = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDPGLog(Message):
    """Full-log transfer.  REPLY: auth peer -> primary (answer to a LOG
    query); ACTIVATE: primary -> replica (authoritative history at
    activation, PG::activate sending MOSDPGLog)."""

    TYPE = 83  # MSG_OSD_PG_LOG
    REPLY = 0
    ACTIVATE = 1

    def __init__(self, pgid=(0, 0), info: PGInfo | None = None,
                 entries: list[LogEntry] | None = None, purpose: int = 0,
                 epoch: int = 0, from_osd: int = 0):
        super().__init__()
        self.pgid = pgid
        self.info = info or PGInfo()
        self.entries = entries or []
        self.purpose = purpose
        self.epoch = epoch
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            _enc_pgid(e, self.pgid), self.info.encode(e),
            e.list(self.entries, lambda e2, ent: ent.encode(e2)),
            e.u8(self.purpose), e.u32(self.epoch), e.s32(self.from_osd)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.pgid = _dec_pgid(d)
            self.info = PGInfo.decode(d)
            self.entries = d.list(LogEntry.decode)
            self.purpose = d.u8()
            self.epoch = d.u32()
            self.from_osd = d.s32()
        dec.versioned(1, body)
