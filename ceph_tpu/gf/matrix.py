"""GF(2^8) matrix generators and inversion.

Matrix semantics follow the reference's ISA plugin contract
(src/erasure-code/isa/ErasureCodeIsa.cc:367-420 calls gf_gen_rs_matrix /
gf_gen_cauchy1_matrix from ISA-L; the library itself is an empty submodule in the
reference checkout, so these are reimplemented from the published constructions):

* cauchy1: rows 0..k-1 are the identity; coding row (i >= k) has
  a[i][j] = inv(i ^ j).  MDS for any k, m with k + m <= 256.
* rs_vandermonde: rows 0..k-1 identity; coding row i >= k is the geometric
  progression [1, g, g^2, ...] with g = 2^(i-k).  NOT guaranteed MDS for large k/m —
  the reference guards k<=32, m<=4 (ErasureCodeIsa.cc:330-361); we expose the same
  construction and the same guard lives in the plugin layer.

Inversion is Gauss-Jordan with row pivoting, mirroring gf_invert_matrix's observable
behaviour (returns failure on a singular matrix; ErasureCodeIsa.cc:274).
"""

from __future__ import annotations

import numpy as np

from .tables import _exp_log, _mul_table, gf_inv


def gen_cauchy1_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) generator matrix: identity stacked on the cauchy block."""
    if k + m > 256:
        raise ValueError(f"k+m={k + m} exceeds GF(2^8) field size")
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k, :k] = np.eye(k, dtype=np.uint8)
    for i in range(k, k + m):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)
    return a


def gen_rs_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) generator matrix: identity stacked on geometric-progression rows."""
    if k + m > 256:
        raise ValueError(f"k+m={k + m} exceeds GF(2^8) field size")
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k, :k] = np.eye(k, dtype=np.uint8)
    gen = 1
    for i in range(k, k + m):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = _gf_mul_int(p, gen)
        gen = _gf_mul_int(gen, 2)
    return a


def _gf_mul_int(a: int, b: int) -> int:
    return int(_mul_table()[a, b])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of uint8 matrices (XOR-accumulated products)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    mt = _mul_table()
    # products[i, l, j] = a[i, l] * b[l, j]; XOR-reduce over l
    prods = mt[a[:, :, None], b[None, :, :]]
    return np.bitwise_xor.reduce(prods, axis=1)


def recovery_matrix(gen: np.ndarray, chosen: list[int],
                    targets: list[int]) -> np.ndarray:
    """Decode matrix: reconstruct chunk rows ``targets`` from chunk rows ``chosen``.

    Mirrors the reference decode structure (ErasureCodeIsa.cc:150-310 /
    jerasure_matrix_decode): take the k surviving generator rows, invert, and
    multiply by the target rows.  ``gen`` is the (k+m, k) generator matrix,
    ``chosen`` exactly k surviving chunk indices, ``targets`` the chunk indices to
    rebuild.  Returns (len(targets), k) uint8 — apply it to the chosen chunks with
    the same batched kernel used for encode.

    Raises ValueError if the chosen rows are singular (non-MDS corner or bad choice).
    """
    gen = np.asarray(gen, dtype=np.uint8)
    k = gen.shape[1]
    if len(chosen) != k:
        raise ValueError(f"need exactly k={k} chosen rows, got {len(chosen)}")
    sub = gen[list(chosen)]
    inv = gf_invert_matrix(sub)
    if inv is None:
        raise ValueError(f"chosen rows {chosen} give a singular submatrix")
    return gf_matmul(gen[list(targets)], inv)


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray | None:
    """Invert a square GF(2^8) matrix; returns None if singular."""
    mat = np.asarray(mat, dtype=np.uint8)
    n = mat.shape[0]
    if mat.shape != (n, n):
        raise ValueError("matrix must be square")
    mt = _mul_table()
    aug = np.concatenate([mat.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot_rows = np.nonzero(aug[col:, col])[0]
        if pivot_rows.size == 0:
            return None
        pr = col + int(pivot_rows[0])
        if pr != col:
            aug[[col, pr]] = aug[[pr, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = mt[aug[col], inv_p]
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= mt[aug[col], aug[row, col]]
    return aug[:, n:].copy()
