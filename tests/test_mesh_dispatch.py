"""Mesh-sharded dispatch: one engine flush fans out across all 8
virtual devices (conftest pins XLA_FLAGS
--xla_force_host_platform_device_count=8, the same mechanism the
driver's multichip dryrun uses).

The load-bearing claims, each pinned here:

  * bit-exactness — mesh-sharded flushes deliver exactly what
    ec_encode_ref / the recovery-matrix oracle / the scalar CRUSH rule
    engine compute, for every kernel the engines carry (encode, the
    heterogeneous-pattern decode with its aux channel, flat_firstn,
    do_rule);
  * shard padding — buckets round up to a multiple of the mesh size
    (jax rejects uneven NamedSharding splits), the pad rows are zeros
    and sliced off, and the padded accounting is exact;
  * the jit compile cache is bounded by the (bucket, mesh) table —
    committed input shardings are part of jax's cache key, so the
    pow-2 bucket discipline carries over unchanged;
  * kernel_mesh_devices=1 is the exact seed path: no mesh, pure pow-2
    buckets, single-device flushes;
  * telemetry/observability: devices_used, sharded flushes, the mesh
    gauges, and the ceph_kernel_mesh_* prometheus family.

Chunk widths here (480, 544) are deliberately absent from every other
suite: the jit cache is process-global and the bounded-cache test
counts entries.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import (DeviceDispatchEngine, bucket_stripes,
                                   mesh_bucket_stripes,
                                   submit_do_rule, submit_flat_firstn)

K1, M1, B1 = 4, 2, 480     # bit-exactness suites
K2, M2, B2 = 6, 2, 544     # bounded-cache suite


def _mesh(n=8, **kw):
    from ceph_tpu.parallel.mesh import make_mesh
    return make_mesh(n, **kw)


def _coding(k, m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, 256, (m, k), dtype=np.uint8)


def _stripes(n, k, b, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, k, b), dtype=np.uint8)


# -- bucketing ----------------------------------------------------------------

def test_mesh_bucket_stripes():
    assert [mesh_bucket_stripes(n, 8) for n in (1, 3, 8, 9, 17, 100)] \
        == [8, 8, 8, 16, 32, 128]
    # non-pow2 mesh: pow-2 bucket rounds UP to a mesh multiple
    assert mesh_bucket_stripes(5, 6) == 12
    assert mesh_bucket_stripes(1, 1) == 1        # degenerate = seed
    assert [mesh_bucket_stripes(n, 1) for n in (3, 5, 9)] \
        == [bucket_stripes(n) for n in (3, 5, 9)]


def test_factor_devices_defaults_to_pure_dp():
    """The engine-mesh bugfix: without an ec_divides promise the split
    is pure data parallelism — ec > 1 would split chunk rows unevenly
    for k+m the axis does not divide."""
    from ceph_tpu.parallel.mesh import factor_devices
    assert factor_devices(8) == (8, 1)
    assert factor_devices(4) == (4, 1)
    assert factor_devices(8, ec_divides=12) == (2, 4)
    m = _mesh(8)
    assert dict(m.shape) == {"dp": 8, "ec": 1}


# -- bit-exactness ------------------------------------------------------------

def test_threaded_mixed_size_encodes_bit_exact_on_mesh():
    """6 writers x 5 mixed-size encodes through ONE mesh-sharded
    engine: every delivered parity equals ec_encode_ref of that
    writer's own data, and the flushes really land on all 8 devices."""
    from ceph_tpu.ops.gf_kernel import ec_encode_ref, make_encoder
    mesh = _mesh(8)
    coding = _coding(K1, M1)
    encode = make_encoder(coding, mesh=mesh)
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(max_delay_us=500.0, stats=stats,
                               mesh=mesh)
    key = ("ec", K1, M1, B1)
    errors: list[str] = []

    def writer(wid):
        rng = np.random.default_rng(300 + wid)
        for i in range(5):
            data = _stripes(int(rng.integers(1, 30)), K1, B1,
                            seed=wid * 100 + i)
            got = eng.submit(key, encode, data).result(timeout=120)
            if not (np.asarray(got) == ec_encode_ref(coding, data)).all():
                errors.append(f"writer {wid} op {i}: mismatch")

    try:
        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert stats.sharded_flushes == stats.batches > 0
        d = stats.devices_used.dump()
        # every flush landed on all 8 devices: the whole histogram
        # mass sits in the le=8 bucket
        assert d["sum"] == 8 * d["count"]
        assert stats.mesh_devices == 8
        assert stats.shard_stripes.count == stats.batches
    finally:
        eng.stop()


def test_codec_submit_chunks_mesh_matches_oracle():
    """ErasureCode.submit_chunks through a mesh engine == the numpy
    oracle; the cpu-runtime codec opts out of placement (host fn) and
    still matches."""
    from ceph_tpu.ec import registry_instance
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(stats=stats, mesh=_mesh(8))
    try:
        for runtime in ("tpu", "cpu"):
            codec = registry_instance().factory(
                "jerasure", {"technique": "reed_sol_van", "k": str(K1),
                             "m": str(M1), "runtime": runtime})
            data = _stripes(9, K1, B1, seed=4)
            got = codec.submit_chunks(eng, data).result(timeout=120)
            assert (np.asarray(got)
                    == codec.encode_chunks(data)).all()
        assert stats.sharded_flushes >= 1          # the tpu flush
        assert stats.devices_used.dump()["buckets"][0] >= 1  # the cpu one
    finally:
        eng.stop()


def test_decode_mixed_patterns_mesh_bit_exact():
    """submit_decode_chunks through a mesh engine: stripes spanning
    MIXED erasure patterns share one sharded call (the pattern index
    rides the aux channel, sharded in lockstep; the stacked matrix
    table replicates over the mesh) and every rebuilt row equals the
    recovery-matrix oracle."""
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.gf.matrix import recovery_matrix
    from ceph_tpu.ops.gf_kernel import ec_encode_ref
    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(K1), "m": str(M1)})
    gen = codec.generator
    stats = telemetry.DecodeDispatchStats()
    eng = DeviceDispatchEngine(max_delay_us=50_000.0, stats=stats,
                               mesh=_mesh(8))
    patterns = [((1, 2, 3, 4), (0,)), ((0, 2, 3, 5), (1, 4)),
                ((0, 1, 3, 4), (2,))]
    release = threading.Event()

    def slow(a):
        release.wait(5.0)
        return a

    try:
        blocker = eng.submit(("slow", 0), slow, np.zeros((1,), np.uint8))
        futs, wants = [], []
        for i, (chosen, targets) in enumerate(patterns):
            data = _stripes(3 + 2 * i, K1, B1, seed=20 + i)
            futs.append(codec.submit_decode_chunks(
                eng, chosen, data, targets))
            wants.append(ec_encode_ref(
                recovery_matrix(gen, list(chosen), list(targets)), data))
        release.set()
        for f, want in zip(futs, wants):
            assert (np.asarray(f.result(timeout=120)) == want).all()
        blocker.result(timeout=120)
        assert stats.sharded_flushes >= 1
        # the three patterns coalesced (engine was busy): at least one
        # call carried > 1 distinct pattern
        assert stats.patterns.sum > stats.patterns.count
    finally:
        eng.stop()


def test_crush_submits_mesh_bit_exact_vs_scalar_oracle():
    """submit_flat_firstn and submit_do_rule through a mesh engine vs
    the SCALAR rule engine (mapper_ref semantics via scalar_rows): the
    sharded remap is bit-identical, padded lanes sliced off."""
    from ceph_tpu.crush import build_flat_map, build_two_level_map
    from ceph_tpu.crush.mapper_jax import BatchMapper
    from ceph_tpu.osd.mapping import scalar_rows
    rng = np.random.default_rng(9)
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats(),
                               mesh=_mesh(8))
    try:
        # flat map: submit_flat_firstn vs the scalar oracle rows
        n_osds = 20
        weights = [0x10000] * 12 + [0x20000] * 8
        m, root, rule = build_flat_map(n_osds, weights)
        bucket = m.bucket(root)
        ids = np.asarray(bucket.items, dtype=np.int32)
        w = np.asarray(bucket.item_weights, dtype=np.int64)
        reweight = np.full(n_osds, 0x10000, dtype=np.int64)
        reweight[3] = 0
        xs = rng.integers(0, 2**32, 53, dtype=np.uint32)  # pads to 56
        got = np.asarray(submit_flat_firstn(
            eng, xs, ids, w, reweight, numrep=3).result(timeout=120))
        want = scalar_rows(m, rule, xs, 3, reweight)
        assert (got == want).all()
        # two-level map: submit_do_rule vs the scalar oracle
        m2, _root2, rule2 = build_two_level_map(4, 3)
        bm = BatchMapper(m2)
        rw2 = np.full(12, 0x10000, dtype=np.int64)
        xs2 = rng.integers(0, 2**32, 21, dtype=np.uint32)
        got2 = np.asarray(submit_do_rule(
            eng, bm, rule2, xs2, 3, rw2).result(timeout=120))
        assert (got2 == scalar_rows(m2, rule2, xs2, 3, rw2)).all()
    finally:
        eng.stop()


# -- shard padding ------------------------------------------------------------

def test_shard_padding_equality_and_accounting():
    """Sizes that divide the mesh unevenly pad up to a mesh multiple;
    the delivered slice equals the unpadded reference and the padded
    accounting is exact (mesh_bucket_stripes, not pow-2)."""
    from ceph_tpu.ops.gf_kernel import ec_encode_ref, make_encoder
    mesh = _mesh(8)
    coding = _coding(K1, M1, seed=1)
    encode = make_encoder(coding, mesh=mesh)
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(stats=stats, mesh=mesh)
    sizes = (1, 3, 7, 9, 13)
    try:
        for n in sizes:
            data = _stripes(n, K1, B1, seed=n)
            got = eng.submit(("pad", K1, M1, B1), encode,
                             data).result(timeout=120)
            assert got.shape == (n, M1, B1)
            assert (np.asarray(got)
                    == ec_encode_ref(coding, data)).all()
        assert stats.padded_stripes == sum(
            mesh_bucket_stripes(n, 8) - n for n in sizes)
    finally:
        eng.stop()


# -- compile-cache bound ------------------------------------------------------

def test_jit_cache_bounded_by_bucket_and_mesh():
    """30 randomized write sizes in [1, 64] through a MESH engine
    compile AT MOST one executable per (mesh-rounded bucket) — the
    sharding is part of jax's compile-cache key, so the (bucket, mesh)
    table bounds the cache exactly as the pow-2 table did on one
    device.  Geometry unique to this test (see module docstring)."""
    from ceph_tpu.ops.gf_kernel import _jit_entries, make_encoder
    mesh = _mesh(8)
    coding = _coding(K2, M2, seed=2)
    encode = make_encoder(coding, mesh=mesh)
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats(),
                               mesh=mesh)
    rng = np.random.default_rng(3)
    sizes = [int(s) for s in rng.integers(1, 65, 30)]
    try:
        before = _jit_entries()
        for i, n in enumerate(sizes):
            out = eng.submit(("bound", K2, M2, B2), encode,
                             _stripes(n, K2, B2, seed=i)
                             ).result(timeout=120)
            assert out.shape == (n, M2, B2)
        grown = _jit_entries() - before
        buckets = {mesh_bucket_stripes(n, 8) for n in sizes}
        assert grown <= len(buckets), \
            f"{grown} compiles for {len(buckets)} buckets {sorted(buckets)}"
    finally:
        eng.stop()


# -- single-device knob == seed path ------------------------------------------

def test_single_device_knob_is_exact_seed_path():
    """kernel_mesh_devices=1: the context builds NO mesh, engines pad
    pure pow-2 buckets, and every flush is single-device — byte-
    identical engine behavior to the pre-mesh seed."""
    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.ops.gf_kernel import ec_encode_ref, make_encoder
    ctx = CephTpuContext("mesh-knob1")
    ctx.conf.set("kernel_mesh_devices", 1)
    assert ctx.kernel_mesh() is None
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(stats=stats, mesh=ctx.kernel_mesh())
    coding = _coding(K1, M1, seed=5)
    encode = make_encoder(coding)
    sizes = (3, 5, 11)
    try:
        for n in sizes:
            data = _stripes(n, K1, B1, seed=40 + n)
            got = eng.submit(("knob1", K1, M1, B1), encode,
                             data).result(timeout=120)
            assert (np.asarray(got)
                    == ec_encode_ref(coding, data)).all()
        # seed pow-2 padding accounting, to the stripe
        assert stats.padded_stripes == sum(
            bucket_stripes(n) - n for n in sizes)
        assert stats.sharded_flushes == 0
        assert stats.mesh_devices == 0
        d = stats.devices_used.dump()
        assert d["buckets"][0] == d["count"] == stats.batches
    finally:
        eng.stop()


def test_context_mesh_knob_default_and_hot_reload():
    """Default knob (0 = all) builds the 8-device mesh; flipping the
    knob at runtime swaps the mesh into LIVE engines (next flush)."""
    from ceph_tpu.common.context import CephTpuContext
    ctx = CephTpuContext("mesh-reload")
    mesh = ctx.kernel_mesh()
    assert mesh is not None and int(mesh.size) == 8
    eng = ctx.dispatch_engine()
    stats = eng.stats
    data = _stripes(4, K1, B1, seed=77)
    try:
        eng.submit(("hot", K1, B1, 0), lambda a: a, data,
                   ).result(timeout=120)
        s0 = stats.sharded_flushes
        assert s0 >= 1
        ctx.conf.set("kernel_mesh_devices", 1)
        assert ctx.kernel_mesh() is None
        eng.submit(("hot", K1, B1, 1), lambda a: a, data,
                   ).result(timeout=120)
        assert stats.sharded_flushes == s0       # unsharded now
        ctx.conf.set("kernel_mesh_devices", 0)
        eng.submit(("hot", K1, B1, 2), lambda a: a, data,
                   ).result(timeout=120)
        assert stats.sharded_flushes == s0 + 1   # sharded again
    finally:
        eng.stop()


# -- mapping-service diff -----------------------------------------------------

def test_mapping_diff_shards_over_mesh_and_matches_host():
    """The on-device old-vs-new raw diff with a mesh equals the host
    diff — for mesh-divisible row counts (sharded) and indivisible
    ones (single-device fallback) alike."""
    from ceph_tpu.osd.mapping import _changed_rows
    mesh = _mesh(8)
    rng = np.random.default_rng(11)
    for rows in (64, 61):       # divisible / not
        old = rng.integers(0, 50, (rows, 3)).astype(np.int32)
        new = old.copy()
        idx = rng.choice(rows, size=7, replace=False)
        new[idx, 0] += 1
        want = np.flatnonzero((old != new).any(axis=1))
        got = _changed_rows(old, new, mesh=mesh)
        assert (np.sort(got) == want).all()


# -- observability ------------------------------------------------------------

class _FakeMap:
    max_osd = 1
    epoch = 3
    osd_weight = [0x10000]

    def is_up(self, o):
        return True

    def exists(self, o):
        return True


class _FakeMgr:
    osdmap = _FakeMap()

    def get(self, name):
        return {
            "health": {"status": "HEALTH_OK"},
            "pg_summary": {},
            "df": {"total_objects": 0, "total_bytes_used": 0},
            "counters": {},
            "perf_reports": {},
        }[name]

    def get_store(self, key, default=None):
        return default


def test_prometheus_mesh_family_and_stats_dump():
    """A sharded flush surfaces in dump_dispatch_stats (devices_used /
    sharded_flushes / mesh gauges) and the scrape exports the
    ceph_kernel_mesh_* family for both engines."""
    from ceph_tpu.mgr.modules.prometheus import Module
    telemetry.reset()
    eng = DeviceDispatchEngine(stats=telemetry.dispatch_stats(),
                               mesh=_mesh(8))
    try:
        eng.submit(("prom", 0), lambda a: a,
                   np.zeros((5, 4), np.int64)).result(timeout=120)
    finally:
        eng.stop()
    d = telemetry.dispatch_dump()
    assert d["sharded_flushes"] == 1
    assert d["mesh_devices"] == 8 and d["mesh_dp"] == 8
    assert d["devices_used"]["sum"] == 8
    assert d["shard_stripes"]["count"] == 1
    mod = Module.__new__(Module)
    mod.mgr = _FakeMgr()
    text = mod.scrape_text()
    assert 'ceph_kernel_mesh_devices{engine="encode"} 8' in text
    assert 'ceph_kernel_mesh_devices{engine="decode"} 0' in text
    assert 'ceph_kernel_mesh_sharded_flushes_total{engine="encode"} 1' \
        in text
    assert '# TYPE ceph_kernel_mesh_flush_devices histogram' in text
    assert 'ceph_kernel_mesh_shard_stripes_bucket' in text


# -- deployment mode (two OS processes, one global mesh) ----------------------

@pytest.mark.slow
def test_dcn_engine_pair_two_processes():
    """The deployment-mode proof: two OS processes, each constructing
    CephTpuContext(process_index=, n_processes=, coordinator=), share
    one global mesh; each drives an EC write workload through its
    mesh-sharded engine (flushes fan out over its local submesh), runs
    a global-mesh DCN collective, and cross-checks digests over the
    TCP messenger stack pick_stack routes to."""
    from ceph_tpu.parallel.dcn import run_engine_pair
    run_engine_pair(8)
