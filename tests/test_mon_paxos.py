"""Multi-monitor consensus: Elector + Paxos (src/mon/Elector.cc,
src/mon/Paxos.cc semantics) on a 3-mon MiniCluster — leader election,
commit replication, leader failover, peon command forwarding, and
rejoin catch-up.
"""

import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


def wait_until(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"never satisfied: {msg}")


@pytest.fixture()
def cluster3():
    c = MiniCluster(n_osds=3, ms_type="loopback", n_mons=3).start()
    try:
        yield c
    finally:
        c.stop()


def test_lowest_rank_wins_election(cluster3):
    wait_until(lambda: all(m.elector.leader == 0 and not m.elector.electing
                           for m in cluster3.mons.values()),
               msg="mon.0 leads everywhere")
    assert cluster3.mons[0].is_leader()
    assert not cluster3.mons[1].is_leader()
    assert sorted(cluster3.mons[0].quorum()) == [0, 1, 2]


def test_commits_replicate_to_all_mons(cluster3):
    cluster3.wait_for_osd_count(3)
    client = cluster3.client()
    pool = cluster3.create_pool(client, pg_num=4, size=3)
    leader = cluster3.mons[0]
    wait_until(lambda: all(
        m.osdmap.epoch == leader.osdmap.epoch
        and pool in m.osdmap.pools for m in cluster3.mons.values()),
        msg="peons converge on the leader's map")
    # the paxos stores hold identical committed tails
    lcs = {m.paxos.last_committed for m in cluster3.mons.values()}
    assert len(lcs) == 1


def test_command_to_peon_is_forwarded(cluster3):
    cluster3.wait_for_osd_count(3)
    from ceph_tpu.client.rados import RadosClient
    # a client that only knows a peon's address still mutates the map
    peon_addr = cluster3.mons[1].addr
    c = RadosClient(peon_addr, ms_type="loopback", timeout=15.0)
    c.connect()
    try:
        res, out = c.mon_command({"prefix": "osd pool create",
                                  "pg_num": "4", "size": "2"})
        assert res == 0, out
        assert "created" in out
    finally:
        c.shutdown()


def test_leader_death_elects_new_leader_and_commits(cluster3):
    cluster3.wait_for_osd_count(3)
    client = cluster3.client(timeout=20.0)
    pool = cluster3.create_pool(client, pg_num=4, size=3)
    io = client.open_ioctx(pool)
    io.write_full("before", b"pre-failover")

    cluster3.kill_mon(0)
    wait_until(lambda: any(m.is_leader() for m in cluster3.mons.values()),
               msg="new leader elected")
    leader = next(m for m in cluster3.mons.values() if m.is_leader())
    assert leader.mon_id == 1  # lowest surviving rank
    assert 0 not in leader.quorum()

    # the cluster still commits map changes...
    res, out = client.mon_command({"prefix": "osd pool create",
                                   "pg_num": "4", "size": "2"})
    assert res == 0, out
    # ...and the data path still works end to end
    io.write_full("after", b"post-failover")
    assert io.read("after") == b"post-failover"
    assert io.read("before") == b"pre-failover"


def test_two_mon_deaths_lose_quorum(cluster3):
    """Majority of the FULL monmap is required: 1 of 3 cannot lead."""
    cluster3.wait_for_osd_count(3)
    cluster3.kill_mon(0)
    cluster3.kill_mon(1)
    time.sleep(3.0)
    assert not cluster3.mons[2].is_leader()


def test_rejoining_mon_catches_up(cluster3):
    cluster3.wait_for_osd_count(3)
    client = cluster3.client(timeout=20.0)
    cluster3.create_pool(client, pg_num=4, size=3)
    cluster3.kill_mon(2)
    # commits happen while mon.2 is gone
    res, out = client.mon_command({"prefix": "osd pool create",
                                   "pg_num": "4", "size": "2"})
    assert res == 0, out
    leader = cluster3.mons[0]
    rejoined = cluster3.run_mon(2)
    wait_until(lambda: rejoined.paxos is not None
               and rejoined.paxos.last_committed
               == leader.paxos.last_committed
               and rejoined.osdmap.epoch == leader.osdmap.epoch,
               timeout=30.0,
               msg="rejoined mon catches up on committed maps")
    assert rejoined.osdmap.pools.keys() == leader.osdmap.pools.keys()


def test_failure_reports_reach_new_leader():
    """OSD heartbeat failure detection works after mon failover."""
    c = MiniCluster(n_osds=3, ms_type="loopback", n_mons=3,
                    heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        c.kill_mon(0)
        wait_until(lambda: any(m.is_leader() for m in c.mons.values()),
                   msg="new leader")
        c.kill_osd(2)
        wait_until(lambda: not c.mon.osdmap.is_up(2), timeout=30.0,
                   msg="osd.2 marked down via the new leader")
    finally:
        c.stop()
