"""Device-boundary telemetry: kernel stats at the JAX offload boundary,
admin-socket surfaces, and the prometheus exposition format.

The retrace-counter test is the load-bearing one: a compile-cache miss
is a retrace+compile (the silent throughput killer), and the counter
must see exactly one miss per distinct shape and zero on repeats.
"""

from __future__ import annotations

import re

import numpy as np
import pytest

from ceph_tpu.ops import telemetry

# chunk widths deliberately absent from every other suite: the jit
# compile cache is process-global, so shape reuse across test files
# would eat the misses this file asserts on
K1, M1, B1 = 5, 2, 224
K2, M2, B2 = 3, 4, 352


def _encode(k, m, b, s=2, seed=0):
    from ceph_tpu.ops.gf_kernel import ec_encode_jax, ec_encode_ref
    rng = np.random.default_rng(seed)
    coeff = rng.integers(1, 256, (m, k), dtype=np.uint8)
    data = rng.integers(0, 256, (s, k, b), dtype=np.uint8)
    out = np.asarray(ec_encode_jax(coeff, data))
    assert (out == ec_encode_ref(coeff, data)).all()
    return s * k * b, s * m * b


# -- kernel stats -------------------------------------------------------------

def test_ec_encode_sample_and_byte_accounting():
    """N batched encodes -> exactly N latency samples, N batch samples,
    and the exact operand/result byte totals."""
    telemetry.reset()
    n, bi, bo = 4, 0, 0
    for i in range(n):
        a, b = _encode(K1, M1, B1, s=3, seed=i)
        bi, bo = bi + a, bo + b
    d = telemetry.dump()["ec_encode"]
    assert d["calls"] == n
    assert d["latency_seconds"]["count"] == n
    assert d["batch_size"]["count"] == n
    assert d["batch_size"]["sum"] == 3 * n
    assert d["bytes_in"] == bi
    assert d["bytes_out"] == bo


def test_jit_retrace_counter_exact():
    """Two distinct (k, m, chunk) shapes -> exactly 2 compile-cache
    misses; repeated same-shape calls -> 0 additional misses."""
    telemetry.reset()
    _encode(K1, M1, B1)
    _encode(K2, M2, B2)
    d = telemetry.dump()["ec_encode"]
    assert d["jit_misses"] == 2, d
    for _ in range(3):
        _encode(K1, M1, B1)
        _encode(K2, M2, B2)
    d = telemetry.dump()["ec_encode"]
    assert d["jit_misses"] == 2, d
    assert d["jit_hits"] == 6
    assert d["calls"] == 8


def test_fence_for_timing_knob():
    telemetry.reset()
    telemetry.set_fence_for_timing(True)
    try:
        _encode(K1, M1, B1)
    finally:
        telemetry.set_fence_for_timing(False)
    d = telemetry.dump()["ec_encode"]
    assert d["latency_seconds"]["count"] == 1
    assert d["latency_seconds"]["sum"] > 0


def test_crush_do_rule_telemetry():
    import jax.numpy as jnp

    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.crush.mapper_jax import BatchMapper

    telemetry.reset()
    m, _root, rid = build_two_level_map(4, 4)
    bm = BatchMapper(m)
    xs = jnp.arange(96, dtype=jnp.uint32)
    rw = jnp.full(16, 0x10000, dtype=jnp.int64)
    bm.do_rule(rid, xs, 3, rw)
    bm.do_rule(rid, xs, 3, rw)
    d = telemetry.dump()["crush_map"]
    assert d["calls"] == 2
    assert d["jit_misses"] == 1
    assert d["jit_hits"] == 1
    assert d["batch_size"]["sum"] == 192
    assert d["bytes_in"] == 2 * (96 * 4 + 16 * 8)
    assert d["bytes_out"] == 2 * 96 * 3 * 4


def test_traced_calls_produce_no_latency_samples():
    """Kernel calls inlined under an outer jit (the bench's chained
    scans) count as traced, not as device calls."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops.gf_kernel import make_encoder

    telemetry.reset()
    rng = np.random.default_rng(7)
    enc = make_encoder(rng.integers(1, 256, (M1, K1), dtype=np.uint8))
    data = jnp.asarray(rng.integers(0, 256, (2, K1, B1), dtype=np.uint8))

    @jax.jit
    def step(d):
        return enc(d)

    step(data)
    d = telemetry.dump()["ec_encode"]
    assert d["traced"] >= 1
    assert d["latency_seconds"]["count"] == 0


# -- admin-socket surfaces ----------------------------------------------------

def test_admin_socket_dump_kernel_stats_and_tracing():
    from ceph_tpu.common import tracing
    from ceph_tpu.common.context import CephTpuContext

    telemetry.reset()
    _encode(K1, M1, B1)
    ctx = CephTpuContext("osd.99")
    ks = ctx.admin.execute("dump_kernel_stats")
    assert ks["ec_encode"]["calls"] == 1
    assert "latency_seconds" in ks["ec_encode"]

    with tracing.trace_ctx() as tid:
        tracing.record("osd.99", "unit-test event")
    rows = ctx.admin.execute("dump_tracing", trace_id=str(tid))
    # span-structured payload: the root span row precedes the event
    assert rows and any(r["event"] == "unit-test event" for r in rows)
    assert rows[0]["kind"] == "span"          # the trace's root span
    ev = next(r for r in rows if r["event"] == "unit-test event")
    assert ev["span_id"] == rows[0]["span_id"]   # attached to the root
    # no filter: the stitched timeline includes our trace
    assert any(r["trace_id"] == tid
               for r in ctx.admin.execute("dump_tracing"))


def test_fence_knob_is_a_config_option():
    from ceph_tpu.common.context import CephTpuContext

    ctx = CephTpuContext("client.knob")
    assert telemetry.registry().fence_for_timing is False
    ctx.conf.set("kernel_fence_for_timing", "true")
    assert telemetry.registry().fence_for_timing is True
    ctx.conf.set("kernel_fence_for_timing", "false")
    assert telemetry.registry().fence_for_timing is False


# -- prometheus exposition ----------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})? (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """Strict line parser: returns {family: {"type", "help",
    "samples": [(metric_name, labels_dict, float_value)]}} and raises
    on any malformed line or sample without a preceding header."""
    fams: dict = {}
    declared: dict[str, dict] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            declared.setdefault(name, {})["help"] = help_
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, typ = rest.partition(" ")
            assert typ in ("gauge", "counter", "histogram", "summary",
                           "untyped"), line
            declared.setdefault(name, {})["type"] = typ
            continue
        assert not line.startswith("#"), f"bad comment line: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group("name")
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and declared.get(base, {}).get("type") in (
                    "histogram", "summary"):
                fam = base
                break
        assert fam in declared, f"sample {name} has no TYPE/HELP header"
        assert "type" in declared[fam] and "help" in declared[fam], name
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        value = float(m.group("value").replace("+Inf", "inf"))
        fams.setdefault(fam, {**declared[fam], "samples": []})[
            "samples"].append((name, labels, value))
    return fams


class _FakeMap:
    max_osd = 2
    epoch = 7
    osd_weight = [0x10000, 0x10000]

    def is_up(self, o):
        return True

    def exists(self, o):
        return True


class _FakeMgr:
    """The minimal MgrDaemon surface the prometheus module reads."""

    def __init__(self, perf_reports=None):
        self._perf = perf_reports or {}

    osdmap = _FakeMap()

    def get(self, name):
        return {
            "health": {"status": "HEALTH_WARN"},
            "pg_summary": {"active": 8, "peering": 1},
            "df": {"total_objects": 12, "total_bytes_used": 34567},
            "counters": {0: {"op_w": 3, "op_w_latency": 1.25}},
            "perf_reports": self._perf,
        }[name]

    def get_store(self, key, default=None):
        return default


def _scrape(perf_reports=None) -> str:
    from ceph_tpu.mgr.modules.prometheus import Module
    mgr = _FakeMgr(perf_reports)
    mod = Module.__new__(Module)
    mod.mgr = mgr
    return mod.scrape_text()


def test_scrape_format_validity():
    """Every line parses; every family has HELP/TYPE; histogram buckets
    are cumulative over monotone le bounds and +Inf equals _count."""
    telemetry.reset()
    _encode(K1, M1, B1, s=3)
    _encode(K1, M1, B1, s=3)
    fams = parse_exposition(_scrape())

    for want in ("ceph_pg_states", "ceph_cluster_total_objects",
                 "ceph_cluster_bytes_used", "ceph_osd_perf"):
        assert want in fams, sorted(fams)
    # floats survive (int(val) used to truncate 1.25 to 1)
    osd_perf = {(l["counter"]): v
                for _n, l, v in fams["ceph_osd_perf"]["samples"]}
    assert osd_perf["op_w_latency"] == 1.25

    hist_fams = [f for f, d in fams.items() if d["type"] == "histogram"]
    assert "ceph_kernel_ec_encode_latency_seconds" in hist_fams
    assert "ceph_kernel_crush_map_latency_seconds" in hist_fams
    for fam in hist_fams:
        samples = fams[fam]["samples"]
        by_series: dict = {}
        for name, labels, value in samples:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            by_series.setdefault(key, {}) \
                .setdefault(name.rsplit("_", 1)[-1]
                            if not name.endswith("_bucket") else "bucket",
                            []).append((labels.get("le"), value))
        for key, parts in by_series.items():
            buckets = parts.get("bucket", [])
            assert buckets, (fam, key)
            les = [float(le.replace("+Inf", "inf")) for le, _ in buckets]
            assert les == sorted(les), (fam, les)
            counts = [v for _le, v in buckets]
            assert counts == sorted(counts), (fam, counts)   # cumulative
            assert les[-1] == float("inf")
            (_, total), = parts["count"]
            assert counts[-1] == total, (fam, counts, total)
            assert "sum" in parts, (fam, key)


def test_scrape_emits_typed_daemon_perf():
    """MMgrReport v3 typed dumps become counter/summary/histogram
    families with untruncated float values."""
    reports = {0: {
        "osd.0": {"op_w": 5,
                  "op_w_latency": {"avgcount": 2, "sum": 0.125}},
        "msgr.osd.0": {"msg_send": 9, "bytes_send": 4096},
        "bluestore": {"commit_lat": {"avgcount": 3, "sum": 1.5}},
        "kern": {"lat": {"bounds": [0.1, 1.0], "buckets": [1, 2, 1],
                         "sum": 2.25}},
    }}
    fams = parse_exposition(_scrape(reports))
    ctr = {(l["set"], l["counter"]): v for _n, l, v
           in fams["ceph_daemon_perf_counter"]["samples"]}
    assert ctr[("msgr.osd.0", "msg_send")] == 9
    assert ctr[("osd.0", "op_w")] == 5
    lat = {(l["set"], l["counter"], n.rsplit("_", 1)[-1]): v
           for n, l, v in fams["ceph_daemon_perf_latency"]["samples"]}
    assert lat[("bluestore", "commit_lat", "sum")] == 1.5
    assert lat[("bluestore", "commit_lat", "count")] == 3
    assert lat[("osd.0", "op_w_latency", "sum")] == 0.125
    assert fams["ceph_daemon_perf_hist"]["type"] == "histogram"
    hist = fams["ceph_daemon_perf_hist"]["samples"]
    inf_bucket = [v for n, l, v in hist
                  if n.endswith("_bucket") and l.get("le") == "+Inf"]
    assert inf_bucket == [4]


# -- wire format --------------------------------------------------------------

def test_mgr_report_v3_perf_roundtrip():
    from ceph_tpu.mgr.daemon import MMgrReport
    from ceph_tpu.msg.message import Message

    perf = {"osd.1": {"op_w": 2,
                      "op_w_latency": {"avgcount": 1, "sum": 0.5}},
            "msgr.osd.1": {"msg_send": 11}}
    msg = MMgrReport(osd_id=1, counters={"op_w": 2},
                     pg_states={"active": 4}, num_objects=9,
                     bytes_used=4096, perf=perf)
    back = Message.decode(msg.encode())
    assert back.osd_id == 1
    assert back.counters == {"op_w": 2}
    assert back.perf == perf
    assert back.pg_states == {"active": 4}


def test_messenger_wire_counters():
    """Loopback send/recv bumps the messenger perf sets, and the counts
    ride the v3 perf payload shape (set name msgr.<entity>)."""
    import time as _t

    from ceph_tpu.mgr.daemon import MMgrReport
    from ceph_tpu.msg.messenger import (
        ConnectionPolicy, Dispatcher, EntityName, Messenger)

    class Sink(Dispatcher):
        def __init__(self):
            self.got = []

        def ms_dispatch(self, msg):
            self.got.append(msg)
            return True

    a = Messenger.create(EntityName("osd", 71), "loopback")
    b = Messenger.create(EntityName("mgr", 72), "loopback")
    sink = Sink()
    for m in (a, b):
        m.set_policy("osd", ConnectionPolicy.stateful_peer())
    b.add_dispatcher_tail(sink)
    try:
        a.bind("lo:osd71")
        b.bind("lo:mgr72")
        a.start()
        b.start()
        con = a.connect_to("lo:mgr72", EntityName("mgr", 72))
        con.send_message(MMgrReport(osd_id=71, counters={"op_w": 1}))
        deadline = _t.time() + 5
        while _t.time() < deadline and not sink.got:
            _t.sleep(0.01)
        assert sink.got
        da = a.perf.dump()
        db = b.perf.dump()
        assert da["msg_send"] == 1
        assert da["bytes_send"] > 0
        assert db["msg_recv"] == 1
        assert db["bytes_recv"] == da["bytes_send"]
    finally:
        a.shutdown()
        b.shutdown()


def test_bluestore_perf_counters(tmp_path):
    from ceph_tpu.objectstore import Transaction, create_objectstore

    store = create_objectstore("bluestore", str(tmp_path / "bs"))
    store.mkfs_if_needed()
    store.mount()
    try:
        t = Transaction()
        t.create_collection("c")
        t.write("c", "o", 0, b"x" * 8192)
        store.queue_transactions([t])
        d = store.perf.dump()
        assert d["txc"] == 1
        assert d["commit_lat"]["avgcount"] == 1
        assert d["commit_lat"]["sum"] > 0
        assert d["apply_lat"]["avgcount"] == 1
    finally:
        store.umount()
