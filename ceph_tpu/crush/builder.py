"""CRUSH map construction (src/crush/builder.c semantics) plus convenience
topologies used by tests, benchmarks and the placement layer.

Weights are 16.16 fixed point throughout (0x10000 == 1.0)."""

from __future__ import annotations

import math

from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_TAKE,
    Bucket,
    CrushMap,
    Rule,
    RuleStep,
)


def make_uniform_bucket(id: int, type: int, items: list[int],
                        item_weight: int) -> Bucket:
    """builder.c:190-228."""
    return Bucket(id=id, type=type, alg=CRUSH_BUCKET_UNIFORM, items=list(items),
                  item_weight=item_weight, weight=len(items) * item_weight)


def make_list_bucket(id: int, type: int, items: list[int],
                     weights: list[int]) -> Bucket:
    """builder.c:230-281 — cumulative sums in insertion order."""
    sums = []
    w = 0
    for wi in weights:
        w += wi
        sums.append(w)
    return Bucket(id=id, type=type, alg=CRUSH_BUCKET_LIST, items=list(items),
                  item_weights=list(weights), sum_weights=sums, weight=w)


def _calc_depth(size: int) -> int:
    """builder.c:307-318."""
    if size == 0:
        return 0
    depth = 1
    t = size - 1
    while t:
        depth += 1
        t >>= 1
    return depth


def make_tree_bucket(id: int, type: int, items: list[int],
                     weights: list[int]) -> Bucket:
    """builder.c:322-394 — leaf i sits at node 2i+1; weights sum upward."""
    size = len(items)
    depth = _calc_depth(size)
    num_nodes = 1 << depth
    node_weights = [0] * num_nodes
    total = 0
    for i, wi in enumerate(weights):
        node = ((i + 1) << 1) - 1  # crush_calc_tree_node (crush.h:504-507)
        node_weights[node] = wi
        total += wi
        for _ in range(1, depth):
            # parent: climb one level (builder.c parent())
            h = 0
            n = node
            while not (n & 1):
                h += 1
                n >>= 1
            if node & (1 << (h + 1)):
                node -= 1 << h
            else:
                node += 1 << h
            node_weights[node] += wi
    return Bucket(id=id, type=type, alg=CRUSH_BUCKET_TREE, items=list(items),
                  item_weights=list(weights), node_weights=node_weights,
                  weight=total)


def _calc_straws(items: list[int], weights: list[int],
                 straw_calc_version: int) -> list[int]:
    """builder.c:427-546 crush_calc_straw — double-precision straw scaling."""
    size = len(items)
    # stable insertion sort ascending by weight (builder.c:436-454)
    reverse = [0] if size else []
    for i in range(1, size):
        for j in range(i):
            if weights[i] < weights[reverse[j]]:
                reverse.insert(j, i)
                break
        else:
            reverse.append(i)
    straws = [0] * size
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if straw_calc_version == 0:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                continue
            straws[reverse[i]] = int(straw * 0x10000) & 0xFFFFFFFF
            i += 1
            if i == size:
                break
            if weights[reverse[i]] == weights[reverse[i - 1]]:
                continue
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            j = i
            while j < size:
                if weights[reverse[j]] == weights[reverse[i]]:
                    numleft -= 1
                    j += 1
                else:
                    break
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
        else:
            if weights[reverse[i]] == 0:
                straws[reverse[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[reverse[i]] = int(straw * 0x10000) & 0xFFFFFFFF
            i += 1
            if i == size:
                break
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = float(weights[reverse[i - 1]])
    return straws


def make_straw_bucket(id: int, type: int, items: list[int], weights: list[int],
                      straw_calc_version: int = 1) -> Bucket:
    """builder.c:548-592 (legacy straw; straw lengths from crush_calc_straw)."""
    return Bucket(id=id, type=type, alg=CRUSH_BUCKET_STRAW, items=list(items),
                  item_weights=list(weights),
                  straws=_calc_straws(items, weights, straw_calc_version),
                  weight=sum(weights))


def make_straw2_bucket(id: int, type: int, items: list[int],
                       weights: list[int]) -> Bucket:
    """builder.c:594-632."""
    return Bucket(id=id, type=type, alg=CRUSH_BUCKET_STRAW2, items=list(items),
                  item_weights=list(weights), weight=sum(weights))


def make_bucket(id: int, alg: int, type: int, items: list[int],
                weights: list[int], straw_calc_version: int = 1) -> Bucket:
    """crush_make_bucket dispatch (builder.c:642-666).  Uniform takes weights[0]
    as the shared item weight."""
    if alg == CRUSH_BUCKET_UNIFORM:
        return make_uniform_bucket(id, type, items, weights[0] if weights else 0)
    if alg == CRUSH_BUCKET_LIST:
        return make_list_bucket(id, type, items, weights)
    if alg == CRUSH_BUCKET_TREE:
        return make_tree_bucket(id, type, items, weights)
    if alg == CRUSH_BUCKET_STRAW:
        return make_straw_bucket(id, type, items, weights, straw_calc_version)
    if alg == CRUSH_BUCKET_STRAW2:
        return make_straw2_bucket(id, type, items, weights)
    raise ValueError(f"unknown bucket alg {alg}")


# ---------------------------------------------------------------------------
# rules (CrushWrapper::add_simple_rule analog, CrushWrapper.cc; "firstn" for
# replicated pools, "indep" for EC pools — ErasureCode::create_rule uses indep,
# src/erasure-code/ErasureCode.cc:53-72)
# ---------------------------------------------------------------------------

def add_simple_rule(map: CrushMap, root_id: int, failure_domain_type: int,
                    mode: str = "firstn", ruleset: int | None = None,
                    rule_type: int = 1) -> int:
    steps = [RuleStep(RULE_TAKE, root_id, 0)]
    if mode == "firstn":
        if failure_domain_type == 0:
            # device-level failure domain: plain choose, no leaf recursion
            # (CrushWrapper::add_simple_rule type==0 branch)
            steps.append(RuleStep(RULE_CHOOSE_FIRSTN, 0, 0))
        else:
            steps.append(
                RuleStep(RULE_CHOOSELEAF_FIRSTN, 0, failure_domain_type))
    elif mode == "indep":
        if failure_domain_type == 0:
            steps.append(RuleStep(RULE_CHOOSE_INDEP, 0, 0))
        else:
            steps.append(RuleStep(RULE_CHOOSELEAF_INDEP, 0, failure_domain_type))
    else:
        raise ValueError(f"unknown mode {mode}")
    steps.append(RuleStep(RULE_EMIT, 0, 0))
    rid = ruleset if ruleset is not None else map.max_rules
    return map.add_rule(Rule(ruleset=rid, type=rule_type, min_size=1,
                             max_size=10, steps=steps))


# ---------------------------------------------------------------------------
# convenience topologies
# ---------------------------------------------------------------------------

def build_flat_map(n_osds: int, weights: list[int] | None = None,
                   alg: int = CRUSH_BUCKET_STRAW2) -> tuple[CrushMap, int, int]:
    """One root bucket holding all OSDs.  Returns (map, root_id, rule_id) with a
    `choose indep 0 osd` EC-style rule and a firstn rule at ruleset 0."""
    m = CrushMap()
    m.max_devices = n_osds
    if weights is None:
        weights = [0x10000] * n_osds
    m.add_bucket(make_bucket(-1, alg, 1, list(range(n_osds)), weights))
    rule = Rule(ruleset=0, type=1, min_size=1, max_size=10, steps=[
        RuleStep(RULE_TAKE, -1, 0),
        RuleStep(RULE_CHOOSE_FIRSTN, 0, 0),
        RuleStep(RULE_EMIT, 0, 0),
    ])
    m.add_rule(rule)
    indep = Rule(ruleset=1, type=3, min_size=1, max_size=20, steps=[
        RuleStep(RULE_TAKE, -1, 0),
        RuleStep(RULE_CHOOSE_INDEP, 0, 0),
        RuleStep(RULE_EMIT, 0, 0),
    ])
    m.add_rule(indep)
    return m, -1, 0


def build_two_level_map(n_hosts: int, osds_per_host: int,
                        host_alg: int = CRUSH_BUCKET_STRAW2,
                        root_alg: int = CRUSH_BUCKET_STRAW2,
                        osd_weight: int = 0x10000) -> tuple[CrushMap, int, int]:
    """root -> hosts -> osds.  Types: osd=0, host=1, root=2.  Returns
    (map, root_id, chooseleaf_firstn_rule_id)."""
    m = CrushMap()
    m.max_devices = n_hosts * osds_per_host
    host_ids = []
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host, (h + 1) * osds_per_host))
        hid = -(h + 2)
        m.add_bucket(make_bucket(hid, host_alg, 1, osds,
                                 [osd_weight] * osds_per_host))
        host_ids.append(hid)
    host_weights = [m.bucket(h).weight for h in host_ids]
    m.add_bucket(make_bucket(-1, root_alg, 2, host_ids, host_weights))
    rid = add_simple_rule(m, -1, 1, "firstn")
    return m, -1, rid
