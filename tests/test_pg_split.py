"""PG split: pg_num growth on a live pool (PG::split_into,
src/osd/PG.cc:2575; OSDMonitor pg_num validation).

The two-step reference semantics: raising pg_num splits PGs in place
(children stay colocated with their parents because the placement seed
stable_mod's back to the parent while pgp_num is unchanged); raising
pgp_num afterwards actually moves the children.  Both steps run here
under concurrent client writes with zero lost objects.
"""

from __future__ import annotations

import threading
import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


def _poll_read(io, name, want, timeout=15.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            got = io.read(name)
            if got == want:
                return
            last = got
        except Exception as e:          # resend window / peering
            last = e
        time.sleep(0.05)
    raise AssertionError(f"object {name}: wanted {want!r}, last {last!r}")


def _grow(cluster, client, pool_id, var, val):
    rc, out = client.mon_command({
        "prefix": "osd pool set", "pool": pool_id,
        "var": var, "val": str(val)})
    assert rc == 0, out
    epoch = cluster.mon.osdmap.epoch
    cluster.wait_for_epoch(epoch)
    client.wait_for_epoch(epoch)


def _run_split_workload(pool_kwargs, n_objects=120):
    c = MiniCluster(n_osds=3).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=30.0)
        pool = c.create_pool(client, pg_num=8, **pool_kwargs)
        io = client.open_ioctx(pool)

        data = {f"obj-{i}": (f"payload-{i}-" * 9).encode()
                for i in range(n_objects)}
        for name, blob in list(data.items())[: n_objects // 2]:
            io.write_full(name, blob)

        # concurrent writer during the split
        errors: list = []
        acked: dict[str, bytes] = {}
        stop = threading.Event()

        def writer():
            items = list(data.items())[n_objects // 2:]
            i = 0
            while not stop.is_set() and i < len(items):
                name, blob = items[i]
                try:
                    io.write_full(name, blob)
                    acked[name] = blob
                    i += 1
                except Exception as e:  # pragma: no cover
                    errors.append((name, e))
                    time.sleep(0.1)

        w = threading.Thread(target=writer, daemon=True)
        w.start()
        _grow(c, client, pool, "pg_num", 32)
        w.join(timeout=60)
        stop.set()
        assert not errors, errors
        assert len(acked) == n_objects - n_objects // 2

        # every object (pre-split and during-split) readable, intact
        for name, blob in data.items():
            _poll_read(io, name, blob)

        # children actually split out on the OSDs: collections beyond the
        # original 8 exist and hold objects
        child_objs = 0
        for osd in c.osds.values():
            for cid in osd.store.list_collections():
                pid, _, num = cid.partition(".")
                if int(pid) == pool and int(num) >= 8:
                    child_objs += sum(
                        1 for o in osd.store.list_objects(cid)
                        if not o.startswith("_pgmeta_"))
        assert child_objs > 0, "no objects moved to child PGs"

        # step 2: raise pgp_num — children remap and recover
        _grow(c, client, pool, "pgp_num", 32)
        for name, blob in data.items():
            _poll_read(io, name, blob)

        # overwrite through the split topology still works
        io.write_full("obj-0", b"rewritten")
        _poll_read(io, "obj-0", b"rewritten")
    finally:
        c.stop()


def test_pg_split_replicated():
    _run_split_workload({})


def test_pg_split_erasure():
    _run_split_workload({"pool_type": "erasure", "k": 2, "m": 1},
                        n_objects=60)


def test_pg_num_validation():
    c = MiniCluster(n_osds=3).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=8)
        rc, out = client.mon_command({
            "prefix": "osd pool set", "pool": pool,
            "var": "pg_num", "val": "4"})
        assert rc == -22, out
        rc, out = client.mon_command({
            "prefix": "osd pool set", "pool": pool,
            "var": "pgp_num", "val": "16"})
        assert rc == -22, out
    finally:
        c.stop()
