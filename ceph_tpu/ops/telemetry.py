"""Kernel telemetry at the JAX offload boundary.

The repo's two batchable numeric kernels — GF(2^8) EC encode/decode
(ops.gf_kernel) and CRUSH straw2 mapping (crush.mapper_jax) — are the
dominant data path, yet the device boundary itself was uninstrumented.
This module is the process-global registry those call sites feed:

  * per-kernel wall-time histograms.  By default the sample is the
    UNFENCED dispatch time (the async runtime acks before execution
    completes); with ``fence_for_timing`` on, each instrumented call
    blocks until the result is ready so the sample is real device
    residency.  The knob is a config option (``kernel_fence_for_timing``)
    because fencing serializes the pipeline — the hot path runs unfenced;
  * batch-size/occupancy histograms (how full each device call is — the
    whole thesis is batching, so occupancy IS the efficiency metric);
  * host->device / device->host byte counters (input operand bytes and
    result bytes crossing the boundary per call);
  * jit compile-cache hit/miss counters.  A miss is a retrace+compile —
    the silent throughput killer when shapes churn.  Counted from the
    jitted entry point's own compile cache (``_cache_size`` delta) when
    available, else from a seen-signature set the call site provides.

Everything here is stdlib-only: importing this module never pulls in
the kernel modules or pallas (the mgr's prometheus scraper and every
CephTpuContext import it; ceph_tpu.ops resolves its kernel exports
lazily for the same reason), and the instrumented call sites pass
callables for anything device-flavored.

Calls made UNDER an outer jit trace (the bench's chained ``lax.scan``
loops, any user jit composing our kernels) return tracers: those are
counted as ``traced`` executions but produce no latency/byte samples —
a tracer has no wall time and fencing it would throw.

Surfaces: ``dump()`` (admin socket ``dump_kernel_stats``), the mgr
prometheus module (histogram families per kernel), and ``summary()``
(bench.py's one-line digest: retraces, p50/p99 latency, occupancy).
"""

from __future__ import annotations

import time
from collections import deque

from ceph_tpu.common import lockdep

#: latency bucket upper bounds, seconds (log-spaced: 10 us .. 1 s; the
#: remote-dispatch tunnel's ~0.9 ms step latency lands mid-range)
LATENCY_BOUNDS = (
    1e-5, 2e-5, 5e-5, 1e-4, 2e-4, 5e-4,
    1e-3, 2e-3, 5e-3, 1e-2, 2e-2, 5e-2, 0.1, 0.25, 0.5, 1.0)

#: batch-occupancy bucket upper bounds (stripes or lanes per call)
BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                2048, 4096, 8192, 16384, 32768, 65536)

#: coalesce-factor / queue-depth bucket upper bounds (requests per
#: device call; the whole point of the dispatch engine is pushing the
#: mass of this histogram above 1)
COALESCE_BOUNDS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 128)

#: fraction bucket upper bounds (shard imbalance, padded-lane share)
FRACTION_BOUNDS = (0.01, 0.02, 0.05, 0.1, 0.15, 0.25, 0.4, 0.6,
                   0.8, 1.0)

#: the dispatch pipeline's phases, in TIMELINE order.  The ledger is
#: continuous — each phase starts exactly where the previous ended —
#: so the per-batch phase sum reconstructs the batch's submit→delivery
#: wall-clock (the "where did the time go" invariant the profiler
#: tests pin):
#:
#:   queue_wait   oldest submit → dispatch thread starts the batch
#:   build        pad/concat of the coalesced host batch (+ aux)
#:   place        device_put / h2d placement (mesh sharding included)
#:   launch       the fn() call — async dispatch ack; a first-call
#:                batch's jit trace+compile lands here (attributed to
#:                the compile ledger, not steady-state)
#:   compute      launch ack → result ready (device execution; also
#:                absorbs completion-thread pickup wait, which overlaps
#:                execution under double buffering)
#:   materialize  d2h materialization (np.asarray of the ready result)
#:   deliver      per-request slicing + future/continuation fan-out
PHASES = ("queue_wait", "build", "place", "launch", "compute",
          "materialize", "deliver")

#: default bound on retained per-batch profile records per engine
#: (the ``kernel_profile_ring`` option rebinds it at runtime)
PROFILE_RING_DEFAULT = 256
_profile_ring = PROFILE_RING_DEFAULT


class Histogram:
    """Cumulative-bucket histogram with a running sum (the Prometheus
    histogram data model: ``le`` buckets + ``_sum`` + ``_count``)."""

    __slots__ = ("bounds", "buckets", "sum")

    def __init__(self, bounds):
        self.bounds = tuple(bounds)
        self.buckets = [0] * (len(self.bounds) + 1)   # last = +Inf
        self.sum = 0.0

    def add(self, value: float) -> None:
        i = 0
        for b in self.bounds:
            if value <= b:
                break
            i += 1
        self.buckets[i] += 1
        self.sum += value

    @property
    def count(self) -> int:
        return sum(self.buckets)

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (upper bound of the bucket holding it);
        0.0 with no samples."""
        total = self.count
        if not total:
            return 0.0
        rank = q * total
        acc = 0
        for i, n in enumerate(self.buckets):
            acc += n
            if acc >= rank:
                return (self.bounds[i] if i < len(self.bounds)
                        else self.bounds[-1])
        return self.bounds[-1]

    def dump(self) -> dict:
        return {"bounds": list(self.bounds),
                "buckets": list(self.buckets),
                "sum": self.sum, "count": self.count}


class KernelStats:
    """Counters for one named kernel (e.g. "ec_encode", "crush_map")."""

    __slots__ = ("name", "calls", "traced", "jit_misses", "jit_hits",
                 "bytes_in", "bytes_out", "latency", "batch",
                 "_signatures", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0          # completed device calls (concrete result)
        self.traced = 0         # executions under an outer jit trace
        self.jit_misses = 0     # compile-cache misses (retrace+compile)
        self.jit_hits = 0       # calls served by a cached executable
        self.bytes_in = 0       # host->device operand bytes
        self.bytes_out = 0      # device->host result bytes
        self.latency = Histogram(LATENCY_BOUNDS)
        self.batch = Histogram(BATCH_BOUNDS)
        self._signatures: set = set()
        self._lock = lockdep.make_lock(f"KernelStats::lock({name})")

    def record(self, seconds: float, *, batch: int = 0, bytes_in: int = 0,
               bytes_out: int = 0, misses: int = 0) -> None:
        with self._lock:
            self.calls += 1
            self.latency.add(seconds)
            if batch:
                self.batch.add(batch)
            self.bytes_in += bytes_in
            self.bytes_out += bytes_out
            if misses > 0:
                self.jit_misses += misses
            else:
                self.jit_hits += 1

    def note_signature(self, sig) -> bool:
        """Fallback miss detector when the jit cache is not
        introspectable: True (miss) the first time a shape signature is
        seen."""
        with self._lock:
            if sig in self._signatures:
                return False
            self._signatures.add(sig)
            return True

    def dump(self) -> dict:
        with self._lock:
            return {
                "calls": self.calls,
                "traced": self.traced,
                "jit_misses": self.jit_misses,
                "jit_hits": self.jit_hits,
                "bytes_in": self.bytes_in,
                "bytes_out": self.bytes_out,
                "latency_seconds": self.latency.dump(),
                "batch_size": self.batch.dump(),
            }


class PhaseStats:
    """Per-batch pipeline phase attribution for one dispatch engine.

    Three ledgers, one question — where does a flushed batch's
    submit→delivery wall-clock go:

    * **phase histograms**, per kernel family (the request label:
      ec_encode, ec_decode, crush_rule, ...) × phase (PHASES above).
      Steady-state only — a first-call batch's launch+compute carry
      jit trace/compile cost and would poison the compute story, so
      they are diverted to
    * the **compile ledger**: total seconds and event count per
      family, attributed on the FIRST flush of each (family, bucket,
      mesh) combination (or whenever the submitter's jit-cache probe
      reports a miss — the ground truth when available);
    * **device utilization**: busy-seconds integral (compute seconds ×
      devices the flush landed on), a utilization gauge over the
      window since construction/clear, and the shard-imbalance story
      for mesh engines (padded-lane share of each sharded flush — rows
      are contiguous, so padding concentrates in the tail shards).

    A bounded ring of recent per-batch profile records rides along so
    ``dump_pipeline_profile`` can show the last N batches verbatim,
    not just aggregates.
    """

    __slots__ = ("_lock", "phase", "compile_seconds", "compile_events",
                 "_compiled_keys", "busy_seconds", "devices_seen",
                 "shard_imbalance", "last_shard_imbalance", "records",
                 "_anchor")

    def __init__(self, name: str = "phase"):
        self._lock = lockdep.make_lock(f"PhaseStats::lock({name})")
        #: (family, phase) -> Histogram of seconds (steady-state)
        self.phase: dict[tuple, Histogram] = {}
        self.compile_seconds: dict[str, float] = {}
        self.compile_events: dict[str, int] = {}
        #: (family, bucket, devices) combos already charged a compile
        self._compiled_keys: set = set()
        self.busy_seconds = 0.0     # sum of compute_s * devices
        self.devices_seen = 1       # widest flush fan-out observed
        self.shard_imbalance = Histogram(FRACTION_BOUNDS)
        self.last_shard_imbalance = 0.0
        self.records: deque = deque(maxlen=_profile_ring)
        self._anchor = time.monotonic()   # utilization window start

    def clear(self) -> None:
        with self._lock:
            self.phase = {}
            self.compile_seconds = {}
            self.compile_events = {}
            self._compiled_keys = set()
            self.busy_seconds = 0.0
            self.devices_seen = 1
            self.shard_imbalance = Histogram(FRACTION_BOUNDS)
            self.last_shard_imbalance = 0.0
            self.records = deque(maxlen=_profile_ring)
            self._anchor = time.monotonic()

    def _resize_ring(self, n: int) -> None:
        with self._lock:
            self.records = deque(self.records, maxlen=n)

    def record_batch(self, family: str, *, phases: dict, e2e_s: float,
                     requests: int, stripes: int, bucket: int,
                     devices: int, misses=None) -> None:
        """One flushed batch's full ledger.  ``phases`` maps PHASES
        names to seconds (missing = 0); ``misses`` is the submitter's
        jit-cache delta when probed (None = not probed — first-call
        detection falls back to the (family, bucket, devices) set)."""
        d = max(1, int(devices))
        with self._lock:
            key = (family, int(bucket), d)
            first = key not in self._compiled_keys
            if first:
                self._compiled_keys.add(key)
            compiled = (misses > 0) if misses is not None else first
            if compiled:
                self.compile_seconds[family] = (
                    self.compile_seconds.get(family, 0.0)
                    + phases.get("launch", 0.0)
                    + phases.get("compute", 0.0))
                self.compile_events[family] = \
                    self.compile_events.get(family, 0) + 1
            for ph in PHASES:
                if compiled and ph in ("launch", "compute"):
                    continue      # charged to the compile ledger above
                h = self.phase.get((family, ph))
                if h is None:
                    h = self.phase[(family, ph)] = \
                        Histogram(LATENCY_BOUNDS)
                h.add(phases.get(ph, 0.0))
            self.busy_seconds += phases.get("compute", 0.0) * d
            if d > self.devices_seen:
                self.devices_seen = d
            if d > 1 and bucket:
                imb = max(0.0, 1.0 - stripes / bucket)
                self.shard_imbalance.add(imb)
                self.last_shard_imbalance = imb
            self.records.append({
                "t": time.time(), "kernel": family,
                "requests": int(requests), "stripes": int(stripes),
                "bucket": int(bucket), "devices": d,
                "compiled": bool(compiled), "e2e_s": float(e2e_s),
                "phases": {ph: float(phases.get(ph, 0.0))
                           for ph in PHASES}})

    def utilization(self) -> float:
        """Device-busy fraction of the window since construction /
        clear: busy-seconds integral over wall × widest fan-out.  An
        always-on approximation (compile time counts as busy), not a
        per-flush exactness claim."""
        with self._lock:
            wall = time.monotonic() - self._anchor
            if wall <= 0.0:
                return 0.0
            return min(1.0, self.busy_seconds
                       / (wall * max(1, self.devices_seen)))

    def dump(self, include_recent: bool = True) -> dict:
        """``include_recent=False`` skips copying the per-batch record
        ring — the prometheus scrape only reads the aggregates, and
        copying 256 dicts under the stats lock per poll is pure
        waste there."""
        util = self.utilization()
        with self._lock:
            fams: dict = {}
            for (family, ph), h in self.phase.items():
                fams.setdefault(family, {})[ph] = h.dump()
            return {
                "phases": fams,
                "compile": {f: {"seconds": self.compile_seconds[f],
                                "events": self.compile_events.get(f, 0)}
                            for f in self.compile_seconds},
                "busy_seconds": self.busy_seconds,
                "utilization": round(util, 4),
                "devices_seen": self.devices_seen,
                "shard_imbalance": self.shard_imbalance.dump(),
                "last_shard_imbalance": self.last_shard_imbalance,
                "window_seconds": round(
                    time.monotonic() - self._anchor, 3),
                "recent": ([dict(r) for r in self.records]
                           if include_recent else []),
            }

    def summary(self) -> dict:
        """Compact digest (MMgrReport carriage / bench JSON): per
        kernel family the phase totals and shares, plus the compile
        ledger and the utilization gauges.  Ring omitted — digests
        travel the wire every tick."""
        util = self.utilization()
        with self._lock:
            fams: dict = {}
            for (family, ph), h in self.phase.items():
                fams.setdefault(family, {})[ph] = h.sum
            out_f: dict = {}
            for family, per in fams.items():
                total = sum(per.values())
                out_f[family] = {
                    "seconds": {ph: round(s, 6)
                                for ph, s in per.items()},
                    "share": {ph: (round(s / total, 4) if total else 0.0)
                              for ph, s in per.items()},
                    "batches": max((self.phase[(family, ph)].count
                                    for ph in PHASES
                                    if (family, ph) in self.phase),
                                   default=0),
                }
            return {
                "kernels": out_f,
                "compile": {f: {"seconds": round(
                                    self.compile_seconds[f], 6),
                                "events": self.compile_events.get(f, 0)}
                            for f in self.compile_seconds},
                "busy_seconds": round(self.busy_seconds, 6),
                "utilization": round(util, 4),
                "devices_seen": self.devices_seen,
                "last_shard_imbalance": round(
                    self.last_shard_imbalance, 4),
            }


#: circuit-breaker states (ceph_kernel_breaker_state gauge values):
#: closed = device path live, open = routing through the host oracle,
#: half-open = a background probe is deciding
BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2


class DispatchStats:
    """Counters for the cross-op coalescing engine (ops.dispatch).

    The engine's efficiency story is four numbers: how many requests
    share each device call (coalesce factor), how long they queue for
    the privilege (queue delay), how deep the backlog runs (queue
    depth), and how many calls are outstanding (in-flight).  Flush
    reasons tell WHY each batch closed — "idle" flushes are the no-wait
    single-op path, "full"/"timeout" flushes are coalescing at work.

    Mesh-sharded engines (ops.dispatch with a device mesh) add the
    fan-out story: how many devices each flush actually landed on
    (devices_used — mass above 1 is the multi-chip path at work), how
    many stripes each device's shard carried (shard_stripes — the
    per-chip occupancy after the batch splits), how many flushes went
    out sharded at all, and the engine's mesh shape gauges.
    """

    __slots__ = ("_lock", "submits", "stripes_in", "batches",
                 "stripes_out", "padded_stripes", "completed",
                 "coalesce", "queue_delay", "queue_depth",
                 "flush_reasons", "in_flight", "max_in_flight_seen",
                 "sharded_flushes", "devices_used", "shard_stripes",
                 "mesh_devices", "mesh_dp", "mesh_ec", "phases",
                 "retries", "retry_successes", "fallback_batches",
                 "fallback_stripes", "breaker_opens", "breaker_closes",
                 "probe_successes", "probe_failures", "thread_deaths",
                 "thread_restarts", "breaker_states")

    def __init__(self):
        self._lock = lockdep.make_lock("DispatchStats::lock")
        #: per-batch pipeline phase attribution (its own lock: the
        #: completion thread records a full profile per flush while
        #: submitters hammer record_submit)
        self.phases = PhaseStats(type(self).__name__)
        self.submits = 0          # requests submitted
        self.stripes_in = 0       # stripes submitted
        self.batches = 0          # device calls dispatched
        self.stripes_out = 0      # stripes dispatched (pre-padding)
        self.padded_stripes = 0   # zero rows added by shape bucketing
        self.completed = 0        # requests delivered
        self.coalesce = Histogram(COALESCE_BOUNDS)   # requests/batch
        self.queue_delay = Histogram(LATENCY_BOUNDS)  # submit->dispatch s
        self.queue_depth = Histogram(COALESCE_BOUNDS)  # pending at flush
        self.flush_reasons = {"idle": 0, "full": 0, "timeout": 0,
                              "stop": 0}
        self.in_flight = 0        # gauge: batches outstanding on device
        self.max_in_flight_seen = 0
        self.sharded_flushes = 0  # flushes placed across > 1 device
        self.devices_used = Histogram(COALESCE_BOUNDS)  # devices/flush
        self.shard_stripes = Histogram(BATCH_BOUNDS)  # stripes/device
        self.mesh_devices = 0     # gauge: devices in the engine's mesh
        self.mesh_dp = 0          # gauge: mesh dp axis
        self.mesh_ec = 0          # gauge: mesh ec axis
        # -- fault-domain counters (ops.dispatch supervised recovery) --
        self.retries = 0          # device re-attempts after a failure
        self.retry_successes = 0  # re-attempts that healed the batch
        self.fallback_batches = 0  # batches served by the host oracle
        self.fallback_stripes = 0  # stripes those batches carried
        self.breaker_opens = 0    # channel breakers opened
        self.breaker_closes = 0   # channel breakers re-closed
        self.probe_successes = 0  # background probes that healed
        self.probe_failures = 0   # background probes that failed
        self.thread_deaths = 0    # engine run-loop deaths observed
        self.thread_restarts = 0  # run-loops revived by supervision
        #: channel -> BREAKER_* (most recent transition per channel
        #: across every engine feeding this sink)
        self.breaker_states: dict[str, int] = {}

    def clear(self) -> None:
        """Reset IN PLACE: live engines hold a reference to this object
        (captured at construction), so reset must not swap it out."""
        with self._lock:
            self.submits = self.stripes_in = 0
            self.batches = self.stripes_out = self.padded_stripes = 0
            self.completed = 0
            self.coalesce = Histogram(COALESCE_BOUNDS)
            self.queue_delay = Histogram(LATENCY_BOUNDS)
            self.queue_depth = Histogram(COALESCE_BOUNDS)
            self.flush_reasons = {"idle": 0, "full": 0, "timeout": 0,
                                  "stop": 0}
            self.in_flight = 0
            self.max_in_flight_seen = 0
            self.sharded_flushes = 0
            self.devices_used = Histogram(COALESCE_BOUNDS)
            self.shard_stripes = Histogram(BATCH_BOUNDS)
            self.mesh_devices = self.mesh_dp = self.mesh_ec = 0
            self.retries = self.retry_successes = 0
            self.fallback_batches = self.fallback_stripes = 0
            self.breaker_opens = self.breaker_closes = 0
            self.probe_successes = self.probe_failures = 0
            self.thread_deaths = self.thread_restarts = 0
            self.breaker_states = {}
        self.phases.clear()

    def record_submit(self, stripes: int) -> None:
        with self._lock:
            self.submits += 1
            self.stripes_in += stripes

    def record_batch(self, *, requests: int, stripes: int, padded: int,
                     reason: str, delays, depth: int,
                     devices: int = 1, shard_stripes: int = 0) -> None:
        with self._lock:
            self.batches += 1
            self.stripes_out += stripes
            self.padded_stripes += padded
            self.coalesce.add(requests)
            self.queue_depth.add(depth)
            for d in delays:
                self.queue_delay.add(d)
            self.flush_reasons[reason] = \
                self.flush_reasons.get(reason, 0) + 1
            self.devices_used.add(devices)
            if devices > 1:
                self.sharded_flushes += 1
                if shard_stripes:
                    self.shard_stripes.add(shard_stripes)

    def set_mesh_shape(self, dp: int, ec: int) -> None:
        """Record the engine's mesh shape (1x1 = single device)."""
        with self._lock:
            self.mesh_dp = int(dp)
            self.mesh_ec = int(ec)
            self.mesh_devices = int(dp) * int(ec)

    def record_retry(self, success: bool) -> None:
        """One device re-attempt of a failed batch finished."""
        with self._lock:
            self.retries += 1
            if success:
                self.retry_successes += 1

    def record_fallback(self, stripes: int) -> None:
        """One batch was served by the bit-exact host oracle."""
        with self._lock:
            self.fallback_batches += 1
            self.fallback_stripes += stripes

    def record_breaker(self, channel: str, state: int) -> None:
        """A channel breaker transitioned (BREAKER_* constants)."""
        with self._lock:
            prev = self.breaker_states.get(channel, BREAKER_CLOSED)
            self.breaker_states[channel] = state
            # opens = CLOSED -> OPEN only (a failed probe's HALF_OPEN
            # -> OPEN is the SAME outage, not a new one); closes =
            # any re-entry into CLOSED
            if state == BREAKER_OPEN and prev == BREAKER_CLOSED:
                self.breaker_opens += 1
            elif state == BREAKER_CLOSED and prev != BREAKER_CLOSED:
                self.breaker_closes += 1

    def record_probe(self, success: bool) -> None:
        with self._lock:
            if success:
                self.probe_successes += 1
            else:
                self.probe_failures += 1

    def record_thread_death(self, restarted: bool) -> None:
        with self._lock:
            self.thread_deaths += 1
            if restarted:
                self.thread_restarts += 1

    def degraded_channels(self) -> list[str]:
        """Channels currently off the device path (breaker not
        closed) — the mgr health feed."""
        with self._lock:
            return sorted(c for c, s in self.breaker_states.items()
                          if s != BREAKER_CLOSED)

    def _fault_dict(self) -> dict:
        """Under self._lock: the ONE fault-counter shape every surface
        (admin dump, MMgrReport digest, prometheus) serializes — a key
        added here reaches them all in lockstep."""
        return {
            "retries": self.retries,
            "retry_successes": self.retry_successes,
            "fallback_batches": self.fallback_batches,
            "fallback_stripes": self.fallback_stripes,
            "breaker_opens": self.breaker_opens,
            "breaker_closes": self.breaker_closes,
            "probe_successes": self.probe_successes,
            "probe_failures": self.probe_failures,
            "thread_deaths": self.thread_deaths,
            "thread_restarts": self.thread_restarts,
            "breaker_states": dict(self.breaker_states),
        }

    def fault_dump(self) -> dict:
        with self._lock:
            return self._fault_dict()

    def record_complete(self, requests: int) -> None:
        with self._lock:
            self.completed += requests

    def set_in_flight(self, n: int) -> None:
        with self._lock:
            self.in_flight = n
            if n > self.max_in_flight_seen:
                self.max_in_flight_seen = n

    def dump(self) -> dict:
        with self._lock:
            return {
                "submits": self.submits,
                "stripes_in": self.stripes_in,
                "batches": self.batches,
                "stripes_out": self.stripes_out,
                "padded_stripes": self.padded_stripes,
                "completed": self.completed,
                "coalesce": self.coalesce.dump(),
                "queue_delay_seconds": self.queue_delay.dump(),
                "queue_depth": self.queue_depth.dump(),
                "flush_reasons": dict(self.flush_reasons),
                "in_flight": self.in_flight,
                "max_in_flight_seen": self.max_in_flight_seen,
                "sharded_flushes": self.sharded_flushes,
                "devices_used": self.devices_used.dump(),
                "shard_stripes": self.shard_stripes.dump(),
                "mesh_devices": self.mesh_devices,
                "mesh_dp": self.mesh_dp,
                "mesh_ec": self.mesh_ec,
            } | {"faults": self._fault_dict()}

    def summary(self) -> dict:
        """bench.py's digest: amortization in three numbers."""
        with self._lock:
            batches = self.batches
            dev_n = self.devices_used.count
            return {
                "submits": self.submits,
                "device_calls": batches,
                "mean_coalesce": (round(self.coalesce.sum / batches, 2)
                                  if batches else 0.0),
                "p99_queue_delay_ms": round(
                    self.queue_delay.quantile(0.99) * 1e3, 3),
                "calls_per_1k_ops": (round(1000.0 * batches
                                           / self.submits, 1)
                                     if self.submits else 0.0),
                "padded_frac": (round(self.padded_stripes
                                      / (self.stripes_out
                                         + self.padded_stripes), 3)
                                if self.stripes_out else 0.0),
                "flush_reasons": dict(self.flush_reasons),
                "mesh_devices": self.mesh_devices,
                "sharded_flushes": self.sharded_flushes,
                "mean_devices": (round(self.devices_used.sum / dev_n, 2)
                                 if dev_n else 0.0),
            }


class DecodeDispatchStats(DispatchStats):
    """Decode-side twin of DispatchStats (the heterogeneous-matrix
    batched GF decode engine).

    Decodes differ from encodes in ONE dimension the base counters
    cannot see: the recovery matrix varies per erasure pattern, and the
    whole point of the heterogeneous kernel is that requests with
    DIFFERENT patterns still share a device call (pattern index carried
    per stripe, matrices gathered from a stacked table on-device).  So
    this adds the heterogeneity story: how many distinct erasure
    patterns each coalesced call carried, and how large the registered
    pattern table has grown (the matrix-table axis of the jit-cache
    bound).
    """

    __slots__ = ("patterns", "pattern_table_size")

    def __init__(self):
        super().__init__()
        self.patterns = Histogram(COALESCE_BOUNDS)  # distinct patterns/call
        self.pattern_table_size = 0   # gauge: registered recovery patterns

    def clear(self) -> None:
        super().clear()
        with self._lock:
            self.patterns = Histogram(COALESCE_BOUNDS)
            self.pattern_table_size = 0

    def record_patterns(self, distinct: int, table_size: int) -> None:
        """One batched decode ran with ``distinct`` erasure patterns
        against a table of ``table_size`` registered patterns."""
        with self._lock:
            self.patterns.add(distinct)
            if table_size > self.pattern_table_size:
                self.pattern_table_size = table_size

    def dump(self) -> dict:
        d = super().dump()
        with self._lock:
            d["patterns"] = self.patterns.dump()
            d["pattern_table_size"] = self.pattern_table_size
        return d

    def summary(self) -> dict:
        s = super().summary()
        with self._lock:
            n = self.patterns.count
            s["mean_patterns"] = (round(self.patterns.sum / n, 2)
                                  if n else 0.0)
            s["pattern_table_size"] = self.pattern_table_size
        return s


class MappingStats:
    """Counters for the shared PG mapping service (osd.mapping).

    The service's efficiency story: how often an epoch actually
    recomputes (vs reusing cached pool tables), how many PGs each
    epoch really changed (the O(changed) scan bound), how many queued
    epochs were skipped outright (burst coalescing), and how often a
    read had to fall back to the scalar oracle (epoch/object mismatch
    — the correctness escape hatch, not an error).

    The PHASE split answers ROADMAP item 2's standing question — is
    the epoch cost device or host: each computed epoch divides into
    ``device`` (pool remaps through the mapper/dispatch engine, pps
    seeding included), ``delta`` (changed-PG candidate extraction: the
    on-device raw-table diff plus state/affinity/override membership),
    and ``host_tail`` (the per-candidate pipeline tail — upmap/
    affinity/temp filtering through ``_finish_from`` — that still
    finishes host-side).

    The FUSED counters track PR 10's device-resident pipeline tail:
    ``fused_epochs``/``unfused_epochs`` count computed epochs that
    published complete packed (up, acting) tables vs those serving the
    host tail, ``fused_lookups`` counts reads answered by a packed-row
    slice (a subset of ``lookups``), and the ``host_tail_share`` gauge
    is the host-tail phase's share of the total epoch cost — the
    number ``profile phases`` watches collapse on a fused cluster.
    """

    __slots__ = ("_lock", "epoch_updates", "epoch_skips",
                 "pools_recomputed", "pools_reused", "full_rescans",
                 "lookups", "lookup_fallbacks", "update_latency",
                 "changed_pgs", "cached_pgs", "cached_pools",
                 "phase_device", "phase_delta", "phase_host_tail",
                 "fused_epochs", "unfused_epochs", "fused_lookups")

    def __init__(self):
        self._lock = lockdep.make_lock("MappingStats::lock")
        self.epoch_updates = 0     # epochs actually computed
        self.epoch_skips = 0       # queued epochs never computed
        self.pools_recomputed = 0  # pool tables rebuilt on device
        self.pools_reused = 0      # pool tables carried over unchanged
        self.full_rescans = 0      # deltas unavailable -> full consumer scan
        self.lookups = 0           # reads served from the cache
        self.lookup_fallbacks = 0  # reads that fell back to the oracle
        self.update_latency = Histogram(LATENCY_BOUNDS)  # per-epoch s
        self.changed_pgs = Histogram(BATCH_BOUNDS)       # delta size/epoch
        self.cached_pgs = 0        # gauge: PGs resident in raw tables
        self.cached_pools = 0      # gauge: pools resident
        # per-epoch phase attribution (see class docstring)
        self.phase_device = Histogram(LATENCY_BOUNDS)
        self.phase_delta = Histogram(LATENCY_BOUNDS)
        self.phase_host_tail = Histogram(LATENCY_BOUNDS)
        # fused-vs-fallback epoch/read accounting (see class docstring)
        self.fused_epochs = 0
        self.unfused_epochs = 0
        self.fused_lookups = 0

    def clear(self) -> None:
        with self._lock:
            self.epoch_updates = self.epoch_skips = 0
            self.pools_recomputed = self.pools_reused = 0
            self.full_rescans = 0
            self.lookups = self.lookup_fallbacks = 0
            self.update_latency = Histogram(LATENCY_BOUNDS)
            self.changed_pgs = Histogram(BATCH_BOUNDS)
            self.cached_pgs = 0
            self.cached_pools = 0
            self.phase_device = Histogram(LATENCY_BOUNDS)
            self.phase_delta = Histogram(LATENCY_BOUNDS)
            self.phase_host_tail = Histogram(LATENCY_BOUNDS)
            self.fused_epochs = self.unfused_epochs = 0
            self.fused_lookups = 0

    def record_phases(self, *, device_s: float, delta_s: float,
                      host_tail_s: float) -> None:
        """One computed epoch's phase split (seconds per phase)."""
        with self._lock:
            self.phase_device.add(device_s)
            self.phase_delta.add(delta_s)
            self.phase_host_tail.add(host_tail_s)

    def record_update(self, *, seconds: float, recomputed: int,
                      reused: int, changed: int, cached_pgs: int,
                      cached_pools: int) -> None:
        with self._lock:
            self.epoch_updates += 1
            self.pools_recomputed += recomputed
            self.pools_reused += reused
            self.update_latency.add(seconds)
            self.changed_pgs.add(changed)
            self.cached_pgs = cached_pgs
            self.cached_pools = cached_pools

    def record_skip(self, n: int = 1) -> None:
        with self._lock:
            self.epoch_skips += n

    def record_full_rescan(self) -> None:
        with self._lock:
            self.full_rescans += 1

    def record_lookup(self, hit: bool, fused: bool = False) -> None:
        with self._lock:
            if hit:
                self.lookups += 1
                if fused:
                    self.fused_lookups += 1
            else:
                self.lookup_fallbacks += 1

    def record_fused_epoch(self, fused: bool) -> None:
        """One computed epoch's tail mode: complete packed fused
        tables vs the host-tail fallback."""
        with self._lock:
            if fused:
                self.fused_epochs += 1
            else:
                self.unfused_epochs += 1

    def _host_tail_share(self) -> float:
        """Called under the lock: host-tail share of the total epoch
        phase cost (the collapse gauge)."""
        total = (self.phase_device.sum + self.phase_delta.sum
                 + self.phase_host_tail.sum)
        return (self.phase_host_tail.sum / total) if total else 0.0

    def dump(self) -> dict:
        with self._lock:
            return {
                "epoch_updates": self.epoch_updates,
                "epoch_skips": self.epoch_skips,
                "pools_recomputed": self.pools_recomputed,
                "pools_reused": self.pools_reused,
                "full_rescans": self.full_rescans,
                "lookups": self.lookups,
                "lookup_fallbacks": self.lookup_fallbacks,
                "update_latency_seconds": self.update_latency.dump(),
                "changed_pgs": self.changed_pgs.dump(),
                "cached_pgs": self.cached_pgs,
                "cached_pools": self.cached_pools,
                "fused_epochs": self.fused_epochs,
                "unfused_epochs": self.unfused_epochs,
                "fused_lookups": self.fused_lookups,
                "host_tail_share": round(self._host_tail_share(), 6),
                "phase_seconds": {
                    "device": self.phase_device.dump(),
                    "delta": self.phase_delta.dump(),
                    "host_tail": self.phase_host_tail.dump(),
                },
            }

    def phase_summary(self) -> dict:
        """Per-phase totals + shares across computed epochs (the
        MMgrReport digest / `profile phases` mapping row)."""
        with self._lock:
            sums = {"device": self.phase_device.sum,
                    "delta": self.phase_delta.sum,
                    "host_tail": self.phase_host_tail.sum}
            epochs = self.phase_device.count
            fused, unfused = self.fused_epochs, self.unfused_epochs
        total = sum(sums.values())
        return {"seconds": {k: round(v, 6) for k, v in sums.items()},
                "share": {k: (round(v / total, 4) if total else 0.0)
                          for k, v in sums.items()},
                "epochs": epochs,
                "fused_epochs": fused,
                "unfused_epochs": unfused}

    def summary(self) -> dict:
        """bench.py's digest: incrementality in a few numbers."""
        with self._lock:
            n = self.update_latency.count
            return {
                "epoch_updates": self.epoch_updates,
                "epoch_skips": self.epoch_skips,
                "pools_recomputed": self.pools_recomputed,
                "pools_reused": self.pools_reused,
                "mean_update_ms": (round(self.update_latency.sum / n
                                         * 1e3, 3) if n else 0.0),
                "mean_changed_pgs": (round(self.changed_pgs.sum
                                           / self.changed_pgs.count, 1)
                                     if self.changed_pgs.count else 0.0),
                "lookups": self.lookups,
                "lookup_fallbacks": self.lookup_fallbacks,
                "fused_epochs": self.fused_epochs,
                "unfused_epochs": self.unfused_epochs,
                "fused_lookups": self.fused_lookups,
                "host_tail_share": round(self._host_tail_share(), 6),
            }


class ScrubStats:
    """Background-integrity counters (deep scrub + verified repair).

    Process-global like the dispatch sinks: every OSD in the process
    folds its scrub accounting in (the per-daemon copies feed
    ``dump_scrub_stats`` and the ``ceph_scrub_*`` prometheus families
    through the MMgrReport tail), so this sink is the cluster-wide
    roll-up the thrasher's scrub-storm gate and bench.py poll —
    "every injected corruption detected and repaired" is a claim
    about the whole MiniCluster, not one daemon."""

    #: the counter vocabulary (unknown keys are still accepted — the
    #: sink must never make a daemon's accounting throw)
    FIELDS = ("sweeps", "pgs_scrubbed", "objects_scrubbed",
              "digest_batches", "digest_objects", "scalar_fallbacks",
              "inconsistent", "repaired", "repair_unverified",
              "missing_peer_scrubs", "missing_peer_retries")

    def __init__(self):
        self._lock = lockdep.make_lock("ScrubStats::lock")
        self._counts: dict[str, int] = {f: 0 for f in self.FIELDS}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def clear(self) -> None:
        with self._lock:
            self._counts = {f: 0 for f in self.FIELDS}

    def dump(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def summary(self) -> dict:
        """bench.py / thrasher digest: the integrity story in a few
        numbers — how much was checked, how it was digested (batched
        vs scalar), and whether every found inconsistency ended in a
        VERIFIED repair."""
        with self._lock:
            c = dict(self._counts)
        batched = c.get("digest_objects", 0)
        scalar_batches = c.get("scalar_fallbacks", 0)
        return {
            "objects_scrubbed": c.get("objects_scrubbed", 0),
            "pgs_scrubbed": c.get("pgs_scrubbed", 0),
            "digest_batches": c.get("digest_batches", 0),
            "batched_digest_objects": batched,
            "scalar_fallback_batches": scalar_batches,
            "inconsistent": c.get("inconsistent", 0),
            "repaired": c.get("repaired", 0),
            "repair_unverified": c.get("repair_unverified", 0),
            "missing_peer_scrubs": c.get("missing_peer_scrubs", 0),
        }


class BlueStoreStats:
    """Device-resident objectstore counters (the ``bluestore_data``
    channel's write/read offload plus block compression and the KV
    journal's truncation ledger).

    Process-global like the other sinks: every BlueStoreLite in the
    process folds its accounting in; ``bluestore_dump`` and the
    ``ceph_bluestore_*`` prometheus families read it, and bench.py's
    objectstore section polls ``summary()``."""

    FIELDS = ("csum_batches", "csum_blocks", "csum_scalar_blocks",
              "csum_fallbacks", "read_verify_batches",
              "read_verify_blocks", "compress_blocks",
              "compress_rejected", "compress_roundtrip_failures",
              "decompress_errors", "csum_errors",
              "kv_journal_truncated", "kv_journal_lost_bytes")

    def __init__(self):
        self._lock = lockdep.make_lock("BlueStoreStats::lock")
        self._counts: dict[str, int] = {f: 0 for f in self.FIELDS}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def clear(self) -> None:
        with self._lock:
            self._counts = {f: 0 for f in self.FIELDS}

    def dump(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def summary(self) -> dict:
        """bench/test digest: how the store's checksum work was
        computed (batched device calls vs scalar), what compression
        did, and whether anything went wrong."""
        with self._lock:
            c = dict(self._counts)
        return {
            "csum_batches": c.get("csum_batches", 0),
            "batched_csum_blocks": c.get("csum_blocks", 0),
            "scalar_csum_blocks": c.get("csum_scalar_blocks", 0),
            "csum_fallbacks": c.get("csum_fallbacks", 0),
            "read_verify_batches": c.get("read_verify_batches", 0),
            "read_verify_blocks": c.get("read_verify_blocks", 0),
            "compress_blocks": c.get("compress_blocks", 0),
            "compress_rejected": c.get("compress_rejected", 0),
            "csum_errors": c.get("csum_errors", 0),
            "kv_journal_truncated": c.get("kv_journal_truncated", 0),
        }


#: ledger bucket for work submitted WITHOUT a cost tag.  Untagged
#: device time is attributed here — visibly — never dropped: the
#: conservation property (sum over tenants == engine busy-seconds)
#: holds only because every batch lands somewhere.
UNTAGGED_TENANT = "_untagged"

#: ledger bucket absorbing tenants beyond the table bound
#: (kernel_tenant_ledger_max_tenants): overflow stays counted, so
#: conservation survives a tenant-name flood; only per-name
#: attribution degrades.
OVERFLOW_TENANT = "_overflow"

#: default bound on distinct tenants the ledger tracks
TENANT_LEDGER_MAX_DEFAULT = 1024


class TenantDeviceStats:
    """Tenant-attributed device-time ledger (per-tenant × engine ×
    channel).

    The dispatch engines apportion each completed batch's busy
    integral (``compute_s × devices``, the same product PhaseStats
    accumulates into ``busy_seconds``) to the batch's requests by
    stripe share and record it here under the request's ``cost_tag``
    (tenant + dmClock class).  Rows carry device-seconds, batch/request
    /stripe counts and a queue-wait histogram (submit → dispatch, the
    same window PhaseStats calls queue_wait); ``dump`` adds
    share-of-device gauges.

    Feeds ``dump_tenant_usage`` (admin socket), the MMgrReport
    ``tenant_usage`` tail (→ mgr tenant_feed → the slo module and the
    ``ceph_tenant_device_seconds_total`` prometheus family), and
    ``tools/profile_report.py``'s per-tenant table.

    Attribution is measurement-only: nothing here feeds back into
    batch admission (that is ROADMAP item 1's unified runtime).
    """

    def __init__(self):
        self._lock = lockdep.make_lock("TenantDeviceStats::lock")
        #: (tenant, engine, channel) -> row dict
        self._rows: dict[tuple, dict] = {}
        self._tenants: set = set()
        self.enabled = True
        self.max_tenants = TENANT_LEDGER_MAX_DEFAULT

    def _key_tenant(self, tenant) -> str:
        t = str(tenant) if tenant else UNTAGGED_TENANT
        if t in self._tenants:
            return t
        if len(self._tenants) >= self.max_tenants and t not in (
                UNTAGGED_TENANT, OVERFLOW_TENANT):
            return OVERFLOW_TENANT
        self._tenants.add(t)
        return t

    def record_batch(self, tenant, qos_class, *, engine: str,
                     channel: str, device_seconds: float,
                     requests: int, stripes: int,
                     queue_waits=()) -> None:
        """Account one tenant's share of one completed device batch."""
        if not self.enabled:
            return
        with self._lock:
            t = self._key_tenant(tenant)
            row = self._rows.get((t, engine, channel))
            if row is None:
                row = self._rows[(t, engine, channel)] = {
                    "qos_class": str(qos_class or ""),
                    "device_seconds": 0.0, "batches": 0,
                    "requests": 0, "stripes": 0,
                    "queue_wait": Histogram(LATENCY_BOUNDS)}
            row["device_seconds"] += float(device_seconds)
            row["batches"] += 1
            row["requests"] += int(requests)
            row["stripes"] += int(stripes)
            for w in queue_waits:
                row["queue_wait"].add(max(0.0, float(w)))

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._tenants.clear()

    def total_device_seconds(self) -> float:
        with self._lock:
            return sum(r["device_seconds"] for r in self._rows.values())

    def dump(self) -> dict:
        """Full ledger (the ``dump_tenant_usage`` admin payload):
        tenant -> engine -> channel rows with queue-wait histograms,
        plus per-tenant share-of-device gauges."""
        with self._lock:
            rows = {k: dict(r) for k, r in self._rows.items()}
        total = sum(r["device_seconds"] for r in rows.values())
        tenants: dict = {}
        for (t, eng, ch), r in sorted(rows.items()):
            trec = tenants.setdefault(
                t, {"device_seconds": 0.0, "share": 0.0, "engines": {}})
            trec["device_seconds"] += r["device_seconds"]
            trec["engines"].setdefault(eng, {})[ch] = {
                "qos_class": r["qos_class"],
                "device_seconds": r["device_seconds"],
                "batches": r["batches"], "requests": r["requests"],
                "stripes": r["stripes"],
                "queue_wait": r["queue_wait"].dump()}
        for trec in tenants.values():
            trec["share"] = (trec["device_seconds"] / total
                             if total else 0.0)
        return {"tenants": tenants, "total_device_seconds": total}

    def digest(self) -> dict:
        """Compact ledger (no histogram buckets) — the MMgrReport
        ``tenant_usage`` tail and bench.py's qos-section carriage."""
        with self._lock:
            rows = {k: dict(r) for k, r in self._rows.items()}
        total = sum(r["device_seconds"] for r in rows.values())
        tenants: dict = {}
        for (t, eng, ch), r in sorted(rows.items()):
            trec = tenants.setdefault(
                t, {"device_seconds": 0.0, "share": 0.0, "engines": {}})
            trec["device_seconds"] += r["device_seconds"]
            trec["engines"].setdefault(eng, {})[ch] = {
                "qos_class": r["qos_class"],
                "device_seconds": round(r["device_seconds"], 9),
                "batches": r["batches"], "requests": r["requests"],
                "stripes": r["stripes"],
                "wait_p99_s": round(r["queue_wait"].quantile(0.99), 6),
                "wait_sum_s": round(r["queue_wait"].sum, 9),
                "wait_count": r["queue_wait"].count}
        for trec in tenants.values():
            trec["share"] = round(
                trec["device_seconds"] / total if total else 0.0, 6)
            trec["device_seconds"] = round(trec["device_seconds"], 9)
        return {"tenants": tenants,
                "total_device_seconds": round(total, 9)}


class KernelTelemetry:
    """The registry: one KernelStats per kernel name."""

    def __init__(self):
        self._lock = lockdep.make_lock("KernelTelemetry::lock")
        self._kernels: dict[str, KernelStats] = {}
        self.dispatch = DispatchStats()
        self.decode_dispatch = DecodeDispatchStats()
        self.mapping = MappingStats()
        self.scrub = ScrubStats()
        self.bluestore = BlueStoreStats()
        self.tenant = TenantDeviceStats()
        #: block_until_ready before closing each latency sample
        self.fence_for_timing = False
        #: master switch; off-path cost when False is one attribute read
        self.enabled = True

    def kernel(self, name: str) -> KernelStats:
        ks = self._kernels.get(name)
        if ks is None:
            with self._lock:
                ks = self._kernels.setdefault(name, KernelStats(name))
        return ks

    def dump(self) -> dict:
        with self._lock:
            kernels = list(self._kernels.values())
        return {ks.name: ks.dump() for ks in kernels}

    def reset(self) -> None:
        """Drop all samples (tests/bench isolation).  Signature sets go
        too, but jit caches live in jax — miss counting stays a delta
        against the real cache, so reset never fabricates misses."""
        with self._lock:
            self._kernels.clear()
        self.dispatch.clear()
        self.decode_dispatch.clear()
        self.mapping.clear()
        self.scrub.clear()
        self.bluestore.clear()
        self.tenant.clear()

    def summary(self) -> dict:
        """Compact digest (bench.py prints this next to its JSON)."""
        out = {}
        for name, d in self.dump().items():
            lat = d["latency_seconds"]
            bat = d["batch_size"]
            ks = self.kernel(name)
            out[name] = {
                "calls": d["calls"],
                "retraces": d["jit_misses"],
                "p50_ms": round(ks.latency.quantile(0.5) * 1e3, 3),
                "p99_ms": round(ks.latency.quantile(0.99) * 1e3, 3),
                "mean_batch": (round(bat["sum"] / bat["count"], 1)
                               if bat["count"] else 0),
                "gb_in": round(d["bytes_in"] / 1e9, 3),
                "mean_ms": (round(lat["sum"] / lat["count"] * 1e3, 3)
                            if lat["count"] else 0.0),
            }
        return out


_REG = KernelTelemetry()


def registry() -> KernelTelemetry:
    return _REG


def dump() -> dict:
    return _REG.dump()


def reset() -> None:
    _REG.reset()


def dispatch_stats() -> DispatchStats:
    """The process-global coalescing-engine counters.  Engines created
    without an explicit stats sink feed this (the MiniCluster's
    daemons share it exactly like the kernel registry); dump_dispatch
    and the mgr's ceph_kernel_coalesce_* families read it."""
    return _REG.dispatch


def dispatch_dump() -> dict:
    return _REG.dispatch.dump()


def dispatch_summary() -> dict:
    return _REG.dispatch.summary()


def decode_dispatch_stats() -> DecodeDispatchStats:
    """The decode-side coalescing counters (heterogeneous-matrix
    batched GF decode): engines built by ``ctx.decode_dispatch_engine``
    feed this, the codec's batched decode fn records the per-call
    pattern heterogeneity into it, and the mgr's
    ``ceph_kernel_decode_coalesce_*`` families read it."""
    return _REG.decode_dispatch


def decode_dispatch_dump() -> dict:
    return _REG.decode_dispatch.dump()


def decode_dispatch_summary() -> dict:
    return _REG.decode_dispatch.summary()


def scrub_stats() -> ScrubStats:
    """The process-global background-integrity counters: every OSD's
    scrub path feeds this alongside its own per-daemon accounting;
    the thrasher's scrub-storm gate and bench.py's scrub section read
    the cluster-wide roll-up here."""
    return _REG.scrub


def scrub_dump() -> dict:
    return _REG.scrub.dump()


def scrub_summary() -> dict:
    return _REG.scrub.summary()


def bluestore_stats() -> BlueStoreStats:
    """The process-global device-resident-objectstore counters: every
    BlueStoreLite's write/read/compression paths feed this;
    ``dump_bluestore_stats``, the ``ceph_bluestore_*`` prometheus
    families and bench.py's objectstore section read it."""
    return _REG.bluestore


def bluestore_dump() -> dict:
    return _REG.bluestore.dump()


def bluestore_summary() -> dict:
    return _REG.bluestore.summary()


def tenant_stats() -> TenantDeviceStats:
    """The process-global tenant-attributed device-time ledger: both
    dispatch engines apportion completed batches here by cost tag;
    ``dump_tenant_usage``, the MMgrReport ``tenant_usage`` tail and
    the ``ceph_tenant_device_seconds_total`` families read it."""
    return _REG.tenant


def tenant_dump() -> dict:
    return _REG.tenant.dump()


def tenant_usage_digest() -> dict:
    """Compact per-tenant ledger digest — the MMgrReport carriage and
    bench.py's qos-section ``tenant_usage`` key."""
    return _REG.tenant.digest()


def mapping_stats() -> MappingStats:
    """The process-global shared-mapping-service counters: every
    SharedPGMappingService (one per context) feeds this, the
    ``dump_mapping_stats`` admin command and the mgr's
    ``ceph_kernel_mapping_*`` families read it."""
    return _REG.mapping


def mapping_dump() -> dict:
    return _REG.mapping.dump()


def mapping_summary() -> dict:
    return _REG.mapping.summary()


def pipeline_profile_dump(include_recent: bool = True) -> dict:
    """The full per-engine pipeline phase profile — the
    ``dump_pipeline_profile`` admin-socket payload: phase histograms
    per kernel family, the compile ledger, utilization gauges, and the
    bounded ring of recent per-batch records, for both dispatch
    engines, plus the mapping service's epoch phase split.
    ``include_recent=False`` drops the ring (aggregate-only readers:
    the prometheus scrape)."""
    return {"encode": _REG.dispatch.phases.dump(include_recent),
            "decode": _REG.decode_dispatch.phases.dump(include_recent),
            "mapping": _REG.mapping.phase_summary()}


def fault_digest() -> dict:
    """Per-engine fault/degradation digest — the MMgrReport v4
    ``faults`` tail (mgr health raises KERNEL_DEGRADED while any
    reported channel breaker is not closed), the ``dump_fault_stats``
    admin payload, and the thrasher chaos gate's reconvergence probe."""
    return {"encode": _REG.dispatch.fault_dump(),
            "decode": _REG.decode_dispatch.fault_dump()}


def pipeline_profile_digest() -> dict:
    """Compact phase-share digest (no histograms, no ring) — the
    MMgrReport v4 carriage and bench.py's ``profile`` section."""
    return {"encode": _REG.dispatch.phases.summary(),
            "decode": _REG.decode_dispatch.phases.summary(),
            "mapping": _REG.mapping.phase_summary()}


def set_profile_ring(n) -> None:
    """Rebind the per-engine recent-batch profile ring bound (the
    ``kernel_profile_ring`` option); existing records are kept up to
    the new bound, newest first."""
    global _profile_ring
    _profile_ring = max(1, int(n))
    _REG.dispatch.phases._resize_ring(_profile_ring)
    _REG.decode_dispatch.phases._resize_ring(_profile_ring)


def set_fence_for_timing(on: bool) -> None:
    _REG.fence_for_timing = bool(on)


def set_enabled(on: bool) -> None:
    _REG.enabled = bool(on)


def configure_from_conf(conf) -> None:
    """Bind the fence knob to a context's config (option
    ``kernel_fence_for_timing``), with hot reload via observer.

    The registry is process-global while configs are per-context
    (multi-daemon processes construct many): construction only turns
    fencing ON when this conf explicitly enables it — it never resets
    the global back to the default, or every later daemon/client
    construction would silently undo an operator's `config set` on
    another daemon.  Runtime changes propagate through the observer.
    """
    try:
        if conf.get("kernel_fence_for_timing"):
            set_fence_for_timing(True)
        conf.add_observer("kernel_fence_for_timing",
                          lambda _n, v: set_fence_for_timing(v))
    except KeyError:   # option table without the knob (stripped config)
        pass
    try:
        ring = int(conf.get("kernel_profile_ring"))
        if ring != PROFILE_RING_DEFAULT:
            set_profile_ring(ring)
        conf.add_observer("kernel_profile_ring",
                          lambda _n, v: set_profile_ring(v))
    except KeyError:
        pass
    # tenant-ledger knobs: same only-turn-away-from-default rule as the
    # fence — a later context's default construction must not undo an
    # operator's `config set` on another daemon in the same process
    try:
        if not bool(conf.get("kernel_tenant_ledger_enabled")):
            _REG.tenant.enabled = False
        conf.add_observer(
            "kernel_tenant_ledger_enabled",
            lambda _n, v: setattr(_REG.tenant, "enabled", bool(v)))
    except KeyError:
        pass
    try:
        cap = int(conf.get("kernel_tenant_ledger_max_tenants"))
        if cap != TENANT_LEDGER_MAX_DEFAULT:
            _REG.tenant.max_tenants = max(1, cap)
        conf.add_observer(
            "kernel_tenant_ledger_max_tenants",
            lambda _n, v: setattr(_REG.tenant, "max_tenants",
                                  max(1, int(v))))
    except KeyError:
        pass


def timed_kernel(name: str, fn, *, batch: int = 0, bytes_in: int = 0,
                 bytes_out: int = 0, cache_entries=None, signature=None):
    """Run ``fn()`` (one device call) under telemetry.

    cache_entries: zero-arg callable returning the current jit
    compile-cache entry count for the kernel's entry points; the delta
    across the call is the miss count.  signature: hashable shape key
    used as the fallback miss detector when cache_entries is None or
    fails.  Tracer results (outer jit trace in progress) are counted
    but not timed.
    """
    if not _REG.enabled:
        return fn()
    ks = _REG.kernel(name)
    # device span on the calling op's trace (common/tracing): a traced
    # slow write shows WHERE its device time went — h2d operand bytes,
    # compute wall time, d2h result bytes, and whether the call
    # retraced.  Free when the thread is untraced (begin_span returns
    # None on trace_id 0 without taking the table lock).
    from ceph_tpu.common import tracing
    dev_span = tracing.begin_span(f"device {name}", "device") \
        if tracing.current() else None
    if dev_span is not None and bytes_in:
        tracing.span_event(dev_span, f"h2d {bytes_in}B")
    before = None
    if cache_entries is not None:
        try:
            before = cache_entries()
        except Exception:
            before = None
    t0 = time.perf_counter()
    try:
        out = fn()
    except BaseException:
        # the failing call is the one most worth seeing in the trace:
        # close the span instead of leaking it open (end=None)
        if dev_span is not None:
            tracing.set_attrs(dev_span, kernel=name, error=True)
            tracing.finish_span(dev_span)
        raise
    if _is_tracer(out):
        with ks._lock:
            ks.traced += 1
        if dev_span is not None:
            tracing.set_attrs(dev_span, kernel=name, traced=True)
            tracing.finish_span(dev_span)
        return out
    if _REG.fence_for_timing:
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:
            pass
    dt = time.perf_counter() - t0
    misses = 0
    if before is not None:
        try:
            misses = max(0, cache_entries() - before)
        except Exception:
            before = None
    if before is None and signature is not None:
        misses = 1 if ks.note_signature(signature) else 0
    ks.record(dt, batch=batch, bytes_in=bytes_in, bytes_out=bytes_out,
              misses=misses)
    if dev_span is not None:
        tracing.span_event(dev_span, f"compute {dt * 1e3:.3f}ms")
        if bytes_out:
            tracing.span_event(dev_span, f"d2h {bytes_out}B")
        tracing.set_attrs(dev_span, kernel=name, batch=batch,
                          bytes_in=bytes_in, bytes_out=bytes_out,
                          retrace=misses > 0,
                          fenced=_REG.fence_for_timing)
        tracing.finish_span(dev_span)
    return out


def _is_tracer(x) -> bool:
    # jax is only imported if the call site already produced a jax
    # value; a numpy/no-jax result short-circuits on the module check
    if type(x).__module__.split(".")[0] != "jax":
        return False
    import jax
    return isinstance(x, jax.core.Tracer)
