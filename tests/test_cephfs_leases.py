"""CephFS dentry leases (MClientLease.h + Client.cc dcache, reduced to
the coherent directory subset): a leased dir stat serves from the
client cache without an MDS round-trip; rename/rmdir/setattr revoke
across clients; TTL is the backstop."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f.mount()
    yield f
    f.unmount()


def _count_requests(fs):
    """Wrap fs._request with a counter (restores on the returned
    callable)."""
    counter = {"n": 0}
    real = fs._request

    def counted(op, args, **kw):
        counter["n"] += 1
        return real(op, args, **kw)

    fs._request = counted
    return counter, lambda: setattr(fs, "_request", real)


def test_dir_stat_served_from_lease_cache(fs):
    fs.mkdir("/cachetop")
    fs.mkdir("/cachetop/proj")
    st1 = fs.stat("/cachetop/proj")      # populates the lease
    counter, restore = _count_requests(fs)
    try:
        for _ in range(5):
            st = fs.stat("/cachetop/proj")
            assert st["ino"] == st1["ino"]
        assert counter["n"] == 0, "leased dir stat hit the MDS"
    finally:
        restore()
    # files are NOT leased (size/mtime are cap territory): every file
    # stat round-trips
    with fs.open("/cachetop/f", "w") as f:
        f.write(b"x")
    fs.stat("/cachetop/f")
    counter, restore = _count_requests(fs)
    try:
        fs.stat("/cachetop/f")
        assert counter["n"] == 1
    finally:
        restore()


def test_lease_revoked_across_clients_on_mutation(cluster, fs):
    fs2 = CephFS(cluster.mon_host, cluster.mds.addr,
                 ms_type="loopback", client_id=777)
    fs2.mount()
    try:
        fs.mkdir("/shared-d")
        assert fs2.stat("/shared-d")["ino"] > 0   # fs2 caches it
        assert "/shared-d" in fs2._lease_cache
        # fs renames the dir: fs2's lease must be revoked — its next
        # stat sees the new world (bounded by revoke delivery; poll
        # within a fraction of the 10s TTL to prove it was the revoke)
        fs.rename("/shared-d", "/shared-e")
        deadline = time.time() + 3.0
        gone = False
        while time.time() < deadline:
            try:
                fs2.stat("/shared-d")
            except OSError:
                gone = True
                break
            time.sleep(0.05)
        assert gone, "stale dir lease survived a rename"
        assert fs2.stat("/shared-e")["ino"] > 0
        # rmdir revokes too
        assert fs2.stat("/shared-e")    # re-cache
        fs.rmdir("/shared-e")
        deadline = time.time() + 3.0
        gone = False
        while time.time() < deadline:
            try:
                fs2.stat("/shared-e")
            except OSError:
                gone = True
                break
            time.sleep(0.05)
        assert gone, "stale dir lease survived rmdir"
    finally:
        fs2.unmount()


def test_dir_rename_revokes_descendant_leases(cluster, fs):
    """Renaming a directory moves every descendant PATH: leases cached
    under the old prefix (on OTHER dentries inside the subtree) must
    revoke, not just the renamed dentry's own."""
    fs2 = CephFS(cluster.mon_host, cluster.mds.addr,
                 ms_type="loopback", client_id=779)
    fs2.mount()
    try:
        fs.mkdir("/tree")
        fs.mkdir("/tree/sub")
        fs.mkdir("/tree/sub/leaf")
        # fs2 leases the DESCENDANT, not /tree itself
        assert fs2.stat("/tree/sub/leaf")["ino"] > 0
        assert "/tree/sub/leaf" in fs2._lease_cache
        fs.rename("/tree", "/forest")
        deadline = time.time() + 3.0
        gone = False
        while time.time() < deadline:
            try:
                fs2.stat("/tree/sub/leaf")
            except OSError:
                gone = True
                break
            time.sleep(0.05)
        assert gone, "descendant lease survived the dir rename"
        assert fs2.stat("/forest/sub/leaf")["ino"] > 0
    finally:
        fs2.unmount()


def test_quota_setattr_revokes_dir_lease(cluster, fs):
    fs2 = CephFS(cluster.mon_host, cluster.mds.addr,
                 ms_type="loopback", client_id=778)
    fs2.mount()
    try:
        fs.mkdir("/qd")
        st = fs2.stat("/qd")
        assert not st.get("quota_bytes")
        fs.set_quota("/qd", max_bytes=1 << 20)
        deadline = time.time() + 3.0
        ok = False
        while time.time() < deadline:
            if fs2.stat("/qd").get("quota_bytes") == 1 << 20:
                ok = True
                break
            time.sleep(0.05)
        assert ok, "stale dir lease survived a quota setattr"
    finally:
        fs2.unmount()


def test_lease_ttl_expiry(cluster, fs):
    cluster.mds.ctx.conf.set("mds_dentry_lease_ttl", "0.3")
    try:
        fs.mkdir("/ttl-d")
        fs.stat("/ttl-d")
        assert "/ttl-d" in fs._lease_cache
        time.sleep(0.4)
        counter, restore = _count_requests(fs)
        try:
            fs.stat("/ttl-d")
            assert counter["n"] == 1, "expired lease served"
        finally:
            restore()
    finally:
        cluster.mds.ctx.conf.set("mds_dentry_lease_ttl", "10.0")
