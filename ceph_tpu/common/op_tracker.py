"""OpTracker / TrackedOp — per-op event timelines with slow-op
detection (src/common/TrackedOp.{h,cc}, osd/OpRequest.{h,cc}).

Every client op entering a daemon gets a TrackedOp; stages of its life
(`queued`, `reached_pg`, `waiting for missing object`, `sub_op_commit`,
`done`) are stamped with mark_event.  The tracker serves the admin
commands the reference exposes: `dump_ops_in_flight` (live ops with
age + their timeline), `dump_historic_ops` (a ring of recently
completed ops, keeping the slowest), and flags ops older than the
complaint threshold the way OSD::check_ops_in_flight feeds
"N slow requests" into the cluster log.  `slow_digests` is the compact
newest-slowest view daemons ship to the mgr in MMgrReport v4 (the
insights module's cluster-wide `slow_ops` feed).

Thread safety: events are appended by dispatch/worker threads and read
by admin/tick threads, so every events-list mutation and read snapshot
goes through the tracker lock (the reference guards TrackedOp state
with OpTracker's sharded lock the same way).
"""

from __future__ import annotations

import time

from ceph_tpu.common import lockdep


class TrackedOp:
    __slots__ = ("tracker", "description", "initiated_at", "events",
                 "_done", "trace_id")

    def __init__(self, tracker: "OpTracker", description: str):
        self.tracker = tracker
        self.description = description
        self.initiated_at = time.time()
        self.events: list[tuple[float, str]] = [(self.initiated_at,
                                                 "initiated")]
        self._done = False
        # ops created while handling a traced message JOIN the trace:
        # their per-op events become span events attached to the
        # handling thread's current span
        from ceph_tpu.common import tracing
        self.trace_id = tracing.current()
        if self.trace_id:
            tracing.record(tracker.daemon, f"op {description}",
                           self.trace_id)

    def mark_event(self, event: str) -> None:
        # appended here, read by dump()/check_ops_in_flight() on other
        # threads: the tracker lock guards both sides
        with self.tracker._lock:
            self.events.append((time.time(), event))
        if self.trace_id:
            from ceph_tpu.common import tracing
            tracing.record(self.tracker.daemon,
                           f"{self.description}: {event}", self.trace_id)

    def finish(self) -> None:
        if not self._done:
            self._done = True
            self.mark_event("done")
            self.tracker._unregister(self)

    @property
    def age(self) -> float:
        return time.time() - self.initiated_at

    @property
    def duration(self) -> float:
        with self.tracker._lock:
            return self.events[-1][0] - self.initiated_at

    def _events_snapshot(self) -> list[tuple[float, str]]:
        with self.tracker._lock:
            return list(self.events)

    def dump(self) -> dict:
        t0 = self.initiated_at
        events = self._events_snapshot()
        d = {"description": self.description,
             "initiated_at": t0,
             "age": round(self.age, 6),
             "duration": round(events[-1][0] - t0, 6),
             "type_data": {"events": [
                 {"time": round(t - t0, 6), "event": e}
                 for t, e in events]}}
        if self.trace_id:
            d["trace_id"] = self.trace_id
        return d


class OpTracker:
    """One per daemon (OSD holds op_tracker; mon/mgr could too)."""

    def __init__(self, complaint_time: float = 30.0,
                 history_size: int = 20,
                 history_slow_size: int = 20,
                 history_slow_threshold: float = 1.0,
                 daemon: str = "?"):
        #: span-event attribution for traced ops (common/tracing)
        self.daemon = daemon
        self.complaint_time = complaint_time
        self.history_size = history_size
        self.history_slow_size = history_slow_size
        self.history_slow_threshold = history_slow_threshold
        # RLock semantics required: mark_event fires under the lock
        # from _unregister-free paths, and duration (which takes the
        # lock) is read inside _unregister's critical section
        self._lock = lockdep.make_lock(f"OpTracker::lock({daemon})")
        self._inflight: dict[int, TrackedOp] = {}
        self._history: list[TrackedOp] = []       # recent completions
        self._slow_history: list[TrackedOp] = []  # slowest completions

    def create_request(self, description: str) -> TrackedOp:
        op = TrackedOp(self, description)
        with self._lock:
            self._inflight[id(op)] = op
        return op

    def _unregister(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(id(op), None)
            self._history.append(op)
            if len(self._history) > self.history_size:
                self._history.pop(0)
            if op.duration >= self.history_slow_threshold:
                self._slow_history.append(op)
                self._slow_history.sort(key=lambda o: -o.duration)
                del self._slow_history[self.history_slow_size:]

    # -- admin-socket surface -------------------------------------------------

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = sorted(self._inflight.values(),
                         key=lambda o: o.initiated_at)
        return {"num_ops": len(ops), "ops": [o.dump() for o in ops]}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            hist = list(self._history)
            slow = list(self._slow_history)
        return {"num_ops": len(hist),
                "ops": [o.dump() for o in hist],
                "slowest": [o.dump() for o in slow]}

    def slow_digests(self, limit: int = 10) -> list[dict]:
        """Compact slowest-completions view for MMgrReport v4: the
        mgr insights module ranks these across every daemon."""
        with self._lock:
            slow = list(self._slow_history)[:limit]
        out = []
        for o in slow:
            events = o._events_snapshot()
            d = {"daemon": self.daemon,
                 "description": o.description,
                 "initiated_at": o.initiated_at,
                 "duration": round(events[-1][0] - o.initiated_at, 6),
                 "last_event": events[-1][1]}
            if o.trace_id:
                d["trace_id"] = o.trace_id
            out.append(d)
        return out

    def check_ops_in_flight(self) -> list[str]:
        """Ops past the complaint threshold ("slow request" warnings,
        OSD::check_ops_in_flight)."""
        now = time.time()
        with self._lock:
            slow = [(o, o.events[-1][1])
                    for o in self._inflight.values()
                    if now - o.initiated_at > self.complaint_time]
        return [f"slow request {o.age:.3f}s: {o.description} "
                f"(last event: {last})" for o, last in slow]
