"""BlueStore-lite — a disk-backed object store in the BlueStore shape
(src/os/bluestore/: raw block device + RocksDB metadata).

Architecture mirrors the reference's split:

  block file       object DATA lives in fixed-size extents of one flat
                   file ("the raw device"), handed out by a bitmap
                   allocator (BitmapAllocator analog) and returned on
                   delete/overwrite — data is NOT resident in RAM,
                   every read hits the block file.
  KV (LogDB)       all METADATA — per-object extent maps, sizes, attrs,
                   omap, collection membership — in the append-only KV
                   store standing in for RocksDB, giving atomic
                   transaction commits and replay-on-mount for free.

Crash consistency is BlueStore's: block-content updates are
COPY-ON-WRITE (a patched block lands in a freshly allocated extent;
the object's extent map flips to it only inside the KV commit), data
is fsync'd before the ONE KV transaction that references it, and the
displaced blocks return to the allocator only after that commit
succeeds.  A crash anywhere leaves the old metadata pointing at
untouched old blocks.  The allocator itself is never trusted from a
snapshot: mount rebuilds the free list from the committed extent maps
(BlueStore fsck/allocation-recovery analog), so a hard kill can never
resurrect in-use blocks as free.
"""

from __future__ import annotations

import json
import os
import shutil
import threading

from .kv import LogDB
from .objectstore import ObjectStore
from .transaction import (
    OP_CLONE, OP_COLL_MOVE, OP_MKCOLL, OP_OMAP_RMKEYS, OP_OMAP_SETKEYS,
    OP_REMOVE, OP_RMCOLL, OP_SETATTR, OP_TOUCH, OP_TRUNCATE, OP_WRITE,
    OP_ZERO,
    Transaction)

BLOCK = 4096          # allocation unit ("min_alloc_size")


class BitmapAllocator:
    """Free-extent tracking over the block file
    (os/bluestore/BitmapAllocator analog, block granularity)."""

    def __init__(self):
        self._free: set[int] = set()
        self._next = 0
        self._lock = threading.Lock()

    def allocate(self, n_blocks: int) -> list[int]:
        with self._lock:
            out = []
            while self._free and len(out) < n_blocks:
                out.append(self._free.pop())
            while len(out) < n_blocks:
                out.append(self._next)
                self._next += 1
            return sorted(out)

    def release(self, blocks: list[int]) -> None:
        with self._lock:
            self._free.update(blocks)

    def restore(self, next_block: int, free: list[int]) -> None:
        with self._lock:
            self._next = next_block
            self._free = set(free)


def _okey(cid: str, oid: str) -> str:
    return f"{cid}\x00{oid}"


class BlueStoreLite(ObjectStore):
    """ObjectStore on a block file + KV metadata."""

    def __init__(self, path: str):
        if not path:
            raise ValueError("bluestore needs a directory path")
        self.path = path
        self._block_path = os.path.join(path, "block")
        self._db = LogDB(os.path.join(path, "kv"))
        self._alloc = BitmapAllocator()
        self._f = None
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"BlueStore::lock({path})")
        #: blocks displaced by the in-flight transaction batch; returned
        #: to the allocator only after its KV commit lands
        self._freed: list[int] = []

    # -- lifecycle ------------------------------------------------------------

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        open(self._block_path, "wb").close()
        kv = os.path.join(self.path, "kv")
        if os.path.isdir(kv):
            shutil.rmtree(kv)
        elif os.path.exists(kv):
            os.unlink(kv)

    def mkfs_if_needed(self) -> None:
        if not os.path.exists(self._block_path):
            self.mkfs()

    def mount(self) -> None:
        self._db.open()
        self._f = open(self._block_path, "r+b")
        # rebuild the allocator from the committed extent maps — the
        # only crash-safe source of truth (fsck-style recovery; a
        # snapshot written at umount would be stale after a hard kill
        # and hand out live blocks)
        used: set[int] = set()
        for blob in self._db.get_range("obj").values():
            meta = json.loads(blob.decode())
            used.update(b for b in meta["extents"] if b >= 0)
        nxt = max(used) + 1 if used else 0
        self._alloc.restore(nxt, sorted(set(range(nxt)) - used))

    def umount(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        self._db.close()

    # -- metadata helpers -----------------------------------------------------

    def _meta(self, cid: str, oid: str) -> dict | None:
        blob = self._db.get("obj", _okey(cid, oid))
        if blob is None:
            return None
        return json.loads(blob.decode())

    def _put_meta(self, kvt, cid: str, oid: str, meta: dict) -> None:
        kvt.set("obj", _okey(cid, oid), json.dumps(meta).encode())

    @staticmethod
    def _new_meta() -> dict:
        return {"size": 0, "extents": [], "attrs": {}, "omap": {}}

    # -- block I/O ------------------------------------------------------------

    def _read_block(self, block: int) -> bytes:
        self._f.seek(block * BLOCK)
        data = self._f.read(BLOCK)
        return data + bytes(BLOCK - len(data))

    def _write_block(self, block: int, data: bytes) -> None:
        self._f.seek(block * BLOCK)
        self._f.write(data[:BLOCK].ljust(BLOCK, b"\x00"))

    def _obj_read(self, meta: dict, offset: int, length: int) -> bytes:
        out = bytearray()
        end = min(offset + length, meta["size"])
        pos = offset
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            if bi < len(meta["extents"]) and meta["extents"][bi] >= 0:
                blk = self._read_block(meta["extents"][bi])
                out += blk[boff:boff + n]
            else:
                out += bytes(n)     # hole
            pos += n
        return bytes(out)

    def _obj_write(self, meta: dict, offset: int, data: bytes) -> None:
        end = offset + len(data)
        need_blocks = -(-max(end, meta["size"]) // BLOCK)
        while len(meta["extents"]) < need_blocks:
            meta["extents"].append(-1)
        pos = offset
        di = 0
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            old_block = meta["extents"][bi]
            if boff == 0 and n == BLOCK:
                patched = data[di:di + n]      # full block: no read
            elif old_block >= 0:
                old = self._read_block(old_block)
                patched = old[:boff] + data[di:di + n] + old[boff + n:]
            else:
                patched = bytes(boff) + data[di:di + n]
            # COW: never touch a committed block in place — the old
            # extent stays valid until the KV commit flips the map
            nb = self._alloc.allocate(1)[0]
            self._write_block(nb, patched)
            meta["extents"][bi] = nb
            if old_block >= 0:
                self._freed.append(old_block)
            pos += n
            di += n
        meta["size"] = max(meta["size"], end)

    def _obj_zero(self, meta: dict, offset: int, length: int) -> None:
        """Punch holes instead of writing zeros: full blocks drop to
        extent -1 (reads synthesize zeros), edges COW-patch."""
        end = offset + length
        pos = offset
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            if bi < len(meta["extents"]) and meta["extents"][bi] >= 0:
                if boff == 0 and n == BLOCK:
                    self._freed.append(meta["extents"][bi])
                    meta["extents"][bi] = -1
                else:
                    old = self._read_block(meta["extents"][bi])
                    nb = self._alloc.allocate(1)[0]
                    self._write_block(nb, old[:boff] + bytes(n)
                                      + old[boff + n:])
                    self._freed.append(meta["extents"][bi])
                    meta["extents"][bi] = nb
            pos += n
        if end > meta["size"]:
            while len(meta["extents"]) < -(-end // BLOCK):
                meta["extents"].append(-1)
            meta["size"] = end

    def _obj_truncate(self, meta: dict, length: int) -> None:
        if length < meta["size"]:
            keep = -(-length // BLOCK) if length else 0
            self._freed.extend(b for b in meta["extents"][keep:]
                               if b >= 0)
            meta["extents"] = meta["extents"][:keep]
            # zero the tail of the boundary block (COW)
            if length % BLOCK and meta["extents"] \
                    and meta["extents"][-1] >= 0:
                blk = self._read_block(meta["extents"][-1])
                nb = self._alloc.allocate(1)[0]
                self._write_block(nb, blk[:length % BLOCK])
                self._freed.append(meta["extents"][-1])
                meta["extents"][-1] = nb
        meta["size"] = length

    # -- transactions ---------------------------------------------------------

    def queue_transactions(self, txns, on_commit=None) -> None:
        with self._lock:
            kvt = self._db.get_transaction()
            cache: dict[tuple, dict | None] = {}
            self._freed = []

            def coll_exists(cid):
                if ("__coll__", cid) in cache:
                    return cache[("__coll__", cid)] is not None
                return self._db.get("coll", cid) is not None

            def get(cid, oid):
                key = (cid, oid)
                if key not in cache:
                    cache[key] = self._meta(cid, oid)
                return cache[key]

            def ensure(cid, oid):
                if not coll_exists(cid):
                    raise KeyError(f"no collection {cid!r}")
                m = get(cid, oid)
                if m is None:
                    m = self._new_meta()
                    cache[(cid, oid)] = m
                return m

            def drop(cid, oid):
                m = get(cid, oid)
                if m is not None:
                    self._freed.extend(b for b in m["extents"]
                                       if b >= 0)
                cache[(cid, oid)] = None

            for t in txns:
                for op in t.ops:
                    if op.op == OP_MKCOLL:
                        cache[("__coll__", op.cid)] = {}
                    elif op.op == OP_RMCOLL:
                        # purge the collection's objects too (MemStore
                        # drops the whole dict; the backends must agree)
                        prefix = f"{op.cid}\x00"
                        for k in self._db.get_range("obj"):
                            if k.startswith(prefix):
                                drop(op.cid, k[len(prefix):])
                        for (cid, oid), m in list(cache.items()):
                            if cid == op.cid and m is not None:
                                drop(cid, oid)
                        cache[("__coll__", op.cid)] = None
                    elif op.op == OP_TOUCH:
                        ensure(op.cid, op.oid)
                    elif op.op == OP_WRITE:
                        m = ensure(op.cid, op.oid)
                        self._obj_write(m, op.offset, op.data)
                    elif op.op == OP_ZERO:
                        m = ensure(op.cid, op.oid)
                        self._obj_zero(m, op.offset, op.length)
                    elif op.op == OP_TRUNCATE:
                        m = ensure(op.cid, op.oid)
                        self._obj_truncate(m, op.length)
                    elif op.op == OP_REMOVE:
                        drop(op.cid, op.oid)
                    elif op.op == OP_OMAP_SETKEYS:
                        m = ensure(op.cid, op.oid)
                        for k, v in op.keys.items():
                            m["omap"][k] = v.hex()
                    elif op.op == OP_OMAP_RMKEYS:
                        m = ensure(op.cid, op.oid)
                        for k in op.rmkeys:
                            m["omap"].pop(k, None)
                    elif op.op == OP_SETATTR:
                        m = ensure(op.cid, op.oid)
                        m["attrs"][op.name] = op.data.hex()
                    elif op.op == OP_COLL_MOVE:
                        # metadata-only move: extents stay where they
                        # are, the object record changes collections
                        if not coll_exists(op.dest):
                            raise KeyError(f"no collection {op.dest!r}")
                        m = get(op.cid, op.oid)
                        if m is not None:
                            prev = get(op.dest, op.oid)
                            if prev is not None:   # overwrite: free old
                                self._freed.extend(
                                    b for b in prev["extents"] if b >= 0)
                            cache[(op.dest, op.oid)] = m
                            cache[(op.cid, op.oid)] = None
                    elif op.op == OP_CLONE:
                        m = get(op.cid, op.oid)
                        if m is None:   # missing src: no-op (MemStore)
                            continue
                        prev = get(op.cid, op.dest)
                        if prev is not None:   # overwrite: free old
                            self._freed.extend(
                                b for b in prev["extents"] if b >= 0)
                        dst = self._new_meta()
                        dst["size"] = m["size"]
                        dst["attrs"] = dict(m["attrs"])
                        dst["omap"] = dict(m["omap"])
                        for src in m["extents"]:
                            if src < 0:
                                dst["extents"].append(-1)
                                continue
                            nb = self._alloc.allocate(1)[0]
                            self._write_block(nb,
                                              self._read_block(src))
                            dst["extents"].append(nb)
                        cache[(op.cid, op.dest)] = dst
            # data before metadata: fsync the block file, then ONE
            # atomic KV commit referencing it.  Displaced blocks return
            # to the allocator only after the commit — a crash (or an
            # exception above) leaves old metadata over untouched old
            # blocks; blocks this batch allocated then leak in-memory
            # only, and the next mount's rebuild reclaims them.
            self._f.flush()
            os.fsync(self._f.fileno())
            # the KV mutations come from the FINAL cache state, never
            # eagerly per-op: a KV transaction applies sets before rms,
            # so a remove+recreate of one key in a batch (recovery's
            # replace-wholesale push) must collapse to a single set
            for (cid, oid), m in cache.items():
                if cid == "__coll__":
                    if m is not None:
                        kvt.set("coll", oid, b"1")
                    else:
                        kvt.rmkey("coll", oid)
                elif m is not None:
                    self._put_meta(kvt, cid, oid, m)
                else:
                    kvt.rmkey("obj", _okey(cid, oid))
            self._db.submit_transaction(kvt)
            self._alloc.release(self._freed)
            self._freed = []
        if on_commit:
            on_commit()

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    # -- reads ----------------------------------------------------------------

    def _get_checked(self, cid: str, oid: str) -> dict:
        if self._db.get("coll", cid) is None:
            raise KeyError(f"no collection {cid!r}")
        m = self._meta(cid, oid)
        if m is None:
            raise KeyError(f"no object {cid}/{oid}")
        return m

    def read(self, cid, oid, offset=0, length=None) -> bytes:
        with self._lock:
            m = self._get_checked(cid, oid)
            if length is None:
                length = m["size"] - offset
            return self._obj_read(m, offset, max(0, length))

    def stat(self, cid, oid) -> dict:
        with self._lock:
            return {"size": self._get_checked(cid, oid)["size"]}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return (self._db.get("coll", cid) is not None
                    and self._meta(cid, oid) is not None)

    def list_objects(self, cid) -> list[str]:
        with self._lock:
            if self._db.get("coll", cid) is None:
                raise KeyError(f"no collection {cid!r}")
            prefix = f"{cid}\x00"
            out = []
            for k in self._db.get_range("obj"):
                if k.startswith(prefix):
                    out.append(k[len(prefix):])
            return sorted(out)

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._db.get_range("coll"))

    def omap_get(self, cid, oid) -> dict:
        with self._lock:
            m = self._get_checked(cid, oid)
            return {k: bytes.fromhex(v) for k, v in m["omap"].items()}

    def getattr(self, cid, oid, name):
        with self._lock:
            m = self._get_checked(cid, oid)
            v = m["attrs"].get(name)
            return bytes.fromhex(v) if v is not None else None
