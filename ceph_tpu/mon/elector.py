"""Leader election among monitors (src/mon/Elector.{h,cc} semantics).

Rank-based: the lowest-ranked reachable monitor wins.  A candidate
broadcasts PROPOSE; higher-ranked peers defer with ACK, lower-ranked peers
counter-propose.  When the election timer expires the candidate declares
VICTORY if a majority (of the *full* monmap, floor(n/2)+1) acked; the
victory message carries the quorum.  Election epochs are monotonically
increasing; stale-epoch messages are dropped (Elector.cc bump_epoch).

The Monitor owns the messenger and timers; this class is the pure state
machine, with send/win/lose callbacks.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message


@register_message
class MMonElection(Message):
    TYPE = 65  # MSG_MON_ELECTION

    PROPOSE = 1
    ACK = 2
    VICTORY = 3

    def __init__(self, op: int = 0, epoch: int = 0, rank: int = 0,
                 quorum: list[int] | None = None):
        super().__init__()
        self.op = op
        self.epoch = epoch
        self.rank = rank
        self.quorum = quorum or []

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u8(self.op), e.u32(self.epoch), e.s32(self.rank),
            e.list(self.quorum, lambda e2, r: e2.s32(r))))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.op = d.u8()
            self.epoch = d.u32()
            self.rank = d.s32()
            self.quorum = d.list(lambda d2: d2.s32())
        dec.versioned(1, body)


class Elector:
    ELECTION_TIMEOUT = 1.0

    def __init__(self, rank: int, ranks, send_fn, on_win, on_lose):
        """send_fn(rank, MMonElection); on_win(epoch, quorum);
        on_lose(epoch, leader, quorum).

        ranks: the monmap's member ranks — an int n (ranks 0..n-1, the
        static-monmap convenience) or an explicit list (runtime
        membership leaves holes after `mon rm`)."""
        self.rank = rank
        self.ranks = (sorted(ranks) if not isinstance(ranks, int)
                      else list(range(ranks)))
        self.send = send_fn
        self.on_win = on_win
        self.on_lose = on_lose
        self.epoch = 0
        self.electing = False
        self.acked_me: set[int] = set()
        self.expire_at = 0.0
        self.leader: int | None = None
        self.quorum: list[int] = []
        #: rank we deferred to this round; a deferrer must stay quiet —
        #: retrying its own candidacy resets the better candidate's
        #: victory timer every cycle and the election never converges
        self.defer_to: int | None = None
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"Elector::lock({rank})")

    def majority(self) -> int:
        return len(self.ranks) // 2 + 1

    def set_ranks(self, ranks: list[int]) -> None:
        """Runtime membership change (monmap epoch bump): the next
        election runs over the new member set."""
        with self._lock:
            self.ranks = sorted(ranks)

    # -- entry points ---------------------------------------------------------

    def start(self) -> None:
        """Call an election (Elector::start)."""
        with self._lock:
            self.epoch += 1
            self.electing = True
            self.leader = None
            self.defer_to = None
            self.acked_me = {self.rank}
            self.expire_at = time.time() + self.ELECTION_TIMEOUT
            epoch = self.epoch
        if self.ranks == [self.rank]:
            self._declare_victory()
            return
        for r in self.ranks:
            if r != self.rank:
                self.send(r, MMonElection(op=MMonElection.PROPOSE,
                                          epoch=epoch, rank=self.rank))

    def tick(self, now: float | None = None) -> None:
        """Election expiry check (driven by the monitor's timer)."""
        now = now or time.time()
        declare = retry = fresh = False
        with self._lock:
            if self.electing and now >= self.expire_at:
                if self.defer_to is not None:
                    # the candidate we deferred to never won: stand again
                    fresh = True
                elif len(self.acked_me) >= self.majority():
                    declare = True
                else:
                    # no quorum yet: keep proposing (peers may be booting)
                    self.expire_at = now + self.ELECTION_TIMEOUT
                    self.epoch += 1
                    epoch = self.epoch
                    retry = True
        if fresh:
            self.start()
        elif declare:
            self._declare_victory()
        elif retry:
            for r in self.ranks:
                if r != self.rank:
                    self.send(r, MMonElection(op=MMonElection.PROPOSE,
                                              epoch=epoch, rank=self.rank))

    def _declare_victory(self) -> None:
        with self._lock:
            self.epoch += 1     # victory epoch (even in the reference)
            self.electing = False
            self.leader = self.rank
            self.quorum = sorted(self.acked_me)
            epoch, quorum = self.epoch, list(self.quorum)
        for r in quorum:
            if r != self.rank:
                self.send(r, MMonElection(op=MMonElection.VICTORY,
                                          epoch=epoch, rank=self.rank,
                                          quorum=quorum))
        self.on_win(epoch, quorum)

    # -- message handling -----------------------------------------------------

    def handle(self, msg: MMonElection) -> None:
        with self._lock:
            if msg.epoch < self.epoch and msg.op != MMonElection.PROPOSE:
                return
        if msg.op == MMonElection.PROPOSE:
            self._handle_propose(msg)
        elif msg.op == MMonElection.ACK:
            self._handle_ack(msg)
        elif msg.op == MMonElection.VICTORY:
            self._handle_victory(msg)

    def _handle_propose(self, msg: MMonElection) -> None:
        with self._lock:
            if msg.epoch > self.epoch:
                self.epoch = msg.epoch
            if msg.rank < self.rank:
                # defer to the better candidate (Elector::defer): go
                # quiet and give it two timeouts to declare victory
                self.electing = True
                self.defer_to = msg.rank
                self.acked_me = set()
                self.expire_at = time.time() + 2 * self.ELECTION_TIMEOUT
                epoch = self.epoch
                send_ack = True
                counter = False
            else:
                send_ack = False
                # I outrank the proposer; counter-propose unless my own
                # in-flight candidacy already outranks its epoch
                counter = not (self.electing and self.defer_to is None
                               and self.epoch > msg.epoch)
        if send_ack:
            self.send(msg.rank, MMonElection(op=MMonElection.ACK,
                                             epoch=epoch, rank=self.rank))
        elif counter:
            self.start()

    def _handle_ack(self, msg: MMonElection) -> None:
        declare = False
        with self._lock:
            if not self.electing or msg.epoch < self.epoch:
                return
            # a deferrer may ack from a higher epoch (it raced its own
            # election before deferring): adopt it, the ack still counts
            self.epoch = max(self.epoch, msg.epoch)
            self.acked_me.add(msg.rank)
            if self.acked_me >= set(self.ranks):
                declare = True   # everyone answered: no need to wait
        if declare:
            self._declare_victory()

    def _handle_victory(self, msg: MMonElection) -> None:
        if msg.rank > self.rank:
            # a worse-ranked mon declaring victory over me (it could not
            # reach me): do not adopt its leadership, out-rank it
            self.start()
            return
        with self._lock:
            self.epoch = max(self.epoch, msg.epoch)
            self.electing = False
            self.leader = msg.rank
            self.quorum = list(msg.quorum)
            epoch = self.epoch
        self.on_lose(epoch, msg.rank, list(msg.quorum))
