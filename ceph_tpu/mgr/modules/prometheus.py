"""Prometheus exporter module (src/pybind/mgr/prometheus analog).

Serves the text exposition format (0.0.4) over HTTP on the module's
configured port.  Every family carries ``# HELP``/``# TYPE`` headers;
histogram-typed perf counters are emitted as real histogram families
(``_bucket{le=...}`` / ``_sum`` / ``_count``), time-avg counters as
summary sum+count pairs, and values are never integer-truncated.

Three data sources feed one scrape:

  * cluster aggregates the mgr already maintains (health, osdmap, pg
    states, df);
  * the TYPED per-daemon perf dumps riding MMgrReport v3 — every
    registered set (osd, messenger, bluestore, ...) of every reporting
    daemon;
  * the process-global device-kernel telemetry registry
    (ceph_tpu.ops.telemetry): latency/batch-occupancy histograms, byte
    counters and jit retrace counts for the EC and CRUSH kernels.  In
    the in-process MiniCluster every daemon shares that registry; in a
    multi-process deployment each daemon serves its own via the admin
    socket (``dump_kernel_stats``) and a sidecar relabels per daemon.
"""

from __future__ import annotations

import http.server
import socketserver
import threading

from ceph_tpu.mgr.module import MgrModule
from ceph_tpu.ops import telemetry


def _num(v) -> str:
    """Exposition value: ints stay integral, floats keep precision
    (the old exporter's int(val) silently corrupted time-avg floats)."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _esc(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(d: dict | None) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in d.items())
    return "{" + inner + "}"


class Exposition:
    """Accumulates samples grouped by family so each family is emitted
    contiguously under exactly one HELP/TYPE header pair (the format's
    grouping requirement)."""

    def __init__(self):
        self._order: list[str] = []
        self._fam: dict[str, tuple[str, str, list[str]]] = {}

    def _family(self, name: str, typ: str, help_: str) -> list[str]:
        fam = self._fam.get(name)
        if fam is None:
            fam = (typ, help_, [])
            self._fam[name] = fam
            self._order.append(name)
        return fam[2]

    def sample(self, name: str, typ: str, help_: str, value,
               labels: dict | None = None, suffix: str = "") -> None:
        self._family(name, typ, help_).append(
            f"{name}{suffix}{_labels(labels)} {_num(value)}")

    def gauge(self, name, help_, value, labels=None):
        self.sample(name, "gauge", help_, value, labels)

    def counter(self, name, help_, value, labels=None):
        self.sample(name, "counter", help_, value, labels)

    def summary(self, name, help_, count, sum_, labels=None):
        rows = self._family(name, "summary", help_)
        rows.append(f"{name}_sum{_labels(labels)} {_num(sum_)}")
        rows.append(f"{name}_count{_labels(labels)} {_num(count)}")

    def histogram(self, name, help_, bounds, buckets, sum_, labels=None):
        """bounds: bucket upper limits; buckets: PER-BUCKET counts with
        one overflow bucket appended (len(bounds)+1)."""
        rows = self._family(name, "histogram", help_)
        acc = 0
        for le, n in zip(bounds, buckets):
            acc += n
            lab = dict(labels or {})
            lab["le"] = _num(le)
            rows.append(f"{name}_bucket{_labels(lab)} {acc}")
        total = acc + buckets[len(bounds)]
        lab = dict(labels or {})
        lab["le"] = "+Inf"
        rows.append(f"{name}_bucket{_labels(lab)} {total}")
        rows.append(f"{name}_sum{_labels(labels)} {_num(sum_)}")
        rows.append(f"{name}_count{_labels(labels)} {total}")

    def render(self) -> str:
        out = []
        for name in self._order:
            typ, help_, rows = self._fam[name]
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {typ}")
            out.extend(rows)
        return "\n".join(out) + "\n"


class Module(MgrModule):
    NAME = "prometheus"
    MODULE_OPTIONS = [{"name": "server_port", "default": 0}]

    def __init__(self, mgr):
        super().__init__(mgr)
        self._httpd: socketserver.ThreadingTCPServer | None = None
        self._port = 0

    # -- payload --------------------------------------------------------------

    #: health summary -> exposition value
    HEALTH_VALUES = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}

    def scrape_text(self) -> str:
        exp = Exposition()
        self._scrape_cluster(exp)
        self._scrape_daemon_perf(exp)
        self._scrape_slow_ops(exp)
        self._scrape_qos(exp)
        self._scrape_tenant_usage(exp)
        self._scrape_slo(exp)
        self._scrape_scrub(exp)
        self._scrape_bluestore(exp)
        self._scrape_fault_feed(exp)
        self._scrape_kernels(exp)
        self._scrape_dispatch(exp)
        self._scrape_decode_dispatch(exp)
        self._scrape_mapping(exp)
        self._scrape_phase_profile(exp)
        return exp.render()

    def _scrape_cluster(self, exp: Exposition) -> None:
        exp.gauge("ceph_health_status",
                  "cluster health (0=OK 1=WARN 2=ERR)",
                  self.HEALTH_VALUES.get(
                      self.get("health")["status"], 1))
        m = self.get_osdmap()
        exp.gauge("ceph_osd_up", "osds up",
                  sum(1 for o in range(m.max_osd) if m.is_up(o)))
        exp.gauge("ceph_osd_in", "osds in (weight > 0)",
                  sum(1 for o in range(m.max_osd)
                      if m.exists(o) and m.osd_weight[o] > 0))
        exp.gauge("ceph_osdmap_epoch", "current osdmap epoch", m.epoch)
        for state, n in sorted(self.get("pg_summary").items()):
            exp.gauge("ceph_pg_states", "pg count by state", n,
                      {"state": state})
        df = self.get("df")
        exp.gauge("ceph_cluster_total_objects",
                  "objects across reporting osds", df["total_objects"])
        exp.gauge("ceph_cluster_bytes_used",
                  "bytes used across reporting osds",
                  df["total_bytes_used"])
        # legacy flat family (the OSD's own u64 counters) kept for
        # existing dashboards; floats pass through untruncated
        for osd, counters in sorted(self.get("counters").items()):
            for name, val in sorted(counters.items()):
                exp.counter("ceph_osd_perf", "osd u64 perf counters",
                            val, {"ceph_daemon": f"osd.{osd}",
                                  "counter": name})

    def _scrape_daemon_perf(self, exp: Exposition) -> None:
        """Typed perf dumps from MMgrReport v3: one family per counter
        type, labelled by daemon / set / counter."""
        for osd, sets in sorted(self.get("perf_reports").items()):
            daemon = f"osd.{osd}"
            for set_name, counters in sorted(sets.items()):
                for cname, val in sorted(counters.items()):
                    lab = {"ceph_daemon": daemon, "set": set_name,
                           "counter": cname}
                    if isinstance(val, dict) and "buckets" in val:
                        exp.histogram(
                            "ceph_daemon_perf_hist",
                            "histogram-typed daemon perf counters",
                            val["bounds"], val["buckets"],
                            val.get("sum", 0.0), lab)
                    elif isinstance(val, dict) and "avgcount" in val:
                        exp.summary(
                            "ceph_daemon_perf_latency",
                            "time-avg daemon perf counters (seconds)",
                            val["avgcount"], val["sum"], lab)
                    else:
                        exp.counter(
                            "ceph_daemon_perf_counter",
                            "u64 daemon perf counters", val, lab)

    def _scrape_slow_ops(self, exp: Exposition) -> None:
        """Per-daemon slow-op counts from the MMgrReport v4 tail (the
        insights feed); absent on hosts without the view (unit stubs)."""
        try:
            feed = self.get("insights_feed")
        except Exception:
            return
        for osd, entry in sorted(feed.items()):
            exp.gauge("ceph_daemon_slow_ops",
                      "slow ops retained in the daemon's historic ring",
                      len(entry.get("slow_ops", [])),
                      {"ceph_daemon": f"osd.{osd}"})
            exp.gauge("ceph_daemon_slow_traces",
                      "tail-retained slow traces reported by daemon",
                      len(entry.get("slow_traces", [])),
                      {"ceph_daemon": f"osd.{osd}"})

    def _scrape_qos(self, exp: Exposition) -> None:
        """Per-tenant dmclock accounting from the MMgrReport v4 qos
        tail: phase-served counters, lane backlog, and cumulative
        queue-wait per (daemon, lane) — the multi-tenant fairness
        story (reservation floors show up as the reservation phase
        share, caps as the limit phase).  Absent on hosts without the
        feed (unit stubs)."""
        try:
            feed = self.get("qos_feed")
        except Exception:
            return
        for osd, entry in sorted(feed.items()):
            daemon = f"osd.{osd}"
            ev = entry.get("evicted", {})
            # the eviction rollup rides the SAME families as one more
            # pseudo-lane ("evicted" cannot collide — real lanes carry
            # the client. prefix): without it, sum-over-lanes
            # dashboards would undercount exactly in the
            # millions-of-one-shot-clients regime eviction targets.
            # The rollup has no backlog (only empty lanes evict).
            rows = sorted(entry.get("lanes", {}).items())
            rows.append(("evicted", {"served": ev.get("served", {}),
                                     "wait_sum_s":
                                         ev.get("wait_sum_s", 0.0)}))
            for lane, row in rows:
                lab = {"ceph_daemon": daemon, "qos_class": lane}
                for phase, n in sorted(row.get("served", {}).items()):
                    exp.counter(
                        "ceph_qos_served_total",
                        "ops served per dmclock phase per lane "
                        "(reservation = floor honored, weight = "
                        "excess share, limit = work-conserving "
                        "fallback past every cap)",
                        n, {**lab, "phase": phase})
                if "backlog" in row:
                    exp.gauge("ceph_qos_backlog",
                              "ops queued in the lane at report time",
                              row.get("backlog", 0), lab)
                exp.counter("ceph_qos_wait_seconds_total",
                            "cumulative dmclock queue wait "
                            "(throttle time) per lane",
                            row.get("wait_sum_s", 0.0), lab)
            exp.counter("ceph_qos_evicted_lanes_total",
                        "idle dynamic lanes evicted by the "
                        "osd_qos_idle_client_timeout sweep",
                        ev.get("classes", 0), {"ceph_daemon": daemon})

    def _scrape_tenant_usage(self, exp: Exposition) -> None:
        """ceph_tenant_*: the tenant device-time ledger from the
        MMgrReport tenant_usage tail — per (daemon, tenant, engine,
        channel) attributed device-seconds and the per-tenant
        share-of-device gauge.  Tenant names are user-supplied strings;
        the label layer escapes them per the exposition spec.  Absent
        on hosts without the feed (unit stubs)."""
        try:
            feed = self.get("tenant_feed")
        except Exception:
            return
        for osd, digest in sorted(feed.items()):
            daemon = f"osd.{osd}"
            for tenant, trec in sorted(
                    (digest.get("tenants") or {}).items()):
                exp.gauge(
                    "ceph_tenant_device_share",
                    "tenant's share of this daemon's attributed "
                    "device-seconds (the _untagged bucket keeps the "
                    "shares summing to 1)",
                    trec.get("share", 0.0),
                    {"ceph_daemon": daemon, "tenant": tenant})
                for eng, chans in sorted(
                        (trec.get("engines") or {}).items()):
                    for ch, row in sorted(chans.items()):
                        lab = {"ceph_daemon": daemon, "tenant": tenant,
                               "engine": eng, "channel": ch}
                        exp.counter(
                            "ceph_tenant_device_seconds_total",
                            "device busy seconds (compute x devices) "
                            "apportioned to the tenant by stripe "
                            "share of each coalesced batch",
                            row.get("device_seconds", 0.0), lab)
                        exp.counter(
                            "ceph_tenant_requests_total",
                            "dispatch requests attributed to the "
                            "tenant", row.get("requests", 0), lab)

    def _scrape_slo(self, exp: Exposition) -> None:
        """ceph_slo_burn_rate{tenant,objective}: the slo module's
        fast-window burn per declared objective (>= 1.0 while the
        objective is violated over the window)."""
        try:
            if not self.get_osdmap().slo_db:
                return
            gauges = self.mgr._module("slo").burn_gauges()
        except Exception:
            return
        for tenant, per in sorted(gauges.items()):
            for obj, burn in sorted(per.items()):
                exp.gauge(
                    "ceph_slo_burn_rate",
                    "fast-window SLO burn rate per tenant objective "
                    "(1.0 = at the objective boundary)",
                    burn, {"tenant": tenant, "objective": obj})

    def _scrape_scrub(self, exp: Exposition) -> None:
        """ceph_scrub_*: per-daemon background-integrity counters from
        the MMgrReport v5 scrub tail — how much each OSD's deep scrub
        checked, how the digests were computed (batched device calls
        vs scalar fallbacks), and the found/repaired/unverified
        ledger.  A non-zero ceph_scrub_repair_unverified_total is the
        alert: a repair was fired whose re-fetched digest never
        matched."""
        try:
            feed = self.get("scrub_feed")
        except Exception:
            return
        families = {
            "sweeps": ("ceph_scrub_sweeps_total",
                       "full scrub_all_pgs sweeps completed"),
            "pgs_scrubbed": ("ceph_scrub_pgs_total",
                             "PG deep-scrub chunks completed"),
            "objects_scrubbed": ("ceph_scrub_objects_total",
                                 "objects deep-scrubbed"),
            "digest_batches": ("ceph_scrub_digest_batches_total",
                               "coalesced scrub_digest device batches"),
            "digest_objects": ("ceph_scrub_digest_objects_total",
                               "object/omap rows digested in batched "
                               "device calls"),
            "scalar_fallbacks": ("ceph_scrub_scalar_fallbacks_total",
                                 "scrub maps that fell back to the "
                                 "scalar shard_crc loop"),
            "inconsistent": ("ceph_scrub_inconsistent_total",
                             "inconsistent objects/shards found"),
            "repaired": ("ceph_scrub_repaired_total",
                         "repairs whose re-fetched digest VERIFIED"),
            "repair_unverified": ("ceph_scrub_repair_unverified_total",
                                  "repairs fired but never verified "
                                  "within osd_scrub_verify_timeout"),
            "missing_peer_scrubs": ("ceph_scrub_missing_peer_total",
                                    "scrubs with a replica map "
                                    "missing (PG not reported clean)"),
        }
        for osd, entry in sorted(feed.items()):
            lab = {"ceph_daemon": f"osd.{osd}"}
            for key, (fam, help_) in families.items():
                exp.counter(fam, help_, entry.get(key, 0), lab)

    def _scrape_bluestore(self, exp: Exposition) -> None:
        """ceph_bluestore_*: the process-global objectstore write/read
        path ledger — how block checksums were computed (coalesced
        bluestore_data device batches vs scalar crc32), the block
        compression outcome mix, and the error counters that should
        alert (csum_errors, decompress_errors, kv_journal_truncated).
        Process-local like the ceph_kernel_* families: one daemon per
        process attributes cleanly; a shared process aggregates."""
        families = {
            "csum_batches": ("ceph_bluestore_csum_batches_total",
                             "coalesced bluestore_data digest batches "
                             "at commit"),
            "csum_blocks": ("ceph_bluestore_csum_blocks_total",
                            "blocks checksummed in batched device "
                            "calls"),
            "csum_scalar_blocks": (
                "ceph_bluestore_csum_scalar_blocks_total",
                "blocks checksummed by the scalar zlib.crc32 path "
                "(knob off, small batch, engine-thread caller, or "
                "fallback)"),
            "csum_fallbacks": ("ceph_bluestore_csum_fallbacks_total",
                               "batched digest calls that failed over "
                               "to scalar crc32"),
            "read_verify_batches": (
                "ceph_bluestore_read_verify_batches_total",
                "wide reads whose block verification rode one "
                "device digest call"),
            "read_verify_blocks": (
                "ceph_bluestore_read_verify_blocks_total",
                "blocks verified in batched read digests"),
            "compress_blocks": ("ceph_bluestore_compress_blocks_total",
                                "blocks committed compressed (ratio "
                                "met, round-trip verified)"),
            "compress_rejected": (
                "ceph_bluestore_compress_rejected_total",
                "blocks stored raw: ratio not met or plugin error"),
            "compress_roundtrip_failures": (
                "ceph_bluestore_compress_roundtrip_failures_total",
                "compressed blocks that failed byte-identical "
                "round-trip verification and were stored raw"),
            "decompress_errors": (
                "ceph_bluestore_decompress_errors_total",
                "reads that hit a corrupt compressed body (EIO)"),
            "csum_errors": ("ceph_bluestore_csum_errors_total",
                            "read-time block checksum mismatches "
                            "(EIO)"),
            "kv_journal_truncated": (
                "ceph_bluestore_kv_journal_truncated_total",
                "KV journal replays that stopped at a short/corrupt "
                "frame (transactions past it are LOST)"),
            "kv_journal_lost_bytes": (
                "ceph_bluestore_kv_journal_lost_bytes_total",
                "unreplayed journal bytes past replay stop points"),
        }
        dump = telemetry.bluestore_dump()
        for key, (fam, help_) in families.items():
            exp.counter(fam, help_, dump.get(key, 0))

    def _scrape_fault_feed(self, exp: Exposition) -> None:
        """Per-daemon circuit-breaker states from the MMgrReport v4
        faults tail.  The process-local ``ceph_kernel_breaker_state``
        family below reads the shared (last-writer-wins) stats sink —
        fine for one daemon per process, but it cannot attribute
        degradation across daemons; this family carries each daemon's
        OWN engine ground truth (ctx.fault_digest overlay), so alerts
        on an open breaker name the right daemon.  Absent on hosts
        without the feed (unit stubs)."""
        try:
            feed = self.get("faults_feed")
        except Exception:
            return
        for osd, digest in sorted(feed.items()):
            for engine, d in sorted(digest.items()):
                if not isinstance(d, dict):
                    continue
                for ch, st in sorted(d.get("breaker_states",
                                           {}).items()):
                    exp.gauge(
                        "ceph_kernel_daemon_breaker_state",
                        "per-daemon per-channel circuit-breaker state "
                        "from the shipped faults digest: 0 closed "
                        "(device path live), 1 open (host oracle), "
                        "2 half-open (probe in flight)",
                        st, {"ceph_daemon": f"osd.{osd}",
                             "engine": engine, "channel": ch})

    def _scrape_kernels(self, exp: Exposition) -> None:
        reg = telemetry.registry()
        # the two offload kernels always appear (zero-valued before
        # first use) so dashboards and the format test can rely on the
        # families existing
        reg.kernel("ec_encode")
        reg.kernel("ec_decode")
        reg.kernel("crush_map")
        for kname, d in sorted(telemetry.dump().items()):
            p = f"ceph_kernel_{kname}"
            lat = d["latency_seconds"]
            bat = d["batch_size"]
            exp.histogram(f"{p}_latency_seconds",
                          f"wall time per {kname} device call "
                          "(fenced = device time; see "
                          "kernel_fence_for_timing)",
                          lat["bounds"], lat["buckets"], lat["sum"])
            exp.histogram(f"{p}_batch_size",
                          f"batch occupancy per {kname} device call",
                          bat["bounds"], bat["buckets"], bat["sum"])
            exp.counter(f"{p}_calls_total",
                        "completed device calls", d["calls"])
            exp.counter(f"{p}_traced_total",
                        "executions inlined under an outer jit trace",
                        d["traced"])
            exp.counter(f"{p}_jit_miss_total",
                        "jit compile-cache misses (retrace+compile)",
                        d["jit_misses"])
            exp.counter(f"{p}_jit_hit_total",
                        "calls served by a cached executable",
                        d["jit_hits"])
            exp.counter(f"{p}_bytes_in_total",
                        "host to device operand bytes", d["bytes_in"])
            exp.counter(f"{p}_bytes_out_total",
                        "device to host result bytes", d["bytes_out"])

    def _scrape_dispatch(self, exp: Exposition) -> None:
        """The cross-op coalescing engine (ops.dispatch): how many
        requests share each device call, how long they queue for the
        privilege, and how deep the pipeline runs."""
        d = telemetry.dispatch_dump()
        self._emit_coalesce(exp, d, "ceph_kernel_coalesce")
        self._emit_mesh(exp, d, "encode")
        self._emit_faults(exp, d, "encode")

    @staticmethod
    def _emit_faults(exp: Exposition, d: dict, engine: str) -> None:
        """ceph_kernel_fallback_* / ceph_kernel_breaker_*: the
        degraded-mode story per dispatch engine — how often the device
        path failed and was retried, how much traffic the bit-exact
        host oracle served, each channel's circuit-breaker state
        (0 closed / 1 open / 2 half-open mid-probe), breaker
        transitions, background-probe outcomes, and engine run-loop
        deaths/restarts under thread supervision."""
        f = d.get("faults", {})
        lab = {"engine": engine}
        p = "ceph_kernel_fallback"
        exp.counter(f"{p}_retries_total",
                    "device re-attempts of failed coalesced batches "
                    "(bounded exponential backoff + jitter)",
                    f.get("retries", 0), lab)
        exp.counter(f"{p}_retry_successes_total",
                    "re-attempts that healed the batch on the device",
                    f.get("retry_successes", 0), lab)
        exp.counter(f"{p}_batches_total",
                    "coalesced batches served by the bit-exact host "
                    "oracle instead of the device",
                    f.get("fallback_batches", 0), lab)
        exp.counter(f"{p}_stripes_total",
                    "stripes those host-oracle batches carried",
                    f.get("fallback_stripes", 0), lab)
        for outcome, key in (("success", "probe_successes"),
                             ("failure", "probe_failures")):
            exp.counter(f"{p}_probes_total",
                        "background device-path probes while a "
                        "breaker was open",
                        f.get(key, 0), lab | {"outcome": outcome})
        exp.counter(f"{p}_thread_deaths_total",
                    "engine run-loop deaths observed by thread "
                    "supervision",
                    f.get("thread_deaths", 0), lab)
        exp.counter(f"{p}_thread_restarts_total",
                    "run-loops revived (in-flight batches re-fanned)",
                    f.get("thread_restarts", 0), lab)
        for transition, key in (("open", "breaker_opens"),
                                ("close", "breaker_closes")):
            exp.counter("ceph_kernel_breaker_transitions_total",
                        "channel circuit-breaker transitions "
                        "(open = device path abandoned for the host "
                        "oracle, close = device path healed)",
                        f.get(key, 0), lab | {"transition": transition})
        states = f.get("breaker_states", {})
        for ch in sorted(states):
            exp.gauge("ceph_kernel_breaker_state",
                      "per-channel circuit-breaker state: 0 closed "
                      "(device path live), 1 open (host oracle), "
                      "2 half-open (probe in flight)",
                      states[ch], lab | {"channel": ch})
        if not states:
            # the family must exist even before any breaker has ever
            # tripped, so dashboards and the format test can rely on it
            exp.gauge("ceph_kernel_breaker_state",
                      "per-channel circuit-breaker state: 0 closed "
                      "(device path live), 1 open (host oracle), "
                      "2 half-open (probe in flight)",
                      0, lab | {"channel": "none"})

    @staticmethod
    def _emit_mesh(exp: Exposition, d: dict, engine: str) -> None:
        """ceph_kernel_mesh_*: the multi-device fan-out story per
        dispatch engine — mesh shape, how many flushes went out
        sharded, how many devices each flush landed on, and per-device
        shard occupancy.  mesh_devices 0 = no mesh configured (single
        device or kernel_mesh_devices=1)."""
        p = "ceph_kernel_mesh"
        lab = {"engine": engine}
        exp.gauge(f"{p}_devices",
                  "devices in the engine's kernel mesh "
                  "(0 = single-device engine)", d["mesh_devices"], lab)
        exp.gauge(f"{p}_dp", "mesh data-parallel axis extent",
                  d["mesh_dp"], lab)
        exp.gauge(f"{p}_ec", "mesh erasure-shard axis extent",
                  d["mesh_ec"], lab)
        exp.counter(f"{p}_sharded_flushes_total",
                    "coalesced flushes placed across more than one "
                    "device", d["sharded_flushes"], lab)
        du = d["devices_used"]
        exp.histogram(f"{p}_flush_devices",
                      "devices each coalesced flush landed on (mass "
                      "above 1 is the multi-chip path at work)",
                      du["bounds"], du["buckets"], du["sum"], lab)
        ss = d["shard_stripes"]
        exp.histogram(f"{p}_shard_stripes",
                      "stripes per device shard per sharded flush "
                      "(per-chip occupancy after the batch splits)",
                      ss["bounds"], ss["buckets"], ss["sum"], lab)

    def _scrape_decode_dispatch(self, exp: Exposition) -> None:
        """The decode-side engine (heterogeneous-matrix batched GF
        decode): the same coalescing families under
        ceph_kernel_decode_coalesce_*, plus the heterogeneity story —
        distinct erasure patterns per device call and the registered
        pattern-table size."""
        d = telemetry.decode_dispatch_dump()
        p = "ceph_kernel_decode_coalesce"
        self._emit_coalesce(exp, d, p)
        self._emit_mesh(exp, d, "decode")
        self._emit_faults(exp, d, "decode")
        pat = d["patterns"]
        exp.histogram(f"{p}_patterns",
                      "distinct erasure patterns per coalesced decode "
                      "call (mass above 1 is heterogeneous-matrix "
                      "batching at work)",
                      pat["bounds"], pat["buckets"], pat["sum"])
        exp.gauge(f"{p}_pattern_table",
                  "recovery patterns registered in the stacked "
                  "matrix table (high-water)", d["pattern_table_size"])

    @staticmethod
    def _scrape_mapping(exp: Exposition) -> None:
        """The shared PG mapping service (osd.mapping): how often an
        epoch actually recomputes vs reuses cached pool tables, how
        many PGs each epoch really changed, burst epoch-skips, and the
        cache-hit story for mapping reads."""
        d = telemetry.mapping_dump()
        p = "ceph_kernel_mapping"
        exp.counter(f"{p}_epoch_updates_total",
                    "map epochs computed by the shared mapping "
                    "service", d["epoch_updates"])
        exp.counter(f"{p}_epoch_skips_total",
                    "map epochs never computed: burst coalescing "
                    "(only the newest queued target runs) and "
                    "multi-epoch catch-up jumps both count",
                    d["epoch_skips"])
        exp.counter(f"{p}_pools_recomputed_total",
                    "pool raw tables rebuilt on device",
                    d["pools_recomputed"])
        exp.counter(f"{p}_pools_reused_total",
                    "pool raw tables carried over unchanged "
                    "(signature hit)", d["pools_reused"])
        exp.counter(f"{p}_full_rescans_total",
                    "consumer scans that could not be served a delta "
                    "(first map, chain gap)", d["full_rescans"])
        exp.counter(f"{p}_lookups_total",
                    "mapping reads served from the cache",
                    d["lookups"])
        exp.counter(f"{p}_lookup_fallbacks_total",
                    "mapping reads that fell back to the scalar "
                    "oracle (epoch/object mismatch)",
                    d["lookup_fallbacks"])
        lat = d["update_latency_seconds"]
        exp.histogram(f"{p}_update_latency_seconds",
                      "per-epoch mapping update wall time "
                      "(incremental recompute + device diff + delta)",
                      lat["bounds"], lat["buckets"], lat["sum"])
        ch = d["changed_pgs"]
        exp.histogram(f"{p}_changed_pgs",
                      "exact changed-PG count per computed epoch "
                      "(the O(changed) map-consumption bound)",
                      ch["bounds"], ch["buckets"], ch["sum"])
        exp.gauge(f"{p}_cached_pgs",
                  "PGs resident in the cached raw tables",
                  d["cached_pgs"])
        exp.gauge(f"{p}_cached_pools",
                  "pools resident in the cached raw tables",
                  d["cached_pools"])
        exp.counter(f"{p}_fused_epochs_total",
                    "computed epochs that published complete fused "
                    "(device-resident) up/acting tables",
                    d.get("fused_epochs", 0))
        exp.counter(f"{p}_unfused_epochs_total",
                    "computed epochs served by the host pipeline "
                    "tail (fused ladder off or unavailable)",
                    d.get("unfused_epochs", 0))
        exp.counter(f"{p}_fused_lookups_total",
                    "mapping reads answered by a packed fused-row "
                    "slice (subset of the cache lookups)",
                    d.get("fused_lookups", 0))
        exp.gauge(f"{p}_host_tail_share",
                  "host-tail share of the total mapping epoch cost "
                  "(device + delta + host_tail) — collapses toward 0 "
                  "when the fused placement ladder serves the tail",
                  d.get("host_tail_share", 0.0))
        for phase, h in sorted(d["phase_seconds"].items()):
            exp.histogram(
                f"{p}_phase_seconds",
                "per-epoch mapping cost split: device remap vs "
                "changed-PG candidate extraction (delta) vs the host "
                "pipeline tail (state/affinity/upmap filtering)",
                h["bounds"], h["buckets"], h["sum"], {"phase": phase})

    @staticmethod
    def _scrape_phase_profile(exp: Exposition) -> None:
        """The pipeline phase profiler (ops.telemetry.PhaseStats):
        where each flushed batch's submit→delivery wall-clock went,
        per engine × kernel family × phase, with first-call jit cost
        in its own compile families and the device-utilization story
        (busy seconds, utilization gauge, shard imbalance).  Ring-less
        dump — the scrape reads only aggregates; the mapping phase
        split is emitted by _scrape_mapping, which already holds the
        mapping dump."""
        prof = telemetry.pipeline_profile_dump(include_recent=False)
        for engine in ("encode", "decode"):
            d = prof[engine]
            lab = {"engine": engine}
            for kernel, per in sorted(d["phases"].items()):
                for phase, h in sorted(per.items()):
                    exp.histogram(
                        "ceph_kernel_phase_seconds",
                        "seconds each pipeline phase contributed per "
                        "coalesced batch (phases sum to the batch's "
                        "submit-to-delivery wall-clock; compile "
                        "batches report launch/compute in the "
                        "compile families instead)",
                        h["bounds"], h["buckets"], h["sum"],
                        {**lab, "kernel": kernel, "phase": phase})
            for kernel, c in sorted(d["compile"].items()):
                klab = {**lab, "kernel": kernel}
                exp.counter("ceph_kernel_compile_seconds_total",
                            "jit trace+compile seconds attributed to "
                            "first-call batches per (kernel, bucket, "
                            "mesh), separate from steady-state "
                            "compute", c["seconds"], klab)
                exp.counter("ceph_kernel_compile_events_total",
                            "first-call batches that paid a jit "
                            "trace+compile", c["events"], klab)
            exp.counter("ceph_kernel_util_busy_seconds_total",
                        "device-busy integral: compute seconds times "
                        "devices each flush landed on",
                        d["busy_seconds"], lab)
            exp.gauge("ceph_kernel_util_utilization",
                      "device-busy fraction of the profiling window "
                      "(busy seconds / wall / devices)",
                      d["utilization"], lab)
            exp.gauge("ceph_kernel_util_devices",
                      "widest flush fan-out the profiler observed",
                      d["devices_seen"], lab)
            si = d["shard_imbalance"]
            exp.histogram("ceph_kernel_util_shard_imbalance",
                          "padded-lane share per sharded flush (rows "
                          "are contiguous, so padding concentrates in "
                          "the tail shards — mass near 0 means even "
                          "per-chip work)",
                          si["bounds"], si["buckets"], si["sum"], lab)

    @staticmethod
    def _emit_coalesce(exp: Exposition, d: dict, p: str) -> None:
        exp.counter(f"{p}_submits_total",
                    "requests submitted to the dispatch engine",
                    d["submits"])
        exp.counter(f"{p}_device_calls_total",
                    "coalesced device calls dispatched", d["batches"])
        exp.counter(f"{p}_completed_total",
                    "requests delivered by the completion thread",
                    d["completed"])
        exp.counter(f"{p}_stripes_total",
                    "stripes dispatched (pre-padding)",
                    d["stripes_out"])
        exp.counter(f"{p}_padded_stripes_total",
                    "zero stripes added by power-of-two shape "
                    "bucketing", d["padded_stripes"])
        co = d["coalesce"]
        exp.histogram(f"{p}_requests",
                      "requests coalesced per device call (mass above "
                      "1 is amortized dispatch latency)",
                      co["bounds"], co["buckets"], co["sum"])
        qd = d["queue_delay_seconds"]
        exp.histogram(f"{p}_queue_delay_seconds",
                      "submit-to-dispatch wait per request (idle "
                      "flushes keep the single-op path near zero)",
                      qd["bounds"], qd["buckets"], qd["sum"])
        dep = d["queue_depth"]
        exp.histogram(f"{p}_queue_depth",
                      "engine backlog observed at each flush",
                      dep["bounds"], dep["buckets"], dep["sum"])
        for reason, n in sorted(d["flush_reasons"].items()):
            exp.counter(f"{p}_flush_total",
                        "batch flushes by reason (idle = no-wait "
                        "single-op path; full/timeout = coalescing)",
                        n, {"reason": reason})
        exp.gauge(f"{p}_in_flight",
                  "device calls currently outstanding", d["in_flight"])
        exp.gauge(f"{p}_in_flight_max",
                  "high-water mark of outstanding device calls",
                  d["max_in_flight_seen"])

    # -- lifecycle ------------------------------------------------------------

    def start_server(self, port: int | None = None) -> int:
        """Bind + serve; returns the bound port (GET /metrics)."""
        if self._httpd is not None:
            return self._port
        if port is None:
            port = int(self.get_module_option("server_port", 0))
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = module.scrape_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", port), Handler)
        self._port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="mgr-prometheus-http", daemon=True)
        t.start()
        return self._port

    def start(self) -> None:
        self.start_server()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
