"""ErasureCode base class — shared logic every matrix-code plugin inherits.

Follows src/erasure-code/ErasureCode.{h,cc}: encode_prepare padding semantics
(SIMD_ALIGN=32, zero-fill the tail of the last data chunks, ErasureCode.cc:
137-172), generic encode via encode_chunks (:174-190), generic decode via
matrix recovery (:198-234), greedy _minimum_to_decode (:89-106), chunk
remapping (:260-279), and profile parsing helpers (:281-329).

The compute path is the batched device kernel: encode_chunks/decode_chunks on
(S, k, B) uint8 arrays lower to one MXU matmul (ceph_tpu.ops.gf_kernel), with
the numpy oracle available for verification (profile runtime=cpu).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf.matrix import recovery_matrix
from ceph_tpu.ops.gf_kernel import ec_encode_ref

from .interface import ErasureCodeInterface, ErasureCodeProfile

SIMD_ALIGN = 32  # ErasureCode.h SIMD_ALIGN — chunk padding quantum


class ErasureCode(ErasureCodeInterface):
    """Systematic GF(2^8) matrix code driven by a (k+m, k) generator matrix.

    Subclasses set self.k, self.m and implement _build_generator() returning the
    generator matrix (identity on top).  Everything else — padding, batched
    device encode, decode-by-inversion with an LRU recovery-matrix cache
    (ErasureCodeIsaTableCache analog) — lives here.
    """

    #: MDS matrix codecs with batched encode_chunks/decode_chunks can be
    #: laid out striped for range rmw (ECUtil stripe math); non-MDS or
    #: layered codecs fall back to whole-object writes
    supports_rmw_striping = True

    #: profile keys consumed by init (reference: parse() per plugin)
    _PROFILE_KEYS = ("k", "m", "technique", "runtime", "plugin",
                     "crush-failure-domain", "crush-root",
                     "crush-device-class", "directory", "w", "packetsize")

    def __init__(self):
        self.k = 0
        self.m = 0
        self.technique = ""
        self.runtime = "tpu"   # "tpu" (device kernel) or "cpu" (numpy oracle)
        self._generator: np.ndarray | None = None
        self._encoder = None
        self._decode_cache: dict = {}
        self._chunk_mapping: list[int] = []

    # -- profile parsing (ErasureCode.cc:281-329 to_int/to_bool) --------------

    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile, default: int) -> int:
        v = profile.get(name, default)
        try:
            return int(v)
        except (TypeError, ValueError):
            raise ValueError(f"{name}={v!r} is not an integer")

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile, default: bool) -> bool:
        v = str(profile.get(name, default)).lower()
        return v in ("true", "1", "yes")

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        self._generator = np.asarray(self._build_generator(), dtype=np.uint8)
        assert self._generator.shape == (self.k + self.m, self.k)
        self._encoder = None
        self._decode_cache.clear()

    def parse(self, profile: ErasureCodeProfile) -> None:
        """Subclasses override to parse technique-specific keys; must set k, m."""
        self.k = self.to_int("k", profile, self._default_k())
        self.m = self.to_int("m", profile, self._default_m())
        self.runtime = profile.get("runtime", "tpu")
        if self.k < 1 or self.m < 1:
            raise ValueError(f"k={self.k} m={self.m} must be >= 1")
        unknown = set(profile) - set(self._PROFILE_KEYS)
        if unknown:
            raise ValueError(f"unknown profile keys {sorted(unknown)}")

    def _default_k(self) -> int:
        return 7

    def _default_m(self) -> int:
        return 3

    def _build_generator(self) -> np.ndarray:
        raise NotImplementedError

    @property
    def generator(self) -> np.ndarray:
        assert self._generator is not None, "init() not called"
        return self._generator

    # -- chunk geometry -------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        """Bytes the object must pad to before splitting into k chunks."""
        return self.k * SIMD_ALIGN

    def get_chunk_size(self, stripe_width: int) -> int:
        """ErasureCodeJerasure::get_chunk_size semantics: pad the object to the
        alignment quantum, then divide by k."""
        alignment = self.get_alignment()
        padded = (stripe_width + alignment - 1) // alignment * alignment
        return padded // self.k

    # -- minimum_to_decode (ErasureCode.cc:89-106) ----------------------------

    def minimum_to_decode(self, want_to_read: set, available: set) -> set:
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise IOError(
                f"cannot decode {sorted(want_to_read)}: only "
                f"{len(available)} of k={self.k} chunks available")
        return set(sorted(available)[:self.k])

    # -- encode (ErasureCode.cc:137-190) --------------------------------------

    def encode_prepare(self, data: bytes) -> np.ndarray:
        """Pad + split into (k, chunk) uint8 — zero-fill tail chunks
        (ErasureCode.cc:137-172)."""
        chunk = self.get_chunk_size(len(data))
        padded = np.zeros(self.k * chunk, dtype=np.uint8)
        padded[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        return padded.reshape(self.k, chunk)

    def encode(self, want_to_encode: set, data: bytes) -> dict:
        chunks = self.encode_prepare(data)
        parity = np.asarray(self.encode_chunks(chunks[None]))[0]
        allc = {i: chunks[i].tobytes() for i in range(self.k)}
        allc.update({self.k + i: parity[i].tobytes() for i in range(self.m)})
        return {i: allc[i] for i in want_to_encode}

    def encode_chunks(self, data_chunks):
        """(S, k, B) uint8 -> (S, m, B) uint8 on the selected runtime.

        runtime "tpu" runs the batched MXU kernel, "native" the in-repo
        single-core C SIMD encode (the ISA-L-class plugin proper — same
        role as the reference's isa plugin on hosts without the device),
        and "cpu" the numpy oracle (verification)."""
        coding = self.generator[self.k:]
        if self.runtime == "cpu":
            return ec_encode_ref(coding, np.asarray(data_chunks))
        if self.runtime == "native":
            from ceph_tpu.native import ec_encode_native
            return ec_encode_native(coding, np.asarray(data_chunks))
        if self._encoder is None:
            from ceph_tpu.ops.gf_kernel import make_encoder
            self._encoder = make_encoder(coding)
        return self._encoder(np.asarray(data_chunks, dtype=np.uint8))

    def submit_chunks(self, engine, data_chunks):
        """Submit an (S, k, B) encode through a dispatch engine
        (ops.dispatch): returns a DispatchFuture of the (S, m, B)
        parity.  Concurrent submits against the same codec and chunk
        width coalesce on the stripe axis into one device call; the
        engine's zero-stripe padding is bit-exact here because the code
        is linear (zeros encode to zeros)."""
        data = np.asarray(data_chunks, dtype=np.uint8)
        key = ("ec_encode", id(self), self.k, self.m, data.shape[-1],
               self.runtime)
        cache_entries = None
        if self.runtime == "tpu":
            from ceph_tpu.ops.gf_kernel import _jit_entries
            cache_entries = _jit_entries
        return engine.submit(key, self.encode_chunks, data,
                             label="ec_encode",
                             cache_entries=cache_entries)

    # -- decode (ErasureCode.cc:198-234 / ErasureCodeIsa.cc:150-310) ----------

    def _recovery(self, chosen: tuple, targets: tuple) -> np.ndarray:
        """LRU-ish cached recovery matrix (ErasureCodeIsaTableCache analog)."""
        key = (chosen, targets)
        if key not in self._decode_cache:
            if len(self._decode_cache) > 256:
                self._decode_cache.clear()
            self._decode_cache[key] = recovery_matrix(
                self.generator, list(chosen), list(targets))
        return self._decode_cache[key]

    def decode_chunks(self, chosen, chunks, targets):
        """chunks: (S, k, B) uint8 rows ``chosen`` -> (S, len(targets), B)."""
        rmat = self._recovery(tuple(chosen), tuple(targets))
        if self.runtime == "cpu":
            return ec_encode_ref(rmat, np.asarray(chunks))
        if self.runtime == "native":
            from ceph_tpu.native import ec_encode_native
            return ec_encode_native(rmat, np.asarray(chunks))
        from ceph_tpu.ops.gf_kernel import ec_encode_jax
        return ec_encode_jax(rmat, np.asarray(chunks, dtype=np.uint8))

    def decode(self, want_to_read: set, chunks: dict) -> dict:
        available = set(chunks)
        out = {i: chunks[i] for i in want_to_read & available}
        missing = sorted(want_to_read - available)
        if not missing:
            return out
        if len(available) < self.k:
            raise IOError(
                f"cannot decode {missing}: only {len(available)} of "
                f"k={self.k} chunks available")
        chosen = sorted(available)[:self.k]
        arr = np.stack([np.frombuffer(chunks[i], dtype=np.uint8)
                        for i in chosen])
        rebuilt = np.asarray(self.decode_chunks(chosen, arr[None], missing))[0]
        for idx, i in enumerate(missing):
            out[i] = rebuilt[idx].tobytes()
        return out

    # -- chunk remapping (ErasureCode.cc:260-279) -----------------------------

    @staticmethod
    def to_mapping(mapping: str) -> list[int]:
        """Parse a mapping string like "_DDD_DD" — 'D' positions hold chunks,
        other characters are gaps (used by LRC; ErasureCode.cc:260-279)."""
        out = []
        for pos, c in enumerate(mapping):
            if c == "D":
                out.append(pos)
        return out

    def get_chunk_mapping(self) -> list:
        return list(self._chunk_mapping)

    # -- CRUSH rule (ErasureCode.cc:53-72) ------------------------------------

    def create_rule(self, name: str, crush_map) -> int:
        from ceph_tpu.crush.builder import add_simple_rule
        return add_simple_rule(crush_map, -1, 0, "indep")
