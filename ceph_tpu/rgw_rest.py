"""RGW S3 REST frontend — an authenticated HTTP gateway over rgw_lite.

The reference's radosgw is an HTTP server (civetweb/asio frontends,
src/rgw/rgw_asio_frontend.cc) that parses S3's REST dialect
(src/rgw/rgw_rest_s3.cc), authenticates AWS signatures
(src/rgw/rgw_auth_s3.cc), and maps operations onto the RADOS layout
(src/rgw/rgw_rados.cc).  This module is that surface over the rgw_lite
storage mapping, sized to the repo:

* stdlib ThreadingHTTPServer frontend (the asio/civetweb analog)
* AWS Signature V4: full canonical-request -> string-to-sign -> derived
  signing key verification (UNSIGNED-PAYLOAD and sha256 payloads), with
  access keys provisioned against the cluster's auth key material
* bucket ops: PUT/DELETE/GET(list) with ListObjectsV2 pagination
  (max-keys / continuation-token / IsTruncated)
* object ops: PUT (with x-amz-meta-*), GET, HEAD, DELETE
* multipart upload: initiate (POST ?uploads), UploadPart
  (PUT ?partNumber&uploadId), complete (POST ?uploadId), abort
  (DELETE ?uploadId) — parts staged as rgw_lite objects and
  concatenated on complete (rgw_rest_s3.cc multipart flow)

Error responses use the S3 XML error envelope with the usual codes
(NoSuchBucket, NoSuchKey, SignatureDoesNotMatch, BucketNotEmpty...).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ceph_tpu.rgw_lite import Bucket

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


# ---------------------------------------------------------------------------
# AWS Signature V4
# ---------------------------------------------------------------------------

def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_signing_key(secret: str, date: str, region: str,
                      service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(query: str) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    enc = [(urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~")) for k, v in pairs]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def sign_request(method: str, path: str, query: str, headers: dict,
                 payload_sha: str, access: str, secret: str,
                 region: str = "default") -> str:
    """Produce the Authorization header value for a request (used by the
    server to verify and by test clients to sign)."""
    amzdate = headers["x-amz-date"]
    date = amzdate[:8]
    signed = sorted(h.lower() for h in ("host", "x-amz-content-sha256",
                                        "x-amz-date") if h in
                    {k.lower() for k in headers})
    canon_headers = "".join(
        f"{h}:{_header(headers, h).strip()}\n" for h in signed)
    # S3's no-double-encode rule: the canonical URI is the path exactly
    # as sent on the wire (already percent-encoded by the client); both
    # signer and verifier must use it verbatim or encoded keys 403
    creq = "\n".join([
        method, path,
        canonical_query(query), canon_headers, ";".join(signed),
        payload_sha])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(sigv4_signing_key(secret, date, region), sts.encode(),
                   hashlib.sha256).hexdigest()
    return (f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")


def _header(headers: dict, name: str) -> str:
    for k, v in headers.items():
        if k.lower() == name.lower():
            return v
    return ""


_AUTH_RE = re.compile(
    r"AWS4-HMAC-SHA256 Credential=(?P<access>[^/]+)/(?P<date>\d{8})/"
    r"(?P<region>[^/]+)/s3/aws4_request,\s*"
    r"SignedHeaders=(?P<signed>[^,]+),\s*Signature=(?P<sig>[0-9a-f]+)")


# ---------------------------------------------------------------------------
# XML helpers (no external deps; S3's dialect is shallow)
# ---------------------------------------------------------------------------

def _x(tag: str, body: str) -> str:
    return f"<{tag}>{body}</{tag}>"


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def _error_xml(code: str, message: str) -> bytes:
    return (f'<?xml version="1.0" encoding="UTF-8"?>'
            f'<Error>{_x("Code", code)}{_x("Message", _esc(message))}'
            f"</Error>").encode()


_ERR_STATUS = {"NoSuchBucket": 404, "NoSuchKey": 404, "NoSuchUpload": 404,
               "BucketNotEmpty": 409, "BucketAlreadyExists": 409,
               "SignatureDoesNotMatch": 403, "AccessDenied": 403,
               "InvalidPart": 400, "MalformedXML": 400,
               "InvalidArgument": 400, "RequestTimeTooSkewed": 403}


class S3Error(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code
        self.status = _ERR_STATUS.get(code, 400)


# ---------------------------------------------------------------------------
# gateway
# ---------------------------------------------------------------------------

class S3Gateway:
    """The op layer: S3 verbs -> rgw_lite buckets over one ioctx."""

    MP_PREFIX = ".mp"

    def __init__(self, ioctx, compression: str = "none"):
        self.io = ioctx
        self.compression = compression
        self._lock = threading.Lock()

    def _bucket(self, name: str, must_exist: bool = True) -> Bucket:
        b = Bucket(self.io, name, compression=self.compression)
        if must_exist and not b.exists():
            raise S3Error("NoSuchBucket", name)
        return b

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, name: str) -> None:
        b = Bucket(self.io, name, compression=self.compression)
        if b.exists():
            raise S3Error("BucketAlreadyExists", name)
        b.create()

    def delete_bucket(self, name: str) -> None:
        b = self._bucket(name)
        try:
            b.delete()
        except OSError:
            raise S3Error("BucketNotEmpty", name)

    def list_objects(self, name: str, prefix: str, max_keys: int,
                     token: str) -> tuple[list[tuple[str, dict]], str]:
        """ListObjectsV2: (entries, next_token); '' token = done."""
        b = self._bucket(name)
        keys = [k for k in b.list(prefix=prefix)
                if not k.startswith(self.MP_PREFIX + ".")]
        if token:
            keys = [k for k in keys if k > token]
        page = keys[:max_keys]
        next_token = page[-1] if len(keys) > max_keys else ""
        out = []
        for k in page:
            try:
                out.append((k, b.head(k)))
            except KeyError:
                continue
        return out, next_token

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   metadata: dict) -> str:
        if key.startswith(self.MP_PREFIX + "."):
            raise S3Error("InvalidArgument",
                          f"key prefix {self.MP_PREFIX!r}. is reserved "
                          "for multipart staging")
        b = self._bucket(bucket)
        b.put(key, data, metadata=metadata)
        return hashlib.md5(data).hexdigest()

    def get_object(self, bucket: str, key: str) -> tuple[bytes, dict]:
        b = self._bucket(bucket)
        try:
            head = b.head(key)
            return b.get(key), head
        except KeyError:
            raise S3Error("NoSuchKey", key)

    def head_object(self, bucket: str, key: str) -> dict:
        try:
            return self._bucket(bucket).head(key)
        except KeyError:
            raise S3Error("NoSuchKey", key)

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            self._bucket(bucket).delete_object(key)
        except KeyError:
            pass   # S3 DELETE is idempotent

    # -- multipart -----------------------------------------------------------

    def _mp_key(self, upload_id: str, part: int | None = None) -> str:
        base = f"{self.MP_PREFIX}.{upload_id}"
        return base if part is None else f"{base}.{part:05d}"

    def initiate_multipart(self, bucket: str, key: str,
                           metadata: dict) -> str:
        with self._lock:
            b = self._bucket(bucket)
            upload_id = hashlib.sha1(
                f"{bucket}/{key}/{time.time_ns()}".encode()).hexdigest()[:16]
            b.put(self._mp_key(upload_id), json.dumps(
                {"key": key, "meta": metadata}).encode())
            return upload_id

    def _mp_manifest(self, b: Bucket, upload_id: str) -> dict:
        try:
            return json.loads(b.get(self._mp_key(upload_id)).decode())
        except KeyError:
            raise S3Error("NoSuchUpload", upload_id)

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part: int, data: bytes) -> str:
        b = self._bucket(bucket)
        self._mp_manifest(b, upload_id)
        b.put(self._mp_key(upload_id, part), data)
        return hashlib.md5(data).hexdigest()

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           parts: list[tuple[int, str]]) -> str:
        # serialized: complete reads parts then deletes them; two racing
        # completes (or a racing abort) must not interleave
        with self._lock:
            return self._complete_locked(bucket, key, upload_id, parts)

    def _complete_locked(self, bucket: str, key: str, upload_id: str,
                         parts: list[tuple[int, str]]) -> str:
        b = self._bucket(bucket)
        manifest = self._mp_manifest(b, upload_id)
        chunks = []
        for num, etag in parts:
            try:
                data = b.get(self._mp_key(upload_id, num))
            except KeyError:
                raise S3Error("InvalidPart", f"part {num} missing")
            if etag and hashlib.md5(data).hexdigest() != etag.strip('"'):
                raise S3Error("InvalidPart", f"part {num} etag mismatch")
            chunks.append(data)
        whole = b"".join(chunks)
        b.put(key, whole, metadata=manifest.get("meta") or {})
        self._abort_locked(b, upload_id)
        return hashlib.md5(whole).hexdigest()

    def abort_multipart(self, bucket: str, key: str,
                        upload_id: str) -> None:
        with self._lock:
            self._abort_locked(self._bucket(bucket), upload_id)

    def _abort_locked(self, b: Bucket, upload_id: str) -> None:
        for k in b.list(prefix=f"{self.MP_PREFIX}.{upload_id}"):
            try:
                b.delete_object(k)
            except KeyError:
                pass


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ceph-tpu-rgw/1.0"

    def log_message(self, fmt, *args):   # quiet
        pass

    # -- auth ----------------------------------------------------------------

    def _authenticate(self, body: bytes) -> None:
        srv: "RgwRestServer" = self.server.rgw     # type: ignore
        auth = self.headers.get("Authorization", "")
        m = _AUTH_RE.match(auth)
        if not m:
            raise S3Error("AccessDenied", "missing or malformed auth")
        secret = srv.keys.get(m.group("access"))
        if secret is None:
            raise S3Error("AccessDenied", "unknown access key")
        payload_sha = self.headers.get("x-amz-content-sha256",
                                       "UNSIGNED-PAYLOAD")
        if payload_sha != "UNSIGNED-PAYLOAD":
            # the signature only binds the HEADER value; the body must
            # match it or a captured signature could carry any payload
            if hashlib.sha256(body).hexdigest() != payload_sha:
                raise S3Error("SignatureDoesNotMatch",
                              "payload hash mismatch")
        amzdate = self.headers.get("x-amz-date", "")
        if not re.match(r"\d{8}T\d{6}Z$", amzdate):
            raise S3Error("AccessDenied", "missing or malformed x-amz-date")
        # freshness: AWS rejects requests outside a ~15-minute skew
        # window — without it any captured signature replays forever
        skew = getattr(srv, "max_skew", 900.0)
        if skew is not None:
            try:
                ts = datetime.datetime.strptime(
                    amzdate, "%Y%m%dT%H%M%SZ").replace(
                    tzinfo=datetime.timezone.utc).timestamp()
            except ValueError:   # 8+6 digits but not a real timestamp
                raise S3Error("AccessDenied", "malformed x-amz-date")
            if abs(srv.clock() - ts) > skew:
                raise S3Error("RequestTimeTooSkewed",
                              "request time too skewed")
        parsed = urllib.parse.urlsplit(self.path)
        hdrs = {"host": self.headers.get("Host", ""),
                "x-amz-date": amzdate,
                "x-amz-content-sha256": payload_sha}
        expect = sign_request(self.command, parsed.path, parsed.query,
                              hdrs, payload_sha, m.group("access"),
                              secret, m.group("region"))
        want_sig = _AUTH_RE.match(expect).group("sig")
        if not hmac.compare_digest(want_sig, m.group("sig")):
            raise S3Error("SignatureDoesNotMatch", "bad signature")

    # -- plumbing ------------------------------------------------------------

    def _respond(self, status: int, body: bytes = b"",
                 headers: dict | None = None) -> None:
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _dispatch(self) -> None:
        gw: S3Gateway = self.server.rgw.gateway     # type: ignore
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        try:
            self._authenticate(body)
            parsed = urllib.parse.urlsplit(self.path)
            q = dict(urllib.parse.parse_qsl(parsed.query,
                                            keep_blank_values=True))
            parts = parsed.path.lstrip("/").split("/", 1)
            bucket = urllib.parse.unquote(parts[0])
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            self._route(gw, self.command, bucket, key, q, body)
        except S3Error as e:
            self._respond(e.status, _error_xml(e.code, str(e)),
                          {"Content-Type": "application/xml"})
        except Exception as e:   # pragma: no cover
            self._respond(500, _error_xml("InternalError", repr(e)),
                          {"Content-Type": "application/xml"})

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _dispatch

    # -- routing -------------------------------------------------------------

    def _route(self, gw: S3Gateway, method: str, bucket: str, key: str,
               q: dict, body: bytes) -> None:
        if not bucket:
            raise S3Error("InvalidArgument", "service-level ops: none")
        if not key:
            return self._route_bucket(gw, method, bucket, q)
        if method == "POST" and "uploads" in q:
            meta = self._meta_headers()
            uid = gw.initiate_multipart(bucket, key, meta)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<InitiateMultipartUploadResult>"
                   + _x("Bucket", _esc(bucket)) + _x("Key", _esc(key))
                   + _x("UploadId", uid)
                   + "</InitiateMultipartUploadResult>").encode()
            return self._respond(200, xml)
        if method == "PUT" and "uploadId" in q and "partNumber" in q:
            etag = gw.upload_part(bucket, key, q["uploadId"],
                                  int(q["partNumber"]), body)
            return self._respond(200, b"", {"ETag": f'"{etag}"'})
        if method == "POST" and "uploadId" in q:
            text = body.decode(errors="replace")
            parts = []
            for block in re.findall(r"<Part>(.*?)</Part>", text, re.S):
                num = re.search(r"<PartNumber>\s*(\d+)\s*</PartNumber>",
                                block)
                if num is None:
                    raise S3Error("MalformedXML", "part without number")
                et = re.search(
                    r"<ETag>\s*(?:&quot;|\")?([0-9a-f]+)", block)
                parts.append((int(num.group(1)),
                              et.group(1) if et else ""))
            if not parts:
                raise S3Error("MalformedXML", "no parts")
            etag = gw.complete_multipart(bucket, key, q["uploadId"],
                                         parts)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<CompleteMultipartUploadResult>"
                   + _x("Key", _esc(key)) + _x("ETag", f'"{etag}"')
                   + "</CompleteMultipartUploadResult>").encode()
            return self._respond(200, xml)
        if method == "DELETE" and "uploadId" in q:
            gw.abort_multipart(bucket, key, q["uploadId"])
            return self._respond(204)
        if method == "PUT":
            etag = gw.put_object(bucket, key, body, self._meta_headers())
            return self._respond(200, b"", {"ETag": f'"{etag}"'})
        if method == "GET":
            data, head = gw.get_object(bucket, key)
            hdrs = {"Content-Type": "application/octet-stream",
                    "ETag": f'"{hashlib.md5(data).hexdigest()}"'}
            for mk, mv in (head.get("meta") or {}).items():
                hdrs[f"x-amz-meta-{mk}"] = mv
            return self._respond(200, data, hdrs)
        if method == "HEAD":
            head = gw.head_object(bucket, key)
            return self._respond(200, b"", {
                "Content-Length-Hint": str(head["size"])})
        if method == "DELETE":
            gw.delete_object(bucket, key)
            return self._respond(204)
        raise S3Error("InvalidArgument", f"unsupported {method}")

    def _route_bucket(self, gw: S3Gateway, method: str, bucket: str,
                      q: dict) -> None:
        if method == "PUT":
            gw.create_bucket(bucket)
            return self._respond(200)
        if method == "DELETE":
            gw.delete_bucket(bucket)
            return self._respond(204)
        if method == "GET":
            max_keys = max(1, min(int(q.get("max-keys", 1000)), 1000))
            entries, next_token = gw.list_objects(
                bucket, q.get("prefix", ""), max_keys,
                q.get("continuation-token", ""))
            items = "".join(
                "<Contents>" + _x("Key", _esc(k))
                + _x("Size", str(h.get("size", 0)))
                + _x("LastModified", datetime.datetime.fromtimestamp(
                    h.get("mtime", 0),
                    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"))
                + "</Contents>"
                for k, h in entries)
            xml = ('<?xml version="1.0" encoding="UTF-8"?>'
                   "<ListBucketResult>"
                   + _x("Name", _esc(bucket))
                   + _x("KeyCount", str(len(entries)))
                   + _x("IsTruncated", "true" if next_token else "false")
                   + (_x("NextContinuationToken", _esc(next_token))
                      if next_token else "")
                   + items + "</ListBucketResult>").encode()
            return self._respond(200, xml,
                                 {"Content-Type": "application/xml"})
        raise S3Error("InvalidArgument", f"unsupported {method} on bucket")

    def _meta_headers(self) -> dict:
        return {k[len("x-amz-meta-"):]: v for k, v in self.headers.items()
                if k.lower().startswith("x-amz-meta-")}


class RgwRestServer:
    """The radosgw daemon shell: HTTP frontend + gateway + key table.

    Access keys are provisioned from cluster auth material:
    ``add_key(access, secret)``; with a cephx-lite cluster key,
    ``provision_from_cephx(key)`` derives a deterministic S3 credential
    pair from it (the AuthMonitor-issues-rgw-credentials analog).
    """

    def __init__(self, ioctx, addr: str = "127.0.0.1:0",
                 compression: str = "none",
                 max_skew: float | None = 900.0, clock=time.time):
        self.gateway = S3Gateway(ioctx, compression=compression)
        self.keys: dict[str, str] = {}
        #: SigV4 freshness window in seconds (AWS: 15 min); None
        #: disables the check.  clock is injectable for tests.
        self.max_skew = max_skew
        self.clock = clock
        host, port = addr.rsplit(":", 1)
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.rgw = self          # type: ignore
        self._thread: threading.Thread | None = None

    @property
    def addr(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"{h}:{p}"

    def add_key(self, access: str, secret: str) -> None:
        self.keys[access] = secret

    def provision_from_cephx(self, cluster_key: bytes | str
                             ) -> tuple[str, str]:
        if isinstance(cluster_key, str):
            cluster_key = cluster_key.encode()
        access = "AK" + hashlib.sha256(b"rgw-access" + cluster_key
                                       ).hexdigest()[:18].upper()
        secret = hashlib.sha256(b"rgw-secret" + cluster_key).hexdigest()
        self.add_key(access, secret)
        return access, secret

    def start(self) -> "RgwRestServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rgw-http", daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
