"""Typed message catalog (src/messages/ analog — the data-path subset).

Each type mirrors its reference counterpart's role:

  MOSDOp / MOSDOpReply          client I/O       (messages/MOSDOp.h)
  MOSDRepOp / MOSDRepOpReply    replication      (messages/MOSDRepOp.h)
  MOSDECSubOpWrite/Read(+Reply) EC shard fan-out (messages/MOSDECSubOpWrite.h)
  MOSDPing                      heartbeats       (messages/MOSDPing.h)
  MOSDFailure                   failure reports  (messages/MOSDFailure.h)
  MOSDMapMsg                    map distribution (messages/MOSDMap.h)
  MMonCommand / MMonCommandAck  admin commands   (messages/MMonCommand.h)
"""

from .osd_msgs import (
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMapMsg,
    MOSDOp,
    MOSDOpReply,
    MOSDPing,
    MOSDRepOp,
    MOSDRepOpReply,
    MMonCommand,
    MPGStats,
    MMonCommandAck,
    OSDOpField,
)

__all__ = [
    "MOSDOp", "MOSDOpReply", "MOSDRepOp", "MOSDRepOpReply",
    "MOSDECSubOpWrite", "MOSDECSubOpWriteReply",
    "MOSDECSubOpRead", "MOSDECSubOpReadReply",
    "MOSDPing", "MOSDFailure", "MOSDMapMsg",
    "MMonCommand",
    "MPGStats", "MMonCommandAck", "OSDOpField",
]
