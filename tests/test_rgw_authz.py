"""RGW object-level authorization (rgw_acl.h:34-120 grant lists,
rgw_iam_policy.cc:620-880 policy evaluator, rgw_cors.cc): a second user
gets per-object access without the bucket going public, an explicit
Deny overrides a grant, and CORS preflight passes — all over real HTTP
with SigV4."""

from __future__ import annotations

import json

import pytest

from ceph_tpu import rgw_auth
from ceph_tpu.rgw_rest import RgwRestServer
from ceph_tpu.tools.vstart import MiniCluster

from test_rgw_versioning import S3Client


@pytest.fixture(scope="module")
def rig():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    pool = c.create_pool(client, pg_num=4, size=2)
    srv = RgwRestServer(client.open_ioctx(pool),
                        max_skew=None).start()
    srv.add_key("alice", "alice-secret")
    srv.add_key("bob", "bob-secret")
    yield {"cluster": c, "srv": srv,
           "alice": S3Client(srv.addr, "alice", "alice-secret"),
           "bob": S3Client(srv.addr, "bob", "bob-secret"),
           "anon": S3Client(srv.addr, None)}
    srv.shutdown()
    c.stop()


# -- pure evaluator units ---------------------------------------------------

def test_policy_parse_and_precedence():
    doc = {"Version": "2012-10-17", "Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::b/*"},
        {"Effect": "Deny", "Principal": {"AWS": "mallory"},
         "Action": "s3:*", "Resource": "arn:aws:s3:::b/*"},
    ]}
    pol = rgw_auth.BucketPolicy.parse(json.dumps(doc))
    assert pol.evaluate("anyone", "s3:GetObject", "b", "k") == "Allow"
    assert pol.evaluate(None, "s3:GetObject", "b", "k") == "Allow"
    # Deny beats the * Allow for the named principal
    assert pol.evaluate("mallory", "s3:GetObject", "b", "k") == "Deny"
    # unmatched action/resource -> None (fall through to ACLs)
    assert pol.evaluate("anyone", "s3:PutObject", "b", "k") is None
    assert pol.evaluate("anyone", "s3:GetObject", "other", "k") is None
    with pytest.raises(rgw_auth.PolicyError):
        rgw_auth.BucketPolicy.parse('{"Statement": [{"Effect": "Maybe"}]}')


def test_acl_grant_semantics():
    grants = [{"grantee": "bob", "permission": "READ"},
              {"grantee": "carol", "permission": "FULL_CONTROL"}]
    assert rgw_auth.acl_allows(grants, "alice", "alice", rgw_auth.WRITE)
    assert rgw_auth.acl_allows(grants, "alice", "bob", rgw_auth.READ)
    assert not rgw_auth.acl_allows(grants, "alice", "bob",
                                   rgw_auth.WRITE)
    assert rgw_auth.acl_allows(grants, "alice", "carol",
                               rgw_auth.WRITE_ACP)
    assert not rgw_auth.acl_allows(grants, "alice", None,
                                   rgw_auth.READ)
    pub = rgw_auth.canned_grants("public-read", "alice")
    assert rgw_auth.acl_allows(pub, "alice", None, rgw_auth.READ)
    assert not rgw_auth.acl_allows(pub, "alice", None, rgw_auth.WRITE)


# -- REST: per-object grants ------------------------------------------------

def test_object_grant_without_bucket_public(rig):
    alice, bob, anon = rig["alice"], rig["bob"], rig["anon"]
    assert alice.request("PUT", "/projA")[0] == 200
    alice.request("PUT", "/projA/shared.txt", body=b"for bob")
    alice.request("PUT", "/projA/secret.txt", body=b"alice only")
    # bob can read NOTHING yet
    assert bob.request("GET", "/projA/shared.txt")[0] == 403
    # grant bob READ on the one object (header form)
    st, body, _ = alice.request(
        "PUT", "/projA/shared.txt", "acl",
        headers_extra={"x-amz-grant-read": "id=bob"})
    assert st == 200, body
    assert bob.request("GET", "/projA/shared.txt")[1] == b"for bob"
    # the grant is per-object: the rest of the bucket stays closed
    assert bob.request("GET", "/projA/secret.txt")[0] == 403
    assert bob.request("GET", "/projA")[0] == 403          # no listing
    assert anon.request("GET", "/projA/shared.txt")[0] == 403
    # bob still cannot write it
    assert bob.request("PUT", "/projA/shared.txt",
                       body=b"overwrite")[0] == 403
    # the object ACL reads back as grants XML
    st, body, _ = alice.request("GET", "/projA/shared.txt", "acl")
    assert st == 200 and b"bob" in body and b">READ<" in body


def test_object_acl_xml_body_and_acp_gates(rig):
    alice, bob = rig["alice"], rig["bob"]
    assert alice.request("PUT", "/projB")[0] == 200
    alice.request("PUT", "/projB/doc", body=b"v1")
    xml = (b"<AccessControlPolicy><AccessControlList>"
           b"<Grant><Grantee><ID>bob</ID></Grantee>"
           b"<Permission>FULL_CONTROL</Permission></Grant>"
           b"</AccessControlList></AccessControlPolicy>")
    assert alice.request("PUT", "/projB/doc", "acl", body=xml)[0] == 200
    # FULL_CONTROL: bob reads, writes, and may change the ACL
    assert bob.request("GET", "/projB/doc")[1] == b"v1"
    assert bob.request("PUT", "/projB/doc", body=b"v2")[0] == 200
    assert bob.request("GET", "/projB/doc", "acl")[0] == 200


# -- REST: bucket policy ----------------------------------------------------

def test_policy_allow_and_deny_override(rig):
    alice, bob, anon = rig["alice"], rig["bob"], rig["anon"]
    assert alice.request("PUT", "/polb")[0] == 200
    alice.request("PUT", "/polb/data.bin", body=b"payload")
    # grant bob READ via object grant, then DENY him via policy:
    # the Deny must win over the grant
    alice.request("PUT", "/polb/data.bin", "acl",
                  headers_extra={"x-amz-grant-read": "id=bob"})
    assert bob.request("GET", "/polb/data.bin")[0] == 200
    policy = json.dumps({"Version": "2012-10-17", "Statement": [
        {"Effect": "Deny", "Principal": {"AWS": "bob"},
         "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::polb/*"}]})
    assert alice.request("PUT", "/polb", "policy",
                         body=policy.encode())[0] == 204
    assert bob.request("GET", "/polb/data.bin")[0] == 403
    # a policy Allow opens anonymous reads without any ACL change
    policy2 = json.dumps({"Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::polb/*"},
        {"Effect": "Deny", "Principal": {"AWS": "bob"},
         "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::polb/*"}]})
    assert alice.request("PUT", "/polb", "policy",
                         body=policy2.encode())[0] == 204
    assert anon.request("GET", "/polb/data.bin")[1] == b"payload"
    assert bob.request("GET", "/polb/data.bin")[0] == 403   # still denied
    # GET/DELETE policy round-trip; non-owner denied
    assert bob.request("GET", "/polb", "policy")[0] == 403
    st, body, _ = alice.request("GET", "/polb", "policy")
    assert st == 200 and json.loads(body)["Statement"]
    assert alice.request("DELETE", "/polb", "policy")[0] == 204
    assert alice.request("GET", "/polb", "policy")[0] == 404
    assert anon.request("GET", "/polb/data.bin")[0] == 403
    # malformed policy refused
    assert alice.request("PUT", "/polb", "policy",
                         body=b'{"Statement": "nope"}')[0] == 400


# -- REST: CORS -------------------------------------------------------------

def test_cors_preflight_and_response_headers(rig):
    alice = rig["alice"]
    assert alice.request("PUT", "/corsb")[0] == 200
    alice.request("PUT", "/corsb/asset.js", body=b"js",
                  headers_extra={"x-amz-acl": "public-read"})
    alice.request("PUT", "/corsb", "acl",
                  headers_extra={"x-amz-acl": "public-read"})
    cors = (b"<CORSConfiguration><CORSRule>"
            b"<AllowedOrigin>https://app.example.com</AllowedOrigin>"
            b"<AllowedMethod>GET</AllowedMethod>"
            b"<AllowedHeader>content-type</AllowedHeader>"
            b"<MaxAgeSeconds>600</MaxAgeSeconds>"
            b"</CORSRule></CORSConfiguration>")
    assert alice.request("PUT", "/corsb", "cors", body=cors)[0] == 200
    # preflight: matching origin+method passes with the CORS headers
    anon = rig["anon"]
    st, _b, hdrs = anon.request(
        "OPTIONS", "/corsb/asset.js",
        headers_extra={"Origin": "https://app.example.com",
                       "Access-Control-Request-Method": "GET",
                       "Access-Control-Request-Headers":
                       "content-type"})
    assert st == 200, hdrs
    assert hdrs.get("Access-Control-Allow-Origin") \
        == "https://app.example.com"
    assert "GET" in hdrs.get("Access-Control-Allow-Methods", "")
    assert hdrs.get("Access-Control-Max-Age") == "600"
    # non-matching origin or method: preflight refused
    st, _b, _h = anon.request(
        "OPTIONS", "/corsb/asset.js",
        headers_extra={"Origin": "https://evil.example.net",
                       "Access-Control-Request-Method": "GET"})
    assert st == 403
    st, _b, _h = anon.request(
        "OPTIONS", "/corsb/asset.js",
        headers_extra={"Origin": "https://app.example.com",
                       "Access-Control-Request-Method": "DELETE"})
    assert st == 403
    # simple request: the actual GET carries the allow-origin header
    st, body, hdrs = anon.request(
        "GET", "/corsb/asset.js",
        headers_extra={"Origin": "https://app.example.com"})
    assert st == 200 and body == b"js"
    assert hdrs.get("Access-Control-Allow-Origin") \
        == "https://app.example.com"
    # config round-trip + delete
    st, body, _ = alice.request("GET", "/corsb", "cors")
    assert st == 200 and b"app.example.com" in body
    assert alice.request("DELETE", "/corsb", "cors")[0] == 204
    assert alice.request("GET", "/corsb", "cors")[0] == 404


def test_copy_object(rig):
    """S3 CopyObject (x-amz-copy-source): server-side copy, source READ
    authorized, metadata COPY vs REPLACE directives."""
    alice, bob = rig["alice"], rig["bob"]
    assert alice.request("PUT", "/srcb")[0] == 200
    assert alice.request("PUT", "/dstb")[0] == 200
    st, _b, _h = alice.request(
        "PUT", "/srcb/orig", body=b"copy me",
        headers_extra={"x-amz-meta-color": "blue"})
    assert st == 200
    # COPY directive (default): metadata travels
    st, body, _ = alice.request(
        "PUT", "/dstb/copied",
        headers_extra={"x-amz-copy-source": "/srcb/orig"})
    assert st == 200 and b"CopyObjectResult" in body
    st, body, hdrs = alice.request("GET", "/dstb/copied")
    assert st == 200 and body == b"copy me"
    assert hdrs.get("x-amz-meta-color") == "blue"
    # REPLACE directive: new metadata only
    st, _b, _h = alice.request(
        "PUT", "/dstb/copied2",
        headers_extra={"x-amz-copy-source": "/srcb/orig",
                       "x-amz-metadata-directive": "REPLACE",
                       "x-amz-meta-shape": "round"})
    assert st == 200
    st, _body, hdrs = alice.request("GET", "/dstb/copied2")
    assert hdrs.get("x-amz-meta-shape") == "round"
    assert "x-amz-meta-color" not in hdrs
    # bob cannot copy FROM a bucket he cannot read
    st, _b, _h = bob.request(
        "PUT", "/dstb/stolen",
        headers_extra={"x-amz-copy-source": "/srcb/orig"})
    assert st == 403


def test_pool_users_and_radosgw_admin(rig):
    """radosgw-admin-created users live in the pool registry and
    authenticate through any gateway over it."""
    import subprocess
    import sys as _sys

    from ceph_tpu.tools import rgw_admin_cli
    c = rig["cluster"]
    srv = rig["srv"]
    pool = srv.gateway.io.pool_id
    base = ["--mon", c.mon_host, "-p", str(pool),
            "--ms-type", "loopback"]
    import io as _io
    out = _io.StringIO()
    real = _sys.stdout
    _sys.stdout = out
    try:
        assert rgw_admin_cli.main(
            base + ["user", "create", "--uid", "carol",
                    "--access", "AKCAROL000", "--secret",
                    "carol-secret"]) == 0
        assert rgw_admin_cli.main(base + ["user", "ls"]) == 0
        assert rgw_admin_cli.main(
            base + ["user", "info", "--uid", "carol"]) == 0
    finally:
        _sys.stdout = real
    assert "carol" in out.getvalue()
    # the pool-registered user authenticates via the RUNNING gateway
    # (read-through cache, no restart)
    from test_rgw_versioning import S3Client
    carol = S3Client(srv.addr, "AKCAROL000", "carol-secret")
    assert carol.request("PUT", "/carols-bucket")[0] == 200
    assert carol.request("PUT", "/carols-bucket/o",
                         body=b"hi")[0] == 200
    assert carol.request("GET", "/carols-bucket/o")[1] == b"hi"
    # rm revokes (after the cache TTL)
    assert rgw_admin_cli.main(base + ["user", "rm", "--uid",
                                      "carol"]) == 0
    import time as _t
    _t.sleep(srv.USER_CACHE_TTL + 0.5)
    assert carol.request("GET", "/carols-bucket/o")[0] == 403


def test_upload_part_copy(rig):
    """S3 UploadPartCopy: multipart parts sourced from an existing
    object, full and ranged; a part from an unreadable source is
    refused."""
    alice, bob = rig["alice"], rig["bob"]
    assert alice.request("PUT", "/mpc-src")[0] == 200
    assert alice.request("PUT", "/mpc-dst")[0] == 200
    blob = bytes(range(256)) * 64          # 16 KiB source
    assert alice.request("PUT", "/mpc-src/big", body=blob)[0] == 200
    st, body, _ = alice.request("POST", "/mpc-dst/assembled",
                                "uploads")
    assert st == 200
    import re as _re
    uid = _re.search(rb"<UploadId>([^<]+)</UploadId>", body).group(
        1).decode()
    # part 1: the whole source; part 2: a byte range; part 3: inline
    st, body, _ = alice.request(
        "PUT", "/mpc-dst/assembled", f"partNumber=1&uploadId={uid}",
        headers_extra={"x-amz-copy-source": "/mpc-src/big"})
    assert st == 200 and b"CopyPartResult" in body
    etag1 = _re.search(rb'<ETag>"?([0-9a-f]+)', body).group(1).decode()
    st, body, _ = alice.request(
        "PUT", "/mpc-dst/assembled", f"partNumber=2&uploadId={uid}",
        headers_extra={"x-amz-copy-source": "/mpc-src/big",
                       "x-amz-copy-source-range": "bytes=0-255"})
    assert st == 200
    etag2 = _re.search(rb'<ETag>"?([0-9a-f]+)', body).group(1).decode()
    st, body, _ = alice.request(
        "PUT", "/mpc-dst/assembled", f"partNumber=3&uploadId={uid}",
        body=b"tail")
    assert st == 200
    import hashlib as _h
    etag3 = _h.md5(b"tail").hexdigest()
    # a bad range on a LIVE upload: 400
    st, _b0, _h0 = alice.request(
        "PUT", "/mpc-dst/assembled", f"partNumber=4&uploadId={uid}",
        headers_extra={"x-amz-copy-source": "/mpc-src/big",
                       "x-amz-copy-source-range": "bytes=5-999999"})
    assert st == 400
    xml = ("<CompleteMultipartUpload>"
           + "".join(f"<Part><PartNumber>{n}</PartNumber>"
                     f"<ETag>\"{e}\"</ETag></Part>"
                     for n, e in ((1, etag1), (2, etag2), (3, etag3)))
           + "</CompleteMultipartUpload>").encode()
    st, _b, _h2 = alice.request("POST", "/mpc-dst/assembled",
                                f"uploadId={uid}", body=xml)
    assert st == 200
    st, got, _ = alice.request("GET", "/mpc-dst/assembled")
    assert st == 200 and got == blob + blob[:256] + b"tail"
    # after completion the uploadId is dead: NoSuchUpload, not 400
    st, _b2, _h3 = alice.request(
        "PUT", "/mpc-dst/assembled", f"partNumber=4&uploadId={uid}",
        headers_extra={"x-amz-copy-source": "/mpc-src/big",
                       "x-amz-copy-source-range": "bytes=5-999999"})
    assert st == 404
    # the SOURCE-read gate alone refuses: bob owns his destination
    # (dest write passes) but cannot read alice's source
    assert bob.request("PUT", "/bob-dst")[0] == 200
    st, body, _ = bob.request("POST", "/bob-dst/steal", "uploads")
    uid2 = _re.search(rb"<UploadId>([^<]+)</UploadId>", body).group(
        1).decode()
    st, _b3, _h4 = bob.request(
        "PUT", "/bob-dst/steal", f"partNumber=1&uploadId={uid2}",
        headers_extra={"x-amz-copy-source": "/mpc-src/big"})
    assert st == 403
