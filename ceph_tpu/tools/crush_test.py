"""crushtool --test analog (src/tools/crushtool.cc -> CrushTester::test,
src/crush/CrushTester.cc:472-560) with the per-x loop replaced by one batched
device call.

Usage:
    python -m ceph_tpu.tools.crush_test --num-rep 3 --min-x 0 --max-x 1023 \
        [--rule N] [--show-utilization] [--show-statistics] [--show-mappings] \
        [--osds N | --hosts H --per-host P] [--backend tpu|scalar]

Output matches the reference's shape: per-rule "rule N (name) num_rep R
result size == S:\tX/Y" lines, optional per-device utilization, and the
choose-tries-style batch statistics.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from ceph_tpu.crush import build_flat_map, build_two_level_map, crush_do_rule
from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE, RULE_CHOOSE_FIRSTN, RULE_EMIT, RULE_TAKE)


def _flat_firstn_operands(m, rid: int):
    """(ids, item_weights) when rule ``rid`` is the shape
    ``ops.crush_kernel.flat_firstn`` computes — ``take <straw2 root of
    devices> / choose firstn 0 osd / emit`` with stock tunables — else
    None and the caller uses the generic rule engine."""
    from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW2
    rule = m.rules[rid] if 0 <= rid < m.max_rules else None
    if rule is None or len(rule.steps) != 3:
        return None
    take, choose, emit = rule.steps
    if (take.op != RULE_TAKE or choose.op != RULE_CHOOSE_FIRSTN
            or choose.arg1 != 0 or choose.arg2 != 0
            or emit.op != RULE_EMIT):
        return None
    root = m.bucket(take.arg1)
    if (root is None or root.alg != CRUSH_BUCKET_STRAW2
            or any(i < 0 for i in root.items)
            or m.tunables != type(m.tunables)()
            or m.choose_args or m.class_bucket):
        return None
    return (np.asarray(root.items, dtype=np.int32),
            np.asarray(root.item_weights, dtype=np.int64))


def _dispatch_flat_firstn(flat, xs, num_rep: int, weight) -> list[list[int]]:
    """Bulk remap through the device dispatch engine: the x range rides
    ``submit_flat_firstn`` in engine-sized chunks, so chunk N+1's h2d
    overlaps chunk N's compute and concurrent callers against the same
    map coalesce into shared device calls (docs/PERF.md)."""
    from ceph_tpu.common.context import default_context
    from ceph_tpu.ops.dispatch import submit_flat_firstn
    ids, weights = flat
    reweight = np.asarray(weight, dtype=np.int64)
    eng = default_context().dispatch_engine()
    futs = [submit_flat_firstn(eng, xs[i:i + eng.max_stripes], ids,
                               weights, reweight, numrep=num_rep)
            for i in range(0, len(xs), eng.max_stripes)]
    out = np.concatenate([np.asarray(f.result()) for f in futs], axis=0)
    return [[int(v) for v in row if v != CRUSH_ITEM_NONE] for row in out]


def run_test(m, rules, min_x: int, max_x: int, num_rep: int,
             backend: str = "tpu", reweight=None,
             show_utilization: bool = False, show_mappings: bool = False,
             out=sys.stdout) -> dict:
    n = m.max_devices
    weight = reweight if reweight is not None else [0x10000] * n
    xs = np.arange(min_x, max_x + 1, dtype=np.uint32)
    stats = {}
    for rid in rules:
        t0 = time.perf_counter()
        if backend == "tpu":
            flat = _flat_firstn_operands(m, rid)
            if flat is not None:
                rows = _dispatch_flat_firstn(flat, xs, num_rep, weight)
            else:
                from ceph_tpu.crush.mapper_jax import BatchMapper
                bm = BatchMapper(m)
                res = np.asarray(bm.do_rule(
                    rid, xs, num_rep, np.asarray(weight, dtype=np.int64)))
                rows = [[int(v) for v in row if v != CRUSH_ITEM_NONE]
                        for row in res]
        else:
            rows = [crush_do_rule(m, rid, int(x), num_rep, list(weight))
                    for x in xs]
        dt = time.perf_counter() - t0
        sizes = {}
        util = np.zeros(n, dtype=np.int64)
        for row in rows:
            sizes[len(row)] = sizes.get(len(row), 0) + 1
            for o in row:
                util[o] += 1
        for size, count in sorted(sizes.items()):
            print(f"rule {rid} num_rep {num_rep} result size == {size}:\t"
                  f"{count}/{len(xs)}", file=out)
        if show_mappings:
            for x, row in zip(xs, rows):
                print(f"CRUSH rule {rid} x {x} {row}", file=out)
        if show_utilization:
            expected = util.sum() / max((util > 0).sum(), 1)
            for o in range(n):
                if util[o] or weight[o]:
                    print(f"  device {o}:\t\tstored : {util[o]}\t "
                          f"expected : {expected:.2f}", file=out)
        stats[rid] = {"sizes": sizes, "util": util.tolist(),
                      "elapsed_s": dt, "mappings_per_s": len(xs) / dt}
    return stats


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crush_test")
    p.add_argument("--num-rep", type=int, default=3)
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1023)
    p.add_argument("--rule", type=int, default=None)
    p.add_argument("--osds", type=int, default=None,
                   help="flat map with N osds")
    p.add_argument("--hosts", type=int, default=16)
    p.add_argument("--per-host", type=int, default=4)
    p.add_argument("--backend", choices=["tpu", "scalar"], default="tpu")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-mappings", action="store_true")
    args = p.parse_args(argv)

    if args.osds is not None:
        m, _root, rule = build_flat_map(args.osds)
    else:
        m, _root, rule = build_two_level_map(args.hosts, args.per_host)
    rules = [args.rule] if args.rule is not None else [rule]
    stats = run_test(m, rules, args.min_x, args.max_x, args.num_rep,
                     backend=args.backend,
                     show_utilization=args.show_utilization,
                     show_mappings=args.show_mappings)
    if args.show_statistics:
        for rid, s in stats.items():
            print(f"rule {rid}: {s['mappings_per_s']:.0f} mappings/s "
                  f"({s['elapsed_s']*1e3:.1f} ms)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
