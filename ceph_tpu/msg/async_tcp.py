"""TCP messenger stack.

API-equivalent to the reference's default AsyncMessenger (src/msg/async/);
internally thread-per-connection like its SimpleMessenger sibling — the
portable structure for a multi-process vstart harness.  Protocol v1-lite
(async/Protocol.h:103 analog):

    banner          b"ceph_tpu v1\\n" both ways
    announce        length-prefixed str(entity_name) both ways
    auth            [u8 mode][16B nonce] both ways, then an HMAC-SHA256
                    proof over the peer's fresh nonce (cephx-lite: the
                    src/auth/cephx challenge shape with a shared cluster
                    key standing in for the ticket infrastructure; fresh
                    nonces per connection give replay protection)
    compression     [u8 offered-mode] both ways; effective mode is the
                    min (0=off, 1=zlib) — msgr2 on-wire compression
                    negotiation (src/msg/async/compression_*)
    frames          [u32 length][u8 comp][Message.encode() bytes or its
                    zlib stream]   (crc inside the message)

Stateful policies reconnect on send failure and resend the queued backlog;
lossy connections drop and notify ms_handle_reset (msg/Policy.h semantics).
Hardening: frames above the policy byte cap are rejected, total in-dispatch
bytes ride a Throttle (msg/Policy.h throttler analog), and dead accepted
connections are reaped instead of leaking on reconnect storms.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import queue
import socket
import struct
import threading
import time
import zlib

from ceph_tpu.common import lockdep

from .message import Message
from .messenger import Connection, ConnectionPolicy, EntityName, Messenger

BANNER = b"ceph_tpu v1\n"
_LEN = struct.Struct("<I")

AUTH_NONE = 0
AUTH_CEPHX = 1

#: largest acceptable frame (DoS guard; the reference uses policy
#: throttles plus osd_max_write_size-scale caps)
MAX_FRAME = 256 << 20


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


from ceph_tpu.msg.features import FEAT_FRAME as _FEAT

#: on-wire compression modes (msgr2 compression negotiation analog)
COMP_NONE = 0
COMP_ZLIB = 1

#: frames below this many bytes ride uncompressed (header-dominated)
COMP_THRESHOLD = 1024


def _handshake(sock: socket.socket, my_name: EntityName,
               auth_key: bytes | None,
               auth_required: bool,
               comp_mode: int = COMP_NONE,
               cephx=None, accepted: bool = False,
               peer_type: str = "",
               features: int | None = None,
               required_fn=None,
               ) -> tuple[EntityName, int, str | None, int]:
    from ceph_tpu.auth.handshake import (
        AUTH_CEPHX_ENTITY, AUTH_CEPHX_TICKET, accept_ticket,
        entity_proof, proof as sess_proof, ticket_for)
    from ceph_tpu.msg.features import (
        FEATURE_WIRE_COMPRESSION, REQUIRED_DEFAULT, SUPPORTED_FEATURES,
        check_compat)
    if features is None:
        features = SUPPORTED_FEATURES
    sock.sendall(BANNER)
    got = _read_exact(sock, len(BANNER))
    if got != BANNER:
        raise ConnectionError(f"bad banner {got!r}")
    me = str(my_name).encode()
    sock.sendall(_LEN.pack(len(me)) + me)
    plen = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
    if plen > 256:
        raise ConnectionError("oversized name frame")
    peer = EntityName.parse(_read_exact(sock, plen).decode())

    # feature negotiation (ceph_features.h / Policy::features_required):
    # both advertise (supported, required-of-this-peer-type); unmet
    # requirements reject the session here, before auth or any message
    my_req = (required_fn(peer.type) if required_fn
              else REQUIRED_DEFAULT)
    sock.sendall(_FEAT.pack(features, my_req))
    pf, pr = _FEAT.unpack(_read_exact(sock, _FEAT.size))
    common = check_compat(str(peer), features, my_req, pf, pr)

    # auth phase: mode + fresh nonce both ways, then mutual proofs
    if cephx is not None:
        my_mode = (cephx.acceptor_mode() if accepted
                   else cephx.initiator_mode(peer_type or peer.type))
    else:
        my_mode = AUTH_CEPHX if auth_key else AUTH_NONE
    my_nonce = os.urandom(16)
    sock.sendall(bytes([my_mode]) + my_nonce)
    hdr = _read_exact(sock, 17)
    peer_mode, peer_nonce = hdr[0], hdr[1:]
    auth_entity: str | None = None
    if cephx is not None:
        if not accepted:
            if my_mode == AUTH_CEPHX_TICKET:
                t = ticket_for(cephx, peer_type or peer.type)
                if t is None:
                    raise ConnectionError(
                        f"no ticket for {peer_type or peer.type}")
                blob = t.blob()
                sock.sendall(_LEN.pack(len(blob)) + blob
                             + sess_proof(t.session_key, peer_nonce,
                                          t.entity))
                skey = t.session_key
            elif my_mode == AUTH_CEPHX_ENTITY:
                ent = cephx.entity.encode()
                sock.sendall(_LEN.pack(len(ent)) + ent
                             + entity_proof(cephx.key, peer_nonce,
                                            cephx.entity))
                skey = cephx.key.encode()
            else:
                skey = None
            if skey is not None:
                peer_proof = _read_exact(sock, 32)
                want = hmac.new(skey, my_nonce + str(peer).encode(),
                                hashlib.sha256).digest()
                if not hmac.compare_digest(peer_proof, want):
                    raise ConnectionError(
                        f"peer {peer} failed cephx proof")
        else:
            if peer_mode in (AUTH_CEPHX_TICKET, AUTH_CEPHX_ENTITY):
                clen = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
                if clen > 4096:
                    raise ConnectionError("oversized auth credential")
                cred = _read_exact(sock, clen)
                if peer_mode == AUTH_CEPHX_TICKET:
                    got2 = accept_ticket(cephx, cred)
                    if got2 is None:
                        raise ConnectionError(
                            f"peer {peer} invalid/expired ticket")
                    auth_entity, skey = got2
                else:
                    auth_entity = cred.decode()
                    key = (cephx.auth_lookup(auth_entity)
                           if cephx.auth_lookup else
                           (cephx.key if auth_entity == cephx.entity
                            else None))
                    if key is None:
                        raise ConnectionError(
                            f"unknown or revoked entity {auth_entity!r}")
                    skey = key.encode()
                peer_proof = _read_exact(sock, 32)
                want = hmac.new(skey,
                                my_nonce + auth_entity.encode(),
                                hashlib.sha256).digest()
                if not hmac.compare_digest(peer_proof, want):
                    raise ConnectionError(
                        f"peer {peer} failed cephx proof")
                sock.sendall(hmac.new(skey, peer_nonce + me,
                                      hashlib.sha256).digest())
            elif cephx.required:
                raise ConnectionError(
                    f"peer {peer} auth mode {peer_mode} not acceptable")
    else:
        if auth_required and peer_mode != AUTH_CEPHX:
            raise ConnectionError(f"peer {peer} refused authentication")
        if my_mode == AUTH_CEPHX and peer_mode == AUTH_CEPHX:
            # prove I hold the key over the PEER's nonce (never my own:
            # fresh peer nonces are the replay protection)
            proof = hmac.new(auth_key, peer_nonce + me,
                             hashlib.sha256).digest()
            sock.sendall(proof)
            peer_proof = _read_exact(sock, 32)
            want = hmac.new(auth_key, my_nonce + str(peer).encode(),
                            hashlib.sha256).digest()
            if not hmac.compare_digest(peer_proof, want):
                raise ConnectionError(
                    f"peer {peer} failed authentication")
    # compression negotiation: both offer; min wins (off beats on).
    # DEGRADE path: a peer without the wire-compression feature gets
    # uncompressed frames regardless of offers
    if not common & FEATURE_WIRE_COMPRESSION:
        comp_mode = COMP_NONE
    sock.sendall(bytes([comp_mode]))
    peer_comp = _read_exact(sock, 1)[0]
    return peer, min(comp_mode, peer_comp), auth_entity, common


class TcpConnection(Connection):
    def __init__(self, messenger: "AsyncMessenger", peer_addr: str,
                 peer_name: EntityName | None, policy: ConnectionPolicy,
                 sock: socket.socket | None = None, accepted: bool = False,
                 comp: int = COMP_NONE):
        super().__init__(messenger, peer_addr)
        self.peer_name = peer_name
        self.policy = policy
        # accepted sessions cannot dial the peer back; on failure they drop
        # and wait for the initiator to reconnect (the reference server side
        # replaces the Connection on re-accept)
        self.accepted = accepted
        #: negotiated on-wire compression mode for this session
        self.comp = comp
        self._sock = sock
        self._sendq: queue.Queue = queue.Queue()
        self._down = False
        self._lock = lockdep.make_lock("TcpConnection::lock")
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()
        if sock is not None:
            self._start_reader()

    # -- public ---------------------------------------------------------------

    def send_message(self, msg: Message) -> None:
        if self._down:
            return
        from ceph_tpu.common import tracing
        from ceph_tpu.msg.features import FEATURE_TRACE, FEATURE_TRACE_SPANS
        if self.features & FEATURE_TRACE:
            # NEVER emit the trace header extension against a peer
            # that did not negotiate it (features.py's invariant)
            tracing.stamp(msg, str(self.messenger.my_name))
            if not self.features & FEATURE_TRACE_SPANS:
                # peer predates the v2 (trace_id, parent_span_id)
                # extension: fall back to the v1 bare-u64 frame
                msg.parent_span_id = 0
        self._sendq.put(msg)

    def mark_down(self) -> None:
        self._down = True
        self._sendq.put(None)
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def is_connected(self) -> bool:
        with self._lock:
            return self._sock is not None and not self._down

    # -- internals ------------------------------------------------------------

    def _start_reader(self) -> None:
        threading.Thread(target=self._read_loop, daemon=True).start()

    def _connect(self) -> None:
        host, port = self.peer_addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        m = self.messenger
        # keep the dial timeout through the handshake: a stalled or
        # malicious peer must not wedge the writer thread forever
        peer, self.comp, _ent, self.features = _handshake(
            s, m.my_name, m.auth_key, m.auth_required, m.comp_mode,
            cephx=m.cephx, accepted=False,
            peer_type=self.peer_name.type if self.peer_name else "",
            features=m.local_features, required_fn=m.required_for)
        s.settimeout(None)
        with self._lock:
            self._sock = s
        if self.peer_name is None:
            self.peer_name = peer
        self._start_reader()

    def _frame(self, msg: Message) -> bytes:
        """Encode + (maybe) compress one message into a wire frame."""
        payload = msg.encode()
        comp = COMP_NONE
        if self.comp == COMP_ZLIB and len(payload) >= COMP_THRESHOLD:
            z = zlib.compress(payload, 1)
            if len(z) < len(payload):
                comp, payload = COMP_ZLIB, z
        return _LEN.pack(len(payload)) + bytes([comp]) + payload

    def _write_loop(self) -> None:
        backlog: list[Message] = []
        while not self._down:
            item = self._sendq.get()
            if item is None:
                return
            backlog.append(item)
            while backlog and not self._down:
                try:
                    with self._lock:
                        sock = self._sock
                    if sock is None:
                        self._connect()
                        with self._lock:
                            sock = self._sock
                    if sock is None:
                        # the reader nulled it already (e.g. the peer
                        # rejected us right after the handshake)
                        raise OSError("connection lost before write")
                    # frame at send time: the negotiated compression can
                    # change across a reconnect
                    frame = self._frame(backlog[0])
                    sock.sendall(frame)
                    self.messenger.count_sent(len(frame))
                    backlog.pop(0)
                except OSError:
                    with self._lock:
                        if self._sock is not None:
                            try:
                                self._sock.close()
                            except OSError:
                                pass
                            self._sock = None
                    if self.policy.lossy or self.accepted:
                        self._down = True
                        self.messenger.notify_reset(self)
                        return
                    if not self.policy.resend_on_reconnect:
                        backlog.clear()
                    time.sleep(0.1)  # reconnect backoff

    def _read_loop(self) -> None:
        from ceph_tpu.common.logging import get_logger
        throttle = self.messenger.dispatch_throttle
        try:
            while not self._down:
                with self._lock:
                    sock = self._sock
                if sock is None:
                    return
                frame_len = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
                if frame_len > MAX_FRAME:
                    raise ConnectionError(
                        f"oversized frame ({frame_len} bytes) from "
                        f"{self.peer_name}")
                comp = _read_exact(sock, 1)[0]
                # policy byte throttle BEFORE buffering the payload:
                # acquiring after the read would leave buffered bytes
                # unbounded (msg/Policy.h reads under the throttle)
                charged = min(frame_len, throttle.max_amount)
                throttled = throttle.get(charged)
                data = _read_exact(sock, frame_len)
                if comp == COMP_ZLIB:
                    # bounded inflate: a hostile stream must not balloon
                    # past the frame cap (zlib-bomb guard)
                    d = zlib.decompressobj()
                    data = d.decompress(data, MAX_FRAME)
                    if d.unconsumed_tail:
                        raise ConnectionError(
                            f"decompressed frame exceeds cap from "
                            f"{self.peer_name}")
                    # the buffered-bytes bound must cover the INFLATED
                    # size, not the wire size, or zlib frames bypass it
                    # by the compression ratio
                    if throttled and len(data) > frame_len:
                        extra = min(len(data) - frame_len,
                                    throttle.max_amount - charged)
                        throttle.get(extra)
                        charged += extra
                try:
                    # a bad frame or handler bug must not kill the reader
                    try:
                        msg = Message.decode(data)
                        # on-wire size (header + possibly-compressed
                        # payload): matches the sender's count_sent
                        msg.wire_bytes = _LEN.size + 1 + frame_len
                        msg.connection = self
                        self.messenger.deliver(msg)
                    except Exception:
                        get_logger("ms").exception(
                            "%s: dispatch failed for frame from %s",
                            self.messenger.my_name, self.peer_name)
                finally:
                    if throttled:
                        throttle.put(charged)
        except (ConnectionError, OSError):
            with self._lock:
                self._sock = None
            if not self._down:
                if self.policy.lossy:
                    self._down = True
                self.messenger.notify_reset(self)
            self.messenger.reap(self)


class AsyncMessenger(Messenger):
    is_wire = True

    #: cap on bytes concurrently in dispatch (policy throttler analog)
    DISPATCH_THROTTLE_BYTES = 512 << 20

    def __init__(self, name: EntityName):
        super().__init__(name)
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: dict[str, TcpConnection] = {}
        self._stop = False
        self.auth_key: bytes | None = None
        self.auth_required = False
        #: per-entity cephx config; supersedes the shared-key handshake
        self.cephx = None
        self.comp_mode = COMP_NONE
        from ceph_tpu.common.throttle import Throttle
        self.dispatch_throttle = Throttle(
            f"msgr-dispatch:{name}", self.DISPATCH_THROTTLE_BYTES)

    def set_compression(self, mode: str | int) -> None:
        """Offer on-wire compression (both peers must offer; min wins):
        "zlib" or "none" (ms_compress_mode analog)."""
        if isinstance(mode, str):
            mode = {"none": COMP_NONE, "zlib": COMP_ZLIB}[mode]
        self.comp_mode = int(mode)

    def set_auth(self, key: bytes | str | None,
                 required: bool = True) -> None:
        """Enable cephx-lite: all connections prove possession of the
        shared cluster key during the handshake; with required=True an
        un-keyed peer is rejected."""
        if isinstance(key, str):
            key = key.encode()
        self.auth_key = key
        self.auth_required = bool(key) and required

    def set_auth_cephx(self, config) -> None:
        self.cephx = config

    def reap(self, con: "TcpConnection") -> None:
        """Drop a dead connection from the table (reconnect storms must
        not accumulate dead accepted sessions)."""
        if not con._down and not con.accepted:
            return   # dialing connections self-heal; keep them
        with self._lock:
            for key, c in list(self._conns.items()):
                if c is con:
                    del self._conns[key]

    def bind(self, addr: str) -> None:
        host, port = addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, int(port)))
        s.listen(64)
        self.my_addr = f"{host}:{s.getsockname()[1]}"  # resolves port 0
        self._listener = s

    def start(self) -> None:
        if self._listener is None:
            return

        def accept_loop():
            while not self._stop:
                try:
                    sock, _ = self._listener.accept()
                except OSError:
                    return
                threading.Thread(target=self._accept_one, args=(sock,),
                                 daemon=True).start()

        self._accept_thread = threading.Thread(target=accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_one(self, sock: socket.socket) -> None:
        if self._stop:
            sock.close()
            return
        try:
            # handshake-phase timeout: an unauthenticated peer that
            # stalls mid-handshake must not leak a thread + fd
            sock.settimeout(10)
            peer, comp, auth_entity, feat = _handshake(
                sock, self.my_name, self.auth_key, self.auth_required,
                self.comp_mode, cephx=self.cephx, accepted=True,
                features=self.local_features,
                required_fn=self.required_for)
            sock.settimeout(None)
        except (ConnectionError, OSError):
            sock.close()
            return
        policy = self.policy_for(peer.type)
        con = TcpConnection(self, f"{sock.getpeername()[0]}:0", peer,
                            policy, sock=sock, accepted=True, comp=comp)
        con.auth_entity = auth_entity
        con.features = feat
        with self._lock:
            if self._stop:
                # raced shutdown(): it already swept _conns — a session
                # registered now would live on as a zombie responder
                stop = True
            else:
                stop = False
                old = self._conns.get(f"accepted:{peer}")
                self._conns[f"accepted:{peer}"] = con
        if stop:
            con.mark_down()
            return
        if old is not None:
            old.mark_down()   # reap the replaced session

    def shutdown(self) -> None:
        self._stop = True
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.mark_down()

    def connect_to(self, addr: str, peer_name: EntityName) -> Connection:
        key = f"{addr}/{peer_name}"
        with self._lock:
            con = self._conns.get(key)
            # keep a live-or-dialing connection: its writer thread owns a
            # backlog and self-heals stateful sessions.  Replacing a con
            # that is merely mid-dial would orphan that backlog — queued
            # messages black-hole while the caller talks to the new con
            # (and each redial storms the peer's accepted-session table)
            if con is not None and not con._down:
                return con
            policy = self.policy_for(peer_name.type)
            con = TcpConnection(self, addr, peer_name, policy)
            self._conns[key] = con
            return con
