"""AsyncReserver: bounded concurrency slots with priority queueing.

The reference throttles recovery/backfill with reservation state machines
(common/AsyncReserver.h; doc/dev/osd_internals/{backfill_reservation,
recovery_reservation}.rst): a PG must hold a local slot (and in the
reference a remote one on the backfill target) before moving data, so an
osd rebuilds at most `osd_max_backfills` PGs at a time instead of
thundering-herd pulling every degraded PG at once.

In this framework recovery is pull-based — the osd that needs data is
the one that requests it — so the puller's local reserver plays both the
local and the remote-target role: every data mover holds a slot on the
node the data lands on.  Source-side load is bounded separately by the
mClock "recovery" class in the sharded op queue (op_queue.py).

Grant callbacks run outside the reserver lock (they issue pulls, which
take the OSD lock) but possibly inline within request() when a slot is
free — callers must tolerate that.
"""

from __future__ import annotations

import heapq
import itertools
import threading


class AsyncReserver:
    def __init__(self, max_allowed: int = 1, name: str = ""):
        self.name = name
        self._max = max(1, int(max_allowed))
        # analysis: allow[bare-lock] -- reservation-table leaf lock
        self._lock = threading.Lock()
        self._granted: set = set()
        #: heap of (-prio, seq, key); callbacks kept aside so a cancel
        #: can drop a queued request without heap surgery
        self._queue: list = []
        self._waiting: dict = {}
        self._seq = itertools.count()

    def set_max(self, n: int) -> None:
        with self._lock:
            self._max = max(1, int(n))
        self._grant_ready()

    def has(self, key) -> bool:
        with self._lock:
            return key in self._granted

    def request(self, key, grant_cb, prio: int = 0) -> None:
        """Ask for a slot; grant_cb() fires when granted (possibly inline).
        Re-requesting a granted or queued key is a no-op."""
        with self._lock:
            if key in self._granted or key in self._waiting:
                return
            self._waiting[key] = grant_cb
        self._grant_ready(push=(prio, key))

    def cancel(self, key) -> None:
        """Release a held slot or abandon a queued request; next in line
        is granted."""
        with self._lock:
            self._granted.discard(key)
            self._waiting.pop(key, None)
        self._grant_ready()

    def dump(self) -> dict:
        with self._lock:
            return {"max": self._max, "granted": sorted(map(str,
                                                            self._granted)),
                    "queued": sorted(str(k) for k in self._waiting)}

    def _grant_ready(self, push=None) -> None:
        grants = []
        with self._lock:
            if push is not None:
                prio, key = push
                heapq.heappush(self._queue, (-prio, next(self._seq), key))
            while self._queue and len(self._granted) < self._max:
                _np, _seq, key = heapq.heappop(self._queue)
                cb = self._waiting.pop(key, None)
                if cb is None:
                    continue  # cancelled while queued
                self._granted.add(key)
                grants.append(cb)
        for cb in grants:
            try:
                cb()
            except Exception:
                # one failing grant must not starve the rest of the batch
                from ceph_tpu.common.logging import get_logger
                get_logger("osd").exception("reserver %s grant callback "
                                            "failed", self.name)
