"""CrushMap → dense-array compilation for the batched device mapper.

The scalar oracle walks Python objects; the batched mapper needs the map as
static dense arrays so every step is a gather.  A compiled map holds, per
bucket: id, type, size, and padded item/weight rows.  Devices are type 0;
negative items index buckets at -1-id, exactly the reference layout
(crush/crush.h:354 crush_map.buckets).

Batchability contract (checked at compile time, ValueError otherwise):
  * every bucket is straw2, tree, or uniform.  Straw2/tree are stateless
    draws; uniform's permutation CACHE (crush_work_bucket) is sequential
    state, but the permutation itself is a pure function of (x, r,
    bucket id) — the batched mapper recomputes the Fisher-Yates prefix
    per lane (mapper.c:73-138), so mixed uniform/straw2 maps (the
    "identical hosts" layout) stay on the fast path.  List and legacy
    straw buckets run through the scalar oracle fallback
    (ceph_tpu.crush.mapper_ref / OSDMapMapping's scalar path).
  * modern tunables: choose_local_tries=0 and choose_local_fallback_tries=0
    (the jewel+ profile, Tunables defaults) — the legacy local-retry ladder
    (mapper.c:497-503) and perm fallback are scalar-only.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .types import (
    CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, CrushMap)


@dataclass
class CompiledCrushMap:
    """Dense form of a CrushMap.  All arrays are host numpy; the mapper moves
    them to device once per map epoch (like OSDMap distribution)."""

    n_buckets: int
    max_size: int
    max_devices: int
    bucket_id: np.ndarray      # (B,) int32  — crush bucket id (negative)
    bucket_type: np.ndarray    # (B,) int32
    bucket_size: np.ndarray    # (B,) int32
    bucket_alg: np.ndarray     # (B,) int32  — CRUSH_BUCKET_{STRAW2,TREE}
    items: np.ndarray          # (B, S) int32, padded with INT32_MIN
    weights: np.ndarray        # (B, S) int64 16.16, padded with 0
    n_nodes: np.ndarray        # (B,) int32  — tree node count (0 if !tree)
    node_weights: np.ndarray   # (B, T) int64 — tree per-node weights
    has_tree: bool             # any tree bucket present
    has_uniform: bool          # any uniform bucket present
    max_uniform_size: int      # largest uniform bucket (perm loop bound)
    tunables_tries: int        # choose_total_tries + 1 (mapper.c:906)
    vary_r: int
    stable: int
    descend_once: int

    def bucket_index(self, item: int) -> int:
        return -1 - item


def compile_map(m: CrushMap) -> CompiledCrushMap:
    t = m.tunables
    if t.choose_local_tries or t.choose_local_fallback_tries:
        raise ValueError(
            "batched mapper requires modern tunables (choose_local_tries=0, "
            "choose_local_fallback_tries=0); use the scalar oracle for legacy "
            "profiles")
    n = len(m.buckets)
    sizes = []
    node_counts = []
    for b in m.buckets:
        if b is None:
            sizes.append(0)
            node_counts.append(0)
            continue
        if b.alg == CRUSH_BUCKET_TREE:
            node_counts.append(len(b.node_weights))
        elif b.alg in (CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_UNIFORM):
            node_counts.append(0)
        else:
            raise ValueError(
                f"batched mapper supports straw2, tree and uniform "
                f"buckets; bucket {b.id} has alg {b.alg} — use the "
                f"scalar oracle")
        sizes.append(b.size)
    s_max = max(sizes, default=1) or 1
    t_max = max(node_counts, default=0) or 1
    bucket_id = np.zeros(n, dtype=np.int32)
    bucket_type = np.zeros(n, dtype=np.int32)
    bucket_size = np.zeros(n, dtype=np.int32)
    bucket_alg = np.zeros(n, dtype=np.int32)
    items = np.full((n, s_max), np.iinfo(np.int32).min, dtype=np.int32)
    weights = np.zeros((n, s_max), dtype=np.int64)
    n_nodes = np.zeros(n, dtype=np.int32)
    node_weights = np.zeros((n, t_max), dtype=np.int64)
    for idx, b in enumerate(m.buckets):
        if b is None:
            continue
        bucket_id[idx] = b.id
        bucket_type[idx] = b.type
        bucket_size[idx] = b.size
        bucket_alg[idx] = b.alg
        items[idx, :b.size] = b.items
        if b.alg == CRUSH_BUCKET_UNIFORM and not b.item_weights:
            # uniform buckets carry ONE shared item weight
            # (crush_bucket_uniform.item_weight)
            weights[idx, :b.size] = b.item_weight
        else:
            weights[idx, :b.size] = b.item_weights
        if b.alg == CRUSH_BUCKET_TREE:
            n_nodes[idx] = len(b.node_weights)
            node_weights[idx, :len(b.node_weights)] = b.node_weights
    return CompiledCrushMap(
        n_buckets=n, max_size=s_max, max_devices=m.max_devices,
        bucket_id=bucket_id, bucket_type=bucket_type, bucket_size=bucket_size,
        bucket_alg=bucket_alg, items=items, weights=weights,
        n_nodes=n_nodes, node_weights=node_weights,
        has_tree=bool((bucket_alg == CRUSH_BUCKET_TREE).any()),
        has_uniform=bool(((bucket_alg == CRUSH_BUCKET_UNIFORM)
                          & (bucket_size > 0)).any()),
        max_uniform_size=int(bucket_size[
            bucket_alg == CRUSH_BUCKET_UNIFORM].max()
            if (bucket_alg == CRUSH_BUCKET_UNIFORM).any() else 0),
        tunables_tries=t.choose_total_tries + 1,
        vary_r=t.chooseleaf_vary_r, stable=t.chooseleaf_stable,
        descend_once=t.chooseleaf_descend_once,
    )
