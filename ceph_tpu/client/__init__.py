"""Client access layer (reference layer 5: src/librados/ + src/osdc/).

RadosClient connects to the mon, subscribes to map updates, and hands out
IoCtx pool handles; the embedded Objecter computes placement client-side
(osdc/Objecter.cc:2795 _calc_target — CRUSH runs in the client, no metadata
lookup) and resends in-flight ops on map change.
"""

from .rados import IoCtx, RadosClient, ceph_str_hash_rjenkins

__all__ = ["RadosClient", "IoCtx", "ceph_str_hash_rjenkins"]
