"""The `ceph` CLI (src/ceph.in analog): argv -> JSON mon command ->
leader (any mon forwards), printing the JSON/text reply.

    python -m ceph_tpu.tools.ceph_cli -m 127.0.0.1:6789 status
    python -m ceph_tpu.tools.ceph_cli -m ... osd tree
    python -m ceph_tpu.tools.ceph_cli -m ... osd pool create pg_num=8 size=3
    python -m ceph_tpu.tools.ceph_cli -m ... osd out 3
    python -m ceph_tpu.tools.ceph_cli -m ... osd pool mksnap pool=1 snap=s1
"""

from __future__ import annotations

import argparse
import sys


#: prefix -> positional argument names (mirrors MonCommands.h schemas)
COMMANDS = {
    ("status",): [],
    ("health",): [],
    ("health", "detail"): [],
    ("config", "set"): ["who", "name", "value"],
    ("config", "get"): ["who", "name"],
    ("config", "rm"): ["who", "name"],
    ("config", "dump"): [],
    ("auth", "get-or-create"): ["entity"],
    ("auth", "get"): ["entity"],
    ("auth", "print-key"): ["entity"],
    ("auth", "ls"): [],
    ("auth", "del"): ["entity"],
    ("quorum_status",): [],
    ("mon", "dump"): [],
    ("log", "last"): ["num"],
    ("log",): ["message"],
    ("mon", "add"): ["id", "addr"],
    ("mon", "rm"): ["id"],
    ("fs", "new"): ["fs_name", "metadata", "data"],
    ("fs", "status"): [],
    ("fs", "set"): ["var", "val"],
    ("osd", "tree"): [],
    ("osd", "getmap"): [],
    ("osd", "pool", "create"): [],
    ("osd", "pool", "set"): ["pool", "var", "val"],
    ("osd", "pool", "mksnap"): [],
    ("osd", "pool", "rmsnap"): [],
    ("osd", "getcrushmap"): [],
    ("osd", "setcrushmap"): [],
    ("osd", "reweight"): ["id", "weight"],
    ("osd", "reweight-by-utilization"): [],
    ("osd", "out"): ["id"],
    ("osd", "in"): ["id"],
    ("osd", "down"): ["id"],
    ("osd", "pg-upmap-items"): ["pgid", "*id_pairs"],
    ("osd", "rm-pg-upmap-items"): ["pgid"],
    ("mgr", "dump"): [],
    ("mgr", "module", "ls"): [],
    ("mgr", "module", "enable"): ["module"],
    ("mgr", "module", "disable"): ["module"],
    ("pg", "dump"): [],
    ("df",): [],
    ("pg", "ls"): ["pool"],
    ("iostat",): [],
    ("balancer", "status"): [],
    ("balancer", "optimize"): [],
    ("telemetry", "show"): [],
    ("osd", "pool", "autoscale-status"): [],
    ("config-key", "set"): ["key", "value"],
    ("config-key", "get"): ["key"],
    ("config-key", "rm"): ["key"],
    ("config-key", "dump"): [],
    ("tracing", "ls"): [],
    ("tracing", "show"): ["trace_id"],
    ("slow_ops",): [],
    ("qos", "set"): ["tenant"],
    ("qos", "rm"): ["tenant"],
    ("qos", "ls"): [],
    ("qos", "slo", "set"): ["tenant"],
    ("qos", "slo", "rm"): ["tenant"],
    ("qos", "slo", "ls"): [],
    ("slo", "status"): [],
    ("usage", "top"): [],
}

#: prefixes served by the active MGR (re-targeted via `mgr dump`),
#: like the reference's mgr command routing
MGR_COMMANDS = {"pg dump", "pg ls", "iostat", "df", "balancer status",
                "balancer optimize", "telemetry show",
                "mgr module ls", "mgr module enable",
                "mgr module disable", "osd pool autoscale-status",
                "tracing ls", "tracing show", "slow_ops",
                "slo status", "usage top"}


def parse_command(words: list[str]) -> dict:
    """Longest matching prefix wins; remaining words become positional
    schema args or key=value pairs."""
    for n in range(min(3, len(words)), 0, -1):
        key = tuple(words[:n])
        if key in COMMANDS:
            cmd = {"prefix": " ".join(key)}
            rest = words[n:]
            schema = COMMANDS[key]
            pos = 0
            for w in rest:
                if pos < len(schema) and schema[pos].startswith("*"):
                    # rest-list argument swallows remaining words
                    cmd.setdefault(schema[pos][1:], []).append(w)
                elif "=" in w:
                    k, v = w.split("=", 1)
                    cmd[k] = v
                elif pos < len(schema):
                    cmd[schema[pos]] = w
                    pos += 1
                else:
                    raise ValueError(f"unexpected argument {w!r}")
            return cmd
    raise ValueError(f"unknown command {' '.join(words)!r}; known: "
                     + ", ".join(" ".join(k) for k in sorted(COMMANDS)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("-m", "--mon-host", required=True,
                    help="comma-separated monitor addresses")
    ap.add_argument("--timeout", type=float, default=15.0)
    ap.add_argument("--auth-key", default=None)
    ap.add_argument("-i", "--infile",
                    help="crush binary for setcrushmap")
    ap.add_argument("-o", "--outfile",
                    help="write getcrushmap output here")
    ap.add_argument("words", nargs="+")
    args = ap.parse_args(argv)
    try:
        cmd = parse_command(args.words)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 22
    if cmd["prefix"] == "osd setcrushmap":
        import base64
        from ceph_tpu.tools.crushtool import read_binary as _rb
        if not args.infile:
            print("setcrushmap needs -i <crushtool binary>",
                  file=sys.stderr)
            return 22
        from ceph_tpu.msg.encoding import Encoder
        from ceph_tpu.osd.map_codec import encode_crush
        try:
            m, names = _rb(args.infile)   # validates framing + names
        except (SystemExit, Exception) as e:   # DecodeError/struct/...
            print(f"cannot read {args.infile}: {e}", file=sys.stderr)
            return 22
        e = Encoder()
        encode_crush(m, e)
        cmd["crush_b64"] = base64.b64encode(e.tobytes()).decode()
        cmd["names"] = {"types": names.types, "items": names.items,
                        "rules": names.rules, "classes": names.classes}
    from ceph_tpu.client.rados import RadosClient
    client = RadosClient(args.mon_host, timeout=args.timeout,
                         auth_key=args.auth_key)
    try:
        client.msgr.bind("127.0.0.1:0")
        client.msgr.start()
        if cmd["prefix"] in MGR_COMMANDS:
            res, out = client.mgr_command(cmd)
        else:
            res, out = client.mon_command(cmd)
        if res == 0 and cmd["prefix"] == "osd getcrushmap" \
                and args.outfile:
            import base64, json
            from ceph_tpu.tools.crushtool import write_binary_blob
            reply = json.loads(out)
            write_binary_blob(args.outfile,
                              base64.b64decode(reply["crush_b64"]),
                              reply.get("names") or {})
            print(f"wrote {args.outfile}")
        else:
            print(out)
        return -res if res < 0 else res
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
