"""cephx handshake over the wire messenger (CephxProtocol on the
AsyncConnection auth phase): ticket mode to services, entity-secret
mode to mons, rejection of forged/expired/revoked credentials."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.auth.cephx import KeyServer, Ticket, TicketKeyring, mint_ticket
from ceph_tpu.auth.handshake import CephxConfig
from ceph_tpu.messages import MOSDPing
from ceph_tpu.msg.messenger import EntityName, Messenger


class Sink:
    def __init__(self):
        self.got = []

    def ms_dispatch(self, msg):
        self.got.append(msg)
        return True

    def ms_handle_reset(self, con):
        pass

    def ms_handle_remote_reset(self, con):
        pass


MS_TYPE = "async"


def mk(name, cfg=None):
    m = Messenger.create(EntityName(*name), MS_TYPE)
    if cfg is not None:
        m.set_auth_cephx(cfg)
    m.bind("127.0.0.1:0")
    m.start()
    return m


def wait_got(sink, n=1, timeout=5.0):
    deadline = time.time() + timeout
    while len(sink.got) < n and time.time() < deadline:
        time.sleep(0.02)
    return len(sink.got) >= n


@pytest.fixture
def ks():
    return KeyServer()


def service_messenger(ks, name=("osd", 1), service="osd"):
    cfg = CephxConfig(service=service,
                      rotating=lambda: ks.rotating_keys(service))
    m = mk(name, cfg)
    sink = Sink()
    m.add_dispatcher_tail(sink)
    return m, sink


def test_ticket_handshake_grants_access(ks):
    server, sink = service_messenger(ks)
    kr = TicketKeyring(lambda svc: ks.grant(svc, "client.alice"))
    client = mk(("client", 7), CephxConfig(entity="client.alice",
                                           keyring=kr))
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 1))
        con.send_message(MOSDPing(from_osd=7, op=MOSDPing.PING))
        assert wait_got(sink), "ticketed client failed to get through"
        # the service knows WHO this is (authorization identity)
        acc = next(iter(server._conns.values()), None) or \
            next(iter(server._accepting), None)
        ents = {c.auth_entity for c in server._conns.values()}
        assert "client.alice" in ents
    finally:
        client.shutdown()
        server.shutdown()


def test_no_ticket_rejected(ks):
    server, sink = service_messenger(ks)
    client = mk(("client", 8))          # no auth at all
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 1))
        con.send_message(MOSDPing(from_osd=8, op=MOSDPing.PING))
        time.sleep(1.0)
        assert sink.got == []
    finally:
        client.shutdown()
        server.shutdown()


def test_forged_ticket_rejected(ks):
    server, sink = service_messenger(ks)
    ks.grant("osd", "seed")             # init generation 1
    forged = mint_ticket("osd", "client.evil", 1, "not-the-service-key")
    kr = TicketKeyring(lambda svc: forged)
    client = mk(("client", 9), CephxConfig(entity="client.evil",
                                           keyring=kr))
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 1))
        con.send_message(MOSDPing(from_osd=9, op=MOSDPing.PING))
        time.sleep(1.0)
        assert sink.got == []
    finally:
        client.shutdown()
        server.shutdown()


def test_expired_ticket_rejected_then_fresh_works(ks):
    server, sink = service_messenger(ks)
    state = {"ttl": -1.0}               # born expired
    kr = TicketKeyring(lambda svc: ks.grant(svc, "client.t",
                                            ttl=state["ttl"]))
    client = mk(("client", 10), CephxConfig(entity="client.t",
                                            keyring=kr))
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 1))
        con.send_message(MOSDPing(from_osd=10, op=MOSDPing.PING))
        time.sleep(1.0)
        assert sink.got == []
        # a fresh ticket heals the connection on its reconnect cycle
        state["ttl"] = 60.0
        kr.invalidate()
        deadline = time.time() + 8
        while not sink.got and time.time() < deadline:
            time.sleep(0.1)
        assert sink.got, "fresh ticket never got through"
    finally:
        client.shutdown()
        server.shutdown()


def test_rotation_kills_old_generation(ks):
    server, sink = service_messenger(ks)
    old = ks.grant("osd", "client.r")   # gen 1
    from ceph_tpu.auth.cephx import LIVE_GENERATIONS
    for _ in range(LIVE_GENERATIONS):
        ks.rotate_now("osd")
    kr = TicketKeyring(lambda svc: old)     # stuck with the old ticket
    client = mk(("client", 11), CephxConfig(entity="client.r",
                                            keyring=kr))
    try:
        con = client.connect_to(server.my_addr, EntityName("osd", 1))
        con.send_message(MOSDPing(from_osd=11, op=MOSDPing.PING))
        time.sleep(1.0)
        assert sink.got == []
    finally:
        client.shutdown()
        server.shutdown()


def test_entity_mode_to_mon_and_revocation(ks):
    db = {"client.alice": "alicekey", "osd.1": "osdkey"}
    mon = mk(("mon", 0), CephxConfig(
        entity="mon.0", key="monkey",
        auth_lookup=lambda e: db.get(e)))
    sink = Sink()
    mon.add_dispatcher_tail(sink)
    alice = mk(("client", 12), CephxConfig(entity="client.alice",
                                           key="alicekey"))
    mallory = mk(("client", 13), CephxConfig(entity="client.alice",
                                             key="wrongkey"))
    try:
        con = alice.connect_to(mon.my_addr, EntityName("mon", 0))
        con.send_message(MOSDPing(from_osd=12, op=MOSDPing.PING))
        assert wait_got(sink)
        ents = {c.auth_entity for c in mon._conns.values()}
        assert "client.alice" in ents

        n0 = len(sink.got)
        con2 = mallory.connect_to(mon.my_addr, EntityName("mon", 0))
        con2.send_message(MOSDPing(from_osd=13, op=MOSDPing.PING))
        time.sleep(1.0)
        assert len(sink.got) == n0      # wrong key: nothing arrives

        # REVOCATION: delete alice; her next reconnect dies at lookup
        del db["client.alice"]
        con.mark_down()
        con3 = alice.connect_to(mon.my_addr, EntityName("mon", 0))
        con3.send_message(MOSDPing(from_osd=12, op=MOSDPing.PING))
        time.sleep(1.0)
        assert len(sink.got) == n0      # revoked entity locked out
    finally:
        alice.shutdown()
        mallory.shutdown()
        mon.shutdown()


def test_ticket_and_entity_on_threaded_stack(ks, monkeypatch):
    """The threaded (blocking) stack speaks the same cephx dialect."""
    import tests.test_cephx_handshake as me
    monkeypatch.setattr(me, "MS_TYPE", "threaded")
    test_ticket_handshake_grants_access(ks)
    test_no_ticket_rejected(KeyServer())
    test_entity_mode_to_mon_and_revocation(KeyServer())
