"""CRUSH scalar-oracle bit-exactness against reference golden vectors.

tests/golden/crush_mapper_golden.txt.gz holds outputs generated (at development time)
by a harness that compiled the reference C sources (src/crush/{crush,mapper,hash,
builder}.c) and printed hash values and crush_do_rule placements for a matrix of maps:
every bucket algorithm, firstn + indep, two-level chooseleaf, reweight vectors,
choose_args overrides, jewel and legacy tunables.  The Python oracle must replay every
line bit-for-bat.  Format: `tag x n id...` per placement, `hashN args... out` per hash.
"""

import collections
import gzip
import pathlib

import pytest

import ceph_tpu  # noqa: F401
from ceph_tpu.crush import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    build_flat_map,
    build_two_level_map,
    crush_do_rule,
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_4,
    crush_hash32_5,
)
from ceph_tpu.crush.builder import add_simple_rule
from ceph_tpu.crush.ln_table import lh_table, ll_table, rh_table
from ceph_tpu.crush.mapper_ref import crush_ln
from ceph_tpu.crush.types import ChooseArg, Tunables

GOLDEN = pathlib.Path(__file__).parent / "golden" / "crush_mapper_golden.txt.gz"


def _load():
    placements = collections.defaultdict(dict)
    hashes = []
    for line in gzip.open(GOLDEN, "rt"):
        p = line.split()
        if p[0].startswith("hash"):
            hashes.append(p)
        else:
            placements[p[0]][int(p[1])] = [int(v) for v in p[3:3 + int(p[2])]]
    return placements, hashes


PLACEMENTS, HASHES = _load()

HASH_FNS = {"hash1": crush_hash32, "hash2": crush_hash32_2,
            "hash3": crush_hash32_3, "hash4": crush_hash32_4,
            "hash5": crush_hash32_5}


def test_hash_golden():
    assert len(HASHES) == 250
    for p in HASHES:
        args = [int(v) for v in p[1:-1]]
        assert HASH_FNS[p[0]](*args) == int(p[-1]), p


def _assert_matches(tag, m, rid, result_max, weight, cargs=None):
    g = PLACEMENTS[tag]
    assert g, f"missing golden tag {tag}"
    for x, want in g.items():
        got = crush_do_rule(m, rid, x, result_max, weight, cargs)
        assert got == want, f"{tag} x={x}: {got} != {want}"


def test_straw2_flat():
    m, _, _ = build_flat_map(10)
    _assert_matches("s2flat_firstn", m, 0, 3, [0x10000] * 10)
    _assert_matches("s2flat_indep", m, 1, 4, [0x10000] * 10)
    rw = [0x10000] * 10
    rw[2] = 0
    rw[5] = 0x8000
    rw[7] = 0x4000
    _assert_matches("s2flat_reweight", m, 0, 3, rw)


def test_straw2_choose_args():
    m, _, _ = build_flat_map(10)
    cargs = {0: ChooseArg(
        ids=[1000 + i for i in range(10)],
        weight_set=[[0x10000 + i * 0x1000 for i in range(10)],
                    [0x20000 - i * 0x800 for i in range(10)]])}
    _assert_matches("s2flat_cargs", m, 0, 3, [0x10000] * 10, cargs)


def test_straw2_varied_weights():
    w = [(i % 5 + 1) * 0x4000 for i in range(16)]
    w[3] = 0
    m, _, _ = build_flat_map(16, weights=w)
    _assert_matches("s2var_firstn", m, 0, 3, [0x10000] * 16)


@pytest.mark.parametrize("alg,name", [
    (CRUSH_BUCKET_UNIFORM, "uni"), (CRUSH_BUCKET_LIST, "list"),
    (CRUSH_BUCKET_TREE, "tree"), (CRUSH_BUCKET_STRAW, "straw")])
def test_legacy_bucket_algs(alg, name):
    wts = [0x10000] * 7 if alg == CRUSH_BUCKET_UNIFORM \
        else [(i + 1) * 0x8000 for i in range(7)]
    m, _, _ = build_flat_map(7, weights=wts, alg=alg)
    _assert_matches(f"{name}_firstn", m, 0, 3, [0x10000] * 7)
    _assert_matches(f"{name}_indep", m, 1, 3, [0x10000] * 7)


def test_two_level_chooseleaf():
    m, root, rid = build_two_level_map(4, 3)
    rid_indep = add_simple_rule(m, root, 1, "indep")
    _assert_matches("2lvl_leaf_firstn", m, rid, 3, [0x10000] * 12)
    _assert_matches("2lvl_leaf_indep", m, rid_indep, 3, [0x10000] * 12)
    out4 = [0x10000] * 12
    out4[4] = 0
    _assert_matches("2lvl_out4", m, rid, 3, out4)


def test_legacy_tunables():
    m, root, rid = build_two_level_map(4, 3)
    m.tunables = Tunables.legacy()
    _assert_matches("2lvl_legacy", m, rid, 3, [0x10000] * 12)


# ---------------------------------------------------------------------------
# ln tables: spot values transcribed from the reference header during the
# development-time diff (crush_ln_table.h), pinning the generator + overrides.
# ---------------------------------------------------------------------------

def test_ln_table_spot_values():
    rh, lh, ll = rh_table(), lh_table(), ll_table()
    assert rh[0] == 0x0001000000000000
    assert rh[1] == 0x0000FE03F80FE040
    assert rh[128] == 0x0000800000000000
    assert lh[0] == 0
    assert lh[1] == 0x000002DFCA16DDE1
    assert lh[128] == 0x0000FFFF00000000  # frozen quirk (math says 2^48)
    assert ll[0] == 0
    assert ll[1] == 0x00000002E2A60A00
    assert ll[2] == 0x000000070CB64EC5   # carries the frozen excess
    assert ll[199] == 0x0000023D13EE805B  # frozen stray
    assert ll[255] == 0x000002DCED24F814  # exact floor


def test_crush_ln_range_and_monotonicity_where_expected():
    # domain used by straw2: xin in [0, 0xffff]; crush_ln(0) = log2(1) = 0
    vals = [crush_ln(x) for x in range(0, 0x10000, 257)]
    assert all(0 <= v < (1 << 48) for v in vals)
    assert vals == sorted(vals)
    assert crush_ln(0xFFFF) == (15 << 44) + ((int(lh_table()[128]) + int(ll_table()[0])) >> 4)
