"""Foundation runtime (reference layer 0: src/common/, src/log/, src/global/).

CephContext-style service locator, typed config registry with hot-reload
observers, PerfCounters, leveled per-subsystem logging, admin-socket-style
introspection, and throttles.  Every daemon and library in ceph_tpu builds on
this layer, as in the reference (SURVEY.md §1 layer 0).
"""

from .config import Option, OPT_INT, OPT_STR, OPT_BOOL, OPT_FLOAT, Config
from .context import CephTpuContext
from .perf_counters import PerfCounters, PerfCountersBuilder
from .logging import dout, get_logger, set_subsys_level
from .admin_socket import AdminSocket
from .throttle import Throttle

__all__ = [
    "Option", "OPT_INT", "OPT_STR", "OPT_BOOL", "OPT_FLOAT", "Config",
    "CephTpuContext", "PerfCounters", "PerfCountersBuilder",
    "dout", "get_logger", "set_subsys_level", "AdminSocket", "Throttle",
]


def free_port() -> int:
    """Allocate an ephemeral localhost TCP port (bind/close; the usual
    harness-grade race window applies)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
