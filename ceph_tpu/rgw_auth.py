"""RGW object-level authorization: ACL grant lists, bucket policy
documents, and CORS rules (src/rgw/rgw_acl.h:34-120 ACLGrant,
src/rgw/rgw_iam_policy.cc:620-880 evaluator, src/rgw/rgw_cors.cc).

Pure logic, no I/O — the gateway stores grant lists / policy JSON /
CORS rules in bucket metadata and object index entries, and routes
every data-path request through :func:`evaluate`:

  1. bucket POLICY first: an explicit Deny ends it; an explicit Allow
     grants without consulting ACLs (the reference's policy-over-ACL
     precedence);
  2. otherwise the ACL grant list — the OBJECT's if it has one, else
     the bucket's (canned ACL names expand to grant lists, so the
     pre-grant canned behaviour is the same table evaluated the same
     way);
  3. the owner always passes.
"""

from __future__ import annotations

import fnmatch
import json

# -- permissions (rgw_acl.h RGW_PERM_*) -------------------------------------

READ = "READ"
WRITE = "WRITE"
READ_ACP = "READ_ACP"
WRITE_ACP = "WRITE_ACP"
FULL_CONTROL = "FULL_CONTROL"
_PERMS = (READ, WRITE, READ_ACP, WRITE_ACP, FULL_CONTROL)

#: group grantees (ACLGroupTypeEnum): every principal incl. anonymous /
#: every authenticated principal
ALL_USERS = "*"
AUTH_USERS = "authenticated"


def canned_grants(canned: str, owner: str) -> list[dict]:
    """Expand a canned ACL name into its grant list
    (rgw_acl_s3.cc create_canned)."""
    out = []
    if owner:
        out.append({"grantee": owner, "permission": FULL_CONTROL})
    if canned == "public-read":
        out.append({"grantee": ALL_USERS, "permission": READ})
    elif canned == "public-read-write":
        out.append({"grantee": ALL_USERS, "permission": READ})
        out.append({"grantee": ALL_USERS, "permission": WRITE})
    elif canned == "authenticated-read":
        out.append({"grantee": AUTH_USERS, "permission": READ})
    # "private": owner only
    return out


def validate_grants(grants: list[dict]) -> list[dict]:
    out = []
    for g in grants:
        grantee = str(g.get("grantee", ""))
        perm = str(g.get("permission", "")).upper().replace("-", "_")
        if not grantee:
            raise ValueError("grant without grantee")
        if perm not in _PERMS:
            raise ValueError(f"unknown permission {perm!r}")
        out.append({"grantee": grantee, "permission": perm})
    return out


def _grantee_matches(grantee: str, principal: str | None) -> bool:
    if grantee == ALL_USERS:
        return True
    if grantee == AUTH_USERS:
        return principal is not None
    return principal is not None and grantee == principal


def acl_allows(grants: list[dict], owner: str,
               principal: str | None, perm: str) -> bool:
    """One grant table lookup (RGWAccessControlPolicy::verify_permission
    reduced): the owner has FULL_CONTROL implicitly; FULL_CONTROL
    implies every permission."""
    if principal is not None and owner and principal == owner:
        return True
    for g in grants:
        if g["permission"] not in (perm, FULL_CONTROL):
            continue
        if _grantee_matches(g["grantee"], principal):
            return True
    return False


# -- bucket policy (rgw_iam_policy reduced) ---------------------------------

class PolicyError(ValueError):
    pass


class BucketPolicy:
    """Parsed policy document: Version + Statement list of
    {Effect, Principal, Action, Resource} — the Allow/Deny x
    Principal/Action/Resource core of the reference's IAM engine
    (Condition clauses are out of scope)."""

    #: the actions the gateway actually evaluates; parse() refuses a
    #: pattern that can never match any of them
    ACTIONS = ("s3:GetObject", "s3:PutObject", "s3:DeleteObject",
               "s3:ListBucket", "s3:GetObjectAcl", "s3:PutObjectAcl")

    def __init__(self, statements: list[dict]):
        self.statements = statements

    @classmethod
    def parse(cls, doc: str | bytes | dict) -> "BucketPolicy":
        if isinstance(doc, (str, bytes)):
            try:
                doc = json.loads(doc)
            except ValueError as e:
                raise PolicyError(f"malformed policy JSON: {e}")
        if not isinstance(doc, dict) or "Statement" not in doc:
            raise PolicyError("policy needs a Statement list")
        stmts = doc["Statement"]
        if isinstance(stmts, dict):
            stmts = [stmts]
        if not isinstance(stmts, list) \
                or not all(isinstance(s, dict) for s in stmts):
            raise PolicyError("Statement must be an object list")
        parsed = []
        for s in stmts:
            effect = s.get("Effect")
            if effect not in ("Allow", "Deny"):
                raise PolicyError(f"bad Effect {effect!r}")
            principal = s.get("Principal", {})
            if principal == "*":
                principals = [ALL_USERS]
            elif isinstance(principal, dict):
                aws = principal.get("AWS", [])
                principals = [aws] if isinstance(aws, str) else list(aws)
            else:
                raise PolicyError("bad Principal")
            actions = s.get("Action", [])
            if isinstance(actions, str):
                actions = [actions]
            for a in actions:
                # a pattern matching NO known action is a typo, and the
                # statement it gates would be permanently inert — an
                # operator's Deny that does nothing is worse than an
                # error at PUT time
                if not any(fnmatch.fnmatchcase(known, a)
                           for known in cls.ACTIONS):
                    raise PolicyError(f"unknown action {a!r}")
            resources = s.get("Resource", [])
            if isinstance(resources, str):
                resources = [resources]
            parsed.append({"effect": effect, "principals": principals,
                           "actions": actions, "resources": resources})
        return cls(parsed)

    @staticmethod
    def _principal_matches(principals: list[str],
                           principal: str | None) -> bool:
        return any(p == ALL_USERS
                   or (principal is not None and p == principal)
                   for p in principals)

    @staticmethod
    def _action_matches(actions: list[str], action: str) -> bool:
        return any(fnmatch.fnmatchcase(action, pat) for pat in actions)

    @staticmethod
    def _resource_matches(resources: list[str], bucket: str,
                          key: str | None) -> bool:
        arn = f"arn:aws:s3:::{bucket}" + (f"/{key}" if key else "")
        return any(fnmatch.fnmatchcase(arn, pat) for pat in resources)

    def evaluate(self, principal: str | None, action: str,
                 bucket: str, key: str | None = None) -> str | None:
        """'Deny' | 'Allow' | None (no statement matched).  Deny wins
        over Allow (rgw_iam_policy's eval order)."""
        verdict: str | None = None
        for s in self.statements:
            if not self._principal_matches(s["principals"], principal):
                continue
            if not self._action_matches(s["actions"], action):
                continue
            if not self._resource_matches(s["resources"], bucket, key):
                continue
            if s["effect"] == "Deny":
                return "Deny"
            verdict = "Allow"
        return verdict


# -- combined decision (rgw_op.cc verify_permission order) ------------------

def evaluate(policy: BucketPolicy | None, grants: list[dict],
             owner: str, principal: str | None, perm: str,
             action: str, bucket: str, key: str | None = None) -> bool:
    if policy is not None:
        verdict = policy.evaluate(principal, action, bucket, key)
        if verdict == "Deny":
            return False
        if verdict == "Allow":
            return True
    return acl_allows(grants, owner, principal, perm)


# -- CORS (rgw_cors.cc reduced) ---------------------------------------------

class CorsRule:
    def __init__(self, origins: list[str], methods: list[str],
                 headers: list[str] | None = None, max_age: int = 0):
        self.origins = origins
        self.methods = [m.upper() for m in methods]
        self.headers = [h.lower() for h in (headers or [])]
        self.max_age = max_age

    def origin_matches(self, origin: str) -> bool:
        # exact or *-wildcard origins ("https://*.example.com", "*")
        return any(fnmatch.fnmatchcase(origin, pat)
                   for pat in self.origins)

    def allows(self, origin: str, method: str,
               req_headers: list[str] | None = None) -> bool:
        if not self.origin_matches(origin):
            return False
        if method.upper() not in self.methods:
            return False
        for h in req_headers or []:
            h = h.strip().lower()
            if not h:
                continue
            if h not in self.headers and "*" not in self.headers:
                return False
        return True

    def to_dict(self) -> dict:
        return {"origins": self.origins, "methods": self.methods,
                "headers": self.headers, "max_age": self.max_age}


class CorsConfig:
    def __init__(self, rules: list[CorsRule]):
        self.rules = rules

    @classmethod
    def from_rules(cls, rules: list[dict]) -> "CorsConfig":
        out = []
        for r in rules:
            methods = [m.upper() for m in r.get("methods", [])]
            for m in methods:
                if m not in ("GET", "PUT", "POST", "DELETE", "HEAD"):
                    raise ValueError(f"bad CORS method {m!r}")
            if not r.get("origins"):
                raise ValueError("CORS rule without origins")
            out.append(CorsRule(list(r["origins"]), methods,
                                list(r.get("headers", [])),
                                int(r.get("max_age", 0))))
        return cls(out)

    def match(self, origin: str, method: str,
              req_headers: list[str] | None = None) -> CorsRule | None:
        """First rule allowing the request (RGWCORSConfiguration::
        host_name_rule + is_rule_applicable)."""
        for r in self.rules:
            if r.allows(origin, method, req_headers):
                return r
        return None

    def to_rules(self) -> list[dict]:
        return [r.to_dict() for r in self.rules]
