"""Distributed object store (RADOS analog): maps, placement, and (as they land)
the OSD daemon, PG logic, and backends.

The placement pipeline mirrors src/osd/OSDMap.{h,cc}: objects hash to PGs
(ceph_stable_mod), PGs hash to placement seeds (pps), CRUSH maps seeds to OSD
sets, then upmap/primary-affinity/temp overrides apply.  Bulk evaluation is the
batched device mapper (ceph_tpu.crush.mapper_jax) — the OSDMapMapping /
ParallelPGMapper analog with the thread pool replaced by one device call.
"""

from .osdmap import OSDMap, PGPool, pg_to_pgid, ceph_stable_mod
from .mapping import MapUpdate, OSDMapMapping, SharedPGMappingService

__all__ = ["OSDMap", "PGPool", "pg_to_pgid", "ceph_stable_mod",
           "OSDMapMapping", "SharedPGMappingService", "MapUpdate"]
