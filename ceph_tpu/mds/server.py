"""MDS daemon: journaled filesystem metadata over RADOS (src/mds/).

The reference MDS keeps the namespace in a metadata pool — each
directory fragment is a RADOS object whose omap maps dentry name to the
encoded inode — and journals every mutation through osdc/Journaler
before acking (MDLog EUpdate events), writing dirty dirfrags back
lazily.  Crash recovery = load backing dirfrags + replay the journal
tail (up:replay -> up:active, MDCache::rejoin machinery reduced to the
single-MDS case).  File DATA never touches the MDS: clients stripe it
straight to the data pool (Striper) and report the new size back
(the reference tracks it via client caps; here an explicit setattr).

Wire surface: MClientRequest/MClientReply (messages/MClientRequest.h,
CEPH_MSG_CLIENT_REQUEST=24 / _REPLY=26) carrying json-ish op payloads.

Object naming in the metadata pool:
    dir.<ino:x>      dirfrag omap: name -> encoded dentry {ino, type}
    inode.<ino:x>    omap: encoded inode attrs (mode, size, times)
    mds.table        omap: next_ino
    mdlog.*          the Journaler stream + head
"""

from __future__ import annotations

import json
import threading
import time

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
from ceph_tpu.mds.caps import ALL as ALL_CAPS
from ceph_tpu.mds.caps import BUFFER, WR, CapTable, caps_str
from ceph_tpu.mds.flock import (
    EOF, F_UNLCK, Lock, LockState, fcntl_range)
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osdc.journaler import Journaler

ROOT_INO = 1

S_IFDIR = 0o040000
S_IFREG = 0o100000


@register_message
class MClientRequest(Message):
    """fs client -> mds (CEPH_MSG_CLIENT_REQUEST=24)."""

    TYPE = 24

    def __init__(self, tid: int = 0, op: str = "", args: dict | None = None):
        super().__init__()
        self.tid = tid
        self.op = op
        self.args = args or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.str(self.op),
            e.bytes(json.dumps(self.args).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.op = d.str()
            self.args = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


@register_message
class MClientReply(Message):
    """mds -> fs client (CEPH_MSG_CLIENT_REPLY=26)."""

    TYPE = 26

    def __init__(self, tid: int = 0, result: int = 0,
                 out: dict | None = None):
        super().__init__()
        self.tid = tid
        self.result = result
        self.out = out or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.s32(self.result),
            e.bytes(json.dumps(self.out).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.result = d.s32()
            self.out = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


@register_message
class MClientSession(Message):
    """Session lifecycle, client <-> mds (CEPH_MSG_CLIENT_SESSION=22):
    request_open / open_ack / renew / request_close / close_ack."""

    TYPE = 22

    def __init__(self, tid: int = 0, op: str = "", client: int = 0):
        super().__init__()
        self.tid = tid
        self.op = op
        self.client = client

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.str(self.op), e.u64(self.client)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.op = d.str()
            self.client = d.u64()
        dec.versioned(1, body)


@register_message
class MClientCaps(Message):
    """Capability traffic (CEPH_MSG_CLIENT_CAPS=0x310).

    mds -> client: op 'revoke' (drop to `caps`, ack after flushing),
    'grant' (upgrade, no ack), 'invalidated' (inode unlinked).
    client -> mds: op 'ack' (revoke done — flushed size/mtime ride
    along), 'release' (last close)."""

    TYPE = 0x310
    HEAD_VERSION = 2       # v2: epoch_barrier rides every cap message

    def __init__(self, op: str = "", ino: int = 0, caps: int = 0,
                 seq: int = 0, client: int = 0, size: int = -1,
                 mtime: float = 0.0, epoch_barrier: int = 0):
        super().__init__()
        self.op = op
        self.ino = ino
        self.caps = caps
        self.seq = seq
        self.client = client
        self.size = size
        self.mtime = mtime
        #: v2: osdmap epoch the client must reach before issuing direct
        #: RADOS writes under these caps (the reference's cap
        #: epoch_barrier, src/messages/MClientCaps.h osd_epoch_barrier
        #: + Client::set_cap_epoch_barrier) — orders post-mksnap writes
        #: after the snapshot's pool epoch
        self.epoch_barrier = epoch_barrier

    def encode_payload(self, enc: Encoder):
        enc.versioned(2, 1, lambda e: (
            e.str(self.op), e.u64(self.ino), e.u32(self.caps),
            e.u64(self.seq), e.u64(self.client), e.s64(self.size),
            e.f64(self.mtime), e.u32(self.epoch_barrier)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.op = d.str()
            self.ino = d.u64()
            self.caps = d.u32()
            self.seq = d.u64()
            self.client = d.u64()
            self.size = d.s64()
            self.mtime = d.f64()
            self.epoch_barrier = d.u32() if v >= 2 else 0
        dec.versioned(2, body)


@register_message
class MClientLease(Message):
    """mds -> client dentry-lease traffic (CEPH_MSG_CLIENT_LEASE=0x311,
    messages/MClientLease.h reduced): op 'revoke' tells the client its
    cached dentry+attrs for `path` are void (a mutation touched the
    name, or a writer opened the file).  Fire-and-forget — the lease's
    TTL is the backstop, which is what makes it a LEASE."""

    TYPE = 0x311

    def __init__(self, op: str = "revoke", path: str = ""):
        super().__init__()
        self.op = op
        self.path = path

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (e.str(self.op),
                                       e.str(self.path)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.op = d.str()
            self.path = d.str()
        dec.versioned(1, body)


@register_message
class MMDSExport(Message):
    """mds -> mds subtree handoff (Migrator MExportDir reduced): the
    exporter has flushed everything and committed the new authority in
    the shared subtree table; this message moves the un-flushable
    in-memory state (file locks) and tells the importer to drop its
    caches of the subtree."""

    TYPE = 530

    def __init__(self, path: str = "", from_rank: int = -1,
                 locks_blob: bytes = b""):
        super().__init__()
        self.path = path
        self.from_rank = from_rank
        self.locks_blob = locks_blob

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.str(self.path), e.s32(self.from_rank),
            e.bytes(self.locks_blob)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.path = d.str()
            self.from_rank = d.s32()
            self.locks_blob = d.bytes()
        dec.versioned(1, body)


class _Park(Exception):
    """Request must wait for cap acks / lock release on this ino
    (the reference's MDSCacheObject add_waiter, as control flow)."""

    def __init__(self, ino: int):
        self.ino = ino


class Inode:
    __slots__ = ("ino", "mode", "size", "mtime", "parent",
                 "quota_bytes", "quota_files", "remote_links")

    def __init__(self, ino: int, mode: int, size: int = 0,
                 mtime: float = 0.0, parent: int = 0,
                 quota_bytes: int = 0, quota_files: int = 0,
                 remote_links: list | None = None):
        self.ino = ino
        self.mode = mode
        self.size = size
        self.mtime = mtime
        #: PRIMARY-link backpointer (CDentry linkage, the primary
        #: dentry): lets a rank reconstruct an ino's path, so ino-op
        #: authority survives a restart (the in-memory exported-ino map
        #: alone would not)
        self.parent = parent
        #: directory quotas (ceph.quota.max_bytes / max_files vxattrs);
        #: 0 = unlimited
        self.quota_bytes = quota_bytes
        self.quota_files = quota_files
        #: REMOTE dentries (CDentry.h:77-90 linkage_t remote_ino,
        #: inverted): [parent_ino, name] of every hardlink beyond the
        #: primary.  nlink derives from it, and unlinking the primary
        #: promotes the first pair (the reference's re-homing via
        #: backtrace)
        self.remote_links: list[list] = remote_links or []

    def is_dir(self) -> bool:
        return bool(self.mode & S_IFDIR)

    @property
    def nlink(self) -> int:
        return 1 + len(self.remote_links)

    def to_dict(self) -> dict:
        d = {"ino": self.ino, "mode": self.mode, "size": self.size,
             "mtime": self.mtime, "parent": self.parent,
             "nlink": self.nlink}
        if self.quota_bytes or self.quota_files:
            d["quota_bytes"] = self.quota_bytes
            d["quota_files"] = self.quota_files
        if self.remote_links:
            d["remote_links"] = [list(p) for p in self.remote_links]
        return d

    @staticmethod
    def from_dict(d: dict) -> "Inode":
        return Inode(d["ino"], d["mode"], d.get("size", 0),
                     d.get("mtime", 0.0), d.get("parent", 0),
                     d.get("quota_bytes", 0), d.get("quota_files", 0),
                     [list(p) for p in d.get("remote_links", [])])


class MDSDaemon(Dispatcher):
    """Single-rank MDS (the reference scales ranks via dirfrag export;
    the namespace model below is rank-count agnostic)."""

    RECONNECT_GRACE = 2.0
    BEACON_INTERVAL = 1.0

    def __init__(self, mon_addr: str, metadata_pool: int | None = None,
                 data_pool: int | None = None,
                 ctx: CephTpuContext | None = None, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None,
                 gid: int | None = None,
                 cephx: tuple[str, str] | None = None):
        import os as _os
        self.gid = gid if gid is not None else \
            int.from_bytes(_os.urandom(6), "big")
        self.mon_addr = mon_addr
        self.rank: int | None = None
        self.ctx = ctx or CephTpuContext(f"mds.{self.gid}")
        self.name = EntityName("mds", 0)
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        #: 0 = no reconnect window; else: until this time, cap-granting
        #: client ops park while old clients reassert (MDS rejoin)
        self._reconnect_until = 0.0
        self._beacon_timer: threading.Timer | None = None
        # analysis: allow[bare-lock] -- MDS daemon RLock; MDS hierarchy conversion deferred with its subsystem
        self._lock = threading.RLock()
        #: ino -> Inode (inode cache; authoritative once loaded)
        self._inodes: dict[int, Inode] = {}
        #: ino -> {name: child_ino} (dirfrag cache)
        self._dirs: dict[int, dict[int, object]] = {}
        self._dirty_dirs: set[int] = set()
        self._dirty_inodes: set[int] = set()
        self._next_ino = ROOT_INO + 1
        self._journaled_since_flush = 0
        self.state = "boot"
        #: client sessions: client id -> {"con", "last_seen"}
        self._sessions: dict[int, dict] = {}
        #: capability table (Locker/Capability state)
        self.caps = CapTable()
        #: per-ino lock tables (flock.cc ceph_lock_state_t)
        self._locks: dict[int, LockState] = {}
        #: requests parked on an ino (waiting for cap acks / locks)
        self._parked: dict[int, list] = {}
        #: (ino, client) -> send time of the oldest un-acked revoke
        self._revoke_sent: dict[tuple[int, int], float] = {}
        #: (parent_ino, name) -> {client: lease expiry} — dentry leases
        #: granted to lookups on quiescent inodes (client dcache;
        #: mutations + writer-opens revoke, TTL is the backstop)
        self._dentry_leases: dict[tuple[int, str], dict[int, float]] = {}
        #: osdmap epoch every WR-cap holder must reach before direct
        #: data writes (bumped by mksnap; rides cap grants and open
        #: replies — the reference's Locker osd_epoch_barrier)
        self._osd_epoch_barrier = 0
        #: grace before a silent revoke target / session is evicted
        self.revoke_grace = 4.0
        self.session_grace = 8.0
        #: parked requests older than this are answered with an error
        #: (EAGAIN for blocking locks) instead of lingering: the client
        #: RPC gives up before this, and granting a lock to a waiter
        #: that stopped waiting would orphan it forever
        self.park_ttl = 240.0
        #: multi-active state (subtree delegation, MDBalancer reduced)
        self._subtrees: dict[str, int] | None = None
        self._subtrees_ts = 0.0
        #: subtree roots currently being exported: ops under them park
        self._frozen: dict[str, int] = {}       # path -> root ino
        #: inos whose authority moved away: ino -> new rank
        self._exported_inos: dict[int, int] = {}
        #: per-top-level-path request counters + a decayed rate
        self._req_counts: dict[str, int] = {}
        self._load_rate = 0.0
        self._load_window = 0
        #: balancer hint from the mon (least-loaded rank + its load)
        self._bal_rank = -1
        self._bal_load = 0.0
        #: my load must exceed min*factor + floor before auto-exporting
        self.bal_factor = 4.0
        self.bal_floor = 50.0
        self.bal_auto = False
        self._bal_tick = 0
        #: an auto-export parked on cap recalls, retried each bal tick
        self._pending_export: tuple[str, int] | None = None
        self._tick_timer: threading.Timer | None = None

        self.objecter = RadosClient(mon_addr, ms_type=ms_type,
                                    auth_key=auth_key, cephx=cephx)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self._cephx = cephx
        if cephx is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            self._rotating: dict[int, str] = {}
            self.msgr.set_auth_cephx(CephxConfig(
                entity=cephx[0], key=cephx[1],
                keyring=TicketKeyring(self.objecter._fetch_ticket),
                service="mds", rotating=lambda: self._rotating))
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr
        self._stop = False
        self.journal: Journaler | None = None

    # -- lifecycle ------------------------------------------------------------

    def _refresh_rotating(self) -> None:
        if self._cephx is None:
            return
        rc, out = self.objecter.mon_command(
            {"prefix": "auth rotating", "service": "mds"})
        if rc == 0:
            self._rotating = {int(g): k
                              for g, k in json.loads(out).items()}

    def init(self) -> None:
        """Direct single-MDS bring-up (no FSMap registration): rank 0,
        journal 'mdlog'.  The FSMap path is init_standby()."""
        self.objecter.connect()
        self._refresh_rotating()
        self.rank = 0
        self.meta_io = self.objecter.open_ioctx(self.metadata_pool)
        self.journal = Journaler(self.meta_io, "mdlog")
        self._load_or_mkfs()
        self.state = "replay"
        n = self.journal.replay(
            lambda payload, _pos: self._replay_entry(payload))
        dout("mds", 5, "mds.0 replayed %d journal events", n)
        if n:
            self._flush_dirty()
            self.journal.trim()
        self.state = "active"
        self.msgr.bind(self._addr)
        self.msgr.start()
        self._schedule_tick()

    def init_standby(self) -> None:
        """FSMap bring-up: register with the mon via beacons and wait
        for a rank (MDSMonitor assignment); standbys idle until a
        failover promotes them."""
        self.objecter.connect()
        self._refresh_rotating()
        self.msgr.bind(self._addr)
        self.msgr.start()
        self.state = "standby"
        self._schedule_tick()
        self._beacon()

    def _beacon(self) -> None:
        if self._stop:
            return
        from ceph_tpu.mon.monitor import MMDSBeacon
        # decayed request rate rides the beacon (MDBalancer load)
        self._load_rate = 0.7 * self._load_rate + 0.3 * self._load_window
        self._load_window = 0
        # fan out to EVERY mon (mon_addr is comma-separated): only the
        # leader assigns ranks, and any mon may be the leader
        for i, addr in enumerate(self.mon_addr.split(",")):
            try:
                con = self.msgr.connect_to(addr.strip(),
                                           EntityName("mon", i))
                con.send_message(MMDSBeacon(
                    gid=self.gid, addr=self.msgr.my_addr,
                    state=self.state, load=self._load_rate,
                    rank=-1 if self.rank is None else self.rank))
            except OSError:
                continue
        self._beacon_timer = threading.Timer(self.BEACON_INTERVAL,
                                             self._beacon)
        self._beacon_timer.daemon = True
        self._beacon_timer.start()

    def _activate(self, rank: int, meta_pool: int = -1,
                  data_pool: int = -1) -> None:
        """Standby promoted to a rank: replay that rank's journal and
        open a reconnect window for the old clients' cap reasserts.
        The pool IDS ride the beacon ack (no fsmap wait), but the
        objecter still needs a map CONTAINING those pools to route the
        journal I/O — wait for it briefly; on timeout leave rank unset
        so the next beacon ack retries instead of wedging half-active."""
        mp = self.metadata_pool if self.metadata_pool is not None \
            else meta_pool
        dp = self.data_pool if self.data_pool is not None else data_pool
        if mp < 0 or dp < 0:
            return              # stale ack with no pools: next beacon
        deadline = time.time() + 8.0
        while time.time() < deadline:
            pools = self.objecter.osdmap.pools
            if mp in pools and dp in pools:
                break
            time.sleep(0.05)
        else:
            dout("mds", 1, "mds gid %d: fs pools not in objecter map "
                 "yet; retrying on next beacon", self.gid)
            return
        with self._lock:
            if self.rank is not None:
                return
            self.metadata_pool = mp
            self.data_pool = dp
            self.rank = rank
            self.meta_io = self.objecter.open_ioctx(self.metadata_pool)
            self.journal = Journaler(self.meta_io, f"mdlog.{rank}")
            self.state = "replay"
            self._load_or_mkfs()
            n = self.journal.replay(
                lambda payload, _pos: self._replay_entry(payload))
            dout("mds", 1, "mds gid %d rank %d: replayed %d events",
                 self.gid, rank, n)
            if n:
                self._flush_dirty()
                self.journal.trim()
            self._reconnect_until = time.time() + self.RECONNECT_GRACE
            self.state = "active"
            self._rerun(0)      # requests that arrived pre-activation

    def _schedule_tick(self) -> None:
        if self._stop:
            return
        self._tick_timer = threading.Timer(1.0, self._tick)
        self._tick_timer.daemon = True
        self._tick_timer.start()

    def _tick(self) -> None:
        try:
            now = time.time()
            with self._lock:
                # prune expired/empty lease rows: without a sweep the
                # table grows one row per dentry ever looked up.  The
                # 60s margin past our expiry stamp keeps holders
                # revokable through the client's later reply-receipt
                # expiry (see _revoke_dentry_lease)
                for key in list(self._dentry_leases):
                    holders = self._dentry_leases[key]
                    for c in [c for c, exp in holders.items()
                              if exp + 60.0 <= now]:
                        del holders[c]
                    if not holders:
                        del self._dentry_leases[key]
                if self._reconnect_until and now >= self._reconnect_until:
                    self._reconnect_until = 0.0
                    self._rerun(0)
                self._bal_tick += 1
                if self._bal_tick % 5 == 0:
                    self._maybe_autobalance()
                # silent revoke targets: the client never acked (dead or
                # wedged) — evict the WHOLE session, exactly like the
                # reference's session-kill on cap-revoke timeout.  A
                # half-evicted client that kept buffering while another
                # client was granted would corrupt the file underneath
                # the new holder.
                for (ino, client), t0 in list(self._revoke_sent.items()):
                    if now - t0 > self.revoke_grace:
                        dout("mds", 1, "mds cap revoke timeout: evicting "
                             "session of client.%d (ino %d)", client, ino)
                        s = self._sessions.get(client)
                        if s is not None:
                            # tell the client it is dead to us: it must
                            # drop caps/dirty state and remount
                            s["con"].send_message(MClientSession(
                                op="evicted", client=client))
                        self._evict_client(client)
                # stale sessions: no renew within the grace -> full evict
                for client, s in list(self._sessions.items()):
                    if now - s["last_seen"] > self.session_grace:
                        dout("mds", 1, "mds session timeout: evicting "
                             "client.%d", client)
                        self._evict_client(client)
                # expired parked requests: answer instead of lingering —
                # the client's RPC already gave up, and granting a lock
                # to an absent waiter would orphan it
                expired = []
                for ino, msgs in list(self._parked.items()):
                    keep = []
                    for m in msgs:
                        if now - m._parked_at > self.park_ttl:
                            expired.append(m)
                        else:
                            keep.append(m)
                    if keep:
                        self._parked[ino] = keep
                    else:
                        del self._parked[ino]
            if self._cephx is not None and self._bal_tick % 60 == 0:
                # rotating-key refresh OUTSIDE the lock: it is a mon
                # round trip over the objecter
                try:
                    self._refresh_rotating()
                except (OSError, TimeoutError):
                    pass
            for m in expired:
                err = -11 if m.op in ("setlk", "flock") else -110
                if m.op == "open":
                    # the opener gave up long ago (client RPC timeout <
                    # park_ttl): un-register its wanted bits or the ino
                    # would be stuck in sync mode forever.  ONLY when
                    # the client holds no issued caps — releasing a
                    # grant backing a live handle from an earlier open
                    # would hand exclusivity to someone else while this
                    # client still buffers under it.
                    with self._lock:
                        _p, ino, _n = self._resolve(m.args["path"])
                        cl = int(m.args.get("client", -1))
                        if ino is not None \
                                and self.caps.issued(ino, cl) == 0:
                            self._do_release(ino, cl)
                            self._rerun(ino)
                m.connection.send_message(
                    MClientReply(tid=m.tid, result=err, out={}))
        finally:
            self._schedule_tick()

    def _evict_client(self, client: int) -> None:
        """Drop every trace of a client: session, caps, locks —
        then re-run anything that was waiting on it."""
        self._sessions.pop(client, None)
        touched = set(self.caps.drop_client(client))
        for (ino, c) in list(self._revoke_sent):
            if c == client:
                del self._revoke_sent[(ino, c)]
        for ino, ls in list(self._locks.items()):
            if ls.drop_client(client):
                touched.add(ino)
            if ls.empty():
                del self._locks[ino]
        for ino in touched:
            self._upgrade_after_release(ino)
            self._rerun(ino)

    def shutdown(self) -> None:
        self._stop = True
        if self._tick_timer:
            self._tick_timer.cancel()
        if self._beacon_timer:
            self._beacon_timer.cancel()
        with self._lock:
            if self.journal is not None:
                self._flush_dirty()
                self.journal.trim()
        self.msgr.shutdown()
        self.objecter.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def _ino_table_key(self) -> str:
        return ("next_ino" if not self.rank
                else f"next_ino.{self.rank}")

    def _ino_base(self) -> int:
        """Each rank allocates from its own ino space (the reference's
        per-MDS InoTable prealloc ranges): two active ranks must never
        mint the same ino."""
        return 2 if not self.rank else (self.rank << 44)

    def _load_or_mkfs(self) -> None:
        self._next_ino = self._ino_base()
        fresh_fs = True
        try:
            table = self.meta_io.get_omap("mds.table")
            fresh_fs = False
            self._next_ino = int(table.get(
                self._ino_table_key(),
                str(self._ino_base()).encode()).decode())
        except OSError:
            pass
        # the journal is PER RANK: its absence does not mean the fs is
        # fresh (a second active rank starts with an empty journal over
        # an existing namespace)
        try:
            self.journal.open()
        except OSError:
            self.journal.create()
        if fresh_fs and not self.rank:
            # fresh filesystem: ONLY rank 0 creates the root (a second
            # rank joining early must not race it; its reads are lazy)
            self._inodes[ROOT_INO] = Inode(ROOT_INO, S_IFDIR | 0o755)
            self._dirs[ROOT_INO] = {}
            self._dirty_dirs.add(ROOT_INO)
            self._dirty_inodes.add(ROOT_INO)
            self._flush_dirty()

    # -- backing store (dirfrag omap objects) ---------------------------------

    def _dir_obj(self, ino: int) -> str:
        return f"dir.{ino:x}"

    def _inode_obj(self, ino: int) -> str:
        return f"inode.{ino:x}"

    def _load_dir(self, ino: int) -> dict:
        d = self._dirs.get(ino)
        if d is not None:
            return d
        try:
            omap = self.meta_io.get_omap(self._dir_obj(ino))
            d = {name: int(v.decode()) for name, v in omap.items()}
        except OSError:
            d = {}
        self._dirs[ino] = d
        return d

    def _load_inode(self, ino: int) -> Inode | None:
        inode = self._inodes.get(ino)
        if inode is not None:
            if inode.remote_links and ino not in self._dirty_inodes:
                # HARDLINKED inodes are shared across ranks (a remote
                # dentry's subtree may be exported): serve them from
                # the store, not a possibly-stale cache — the mutating
                # rank writes them through (see link/unlink handlers)
                self._inodes.pop(ino, None)
            else:
                return inode
        try:
            omap = self.meta_io.get_omap(self._inode_obj(ino))
        except OSError:
            return None
        if "json" not in omap:
            return None
        inode = Inode.from_dict(json.loads(omap["json"].decode()))
        self._inodes[ino] = inode
        return inode

    def _flush_dirty(self) -> None:
        """Write dirty dirfrags/inodes back (MDCache::flush, the lazy
        CDir commit), then persist the ino allocator."""
        for ino in sorted(self._dirty_dirs):
            d = self._dirs.get(ino, {})
            # rewrite wholesale: dirfrags are small omaps here
            try:
                self.meta_io.remove(self._dir_obj(ino))
            except OSError:
                pass
            self.meta_io.set_omap(
                self._dir_obj(ino),
                {name: str(child).encode() for name, child in d.items()})
        self._dirty_dirs.clear()
        for ino in sorted(self._dirty_inodes):
            inode = self._inodes.get(ino)
            if inode is None:
                continue
            self.meta_io.set_omap(
                self._inode_obj(ino),
                {"json": json.dumps(inode.to_dict()).encode()})
        self._dirty_inodes.clear()
        # omap sets merge: each rank maintains its own allocator key
        self.meta_io.set_omap(
            "mds.table",
            {self._ino_table_key(): str(self._next_ino).encode()})

    # -- journal (MDLog EUpdate) ----------------------------------------------

    def _journal(self, event: dict) -> None:
        self.journal.append_entry(json.dumps(event).encode())
        self.journal.flush()

    def _maybe_trim(self) -> None:
        """Segment boundary (MDLog trim): write dirty state back, then
        expire the journal.  MUST run only after the current event is
        both journaled AND applied — trimming first would expire an
        acked mutation that is in neither the journal nor the store."""
        self._journaled_since_flush += 1
        if self._journaled_since_flush >= 64:
            self._flush_dirty()
            self.journal.trim()
            self._journaled_since_flush = 0

    def _replay_entry(self, payload: bytes) -> None:
        ev = json.loads(payload.decode())
        self._apply(ev, replay=True)

    # -- namespace mutations (journaled, replayable) --------------------------

    def _apply(self, ev: dict, replay: bool = False) -> None:
        """Apply one journaled event to the cache.  Must be idempotent:
        replay re-applies events the backing store may already hold."""
        kind = ev["e"]
        if kind == "batch":
            # one journal entry, several sub-events: the atomic EUpdate
            # shape (rename's link+unlink must never tear)
            for sub in ev["events"]:
                self._apply(sub, replay=replay)
            return
        if kind == "alloc":
            self._next_ino = max(self._next_ino, ev["next_ino"])
            return
        if kind == "link":
            parent, name, ino = ev["parent"], ev["name"], ev["ino"]
            self._load_dir(parent)[name] = ino
            self._dirty_dirs.add(parent)
            if "mode" in ev:
                self._inodes[ino] = Inode(ino, ev["mode"], ev.get("size", 0),
                                          ev.get("mtime", 0.0),
                                          parent=parent)
                if self._inodes[ino].is_dir():
                    self._dirs.setdefault(ino, {})
                    self._dirty_dirs.add(ino)
                self._dirty_inodes.add(ino)
            elif ev.get("remote"):
                # hardlink: a REMOTE dentry — the primary backpointer
                # stays put; idempotent on replay (pair set-semantics)
                inode = self._load_inode(ino)
                if inode is not None \
                        and [parent, name] not in inode.remote_links:
                    inode.remote_links.append([parent, name])
                    self._dirty_inodes.add(ino)
                    self._flush_hardlinked = True
            else:
                # plain link (rename target): move the backpointer
                inode = self._load_inode(ino)
                if inode is not None and inode.parent != parent:
                    inode.parent = parent
                    self._dirty_inodes.add(ino)
            return
        if kind == "unlink":
            parent, name = ev["parent"], ev["name"]
            d = self._load_dir(parent)
            ino = d.pop(name, None)
            self._dirty_dirs.add(parent)
            if ino is None:
                return
            inode = self._load_inode(ino)
            if inode is not None and [parent, name] in \
                    inode.remote_links:
                # removing a remote dentry: the inode survives at its
                # primary (and drop_inode means drop-if-LAST-link)
                inode.remote_links.remove([parent, name])
                self._dirty_inodes.add(ino)
                self._flush_hardlinked = True
                return
            if inode is not None and inode.remote_links \
                    and inode.parent == parent \
                    and ev.get("drop_inode"):
                # unlinking the PRIMARY with hardlinks remaining:
                # re-home the inode onto its first remote dentry
                # (MDCache remote-link promotion via backtrace).
                # ONLY on a real unlink — a rename's batch unlink
                # (no drop_inode) merely moved the dentry and removes
                # no link
                np, _nn = inode.remote_links.pop(0)
                inode.parent = np
                self._dirty_inodes.add(ino)
                self._flush_hardlinked = True
                return
            if ev.get("drop_inode"):
                self._inodes.pop(ino, None)
                self._dirs.pop(ino, None)
                try:
                    self.meta_io.remove(self._inode_obj(ino))
                except OSError:
                    pass
                try:
                    self.meta_io.remove(self._dir_obj(ino))
                except OSError:
                    pass
            return
        if kind == "setattr":
            inode = self._load_inode(ev["ino"])
            if inode is not None:
                if inode.remote_links:
                    # size/mode writebacks on a hardlinked inode must
                    # write through like any other shared-inode change
                    self._flush_hardlinked = True
                if "size" in ev:
                    # size WRITEBACK is grow-only (a writer reporting
                    # how far it has written must never undo another
                    # client's longer write); only an explicit truncate
                    # carries plain size
                    if ev.get("grow"):
                        inode.size = max(inode.size, ev["size"])
                    else:
                        inode.size = ev["size"]
                if "mtime" in ev:
                    inode.mtime = ev["mtime"]
                if "mode" in ev:
                    inode.mode = ev["mode"]
                if "quota_bytes" in ev:
                    inode.quota_bytes = int(ev["quota_bytes"])
                if "quota_files" in ev:
                    inode.quota_files = int(ev["quota_files"])
                self._dirty_inodes.add(inode.ino)
            return
        if kind == "mksnap":
            # directory snapshot (snaprealm reduced): the frozen subtree
            # metadata persists under snap.<ino>; file DATA as of the
            # snapshot is served by pool-snapshot reads at ev["snapid"].
            # The epoch barrier survives restart with the journal: a
            # replayed MDS keeps gating re-grants on the snap's epoch
            self._osd_epoch_barrier = max(self._osd_epoch_barrier,
                                          int(ev.get("epoch", 0)))
            recs = self._load_snaps(ev["ino"])
            recs[ev["name"]] = {"snapid": ev["snapid"],
                                "created": ev.get("created", 0.0),
                                "tree": ev["tree"]}
            self.meta_io.set_omap(
                self._snap_obj(ev["ino"]),
                {"json": json.dumps(recs).encode()})
            return
        if kind == "rmsnap":
            recs = self._load_snaps(ev["ino"])
            if recs.pop(ev["name"], None) is not None:
                self.meta_io.set_omap(
                    self._snap_obj(ev["ino"]),
                    {"json": json.dumps(recs).encode()})
            return
        raise ValueError(f"unknown journal event {kind!r}")

    #: set by _apply when a mutation touched a HARDLINKED inode: those
    #: are cross-rank shared through the store (see _load_inode), so
    #: the mutating rank must write them through immediately — a
    #: deferred flush would let another rank read a stale copy
    _flush_hardlinked = False

    def _mutate(self, ev: dict) -> None:
        """Journal-then-apply (the EUpdate ordering: an acked mutation
        is always recoverable), then maybe roll the segment."""
        self._journal(ev)
        self._apply(ev)
        if self._flush_hardlinked:
            self._flush_hardlinked = False
            self._flush_dirty()
        self._maybe_trim()

    # -- quotas (ceph.quota.max_bytes/max_files vxattrs reduced) --------------

    def _quota_roots(self, ino: int):
        """Quota-bearing ancestor dirs of ino, nearest first (the
        snaprealm-style walk up primary-link backpointers)."""
        seen = set()
        cur = self._load_inode(ino)
        while cur is not None and cur.ino not in seen:
            seen.add(cur.ino)
            if cur.is_dir() and (cur.quota_bytes or cur.quota_files):
                yield cur
            if cur.ino == ROOT_INO:
                return
            cur = self._load_inode(cur.parent)

    def _subtree_usage(self, ino: int) -> tuple[int, int]:
        """(bytes, entries) under a dir — a walk, not cached rstats:
        quota checks here are O(subtree), the honest trade at this
        scale (the reference maintains recursive statistics)."""
        nbytes = nfiles = 0
        stack = [ino]
        seen = set()
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for _name, child in self._load_dir(cur).items():
                ci = self._load_inode(child)
                if ci is None:
                    continue
                nfiles += 1
                if ci.is_dir():
                    stack.append(child)
                else:
                    nbytes += ci.size
        return nbytes, nfiles

    def _check_quota(self, at_ino: int, add_files: int = 0,
                     add_bytes: int = 0) -> bool:
        """True iff adding (files, bytes) under at_ino stays within
        every enclosing quota (Client::check_quota_condition)."""
        for root in self._quota_roots(at_ino):
            used_b, used_f = self._subtree_usage(root.ino)
            if root.quota_files and add_files \
                    and used_f + add_files > root.quota_files:
                return False
            if root.quota_bytes and add_bytes \
                    and used_b + add_bytes > root.quota_bytes:
                return False
        return True

    # -- snapshots (snaprealm/SnapServer reduced) -----------------------------

    def _snap_obj(self, ino: int) -> str:
        return f"snap.{ino:x}"

    def _load_snaps(self, ino: int) -> dict:
        try:
            omap = self.meta_io.get_omap(self._snap_obj(ino))
        except OSError:
            return {}
        blob = omap.get("json")
        return json.loads(blob.decode()) if blob else {}

    @staticmethod
    def _split_snap_path(path: str) -> tuple[str, str, str] | None:
        """('/d', 's1', 'rest/of/path') for '/d/.snap/s1/rest', or None
        for a live path.  '/d/.snap' itself returns ('/d', '', '')."""
        parts = [p for p in path.split("/") if p]
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        dirpath = "/" + "/".join(parts[:i])
        snap = parts[i + 1] if len(parts) > i + 1 else ""
        rest = "/".join(parts[i + 2:])
        return dirpath, snap, rest

    def _freeze_tree(self, ino: int, client: int,
                     revoke_wr: bool = False) -> dict:
        """Frozen metadata of the subtree rooted at ino: relpath ->
        inode dict ('' = the root dir).  Buffered writers are recalled
        first so frozen sizes are the truth (may _Park; reruns).

        With revoke_wr (the mksnap path), WR is recalled from EVERY
        holder — the snapshotting client included — so any write after
        the snapshot requires a cap round-trip, which hands the writer
        the new osd epoch barrier before it may touch RADOS again."""
        tree: dict[str, dict] = {}
        stack = [("", ino)]
        while stack:
            rel, cur = stack.pop()
            inode = self._load_inode(cur)
            if inode is None:
                continue
            if not inode.is_dir():
                if revoke_wr:
                    revokes = self.caps.recall(cur, WR | BUFFER)
                    if revokes:
                        self._issue_revokes(cur, revokes)
                    if self.caps.pending_revokes(cur):
                        raise _Park(cur)
                else:
                    self._fresh_inode(cur, requester=client)
                inode = self._load_inode(cur)
            tree[rel] = inode.to_dict()
            if inode.is_dir():
                for name, child in self._load_dir(cur).items():
                    stack.append((f"{rel}/{name}".lstrip("/"), child))
        return tree

    def _do_mksnap(self, a: dict) -> tuple[int, dict]:
        client = int(a.get("client", -1))
        name = a.get("snap", "")
        if not name or "/" in name or name.startswith("."):
            return -22, {}
        _parent, ino, _n = self._resolve(a["path"])
        if ino is None:
            return -2, {}
        inode = self._load_inode(ino)
        if inode is None or not inode.is_dir():
            return -20, {}   # ENOTDIR
        if name in self._load_snaps(ino):
            return -17, {}   # EEXIST
        # freeze metadata FIRST (parks until buffers flushed AND every
        # WR holder dropped its cap — subsequent writes require a cap
        # round-trip), then take the pool snapshot: data written after
        # the freeze point but before the pool snap can only make the
        # snapshot NEWER than the frozen sizes claim, never truncate it
        tree = self._freeze_tree(ino, client, revoke_wr=True)
        rc, out = self.objecter.mon_command({
            "prefix": "osd pool mksnap", "pool": self.data_pool,
            "snap": f"cephfs.{ino:x}.{name}"})
        if rc != 0:
            return rc if rc < 0 else -5, {}
        reply = json.loads(out)
        if "epoch" in reply:
            self.objecter.wait_for_epoch(reply["epoch"])
            # every cap re-grant from here on carries this barrier:
            # writers wait for their osdmap to reach the snap's epoch
            # (and so stamp ops with the new snap_seq) before touching
            # RADOS — closing the COW race with OSDs on older maps
            self._osd_epoch_barrier = max(self._osd_epoch_barrier,
                                          reply["epoch"])
        self._mutate({"e": "mksnap", "ino": ino, "name": name,
                      "snapid": reply["snapid"], "tree": tree,
                      "created": time.time(),
                      "epoch": reply.get("epoch", 0)})
        return 0, {"snapid": reply["snapid"]}

    def _do_rmsnap(self, a: dict) -> tuple[int, dict]:
        name = a.get("snap", "")
        _parent, ino, _n = self._resolve(a["path"])
        if ino is None:
            return -2, {}
        if name not in self._load_snaps(ino):
            return -2, {}
        rc, _out = self.objecter.mon_command({
            "prefix": "osd pool rmsnap", "pool": self.data_pool,
            "snap": f"cephfs.{ino:x}.{name}"})
        # ONLY ENOENT from the mon is fine (a crash between rmsnap
        # halves left the pool snap already gone); any other failure
        # must surface BEFORE the record that names the pool snapshot
        # is dropped — otherwise the snap and its clones leak with no
        # retry path
        if rc not in (0, -2):
            return rc if rc < 0 else -5, {}
        self._mutate({"e": "rmsnap", "ino": ino, "name": name})
        return 0, {}

    def _snap_record(self, path: str) -> tuple[int, dict, str, dict] | None:
        """(dir_ino, snap_record, rest, tree) for a .snap path whose
        snapshot exists, else None."""
        sp = self._split_snap_path(path)
        if sp is None:
            return None
        dirpath, snap, rest = sp
        _parent, ino, _n = self._resolve(dirpath)
        if ino is None:
            return None
        recs = self._load_snaps(ino)
        rec = recs.get(snap)
        if rec is None:
            return None
        return ino, rec, rest, rec["tree"]

    def _handle_snap_path(self, op: str, a: dict) -> tuple[int, dict]:
        """Read-only ops under dir/.snap/... served from frozen trees."""
        path = a["path"]
        sp = self._split_snap_path(path)
        dirpath, snap, rest = sp
        if not snap:
            # dir/.snap listing: snapshot names as directory entries
            _parent, ino, _n = self._resolve(dirpath)
            if ino is None:
                return -2, {}
            if op == "readdir":
                recs = self._load_snaps(ino)
                return 0, {"entries": {n: {"snapid": r["snapid"],
                                           "created": r["created"]}
                                       for n, r in recs.items()}}
            return -22, {}
        found = self._snap_record(path)
        if found is None:
            return -2, {}
        _ino, rec, rest, tree = found
        entry = tree.get(rest)
        if entry is None:
            return -2, {}
        if op in ("lookup", "getattr"):
            return 0, {"inode": dict(entry), "snapid": rec["snapid"]}
        if op == "open":
            if a.get("create") or (int(a.get("wanted", 0))
                                   & (WR | BUFFER)):
                return -30, {}   # EROFS: snapshots are immutable
            # no capabilities: the content is frozen, nothing to revoke
            return 0, {"inode": dict(entry), "snapid": rec["snapid"],
                       "caps": 0, "cap_seq": 0}
        if op == "readdir":
            prefix = rest + "/" if rest else ""
            out = {}
            for rel, ent in tree.items():
                if rel == rest or not rel.startswith(prefix):
                    continue
                tail = rel[len(prefix):]
                if "/" not in tail:
                    out[tail] = {"ino": ent.get("ino"),
                                 "dir": bool(ent.get("mode", 0)
                                             & S_IFDIR)}
            return 0, {"entries": out}
        return -30, {}   # any mutation under .snap

    # -- subtree authority (Migrator/MDBalancer reduced) ----------------------

    SUBTREE_OBJ = "mds.subtrees"
    SUBTREE_TTL = 2.0

    @staticmethod
    def _norm(path: str) -> str:
        return "/" + "/".join(p for p in path.split("/") if p)

    def _load_subtrees(self, force: bool = False) -> dict[str, int]:
        now = time.time()
        if (not force and self._subtrees is not None
                and now - self._subtrees_ts < self.SUBTREE_TTL):
            return self._subtrees
        try:
            omap = self.meta_io.get_omap(self.SUBTREE_OBJ)
            self._subtrees = {k: int(v.decode()) for k, v in
                              omap.items() if k != "__version__"}
        except OSError:
            self._subtrees = {}
        self._subtrees_ts = now
        return self._subtrees

    def _authority(self, path: str) -> int:
        """Rank owning a path: deepest delegated prefix wins; the root
        default is rank 0 (dirfrag auth, reduced to path prefixes)."""
        norm = self._norm(path)
        best, bestlen = 0, 0
        for pref, r in self._load_subtrees().items():
            if norm == pref or norm.startswith(pref + "/") \
                    or pref == "/":
                if len(pref) > bestlen:
                    best, bestlen = r, len(pref)
        return best

    def _check_path_authority(self, path: str,
                              allow_frozen: bool = False):
        """Returns a forward reply for a path that is not ours, parks
        if it is mid-export, else None (ours: proceed).  Also feeds the
        per-subtree load counters.  allow_frozen is for the export op
        itself — it IS the freezer and must re-enter."""
        if not allow_frozen:
            for pref, root_ino in self._frozen.items():
                norm = self._norm(path)
                if norm == pref or norm.startswith(pref + "/"):
                    raise _Park(root_ino)
        r = self._authority(path)
        if r != self.rank:
            return 0, {"forward": r}
        norm = self._norm(path)
        top = "/" + norm.split("/")[1] if norm != "/" else "/"
        self._req_counts[top] = self._req_counts.get(top, 0) + 1
        self._load_window += 1
        return None

    def _ino_path(self, ino: int) -> str | None:
        """Reconstruct an ino's path via PRIMARY parent backpointers
        (name found by scanning the parent dirfrag; a hardlinked inode
        resolves to its primary path — the reference's backtrace)."""
        parts: list[str] = []
        cur = ino
        for _ in range(64):         # depth bound
            if cur == ROOT_INO:
                return "/" + "/".join(reversed(parts))
            inode = self._load_inode(cur)
            if inode is None or not inode.parent:
                return None
            name = next((n for n, c in
                         self._load_dir(inode.parent).items()
                         if c == cur), None)
            if name is None:
                return None
            parts.append(name)
            cur = inode.parent
        return None

    def _check_ino_authority(self, ino: int):
        fwd = self._exported_inos.get(ino)
        if fwd is not None:
            return 0, {"forward": fwd}
        # durable check: a restarted rank has an empty _exported_inos,
        # but the subtree table + parent backpointers survive
        if self._load_subtrees():
            path = self._ino_path(ino)
            if path is not None:
                r = self._authority(path)
                if r != self.rank:
                    self._exported_inos[ino] = r    # cache
                    return 0, {"forward": r}
        return None

    def _subtree_inos(self, root_ino: int) -> list[int]:
        """Every ino under a directory (recursive walk of the shared
        dirfrags)."""
        out = []
        stack = [root_ino]
        while stack:
            cur = stack.pop()
            for _name, child in self._load_dir(cur).items():
                out.append(child)
                inode = self._load_inode(child)
                if inode is not None and inode.is_dir():
                    stack.append(child)
        return out

    def _do_export(self, path: str, to_rank: int) -> tuple[int, dict]:
        """Export a subtree to another rank (Migrator::export_dir,
        reduced).  Phases: freeze -> recall every cap to nothing and
        flush (so NOTHING dirty or delegated remains) -> commit the new
        authority in the shared table -> hand the lock state to the
        importer -> drop local state and forward from now on.
        Re-entered via the park/retry machinery while recalls drain."""
        norm = self._norm(path)
        _p, root_ino, _n = self._resolve(path)
        if root_ino is None:
            return -2, {}
        # leases are RANK-LOCAL state: the importer cannot revoke what
        # it never granted, so void them (clients re-lease from the
        # new authority on their next lookup) — the subtree's AND the
        # exported root's own dentry leases
        self._revoke_lease_subtree(root_ino)
        self._revoke_ino_leases(root_ino)
        inode = self._load_inode(root_ino)
        if inode is None or not inode.is_dir():
            return -20, {}
        fs = self.objecter.osdmap.fs_db
        if str(to_rank) not in (fs or {}).get("ranks", {}):
            return -22, {}
        if to_rank == self.rank:
            return 0, {"noop": True}
        self._frozen[norm] = root_ino
        try:
            inos = self._subtree_inos(root_ino)
            pending_ino = None
            for ino in inos:
                revokes = self.caps.recall(ino, ALL_CAPS)
                if revokes:
                    self._issue_revokes(ino, revokes)
                if pending_ino is None \
                        and self.caps.pending_revokes(ino):
                    pending_ino = ino
            if pending_ino is not None:
                # park on a PENDING ino: its ack (or revoke-timeout
                # eviction) re-runs us, and we re-check the rest.
                # Deliberately still frozen: re-entry needs it.
                raise _Park(pending_ino)
            # everything is flushed client-side; persist our state
            self._flush_dirty()
            self.journal.trim()
            # COMMIT POINT: the shared table now names the importer
            table = {k: str(v).encode()
                     for k, v in
                     self._load_subtrees(force=True).items()}
            table[norm] = str(to_rank).encode()
            self.meta_io.set_omap(self.SUBTREE_OBJ, table)
            self._subtrees = None       # re-read next time
        except _Park:
            raise
        except Exception:
            # pre/at-commit failure: unfreeze and let waiters re-run
            # (the table either still names us, or — if the omap write
            # landed before raising — the durable authority check
            # forwards from now on; both are consistent states)
            del self._frozen[norm]
            self._rerun(root_ino)
            raise
        # post-commit: the export MUST complete — the table already
        # names the importer.  The lock handoff is best-effort (a dead
        # importer loses in-memory locks, exactly like an MDS failover
        # does); everything else is local.
        locks = {}
        for ino in inos:
            ls = self._locks.pop(ino, None)
            if ls is not None and not ls.empty():
                locks[str(ino)] = {
                    "posix": [[k.client, k.owner, k.type, k.start,
                               k.end] for k in ls.posix],
                    "flock": [[k.client, k.owner, k.type] for k in
                              ls.flock]}
        try:
            ent = fs["ranks"][str(to_rank)]
            con = self.msgr.connect_to(ent["addr"],
                                       EntityName("mds", 0))
            con.send_message(MMDSExport(
                path=norm, from_rank=self.rank,
                locks_blob=json.dumps(locks).encode()))
        except OSError:
            dout("mds", 0, "export %s: lock handoff to rank %d failed "
                 "(locks dropped, like a failover)", norm, to_rank)
        # drop grants (clients re-open at the importer on next need)
        for ino in inos:
            for c in list(self.caps.holders(ino)):
                self._send_caps(c, MClientCaps(
                    op="invalidated", ino=ino, caps=0, client=c))
                self.caps.force_drop(ino, c)
                self._revoke_sent.pop((ino, c), None)
            self._exported_inos[ino] = to_rank
        self._exported_inos[root_ino] = to_rank
        # drop ONLY the subtree's cached state (it was flushed above;
        # the rest of the cache is still ours and still hot)
        for ino in [root_ino] + inos:
            self._inodes.pop(ino, None)
            self._dirs.pop(ino, None)
        self._req_counts.pop("/" + norm.split("/")[1], None)
        del self._frozen[norm]
        self._rerun(root_ino)
        for ino in inos:
            self._rerun(ino)
        dout("mds", 1, "mds rank %s exported %s -> rank %d (%d inos)",
             self.rank, norm, to_rank, len(inos))
        return 0, {"inos": len(inos)}

    def _maybe_autobalance(self) -> None:
        """MDBalancer reduced: when my request rate dwarfs the least-
        loaded rank's (the mon computes the hint into beacon acks),
        export my hottest top-level subtree to it."""
        if not (self.bal_auto and self.rank is not None
                and self.state == "active"):
            return
        if self._pending_export is not None:
            # an auto-export parked on cap recalls: it MUST be retried
            # past the load gates (the freeze itself kills the load
            # signal) or the subtree would stay frozen forever
            path, to_rank = self._pending_export
            try:
                self._do_export(path, to_rank)
                self._pending_export = None
            except _Park:
                pass
            except OSError:
                self._pending_export = None
            return
        if self._bal_rank < 0 or self._bal_rank == self.rank:
            return
        if self._load_rate <= (self.bal_factor * self._bal_load
                               + self.bal_floor):
            return
        cands = {p: n for p, n in self._req_counts.items() if p != "/"}
        if not cands:
            return
        hot = max(cands, key=lambda p: cands[p])
        try:
            self._do_export(hot, self._bal_rank)
        except _Park:
            self._pending_export = (hot, self._bal_rank)
        except OSError:
            pass

    def _handle_export_msg(self, msg: MMDSExport) -> None:
        """Importer side: install the handed-over locks and drop any
        cached view of the subtree (reload from the shared pool)."""
        with self._lock:
            locks = json.loads(msg.locks_blob.decode() or "{}")
            for ino_s, st in locks.items():
                ls = self._locks.setdefault(int(ino_s), LockState())
                ls.posix = [Lock(*row) for row in st.get("posix", [])]
                ls.flock = [Lock(c, o, t, 0, EOF)
                            for c, o, t in st.get("flock", [])]
            # OUR dirty state must land before the cache drop, or the
            # next flush would rewrite those dirfrags from empty caches
            self._flush_dirty()
            self._inodes.clear()
            self._dirs.clear()
            self._subtrees = None
            # inos under the imported subtree are OURS again even if a
            # past export of the same subtree recorded them as gone
            norm = self._norm(msg.path)
            _p, root_ino, _n = self._resolve(msg.path)
            if root_ino is not None:
                for ino in [root_ino] + self._subtree_inos(root_ino):
                    self._exported_inos.pop(ino, None)
            dout("mds", 1, "mds rank %s imported %s from rank %d",
                 self.rank, msg.path, msg.from_rank)

    # -- path resolution ------------------------------------------------------

    def _resolve(self, path: str) -> tuple[int | None, int | None, str]:
        """path -> (parent_ino, ino, last_name); ino None if the leaf
        does not exist, parent None if an intermediate is missing."""
        parts = [p for p in path.split("/") if p]
        cur = ROOT_INO
        if not parts:
            return None, ROOT_INO, "/"
        for p in parts[:-1]:
            child = self._load_dir(cur).get(p)
            if child is None:
                return None, None, parts[-1]
            inode = self._load_inode(child)
            if inode is None or not inode.is_dir():
                return None, None, parts[-1]
            cur = child
        name = parts[-1]
        return cur, self._load_dir(cur).get(name), name

    # -- request handling -----------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if self._stop:
            return True
        if isinstance(msg, MClientRequest):
            self._handle_request(msg)
            return True
        if isinstance(msg, MClientSession):
            self._handle_session(msg)
            return True
        if isinstance(msg, MClientCaps):
            self._handle_caps_msg(msg)
            return True
        if isinstance(msg, MMDSExport):
            self._handle_export_msg(msg)
            return True
        from ceph_tpu.mon.monitor import MMDSBeacon
        if isinstance(msg, MMDSBeacon):       # mon ack
            self._bal_rank = getattr(msg, "bal_rank", -1)
            self._bal_load = getattr(msg, "bal_load", 0.0)
            if msg.state == "ack" and msg.rank >= 0 \
                    and self.rank is None:
                self._activate(msg.rank, meta_pool=msg.meta_pool,
                               data_pool=msg.data_pool)
            return True
        return False

    def _handle_request(self, msg) -> None:
        try:
            with self._lock:
                if "client" in msg.args:
                    s = self._sessions.get(int(msg.args["client"]))
                    if s is not None:
                        s["last_seen"] = time.time()
                        s["con"] = msg.connection
                result, out = self._handle(msg.op, msg.args)
                # reply INSIDE the lock: a grant reply must hit the wire
                # before any revoke a competing request issues against
                # it (per-connection FIFO then guarantees the client
                # installs the grant before seeing the revoke)
                msg.connection.send_message(
                    MClientReply(tid=msg.tid, result=result, out=out))
            return
        except _Park as p:
            # request waits for cap acks / lock release on this ino;
            # re-dispatched verbatim when the state changes
            if not hasattr(msg, "_parked_at"):
                msg._parked_at = time.time()
            with self._lock:
                self._parked.setdefault(p.ino, []).append(msg)
            return
        except Exception:
            from ceph_tpu.common.logging import get_logger
            get_logger("mds").exception("mds request %s failed", msg.op)
            result, out = -5, {}
        msg.connection.send_message(
            MClientReply(tid=msg.tid, result=result, out=out))

    def _rerun(self, ino: int) -> None:
        """Re-dispatch every request parked on an ino (waiters fire on
        any cap/lock state change there)."""
        msgs = self._parked.pop(ino, [])
        for m in msgs:
            self._handle_request(m)

    # -- sessions --------------------------------------------------------------

    def _handle_session(self, msg: MClientSession) -> None:
        with self._lock:
            if msg.op == "request_open":
                self._sessions[msg.client] = {
                    "con": msg.connection, "last_seen": time.time()}
                msg.connection.send_message(MClientSession(
                    tid=msg.tid, op="open_ack", client=msg.client))
            elif msg.op == "renew":
                s = self._sessions.get(msg.client)
                if s is not None:
                    s["last_seen"] = time.time()
                    s["con"] = msg.connection
            elif msg.op == "request_close":
                self._evict_client(msg.client)
                msg.connection.send_message(MClientSession(
                    tid=msg.tid, op="close_ack", client=msg.client))

    # -- capability traffic ----------------------------------------------------

    def _send_caps(self, client: int, m: MClientCaps) -> bool:
        s = self._sessions.get(client)
        if s is None:
            # no session to talk to: the grant is unrecallable — drop it
            self.caps.force_drop(m.ino, client)
            return False
        # every cap message carries the current barrier: an async
        # re-grant of WR must not hand a client write permission
        # without also handing it the epoch it must reach first
        m.epoch_barrier = max(m.epoch_barrier, self._osd_epoch_barrier)
        s["con"].send_message(m)
        return True

    def _revoke_dentry_lease(self, parent: int, name: str,
                             exclude: int | None = None) -> None:
        """Void every client's lease on one dentry (fire-and-forget +
        TTL backstop — lease semantics, MClientLease revoke)."""
        holders = self._dentry_leases.pop((parent, name), None)
        if not holders:
            return
        # revoke even "expired" holders: the client stamps its expiry
        # at REPLY-receipt time, later than our grant stamp — filtering
        # by our clock would skip a revoke the client still needs
        live = [c for c in holders if c != exclude]
        if not live:
            return
        ppath = self._ino_path(parent)
        if ppath is None:
            return
        path = ppath.rstrip("/") + "/" + name
        for c in live:
            s = self._sessions.get(c)
            if s is not None:
                s["con"].send_message(MClientLease(op="revoke",
                                                   path=path))

    def _revoke_lease_subtree(self, root_ino: int) -> None:
        """Void every lease whose dentry lives UNDER root_ino (dir
        rename moves every descendant path; subtree export moves
        authority away from this rank's lease table) — walk each leased
        parent's backpointer chain to test membership."""
        for (p, n) in list(self._dentry_leases):
            cur = p
            for _ in range(64):
                if cur == root_ino:
                    self._revoke_dentry_lease(p, n)
                    break
                node = self._inodes.get(cur) or self._load_inode(cur)
                if node is None or not node.parent or cur == ROOT_INO:
                    break
                cur = node.parent

    def _revoke_ino_leases(self, ino: int,
                           exclude: int | None = None) -> None:
        """Void leases on EVERY dentry of an inode (attr change, or a
        writer just got WR: cached stats would go stale)."""
        inode = self._inodes.get(ino) or self._load_inode(ino)
        if inode is None or not inode.is_dir():
            # only DIRECTORY dentries are ever leased: skip the parent
            # dirfrag scan on the file setattr hot path (buffered-size
            # writebacks land here for every flush)
            return
        dentries = list(inode.remote_links)
        parent = inode.parent
        if parent:
            for n, child in self._load_dir(parent).items():
                if child == ino:
                    dentries.append([parent, n])
        for p, n in dentries:
            self._revoke_dentry_lease(int(p), n, exclude=exclude)

    def _issue_revokes(self, ino: int, revokes) -> None:
        now = time.time()
        for client, new_caps, seq in revokes:
            dout("mds", 10, "mds revoking ino %d client.%d -> %s",
                 ino, client, caps_str(new_caps))
            if self._send_caps(client, MClientCaps(
                    op="revoke", ino=ino, caps=new_caps, seq=seq,
                    client=client)):
                self._revoke_sent.setdefault((ino, client), now)

    def _handle_caps_msg(self, msg: MClientCaps) -> None:
        with self._lock:
            if msg.op == "ack":
                if self.caps.ack(msg.ino, msg.client, msg.seq):
                    self._revoke_sent.pop((msg.ino, msg.client), None)
                if msg.size >= 0:
                    # flushed dirty metadata rides the ack (journaled
                    # like any setattr so replay keeps it; grow-only —
                    # writeback never truncates)
                    if self._load_inode(msg.ino) is not None:
                        self._mutate({"e": "setattr", "ino": msg.ino,
                                      "size": msg.size, "grow": True,
                                      "mtime": msg.mtime})
            elif msg.op == "release":
                self._do_release(msg.ino, msg.client)
            else:
                return
            # rerun INSIDE the lock: outside it, the tick thread's
            # parked-list rewrite could re-insert a request this rerun
            # already dispatched (double lock grant)
            self._rerun(msg.ino)

    def _do_release(self, ino: int, client: int) -> None:
        for c, new_caps, seq in self.caps.release(ino, client):
            self._send_caps(c, MClientCaps(
                op="grant", ino=ino, caps=new_caps, seq=seq, client=c))
        self._revoke_sent.pop((ino, client), None)

    def _upgrade_after_release(self, ino: int) -> None:
        """Re-evaluate an ino after a holder vanished (release path is
        _do_release; this one serves evictions)."""
        for c, new_caps, seq in self.caps.release(ino, -1):
            self._send_caps(c, MClientCaps(
                op="grant", ino=ino, caps=new_caps, seq=seq, client=c))

    def _fresh_inode(self, ino: int, requester: int | None) -> None:
        """Before answering attrs: recall BUFFER from every OTHER
        holder so the size answered is the truth (Locker file_eval
        before a stat — the stat-sees-latest-write coherence rule)."""
        revokes = self.caps.recall(ino, BUFFER, exclude=requester)
        if revokes:
            self._issue_revokes(ino, revokes)
        if self.caps.pending_revokes(ino, exclude=requester):
            raise _Park(ino)

    def _handle(self, op: str, a: dict) -> tuple[int, dict]:
        client = int(a.get("client", -1))
        if self.state != "active":
            # the FSMap can point clients here before activation
            # completes (or while we are a standby a stale client
            # still targets): hold the request, activation reruns it
            raise _Park(0)
        if self._reconnect_until and op not in ("cap_reassert", "statfs"):
            if time.time() < self._reconnect_until:
                # reconnect window after a takeover: hold client ops
                # until the old clients reasserted their caps (ino 0 is
                # the window's wait key; the tick releases it)
                raise _Park(0)
            self._reconnect_until = 0.0
            self._rerun(0)

        # multi-active authority: path ops forward to the delegated
        # rank; ino ops forward once the ino's subtree was exported
        if op in ("lookup", "mkdir", "create", "open", "readdir",
                  "unlink", "rmdir", "export_dir", "mksnap", "rmsnap",
                  "lssnap", "setquota", "getquota"):
            fwd = self._check_path_authority(
                a["path"], allow_frozen=(op == "export_dir"))
            if fwd is not None:
                return fwd
        # read-only views into directory snapshots (dir/.snap/...):
        # SEGMENT-based detection — a component merely prefixed
        # ".snap" (".snapshots") is an ordinary name
        if "path" in a and self._split_snap_path(
                self._norm(a["path"])) is not None:
            if op in ("lookup", "open", "readdir", "getattr"):
                return self._handle_snap_path(op, a)
            if op in ("mkdir", "create", "unlink", "rmdir", "setattr",
                      "rename", "mksnap", "rmsnap", "setquota"):
                return -30, {}   # EROFS: snapshots are immutable
        if op == "mksnap":
            return self._do_mksnap(a)
        if op == "rmsnap":
            return self._do_rmsnap(a)
        if op == "setquota":
            _p, qino, _n = self._resolve(a["path"])
            if qino is None:
                return -2, {}
            qi = self._load_inode(qino)
            if qi is None or not qi.is_dir():
                return -20, {}
            self._revoke_ino_leases(qino, exclude=client)
            self._mutate({"e": "setattr", "ino": qino,
                          "quota_bytes": int(a.get("max_bytes", 0)),
                          "quota_files": int(a.get("max_files", 0))})
            return 0, {}
        if op == "getquota":
            _p, qino, _n = self._resolve(a["path"])
            if qino is None:
                return -2, {}
            qi = self._load_inode(qino)
            if qi is None:
                return -2, {}
            used_b, used_f = self._subtree_usage(qino) \
                if qi.is_dir() else (qi.size, 0)
            return 0, {"max_bytes": qi.quota_bytes,
                       "max_files": qi.quota_files,
                       "used_bytes": used_b, "used_files": used_f}
        if op == "lssnap":
            _p, sino, _n = self._resolve(a["path"])
            if sino is None:
                return -2, {}
            return 0, {"snaps": {n: {"snapid": r["snapid"],
                                     "created": r["created"]}
                                 for n, r in
                                 self._load_snaps(sino).items()}}
        elif op in ("rename", "link"):
            fa = self._check_path_authority(a["src"])
            if fa is not None:
                return fa
            if self._authority(a["dst"]) != self.rank:
                # cross-subtree rename/link: the reference migrates;
                # here it is an honest EXDEV (callers copy+unlink)
                return -18, {}
            norm_src = self._norm(a["src"])
            for pref in self._load_subtrees():
                if pref == norm_src or pref.startswith(norm_src + "/"):
                    # renaming a delegation root (or an ancestor of
                    # one) would silently orphan the delegation
                    return -16, {}
        elif "ino" in a and op != "cap_reassert":
            fwd = self._check_ino_authority(int(a["ino"]))
            if fwd is not None:
                return fwd

        if op == "export_dir":
            return self._do_export(a["path"], int(a["to"]))

        if op == "cap_reassert":
            # failover rejoin: a surviving client re-asserts the caps
            # (and buffered size) it held under the dead rank — trusted
            # within the window, like the reference's reconnect phase
            for ent in a.get("caps", []):
                self.caps.reassert(int(ent["ino"]), client,
                                   int(ent["caps"]))
                if ent.get("size", -1) >= 0 and \
                        self._load_inode(int(ent["ino"])) is not None:
                    self._mutate({"e": "setattr", "ino": int(ent["ino"]),
                                  "size": int(ent["size"]), "grow": True,
                                  "mtime": float(ent.get("mtime", 0.0))})
            return 0, {}

        if op == "lookup":
            parent, ino, name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None:
                return -2, {}
            if not inode.is_dir():
                # stat must see the latest write: flush buffered
                # writers first (parks until their acks land)
                self._fresh_inode(ino, requester=client)
                inode = self._load_inode(ino)
            out = {"inode": inode.to_dict()}
            # dentry lease (Locker::issue_client_lease, reduced to the
            # coherent subset): DIRECTORY dentries+attrs only.  A file
            # lease would have to exclude size/mtime — those are cap
            # (Fs) territory, and a leased file stat racing a writer's
            # open would miss its sizes; directory attrs here change
            # only through ops that revoke (rename/rmdir/setattr), so
            # dir leases are coherent by construction
            if parent is not None and name and client >= 0 \
                    and inode.is_dir():
                ttl = float(self.ctx.conf.get("mds_dentry_lease_ttl"))
                if ttl > 0:
                    self._dentry_leases.setdefault(
                        (parent, name), {})[client] = time.time() + ttl
                    out["lease"] = ttl
            return 0, out

        if op == "getattr":
            inode = self._load_inode(a["ino"])
            if inode is None:
                return -2, {}
            if not inode.is_dir():
                self._fresh_inode(inode.ino, requester=client)
                inode = self._load_inode(inode.ino)
            return 0, {"inode": inode.to_dict()}

        if op == "open":
            # create-if-needed + capability issue (the Locker half of
            # Server::handle_client_open)
            parent, ino, name = self._resolve(a["path"])
            created = False
            if ino is None:
                if parent is None:
                    return -2, {}
                if not a.get("create"):
                    return -2, {}
                if not self._check_quota(parent, add_files=1):
                    return -122, {}   # EDQUOT
                ino = self._alloc_ino()
                self._mutate({"e": "link", "parent": parent, "name": name,
                              "ino": ino,
                              "mode": S_IFREG | a.get("mode", 0o644),
                              "size": 0, "mtime": time.time()})
                created = True
            inode = self._load_inode(ino)
            if inode is None:
                return -2, {}
            if inode.is_dir():
                return -21, {}  # EISDIR
            granted, revokes = self.caps.open_want(
                ino, client, int(a["wanted"]))
            if revokes:
                self._issue_revokes(ino, revokes)
            if granted is None:
                raise _Park(ino)
            return 0, {"inode": inode.to_dict(), "caps": granted,
                       "cap_seq": self.caps.grant_seq(ino, client),
                       "created": created, "data_pool": self.data_pool,
                       "epoch_barrier": self._osd_epoch_barrier}

        if op == "cap_want":
            # cap re-acquisition after a revoke (Client::get_caps): a
            # writer whose WR was recalled — e.g. by mksnap's freeze —
            # round-trips here before touching RADOS again, and leaves
            # with the current epoch barrier
            ino = a["ino"]
            if self._load_inode(ino) is None:
                return -2, {}
            granted, revokes = self.caps.open_want(
                ino, client, int(a["wanted"]))
            if revokes:
                self._issue_revokes(ino, revokes)
            if granted is None:
                raise _Park(ino)
            return 0, {"caps": granted,
                       "cap_seq": self.caps.grant_seq(ino, client),
                       "epoch_barrier": self._osd_epoch_barrier}

        if op == "cap_release":
            # synchronous form of MClientCaps 'release' (close path
            # wants the upgrade side effects ordered before its return)
            self._do_release(a["ino"], client)
            self._rerun(a["ino"])
            return 0, {}

        if op == "open_cancel":
            # the client's open RPC timed out: withdraw whatever grant/
            # wanted registration the (possibly still-parked) open left,
            # so the ino does not stay in sync mode for a ghost
            parent, ino, _name = self._resolve(a["path"])
            if ino is not None:
                self._do_release(ino, client)
                self._rerun(ino)
            return 0, {}

        if op in ("setlk", "flock"):
            ino = a["ino"]
            if self._load_inode(ino) is None:
                return -2, {}
            ls = self._locks.setdefault(ino, LockState())
            owner = str(a["owner"])
            ltype = int(a["type"])
            if op == "setlk":
                start, end = fcntl_range(int(a.get("start", 0)),
                                         int(a.get("len", 0)))
                ok = ls.posix_set(client, owner, ltype, start, end)
            else:
                ok = ls.flock_set(client, owner, ltype)
            if ok:
                if ltype == F_UNLCK and ls.empty():
                    del self._locks[ino]
                # ANY successful change can unblock a waiter (unlock,
                # but also a WRLCK->RDLCK downgrade or a range shrink)
                self._rerun(ino)
                return 0, {}
            if a.get("wait"):
                raise _Park(ino)        # F_SETLKW / LOCK_EX blocking
            return -11, {}              # EAGAIN

        if op == "getlk":
            ls = self._locks.get(a["ino"])
            if ls is None:
                return 0, {"lock": None}
            start, end = fcntl_range(int(a.get("start", 0)),
                                     int(a.get("len", 0)))
            return 0, {"lock": ls.getlk(client, str(a["owner"]),
                                        int(a["type"]), start, end)}

        if op == "mkdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                return -17, {}  # EEXIST
            if not self._check_quota(parent, add_files=1):
                return -122, {}   # EDQUOT
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFDIR | a.get("mode", 0o755),
                          "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict()}

        if op == "create":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                inode = self._load_inode(ino)
                if inode is None or inode.is_dir():
                    return -21, {}  # EISDIR
                return 0, {"inode": inode.to_dict(),
                           "data_pool": self.data_pool}
            if not self._check_quota(parent, add_files=1):
                return -122, {}   # EDQUOT
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFREG | a.get("mode", 0o644),
                          "size": 0, "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict(),
                       "data_pool": self.data_pool}

        if op == "readdir":
            _parent, ino, _name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}  # ENOTDIR
            out = {}
            for name, child in sorted(self._load_dir(ino).items()):
                ci = self._load_inode(child)
                if ci is not None:
                    out[name] = ci.to_dict()
            return 0, {"entries": out}

        if op == "unlink":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is not None and inode.is_dir():
                return -21, {}
            had_links = inode is not None and bool(inode.remote_links)
            self._revoke_dentry_lease(parent, name)
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            # no store re-read: with links the inode survived
            # (re-homed or pair-removed); without, drop_inode took it
            removed = inode is None or not had_links
            if removed:
                # last link gone: caps/locks die with the inode.  With
                # hardlinks remaining the inode re-homed and open
                # handles stay valid (POSIX unlink semantics)
                self._drop_ino_state(ino)
            return 0, {"ino": ino, "removed": removed}

        if op == "link":
            # hardlink (CDentry.h:77-90 remote dentries): a second
            # name for an existing file inode, possibly in another
            # directory; nlink derives from the remote-link table
            sp, sino, _sn = self._resolve(a["src"])
            if sp is None or sino is None:
                return -2, {}
            inode = self._load_inode(sino)
            if inode is None:
                return -2, {}
            if inode.is_dir():
                return -1, {}    # EPERM: no directory hardlinks
            dp, dino, dname = self._resolve(a["dst"])
            if dp is None:
                return -2, {}
            if dino is not None:
                return -17, {}   # EEXIST
            if not self._check_quota(dp, add_files=1):
                return -122, {}  # EDQUOT
            self._mutate({"e": "link", "parent": dp, "name": dname,
                          "ino": sino, "remote": True})
            return 0, {"ino": sino,
                       "inode": self._load_inode(sino).to_dict()}

        if op == "rmdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}
            if self._load_dir(ino):
                return -39, {}  # ENOTEMPTY
            self._revoke_dentry_lease(parent, name)
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            norm = self._norm(a["path"])
            if norm in self._load_subtrees(force=True):
                # removing a delegation root retires its table entry
                # (omap sets merge — deletion needs an explicit rm)
                self.meta_io.rm_omap_keys(self.SUBTREE_OBJ, [norm])
                self._subtrees = None
            return 0, {}

        if op == "rename":
            sp, sino, sname = self._resolve(a["src"])
            if sp is None or sino is None:
                return -2, {}
            dp, dino, dname = self._resolve(a["dst"])
            if dp is None:
                return -2, {}
            if dino is not None:
                return -17, {}
            # one atomic journal entry for link-at-dst + unlink-src (the
            # reference's single EUpdate): a crash can never leave the
            # inode reachable from both paths.  Renaming a REMOTE
            # dentry moves the remote pair, never the backpointer
            s_inode = self._load_inode(sino)
            remote = (s_inode is not None
                      and [sp, sname] in s_inode.remote_links)
            self._revoke_dentry_lease(sp, sname)
            self._revoke_dentry_lease(dp, dname)
            if s_inode is not None and s_inode.is_dir():
                # every descendant's cached PATH string moved with it
                self._revoke_lease_subtree(sino)
            self._mutate({"e": "batch", "events": [
                {"e": "link", "parent": dp, "name": dname, "ino": sino,
                 **({"remote": True} if remote else {})},
                {"e": "unlink", "parent": sp, "name": sname}]})
            return 0, {"ino": sino}

        if op == "setattr":
            self._revoke_ino_leases(int(a["ino"]), exclude=client)
            ev = {"e": "setattr", "ino": a["ino"]}
            for k in ("size", "mtime", "mode", "grow"):
                if k in a:
                    ev[k] = a[k]
            if self._load_inode(a["ino"]) is None:
                return -2, {}
            if "size" in a:
                # a size change (truncate / size writeback) must not
                # race a buffered writer: flush them first
                self._fresh_inode(a["ino"], requester=client)
                cur = self._load_inode(a["ino"])
                delta = int(a["size"]) - (cur.size if cur else 0)
                if delta > 0 and not self._check_quota(
                        a["ino"], add_bytes=delta):
                    return -122, {}   # EDQUOT
            self._mutate(ev)
            return 0, {"inode": self._inodes[a["ino"]].to_dict()}

        if op == "statfs":
            return 0, {"next_ino": self._next_ino,
                       "data_pool": self.data_pool,
                       "metadata_pool": self.metadata_pool}

        return -22, {}

    def _drop_ino_state(self, ino: int) -> None:
        """Unlinked inode: its caps and locks evaporate; surviving
        holders are TOLD (op 'invalidated') so they stop buffering
        against purged data; anything parked re-runs (and sees
        ENOENT)."""
        for c in list(self.caps.holders(ino)):
            self._send_caps(c, MClientCaps(
                op="invalidated", ino=ino, caps=0, client=c))
            self.caps.force_drop(ino, c)
            self._revoke_sent.pop((ino, c), None)
        self._locks.pop(ino, None)
        self._rerun(ino)

    def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        # journal the allocation so replay never re-issues a used ino
        self._journal({"e": "alloc", "next_ino": self._next_ino})
        self._maybe_trim()
        return ino
