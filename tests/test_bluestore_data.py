"""The device-resident objectstore write path: the ``bluestore_data``
dispatch channel's bit-exactness and fault ladder, the tpu_bitplane
compressor plugin, the compressor registry's kwargs/typed-error
contract, the KV journal's loud truncation ledger, and BlueStoreLite
end-to-end with batched checksums + block compression."""

from __future__ import annotations

import json
import os
import struct
import time
import zlib

import numpy as np
import pytest

from ceph_tpu import compressor
from ceph_tpu.common import failpoint
from ceph_tpu.objectstore import Transaction
from ceph_tpu.objectstore.bluestore import BLOCK, BlueStoreLite
from ceph_tpu.objectstore.kv import KVTransaction, LogDB
from ceph_tpu.ops import checksum_kernel as ck
from ceph_tpu.ops import compression_kernel as bk
from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import (
    DeviceDispatchEngine, submit_bluestore_data)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


def _engine(**kw):
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats(), **kw)
    eng.fault_backoff_ms = 1.0
    eng.fault_backoff_max_ms = 5.0
    eng.probe_interval = 0.05
    return eng


def _wait_breaker(eng, channel, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.breaker_states().get(channel) == state:
            return True
        time.sleep(0.02)
    return False


# -- the bluestore_data digest channel ---------------------------------------

class TestBluestoreDataChannel:
    #: empty, sub-word, odd, and width-bucket-edge sizes: the unpad
    #: epilogue must hold across all of them
    SIZES = [0, 1, 3, 7, 8, 9, 63, 64, 65, 255, 256, 1000,
             ck.MIN_WIDTH - 1, ck.MIN_WIDTH, ck.MIN_WIDTH + 1,
             4095, 4096, 4097]

    def test_bit_exact_property_vs_zlib_crc32(self):
        """The acceptance pin: column 0 of a submit_bluestore_data
        batch (through the engine, padding and Z^-pad unpadding
        included) equals the host zlib.crc32 of every stored payload,
        for sizes 0 / odd / bucket-edge and random patterns."""
        rng = np.random.default_rng(17)
        eng = _engine()
        try:
            for round_ in range(2):
                sizes = list(self.SIZES) + [
                    int(s) for s in rng.integers(0, 6000, 12)]
                blobs = [rng.integers(0, 256, s, dtype=np.uint8)
                         .tobytes() for s in sizes]
                got = np.asarray(
                    submit_bluestore_data(eng, blobs).result(60))
                for i, b in enumerate(blobs):
                    assert int(got[i, 0]) == (zlib.crc32(b)
                                              & 0xFFFFFFFF), (round_, i)
        finally:
            eng.stop()

    def test_shares_scrub_jit_executable(self):
        """bluestore_digest_batched delegates to the SAME jitted entry
        point scrub uses: digesting through both names at one width
        must not add a compile cache entry for the second."""
        rng = np.random.default_rng(5)
        batch = rng.integers(0, 256, (4, 64), dtype=np.uint8)
        lengths = [64, 63, 1, 0]
        for i, n in enumerate(lengths):   # rows are ZERO-padded past n
            batch[i, n:] = 0
        mats, invp = ck.digest_operands(lengths, 64)
        ck.scrub_digest_batched(batch, mats, invp)
        before = ck.digest_jit_entries()
        got = np.asarray(
            ck.bluestore_digest_batched(batch, mats, invp))
        assert ck.digest_jit_entries() == before
        ref = ck.scrub_digest_ref(batch, lengths)
        assert np.array_equal(got, np.asarray(ref))

    def test_transient_fault_retries_bit_exact(self):
        eng = _engine()
        try:
            failpoint.set("dispatch.launch:bluestore_data", "nth:1")
            blobs = [b"retry-me" * 40, b"x" * 7]
            got = np.asarray(
                submit_bluestore_data(eng, blobs).result(60))
            for i, b in enumerate(blobs):
                assert int(got[i, 0]) == (zlib.crc32(b) & 0xFFFFFFFF)
            d = eng.stats.fault_dump()
            assert d["retries"] >= 1 and d["retry_successes"] >= 1, d
        finally:
            eng.stop()

    def test_hard_outage_opens_breaker_falls_back_then_recloses(self):
        """The PR 11 fault ladder on the sixth channel: a hard device
        outage opens the bluestore_data breaker, every batch is served
        by the bit-exact scrub_digest_ref oracle, and clearing the
        fault lets the background probe re-close the breaker."""
        eng = _engine()
        eng.breaker_threshold = 2
        try:
            failpoint.set("dispatch.launch:bluestore_data", "always")
            blobs = [b"outage" * 50, b"", b"z" * 129]
            for _ in range(3):
                got = np.asarray(
                    submit_bluestore_data(eng, blobs).result(60))
                for i, b in enumerate(blobs):
                    assert int(got[i, 0]) == (zlib.crc32(b)
                                              & 0xFFFFFFFF)
            d = eng.stats.fault_dump()
            assert d["breaker_opens"] >= 1, d
            assert d["fallback_batches"] >= 1, d
            assert eng.breaker_states()["bluestore_data"] == \
                telemetry.BREAKER_OPEN
            failpoint.clear()
            assert _wait_breaker(eng, "bluestore_data",
                                 telemetry.BREAKER_CLOSED)
            got = np.asarray(submit_bluestore_data(
                eng, [b"healed" * 3]).result(60))
            assert int(got[0, 0]) == (zlib.crc32(b"healed" * 3)
                                      & 0xFFFFFFFF)
        finally:
            eng.stop()


# -- the bitplane compression kernel + plugin ---------------------------------

class TestBitplane:

    def test_planes_device_matches_ref(self):
        rng = np.random.default_rng(9)
        batch = rng.integers(0, 256, (5, 96), dtype=np.uint8)
        ref = bk.bitplane_planes_ref(batch)
        dev = bk.bitplane_planes_batched(batch)
        assert np.array_equal(np.asarray(dev), ref)

    def test_encode_decode_roundtrip_property(self):
        rng = np.random.default_rng(11)
        blobs = [b"", b"\x00" * 100, b"a" * 999,
                 bytes(rng.integers(0, 256, 4096, dtype=np.uint8)),
                 bytes(rng.integers(0, 64, 4097, dtype=np.uint8)),
                 b"the quick brown fox " * 37]
        blobs += [bytes(rng.integers(0, 128, int(s), dtype=np.uint8))
                  for s in rng.integers(1, 3000, 8)]
        planes = bk.pack_planes(blobs)
        for b, p in zip(blobs, planes):
            body = bk.encode_block(b, p)
            assert bk.decode_block(body) == b

    def test_plugin_roundtrip_and_ratio_win_on_structured(self):
        """6-bit data has two provably-zero planes: the plugin must
        round-trip byte-identical AND beat the raw size clearly."""
        rng = np.random.default_rng(13)
        c = compressor.create("tpu_bitplane")
        data = bytes(rng.integers(0, 64, BLOCK, dtype=np.uint8))
        comp = c.compress(data)
        assert c.decompress(comp) == data
        assert len(comp) <= BLOCK * 0.8
        # random data keeps all planes: stored raw-tagged, one byte of
        # overhead, still round-trips
        rnd = bytes(rng.integers(0, 256, BLOCK, dtype=np.uint8))
        comp = c.compress(rnd)
        assert c.decompress(comp) == rnd
        assert len(comp) == BLOCK + 1

    def test_compress_batch_matches_single(self):
        rng = np.random.default_rng(15)
        c = compressor.create("tpu_bitplane")
        blobs = [bytes(rng.integers(0, 64, BLOCK, dtype=np.uint8))
                 for _ in range(4)]
        batch = c.compress_batch(blobs)
        for b, body in zip(blobs, batch):
            assert c.decompress(body) == b

    def test_corrupt_bodies_raise_compression_error(self):
        c = compressor.create("tpu_bitplane")
        good = c.compress(b"hello bitplane world" * 40)
        with pytest.raises(compressor.CompressionError):
            c.decompress(b"")                    # empty payload
        with pytest.raises(compressor.CompressionError):
            c.decompress(b"\x07whatever")        # unknown scheme tag
        with pytest.raises(compressor.CompressionError):
            c.decompress(good[:1])               # chopped header
        if good[:1] == b"\x01":
            with pytest.raises(compressor.CompressionError):
                c.decompress(good[:-3])          # truncated planes
        with pytest.raises(compressor.CompressionError):
            c.decompress(b"\x02not-zlib-data")   # corrupt zlib body


# -- the compressor registry contract ----------------------------------------

class TestCompressorRegistry:

    def test_unknown_kwarg_names_accepted_set(self):
        with pytest.raises(ValueError, match="accepted kwargs"):
            compressor.create("zlib", levle=3)
        with pytest.raises(ValueError, match="tpu_bitplane"):
            compressor.create("tpu_bitplane", mode="fast")
        # valid kwargs still construct
        assert compressor.create("zlib", level=1).level == 1
        assert compressor.create("tpu_bitplane", device=False) \
            .device is False

    def test_lzma_honors_level(self):
        """The seed's LzmaCompressor accepted a level and silently
        ignored it: preset must now follow the kwarg (preset 0 and 9
        produce different streams for compressible data)."""
        data = b"abcdefgh" * 4096
        fast = compressor.create("lzma", level=0).compress(data)
        small = compressor.create("lzma", level=9).compress(data)
        assert fast != small
        assert compressor.create("lzma").decompress(fast) == data
        assert compressor.create("lzma").decompress(small) == data

    def test_corrupt_input_raises_typed_error(self):
        for name in ("zlib", "lzma"):
            with pytest.raises(compressor.CompressionError):
                compressor.create(name).decompress(b"\xff" * 32)


# -- KV journal truncation ledger ---------------------------------------------

class TestKvJournalTruncation:

    def _logdb_with_tail(self, tmp_path, tail: bytes) -> LogDB:
        db = LogDB(str(tmp_path / "kv"))
        db.open()
        for i in range(3):
            db.submit_transaction(
                KVTransaction().set("p", f"k{i}", b"v"))
        db.close()
        with open(db._log_path, "ab") as f:
            f.write(tail)
        return db

    def test_clean_replay_reports_no_truncation(self, tmp_path):
        db = self._logdb_with_tail(tmp_path, b"")
        db.open()
        try:
            assert db.truncated_frames == 0
            assert db.truncated_bytes == 0
            assert db.get("p", "k2") == b"v"
        finally:
            db.close()

    def test_corrupt_tail_counts_frames_and_bytes(self, tmp_path):
        garbage = struct.pack("<II", 40, 0xDEAD) + b"x" * 11
        db = self._logdb_with_tail(tmp_path, garbage)
        db.open()
        try:
            # everything before the stop replayed; the chopped tail is
            # counted loudly instead of presenting a clean mount
            assert db.get("p", "k2") == b"v"
            assert db.truncated_frames == 1
            assert db.truncated_bytes == len(garbage)
        finally:
            db.close()

    def test_reopen_does_not_double_count(self, tmp_path):
        garbage = b"\x01\x02\x03\x04\x05"
        db = self._logdb_with_tail(tmp_path, garbage)
        db.open()
        db.close()
        db.open()
        try:
            assert db.truncated_frames == 1
            assert db.truncated_bytes == len(garbage)
        finally:
            db.close()

    def test_bluestore_mount_surfaces_counter(self, tmp_path):
        s = BlueStoreLite(str(tmp_path))
        s.mkfs()
        s.mount()
        s.apply_transaction(Transaction().create_collection("1.0"))
        s.umount()
        with open(os.path.join(str(tmp_path), "kv", "kv.log"),
                  "ab") as f:
            f.write(b"torn-tail")
        before = telemetry.bluestore_dump()
        s2 = BlueStoreLite(str(tmp_path))
        s2.mount()
        try:
            assert s2.perf.value("kv_journal_truncated") == 1
            after = telemetry.bluestore_dump()
            assert after["kv_journal_truncated"] == \
                before["kv_journal_truncated"] + 1
            assert after["kv_journal_lost_bytes"] == \
                before["kv_journal_lost_bytes"] + len(b"torn-tail")
        finally:
            s2.umount()


# -- BlueStoreLite end-to-end -------------------------------------------------

@pytest.fixture(scope="class")
def ctx():
    from ceph_tpu.common.context import CephTpuContext
    c = CephTpuContext("test-bluestore-data")
    c.conf.set("bluestore_batched_csum_min", "1", source="cli")
    c.conf.set("bluestore_batched_read_min", "1", source="cli")
    try:
        yield c
    finally:
        for attr in ("_decode_dispatch", "_dispatch"):
            e = getattr(c, attr, None)
            if e is not None:
                e.stop()


def _host_csum_audit(store) -> bool:
    """Every committed csum equals host zlib.crc32 of the STORED
    bytes — the bit-exactness gate on whatever path computed it."""
    for blob in store._db.get_range("obj").values():
        meta = json.loads(blob.decode())
        co = meta.get("comp") or []
        for bi, b in enumerate(meta["extents"]):
            if b < 0:
                continue
            comp = co[bi] if bi < len(co) else None
            data = store._read_block(b)
            stored = data[:comp[1]] if comp else data
            if zlib.crc32(stored) != meta["csum"][bi]:
                return False
    return True


class TestBlueStoreBatched:

    def _store(self, tmp_path, ctx, name="s"):
        s = BlueStoreLite(str(tmp_path / name), ctx=ctx)
        s.mkfs()
        s.mount()
        s.apply_transaction(Transaction().create_collection("2.0"))
        return s

    def test_batched_csums_equal_scalar_store(self, tmp_path, ctx):
        """The same writes through a batched store and a bare scalar
        store commit IDENTICAL csum lists (and both satisfy the host
        audit) — the channel changes how checksums are computed, never
        what they are."""
        rng = np.random.default_rng(2)
        payload = bytes(rng.integers(0, 256, 6 * BLOCK + 123,
                                     dtype=np.uint8))
        batched = self._store(tmp_path, ctx, "batched")
        scalar = self._store(tmp_path, None, "scalar")
        try:
            before = telemetry.bluestore_dump()
            for s in (batched, scalar):
                t = Transaction()
                t.write("2.0", "obj", 0, payload)
                t.write("2.0", "obj", 3 * BLOCK + 7, b"patch" * 100)
                s.apply_transaction(t)
            after = telemetry.bluestore_dump()
            assert after["csum_batches"] > before["csum_batches"]
            mb = json.loads(
                batched._db.get("obj", "2.0\x00obj").decode())
            ms = json.loads(
                scalar._db.get("obj", "2.0\x00obj").decode())
            assert mb["csum"] == ms["csum"]
            assert None not in mb["csum"]
            assert _host_csum_audit(batched)
            assert batched.read("2.0", "obj") == \
                scalar.read("2.0", "obj")
        finally:
            batched.umount()
            scalar.umount()

    def test_channel_outage_scalar_oracle_carries_commits(
            self, tmp_path, ctx):
        """Kill the device launch under the channel: commits must keep
        landing with correct csums (engine-level host oracle or the
        store's scalar fallback — either way bit-exact)."""
        rng = np.random.default_rng(3)
        s = self._store(tmp_path, ctx, "outage")
        eng = ctx.decode_dispatch_engine()
        old_thresh = eng.breaker_threshold
        eng.breaker_threshold = 2
        try:
            failpoint.set("dispatch.launch:bluestore_data", "always")
            for i in range(3):
                t = Transaction()
                t.write("2.0", f"o{i}", 0,
                        bytes(rng.integers(0, 256, 3 * BLOCK,
                                           dtype=np.uint8)))
                s.apply_transaction(t)
            assert _host_csum_audit(s)
            assert eng.breaker_states().get("bluestore_data") == \
                telemetry.BREAKER_OPEN
            failpoint.clear()
            assert _wait_breaker(eng, "bluestore_data",
                                 telemetry.BREAKER_CLOSED)
            # channel healed: the next commit rides the device again
            t = Transaction()
            t.write("2.0", "healed", 0, b"h" * BLOCK)
            s.apply_transaction(t)
            assert _host_csum_audit(s)
        finally:
            eng.breaker_threshold = old_thresh
            s.umount()

    def test_compression_force_roundtrip_and_shrink(self, tmp_path,
                                                    ctx):
        rng = np.random.default_rng(4)
        s = self._store(tmp_path, ctx, "comp")
        try:
            s.set_pool_compression(2, "force", "tpu_bitplane")
            payload = bytes(rng.integers(0, 64, 8 * BLOCK,
                                         dtype=np.uint8))
            t = Transaction()
            t.write("2.0", "z", 0, payload)
            s.apply_transaction(t)
            m = json.loads(s._db.get("obj", "2.0\x00z").decode())
            assert all(c is not None and c[0] == "tpu_bitplane"
                       and c[1] < BLOCK for c in m["comp"])
            assert _host_csum_audit(s)
            assert s.read("2.0", "z") == payload
            # partial overwrite of a compressed block round-trips too
            t = Transaction()
            t.write("2.0", "z", BLOCK + 11, b"Y" * 100)
            s.apply_transaction(t)
            exp = bytearray(payload)
            exp[BLOCK + 11:BLOCK + 111] = b"Y" * 100
            assert s.read("2.0", "z") == bytes(exp)
            # clone copies stored (compressed) bytes
            t = Transaction()
            t.clone("2.0", "z", "z2")
            s.apply_transaction(t)
            assert s.read("2.0", "z2") == bytes(exp)
        finally:
            s.umount()

    def test_corrupt_compressed_block_is_eio(self, tmp_path, ctx):
        rng = np.random.default_rng(5)
        s = self._store(tmp_path, ctx, "corrupt")
        try:
            s.set_pool_compression(2, "force", "tpu_bitplane")
            payload = bytes(rng.integers(0, 64, BLOCK,
                                         dtype=np.uint8))
            t = Transaction()
            t.write("2.0", "x", 0, payload)
            s.apply_transaction(t)
            m = json.loads(s._db.get("obj", "2.0\x00x").decode())
            block, clen = m["extents"][0], m["comp"][0][1]
            # flip a stored byte on disk: the crc must catch it before
            # decompression is even attempted
            s._f.seek(block * BLOCK + clen // 2)
            old = s._f.read(1)
            s._f.seek(block * BLOCK + clen // 2)
            s._f.write(bytes([old[0] ^ 0x40]))
            s._f.flush()
            with pytest.raises(IOError, match="checksum mismatch"):
                s.read("2.0", "x")
            # now break the body STRUCTURALLY (unknown scheme tag) and
            # make the crc match it, so only decompression can object
            # -> still EIO, attributed to decompress_errors
            s._f.seek(block * BLOCK)
            s._f.write(b"\x07")
            s._f.flush()
            s._f.seek(block * BLOCK)
            body = s._f.read(clen)
            m["csum"][0] = zlib.crc32(body)
            kvt = s._db.get_transaction()
            kvt.set("obj", "2.0\x00x", json.dumps(m).encode())
            s._db.submit_transaction(kvt)
            before = telemetry.bluestore_dump()
            with pytest.raises(IOError, match="decompress"):
                s.read("2.0", "x")
            after = telemetry.bluestore_dump()
            assert after["decompress_errors"] > \
                before["decompress_errors"]
        finally:
            s.umount()

    def test_batched_read_verify_catches_flip(self, tmp_path, ctx):
        rng = np.random.default_rng(6)
        s = self._store(tmp_path, ctx, "readv")
        try:
            payload = bytes(rng.integers(0, 256, 12 * BLOCK,
                                         dtype=np.uint8))
            t = Transaction()
            t.write("2.0", "r", 0, payload)
            s.apply_transaction(t)
            before = telemetry.bluestore_dump()
            assert s.read("2.0", "r") == payload
            after = telemetry.bluestore_dump()
            assert after["read_verify_batches"] > \
                before["read_verify_batches"]
            m = json.loads(s._db.get("obj", "2.0\x00r").decode())
            s._f.seek(m["extents"][5] * BLOCK + 99)
            s._f.write(b"\xff")
            s._f.flush()
            with pytest.raises(IOError, match="checksum mismatch"):
                s.read("2.0", "r")
        finally:
            s.umount()

    def test_wal_deferred_and_remount_survive_batching(self, tmp_path,
                                                       ctx):
        """Deferred small writes, folds, and a remount all interleave
        with the batched csum path without losing a byte."""
        rng = np.random.default_rng(7)
        path = tmp_path / "wal"
        s = BlueStoreLite(str(path), ctx=ctx)
        s.mkfs()
        s.mount()
        s.apply_transaction(Transaction().create_collection("2.0"))
        base = bytes(rng.integers(0, 256, 4 * BLOCK, dtype=np.uint8))
        t = Transaction()
        t.write("2.0", "w", 0, base)
        s.apply_transaction(t)
        exp = bytearray(base)
        for i in range(20):   # > WAL_MAX forces a fold mid-stream
            off = (i * 37) % (4 * BLOCK - 64)
            t = Transaction()
            t.write("2.0", "w", off, bytes([i]) * 64)
            s.apply_transaction(t)
            exp[off:off + 64] = bytes([i]) * 64
        assert s.read("2.0", "w") == bytes(exp)
        s.umount()
        s2 = BlueStoreLite(str(path), ctx=ctx)
        s2.mount()
        try:
            assert s2.read("2.0", "w") == bytes(exp)
            assert _host_csum_audit(s2)
        finally:
            s2.umount()
