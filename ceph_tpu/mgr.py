"""Manager daemon — non-consensus cluster aggregation (src/mgr/ analog).

OSDs stream MMgrReport (perf counters + per-PG states) on their tick;
the mgr aggregates into the views the reference's mgr modules serve:
cluster health/df summaries, a PG state histogram (the balancer input),
and per-OSD op counters (prometheus-module shape, minus HTTP).
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.messages import MOSDMapMsg
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osd.map_codec import advance_map
from ceph_tpu.osd.osdmap import OSDMap


def _enc_pg_stat(e: Encoder, st: dict) -> None:
    e.str(st.get("state", ""))
    e.list(st.get("up", []), lambda e2, v: e2.s32(v))
    e.u64(st.get("num_objects", 0))
    e.u64(st.get("bytes", 0))
    e.u64(st.get("missing", 0))
    e.u64(st.get("log_size", 0))
    lh = st.get("log_head", (0, 0))
    lt = st.get("log_tail", (0, 0))
    e.u64(lh[0]).u64(lh[1]).u64(lt[0]).u64(lt[1])


def _dec_pg_stat(d: Decoder) -> dict:
    return {"state": d.str(),
            "up": d.list(lambda d2: d2.s32()),
            "num_objects": d.u64(), "bytes": d.u64(),
            "missing": d.u64(), "log_size": d.u64(),
            "log_head": (d.u64(), d.u64()),
            "log_tail": (d.u64(), d.u64())}


@register_message
class MMgrReport(Message):
    """osd -> mgr: perf counters + pg states (messages/MMgrReport.h).
    v2 adds per-PG stat records for the PGs this osd leads — the pg_dump
    / pg ls / iostat feed (pg_stat_t reduced); v1 peers interoperate,
    they just feed the histogram views only."""

    TYPE = 0x701
    HEAD_VERSION = 2
    COMPAT_VERSION = 1

    def __init__(self, osd_id: int = 0, counters: dict | None = None,
                 pg_states: dict | None = None, num_objects: int = 0,
                 bytes_used: int = 0, pg_stats: dict | None = None):
        super().__init__()
        self.osd_id = osd_id
        self.counters = counters or {}
        self.pg_states = pg_states or {}
        self.num_objects = num_objects
        self.bytes_used = bytes_used
        #: pgid-str -> per-PG stat record (primary PGs only)
        self.pg_stats = pg_stats or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(2, 1, lambda e: (
            e.s32(self.osd_id),
            e.map(self.counters, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.u64(int(v))),
            e.map(self.pg_states, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.u32(v)),
            e.u64(self.num_objects), e.u64(self.bytes_used),
            e.map(self.pg_stats, lambda e2, k: e2.str(k),
                  _enc_pg_stat)))

    def decode_payload(self, dec: Decoder, version):
        # decode constructs via __new__: every field needs a default
        # here, v1 payloads carry no pg_stats
        self.pg_stats = {}

        def body(d, v):
            self.osd_id = d.s32()
            self.counters = d.map(lambda d2: d2.str(),
                                  lambda d2: d2.u64())
            self.pg_states = d.map(lambda d2: d2.str(),
                                   lambda d2: d2.u32())
            self.num_objects = d.u64()
            self.bytes_used = d.u64()
            if v >= 2:
                self.pg_stats = d.map(lambda d2: d2.str(), _dec_pg_stat)
        dec.versioned(2, body)


class MgrDaemon(Dispatcher):
    """DaemonServer + ActivePyModules, collapsed: collect reports,
    serve aggregate views."""

    def __init__(self, mon_addr: str, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None,
                 cephx: tuple[str, str] | None = None, mgr_id: int = 0):
        self.mon_addr = mon_addr
        self.mgr_id = mgr_id
        self.name = EntityName("mgr", mgr_id)
        self.osdmap = OSDMap()
        self._lock = threading.Lock()
        #: osd -> (last report time, MMgrReport)
        self.reports: dict[int, tuple[float, MMgrReport]] = {}
        #: osd -> (time, counters) of the PREVIOUS report (iostat rates)
        self._prev_counters: dict[int, tuple[float, dict]] = {}
        #: last balancer optimize outcome (balancer status)
        self._balancer_last: dict = {}
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self._cephx = cephx
        self._rotating: dict[int, str] = {}
        self._rotating_at = 0.0
        from ceph_tpu.common.moncmd import MonCommander
        self.mon_cmd = MonCommander(
            self.msgr, [x for x in mon_addr.split(",") if x])
        if cephx is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            self.msgr.set_auth_cephx(CephxConfig(
                entity=cephx[0], key=cephx[1],
                keyring=TicketKeyring(self.mon_cmd.fetch_ticket),
                service="mgr", rotating=lambda: self._rotating))
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_server())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr

    def _refresh_rotating(self) -> None:
        keys = self.mon_cmd.fetch_rotating("mgr")
        if keys is not None:
            self._rotating = keys
            self._rotating_at = time.time()

    def _subscribe(self) -> None:
        from ceph_tpu.mon.monitor import MMonSubscribe
        for rank, a in enumerate(
                [x for x in self.mon_addr.split(",") if x]):
            con = self.msgr.connect_to(a, EntityName("mon", rank))
            con.send_message(MMonSubscribe(name=str(self.name),
                                           addr=self.msgr.my_addr,
                                           epoch=self.osdmap.epoch))

    def _renew_tick(self) -> None:
        """Timer thread — NEVER the dispatch thread: the rotating
        refresh blocks on a mon ack only the dispatch thread delivers.
        Also renews the map subscription: pushes ride the mon-side
        session, so a dropped session must be re-established."""
        if getattr(self, "_stopped", False):
            return
        try:
            self._subscribe()
            if self._cephx is not None \
                    and time.time() - self._rotating_at > 55.0:
                self._refresh_rotating()
        except (OSError, TimeoutError):
            pass
        self._rot_timer = threading.Timer(5.0, self._renew_tick)
        self._rot_timer.daemon = True
        self._rot_timer.start()

    def init(self) -> None:
        self.msgr.bind(self._addr)
        self.msgr.start()
        self._rot_timer = None
        if self._cephx is not None:
            self._refresh_rotating()
        self._renew_tick()

    def shutdown(self) -> None:
        self._stopped = True
        if getattr(self, "_rot_timer", None) is not None:
            self._rot_timer.cancel()
        if getattr(self, "_prom", None) is not None:
            self._prom.shutdown()
            self._prom.server_close()
        self.msgr.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def ms_dispatch(self, msg) -> bool:
        from ceph_tpu.messages import (
            MMonCommand, MMonCommandAck)
        if isinstance(msg, MMonCommandAck):
            self.mon_cmd.handle_ack(msg)
            return True
        if isinstance(msg, MMonCommand):
            # the mgr serves its own command tier (DaemonServer
            # handle_command): clients re-target here after `mgr dump`
            out, rc = self._handle_command(msg.cmd)
            if msg.connection is not None:
                msg.connection.send_message(MMonCommandAck(
                    tid=msg.tid, result=rc, output=out))
            return True
        if isinstance(msg, MMgrReport):
            with self._lock:
                prev = self.reports.get(msg.osd_id)
                if prev is not None:
                    # keep one older counter sample per osd: the iostat
                    # rate window (current - previous) / dt
                    self._prev_counters[msg.osd_id] = (
                        prev[0], dict(prev[1].counters))
                self.reports[msg.osd_id] = (time.time(), msg)
            return True
        if isinstance(msg, MOSDMapMsg):
            newmap, gapped = advance_map(self.osdmap, msg)
            if newmap is not None:
                self.osdmap = newmap
            elif gapped:
                self._subscribe()
            return True
        return False

    # -- command tier (DaemonServer::handle_command reduced) ------------------

    def _handle_command(self, cmd: dict) -> tuple[str, int]:
        import json as _json
        prefix = cmd.get("prefix", "")
        try:
            if prefix == "pg dump":
                return _json.dumps(self.pg_dump()), 0
            if prefix == "pg ls":
                pool = cmd.get("pool")
                states = cmd.get("states") or None
                if isinstance(states, str):
                    states = [states]
                return _json.dumps(self.pg_ls(
                    pool=int(pool) if pool is not None else None,
                    states=states)), 0
            if prefix == "iostat":
                return _json.dumps(self.iostat()), 0
            if prefix == "balancer status":
                return _json.dumps(self.balancer_status()), 0
            if prefix == "balancer optimize":
                return _json.dumps({"commands": self.balance_plan()}), 0
            if prefix == "telemetry show":
                return _json.dumps(self.telemetry_report()), 0
            return f"unknown mgr command {prefix!r}", -22
        except Exception as e:
            return f"mgr command failed: {e!r}", -22

    # -- aggregate views (mgr module surface) ---------------------------------

    def pg_summary(self) -> dict:
        """PG state histogram across OSD reports (`ceph status` pgs)."""
        out: dict[str, int] = {}
        with self._lock:
            for _t, rep in self.reports.values():
                for state, n in rep.pg_states.items():
                    out[state] = out.get(state, 0) + n
        return out

    def df(self) -> dict:
        with self._lock:
            return {
                "total_objects": sum(r.num_objects
                                     for _t, r in self.reports.values()),
                "total_bytes_used": sum(
                    r.bytes_used for _t, r in self.reports.values()),
                "per_osd": {o: {"objects": r.num_objects,
                                "bytes": r.bytes_used}
                            for o, (_t, r) in self.reports.items()},
            }

    def counters(self) -> dict:
        with self._lock:
            return {o: dict(r.counters)
                    for o, (_t, r) in self.reports.items()}

    def balance_plan(self, **kw) -> list[dict]:
        """Balancer module in upmap mode: mon commands that flatten the
        per-OSD PG histogram of the mgr's current osdmap."""
        from ceph_tpu.balancer import plan_commands
        cmds = plan_commands(self.osdmap, **kw)
        self._balancer_last = {"time": time.time(),
                               "commands": len(cmds),
                               "pool_spread": self._pool_spread_scores()}
        return cmds

    def _pool_spread_scores(self) -> dict:
        from ceph_tpu.balancer import spread
        m = self.osdmap          # snapshot: dispatch may swap the map
        scores = {}
        for pid in list(m.pools):
            lo, hi = spread(m, pid)
            scores[pid] = {"min": lo, "max": hi}
        return scores

    def balancer_status(self) -> dict:
        """`ceph balancer status` shape: mode, the last optimize
        outcome, and the current per-pool PG spread score."""
        return {"mode": "upmap", "active": True,
                "last_optimize": dict(self._balancer_last),
                "pool_spread": self._pool_spread_scores()}

    # -- pg introspection (DaemonServer `pg dump` / `pg ls`) ------------------

    def _pg_rows(self) -> list[dict]:
        """Merged per-PG records across osd reports; when two osds both
        claim a pg (a remap race window) the NEWEST report wins."""
        best: dict[str, tuple[float, int, dict]] = {}
        with self._lock:
            for osd, (t, rep) in self.reports.items():
                for pgid, st in (rep.pg_stats or {}).items():
                    cur = best.get(pgid)
                    if cur is None or t > cur[0]:
                        best[pgid] = (t, osd, st)
        rows = []
        for pgid, (t, osd, st) in best.items():
            row = dict(st)
            row["pgid"] = pgid
            row["reported_by"] = osd
            row["stamp"] = t
            rows.append(row)
        rows.sort(key=lambda r: tuple(
            int(x) for x in r["pgid"].split(".")))
        return rows

    def pg_dump(self) -> dict:
        """`ceph pg dump` (DaemonServer::_handle_pg_dump reduced):
        every PG's state/acting/usage/log bounds plus per-osd totals."""
        rows = self._pg_rows()
        with self._lock:
            osd_stats = {o: {"num_objects": r.num_objects,
                             "bytes_used": r.bytes_used,
                             "stamp": t}
                         for o, (t, r) in self.reports.items()}
        return {"pg_stats": rows, "osd_stats": osd_stats,
                "num_pgs": len(rows)}

    def pg_ls(self, pool: int | None = None,
              states: list[str] | None = None) -> list[dict]:
        """`ceph pg ls [pool] [states...]`."""
        rows = self._pg_rows()
        if pool is not None:
            rows = [r for r in rows
                    if int(r["pgid"].split(".")[0]) == pool]
        if states:
            rows = [r for r in rows if r["state"] in states]
        return rows

    # -- iostat module (src/pybind/mgr/iostat analog) -------------------------

    def iostat(self) -> dict:
        """Cluster I/O rates from successive report counter samples:
        per-osd and total wr/rd ops per second over each osd's last
        report interval."""
        out: dict = {"osds": {}, "total_wr_ops_s": 0.0,
                     "total_rd_ops_s": 0.0}
        now = time.time()
        with self._lock:
            for osd, (t, rep) in self.reports.items():
                if now - t > 10.0:
                    # a dead osd's last interval is not a current rate:
                    # stale reporters drop out instead of reporting
                    # their final rate forever
                    continue
                prev = self._prev_counters.get(osd)
                if prev is None:
                    continue
                pt, pc = prev
                dt = t - pt
                if dt <= 0:
                    continue
                wr = (rep.counters.get("op_w", 0)
                      - pc.get("op_w", 0)) / dt
                rd = (rep.counters.get("op_r", 0)
                      - pc.get("op_r", 0)) / dt
                out["osds"][osd] = {"wr_ops_s": round(max(wr, 0.0), 3),
                                    "rd_ops_s": round(max(rd, 0.0), 3),
                                    "interval_s": round(dt, 3)}
                out["total_wr_ops_s"] += max(wr, 0.0)
                out["total_rd_ops_s"] += max(rd, 0.0)
        out["total_wr_ops_s"] = round(out["total_wr_ops_s"], 3)
        out["total_rd_ops_s"] = round(out["total_rd_ops_s"], 3)
        return out

    # -- telemetry module (src/pybind/mgr/telemetry analog) -------------------

    def telemetry_report(self) -> dict:
        """Anonymized cluster-shape report (`ceph telemetry show`): no
        object names, no addresses — counts, sizes, states, pool shapes
        and daemon versions only, like the reference's opt-in payload."""
        m = self.osdmap
        pools = []
        for pid, p in m.pools.items():
            pools.append({
                "pool": pid, "pg_num": p.pg_num,
                "type": ("erasure" if p.is_erasure() else "replicated"),
                "size": getattr(p, "size", 0),
                "cache_tier": p.tier_of >= 0})
        df = self.df()
        return {
            "report_version": 1,
            "osd": {"count": sum(1 for o in range(m.max_osd)
                                 if m.exists(o)),
                    "up": sum(1 for o in range(m.max_osd)
                              if m.is_up(o))},
            "osdmap_epoch": m.epoch,
            "pools": pools,
            "pg_states": self.pg_summary(),
            "usage": {"total_objects": df["total_objects"],
                      "total_bytes_used": df["total_bytes_used"]},
            "health": self.health()["status"],
        }

    def health(self, stale_after: float = 10.0) -> dict:
        now = time.time()
        with self._lock:
            stale = [o for o, (t, _r) in self.reports.items()
                     if now - t > stale_after]
        checks = []
        if stale:
            checks.append({"check": "MGR_STALE_REPORTS", "osds": stale})
        summary = self.pg_summary()
        degraded = sum(n for s, n in summary.items()
                       if s not in ("active", "replica"))
        if degraded:
            checks.append({"check": "PG_DEGRADED", "count": degraded})
        return {"status": "HEALTH_OK" if not checks else "HEALTH_WARN",
                "checks": checks}

    # -- prometheus module (src/pybind/mgr/prometheus analog) -----------------

    def prometheus_text(self) -> str:
        """The exporter's scrape payload: every aggregated counter and
        gauge in the prometheus text exposition format."""
        lines = [
            "# HELP ceph_health_status cluster health (0=OK 1=WARN)",
            "# TYPE ceph_health_status gauge",
            f"ceph_health_status "
            f"{0 if self.health()['status'] == 'HEALTH_OK' else 1}",
        ]
        m = self.osdmap
        lines += [
            "# TYPE ceph_osd_up gauge",
            f"ceph_osd_up {sum(1 for o in range(m.max_osd) if m.is_up(o))}",
            "# TYPE ceph_osd_in gauge",
            f"ceph_osd_in {sum(1 for o in range(m.max_osd) if m.exists(o) and m.osd_weight[o] > 0)}",
            "# TYPE ceph_osdmap_epoch gauge",
            f"ceph_osdmap_epoch {m.epoch}",
        ]
        for state, n in sorted(self.pg_summary().items()):
            lines.append(f'ceph_pg_states{{state="{state}"}} {n}')
        df = self.df()
        lines.append(f"ceph_cluster_total_objects {df['total_objects']}")
        lines.append(f"ceph_cluster_bytes_used {df['total_bytes_used']}")
        for osd, (_t, rep) in sorted(self.reports.items()):
            for name, val in sorted(rep.counters.items()):
                lines.append(
                    f'ceph_osd_perf{{ceph_daemon="osd.{osd}",'
                    f'counter="{name}"}} {int(val)}')
        return "\n".join(lines) + "\n"

    def serve_prometheus(self, port: int = 0) -> int:
        """Start the HTTP exporter; returns the bound port (GET /metrics
        — the mgr prometheus module's endpoint)."""
        import http.server
        import socketserver

        mgr = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = mgr.prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._prom = Server(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._prom.serve_forever, daemon=True)
        t.start()
        return self._prom.server_address[1]
