"""RGW Swift frontend — the OpenStack Object Storage dialect over the
same S3Gateway/rgw_lite storage mapping (src/rgw/rgw_rest_swift.cc +
rgw_swift_auth.cc analog).

Surface (the Swift v1 core the reference serves):

  * TempAuth-style v1.0 auth: ``GET /auth/v1.0`` with X-Auth-User /
    X-Auth-Key returns X-Auth-Token + X-Storage-Url; tokens are HMACs
    over the account with an expiry, verified statelessly
  * account: ``GET /v1/AUTH_<acct>`` lists containers (text or JSON)
  * container: PUT (create), DELETE (must be empty), GET (list objects,
    prefix/marker/limit paging, text or JSON), HEAD (object count)
  * object: PUT (with X-Object-Meta-*), GET, HEAD, DELETE; COPY via
    X-Copy-From

Buckets are shared with the S3 frontend one-to-one: a container created
here is a bucket there (the reference stores both dialects over the
same rgw_rados layout).  Swift-created containers are owned by the
authenticated account and private by default.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time
import urllib.parse
from ceph_tpu.rgw_frontend import AsyncHttpFrontend

from ceph_tpu.rgw_rest import S3Error, S3Gateway

TOKEN_TTL = 3600.0


class SwiftRestServer:
    """The Swift-dialect HTTP shell around an S3Gateway."""

    def __init__(self, ioctx=None, addr: str = "127.0.0.1:0",
                 gateway: S3Gateway | None = None, clock=time.time,
                 token_ttl: float = TOKEN_TTL):
        if gateway is None:
            gateway = S3Gateway(ioctx, clock=clock)
        self.gateway = gateway
        self.clock = clock
        self.token_ttl = token_ttl
        #: account -> swift key (X-Auth-User "acct:user" uses acct part)
        self.accounts: dict[str, str] = {}
        # per-server random key (rgw_swift_auth's server-held secret):
        # a captured token must not let an attacker brute-force the key
        # offline and mint tokens for other accounts
        self._token_secret = os.urandom(32)
        #: the same event-driven frontend the S3 dialect rides
        #: (rgw_frontend: one I/O loop + bounded handler pool)
        self._frontend = AsyncHttpFrontend(
            lambda req: _SwiftRequest(self, req).handle(), addr)

    # -- lifecycle ------------------------------------------------------------

    @property
    def addr(self) -> str:
        return self._frontend.addr

    def start(self) -> "SwiftRestServer":
        self._frontend.start()
        return self

    def shutdown(self) -> None:
        self._frontend.stop()

    # -- accounts / tokens ----------------------------------------------------

    def add_account(self, account: str, key: str) -> None:
        self.accounts[account] = key

    def issue_token(self, account: str) -> str:
        exp = int(self.clock() + self.token_ttl)
        mac = hmac.new(self._token_secret,
                       f"{account}:{exp}".encode(),
                       hashlib.sha256).hexdigest()[:32]
        return f"AUTH_tk_{account}_{exp}_{mac}"

    def verify_token(self, token: str) -> str | None:
        """Account name for a valid unexpired token, else None."""
        if not token.startswith("AUTH_tk_"):
            return None
        try:
            body = token[len("AUTH_tk_"):]
            account, exp_s, mac = body.rsplit("_", 2)
            exp = int(exp_s)
        except ValueError:
            return None
        want = hmac.new(self._token_secret,
                        f"{account}:{exp}".encode(),
                        hashlib.sha256).hexdigest()[:32]
        if not hmac.compare_digest(mac, want):
            return None
        if self.clock() > exp:
            return None
        return account


class _SwiftRequest:
    """One request's routing context over the async frontend (the same
    transport-neutral shape as rgw_rest._S3Request)."""

    def __init__(self, srv: "SwiftRestServer", req) -> None:
        self._srv = srv
        self.command = req.method
        self.path = req.target
        self.headers = req.headers
        self._body = req.body
        self._out: tuple[int, dict, bytes] | None = None

    def handle(self) -> tuple[int, dict, bytes]:
        self._dispatch()
        if self._out is None:
            self._out = (500, {}, b"no response")
        return self._out

    # -- plumbing -------------------------------------------------------------

    def _respond(self, status: int, body: bytes = b"",
                 headers: dict | None = None) -> None:
        merged = dict(headers or {})
        merged["Content-Length"] = str(len(body))
        self._out = (status, merged,
                     b"" if self.command == "HEAD" else body)

    def _dispatch(self) -> None:
        srv = self._srv
        body = self._body
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query,
                                        keep_blank_values=True))
        path = urllib.parse.unquote(parsed.path)
        try:
            if path == "/auth/v1.0":
                return self._auth(srv)
            if not path.startswith("/v1/AUTH_"):
                return self._respond(404, b"not a swift path")
            rest = path[len("/v1/AUTH_"):]
            parts = rest.split("/", 2)
            account = parts[0]
            container = parts[1] if len(parts) > 1 else ""
            obj = parts[2] if len(parts) > 2 else ""
            token = self.headers.get("X-Auth-Token", "")
            principal = srv.verify_token(token)
            if principal is None or principal != account:
                return self._respond(401, b"invalid or expired token")
            if not container:
                return self._account(srv, account, q)
            if not obj:
                return self._container(srv, account, container, q)
            return self._object(srv, account, container, obj, body)
        except S3Error as e:
            code = {"NoSuchBucket": 404, "NoSuchKey": 404,
                    "BucketNotEmpty": 409,
                    "AccessDenied": 403}.get(e.code, 400)
            return self._respond(code, str(e).encode())
        except Exception as e:   # pragma: no cover
            return self._respond(500, repr(e).encode())

    # -- auth -----------------------------------------------------------------

    def _auth(self, srv: SwiftRestServer) -> None:
        user = self.headers.get("X-Auth-User", "")
        key = self.headers.get("X-Auth-Key", "")
        account = user.split(":", 1)[0]
        want = srv.accounts.get(account)
        if want is None or not hmac.compare_digest(want, key):
            return self._respond(401, b"bad credentials")
        token = srv.issue_token(account)
        host = self.headers.get("Host", srv.addr)
        self._respond(200, b"", {
            "X-Auth-Token": token,
            "X-Storage-Token": token,
            "X-Storage-Url": f"http://{host}/v1/AUTH_{account}"})

    # -- account --------------------------------------------------------------

    def _acct_buckets(self, srv: SwiftRestServer, account: str
                      ) -> list[str]:
        # ONE registry read: owners live in the registry values, so an
        # account listing does not fetch every bucket's index
        gw = srv.gateway
        try:
            reg = gw.io.get_omap(gw.REGISTRY)
        except OSError:
            return []
        want = f"swift:{account}".encode()
        return sorted(n for n, owner in reg.items() if owner == want)

    def _account(self, srv: SwiftRestServer, account: str,
                 q: dict) -> None:
        if self.command not in ("GET", "HEAD"):
            return self._respond(405)
        names = self._acct_buckets(srv, account)
        if q.get("format") == "json":
            body = json.dumps([{"name": n} for n in names]).encode()
            ctype = "application/json"
        else:
            body = ("\n".join(names) + ("\n" if names else "")).encode()
            ctype = "text/plain"
        self._respond(200 if names else 204, body, {
            "Content-Type": ctype,
            "X-Account-Container-Count": str(len(names))})

    # -- container ------------------------------------------------------------

    def _container(self, srv: SwiftRestServer, account: str,
                   name: str, q: dict) -> None:
        gw = srv.gateway
        principal = f"swift:{account}"
        if self.command == "PUT":
            try:
                gw.create_bucket(name, owner=principal)
                return self._respond(201)
            except S3Error as e:
                if e.code == "BucketAlreadyExists":
                    return self._respond(202)   # idempotent in swift
                raise
        gw.authorize_owner(name, principal)
        if self.command == "DELETE":
            gw.delete_bucket(name)
            return self._respond(204)
        if self.command in ("GET", "HEAD"):
            limit = max(1, min(int(q.get("limit", 10000)), 10000))
            entries, _tok = gw.list_objects(
                name, q.get("prefix", ""), limit, q.get("marker", ""))
            if q.get("format") == "json":
                rows = [{"name": k, "bytes": h.get("size", 0),
                         "last_modified": h.get("mtime", 0)}
                        for k, h in entries]
                body = json.dumps(rows).encode()
                ctype = "application/json"
            else:
                body = ("\n".join(k for k, _h in entries)
                        + ("\n" if entries else "")).encode()
                ctype = "text/plain"
            return self._respond(200 if entries else 204, body, {
                "Content-Type": ctype,
                "X-Container-Object-Count": str(len(entries))})
        self._respond(405)

    # -- object ---------------------------------------------------------------

    def _object(self, srv: SwiftRestServer, account: str,
                container: str, obj: str, body: bytes) -> None:
        gw = srv.gateway
        principal = f"swift:{account}"
        gw.authorize_owner(container, principal)
        if self.command == "PUT":
            src = self.headers.get("X-Copy-From", "")
            if src:
                sc, _, so = src.lstrip("/").partition("/")
                # the SOURCE needs read authorization too — without it
                # any authenticated account could exfiltrate another
                # account's private data via copy
                gw.authorize(sc, principal, write=False)
                data, head = gw.get_object(sc, so)
                gw.put_object(container, obj, data,
                              dict(head.get("meta") or {}))
                return self._respond(201)
            meta = {k[len("X-Object-Meta-"):]: v
                    for k, v in self.headers.items()
                    if k.lower().startswith("x-object-meta-")}
            etag, _vid = gw.put_object(container, obj, body, meta)
            return self._respond(201, b"", {"ETag": etag})
        if self.command == "HEAD":
            # metadata only — never read/decompress the body for HEAD
            head = gw.head_object(container, obj)
            hdrs = {"Content-Type": "application/octet-stream",
                    "Content-Length-Hint": str(head.get("size", 0))}
            if head.get("etag"):
                hdrs["ETag"] = head["etag"]
            for mk, mv in (head.get("meta") or {}).items():
                hdrs[f"X-Object-Meta-{mk}"] = mv
            return self._respond(200, b"", hdrs)
        if self.command == "GET":
            data, head = gw.get_object(container, obj)
            hdrs = {"Content-Type": "application/octet-stream",
                    "ETag": head.get("etag")
                    or hashlib.md5(data).hexdigest()}
            for mk, mv in (head.get("meta") or {}).items():
                hdrs[f"X-Object-Meta-{mk}"] = mv
            return self._respond(200, data, hdrs)
        if self.command == "DELETE":
            gw.head_object(container, obj)   # swift 404s a missing obj
            gw.delete_object(container, obj)
            return self._respond(204)
        self._respond(405)
