"""Heterogeneous-matrix batched GF decode riding the dispatch engine.

The decode-side twin of test_dispatch.py.  Encode coalesces trivially
(one matrix for everyone); decode's recovery matrix differs per erasure
pattern, so the load-bearing claims here are pattern-shaped:

  * bit-exactness under MIXED patterns — N threads submitting decodes
    with different erasure patterns AND different stripe counts through
    one engine each get exactly what the numpy recovery_matrix oracle
    computes for their own pattern, however the engine stacked, padded,
    gathered, and sliced;
  * padded-bucket equality — stripe-axis zero padding, matrix-table
    pow-2 padding, and target-row padding (t < t_bucket) are all
    invisible in delivered bytes;
  * the jit compile cache is bounded by the PRODUCT of the two bucket
    tables (stripe axis x matrix-table axis), not by the number of
    distinct erasure patterns or request sizes (exact-count via the
    decode entry point's compile-cache delta);
  * mixed-pattern requests queued while the engine is busy share ONE
    device call (the claim the per-stripe pattern index exists for),
    and the decode stats record the heterogeneity.

Chunk widths here are unique to this suite: the jit cache is
process-global and the bounded-cache test counts entries.
"""

from __future__ import annotations

import threading

import numpy as np

from ceph_tpu.gf.matrix import recovery_matrix
from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import DeviceDispatchEngine, bucket_stripes
from ceph_tpu.ops.gf_kernel import (decode_bit_table, ec_decode_batched,
                                    ec_decode_ref, ec_encode_ref)

K1, M1, B1 = 4, 2, 352     # bit-exactness suites
K2, M2, B2 = 5, 3, 224     # bounded-cache suite


def _codec(k, m, runtime="tpu"):
    from ceph_tpu.ec import registry_instance
    return registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m),
                "runtime": runtime})


def _patterns(k, m, count):
    """Deterministic spread of erasure patterns: (chosen, targets)
    pairs with 1..m erased data chunks, parity filling in."""
    out = []
    n = k + m
    for i in range(count):
        n_erase = 1 + i % m
        erased = sorted({(i * 7 + j * 3) % k for j in range(n_erase)})
        chosen = [c for c in range(n) if c not in erased][:k]
        out.append((tuple(chosen), tuple(erased)))
    # dedup, keep order
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def _stripes(n, k, b, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, k, b), dtype=np.uint8)


# -- kernel level -------------------------------------------------------------

def test_decode_ref_matches_encode_ref_per_pattern():
    """The heterogeneous oracle degenerates to the plain one when every
    stripe shares a pattern."""
    codec = _codec(K1, M1)
    (chosen, targets) = _patterns(K1, M1, 3)[1]
    rmat = recovery_matrix(codec.generator, list(chosen), list(targets))
    data = _stripes(6, K1, B1, seed=1)
    pidx = np.zeros(6, np.int32)
    got = ec_decode_ref(rmat[None], pidx, data)
    assert (got == ec_encode_ref(rmat, data)).all()


def test_kernel_mixed_patterns_one_call_bit_exact():
    """ec_decode_batched with stripes spanning several patterns equals
    the per-stripe oracle — the batched gather+matmul is the tentpole."""
    codec = _codec(K1, M1)
    pats = _patterns(K1, M1, 4)
    t = max(len(tg) for _c, tg in pats)
    mats = []
    for chosen, targets in pats:
        r = recovery_matrix(codec.generator, list(chosen), list(targets))
        p = np.zeros((t, K1), np.uint8)
        p[:len(targets)] = r
        mats.append(p)
    tab = decode_bit_table(mats)
    rng = np.random.default_rng(2)
    data = _stripes(19, K1, B1, seed=2)
    pidx = rng.integers(0, len(pats), 19).astype(np.int32)
    got = np.asarray(ec_decode_batched(tab, pidx, data, k=K1, t=t))
    want = ec_decode_ref(np.stack(mats), pidx, data)
    assert (got == want).all()


# -- codec submit path: bit-exactness under threaded mixed patterns ----------

def test_threaded_mixed_pattern_decodes_bit_exact():
    """8 readers x 5 decodes each — random erasure pattern AND random
    stripe count per op, all through one engine: every delivered
    reconstruction equals the numpy recovery_matrix oracle for that
    reader's own pattern and data."""
    codec = _codec(K1, M1)
    pats = _patterns(K1, M1, 2 * M1)
    eng = DeviceDispatchEngine(max_delay_us=500.0,
                               stats=telemetry.DecodeDispatchStats())
    errors: list[str] = []

    def reader(rid):
        rng = np.random.default_rng(300 + rid)
        for i in range(5):
            chosen, targets = pats[int(rng.integers(0, len(pats)))]
            data = _stripes(int(rng.integers(1, 27)), K1, B1,
                            seed=rid * 100 + i)
            got = codec.submit_decode_chunks(
                eng, chosen, data, targets).result(timeout=120)
            rmat = recovery_matrix(codec.generator, list(chosen),
                                   list(targets))
            want = ec_encode_ref(rmat, data)
            if np.asarray(got).shape != want.shape:
                errors.append(f"reader {rid} op {i}: shape "
                              f"{np.asarray(got).shape} != {want.shape}")
            elif not (np.asarray(got) == want).all():
                errors.append(f"reader {rid} op {i}: mismatch "
                              f"(pattern {targets})")

    try:
        threads = [threading.Thread(target=reader, args=(r,))
                   for r in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
    finally:
        eng.stop()


def test_padded_bucket_decode_equals_unpadded():
    """Non-pow2 stripe counts, a non-pow2 pattern table, and t below
    the target bucket all pad with zeros on dispatch; delivered rows
    must equal the unpadded oracle."""
    codec = _codec(K1, M1)
    pats = _patterns(K1, M1, 3)       # 3 patterns -> table pads to 4
    stats = telemetry.DecodeDispatchStats()
    eng = DeviceDispatchEngine(stats=stats)
    try:
        for n, (chosen, targets) in zip((3, 5, 7, 11), pats + pats[:1]):
            data = _stripes(n, K1, B1, seed=n)
            got = codec.submit_decode_chunks(
                eng, chosen, data, targets).result(timeout=120)
            rmat = recovery_matrix(codec.generator, list(chosen),
                                   list(targets))
            want = ec_encode_ref(rmat, data)
            assert np.asarray(got).shape == (n, len(targets), B1)
            assert (np.asarray(got) == want).all()
        # 3->4, 5->8, 7->8, 11->16: stripe padding genuinely happened
        assert stats.padded_stripes == (1 + 3 + 1 + 5)
    finally:
        eng.stop()


# -- compile-cache bound: stripe buckets x table buckets ---------------------

def test_decode_jit_cache_bounded_by_bucket_tables():
    """30 randomized decodes over mixed sizes AND mixed patterns
    compile AT MOST one executable per (stripe bucket x table bucket)
    pair — the two-axis bound the pow-2 padding exists for.  Unbucketed,
    the same traffic would retrace per (size, pattern-count) pair."""
    from ceph_tpu.ops.gf_kernel import _decode_jit_entries
    codec = _codec(K2, M2)
    pats = _patterns(K2, M2, 2 * M2)
    eng = DeviceDispatchEngine(stats=telemetry.DecodeDispatchStats())
    rng = np.random.default_rng(5)
    sizes = [int(s) for s in rng.integers(1, 49, 30)]
    table_buckets = set()
    before = _decode_jit_entries()
    try:
        n_pat = 0
        for i, n in enumerate(sizes):
            # grow the pattern population as we go: the table crosses
            # pow-2 boundaries mid-sweep
            n_pat = min(n_pat + 1, len(pats))
            chosen, targets = pats[i % n_pat]
            out = codec.submit_decode_chunks(
                eng, chosen, _stripes(n, K2, B2, seed=i),
                targets).result(timeout=120)
            assert np.asarray(out).shape == (n, len(targets), B2)
            table_buckets.add(bucket_stripes(n_pat))
        grown = _decode_jit_entries() - before
        stripe_buckets = {bucket_stripes(n) for n in sizes}
        bound = len(stripe_buckets) * len(table_buckets)
        assert grown <= bound, \
            f"{grown} compiles for {len(stripe_buckets)} stripe x " \
            f"{len(table_buckets)} table buckets (bound {bound})"
    finally:
        eng.stop()


# -- mixed patterns share one device call ------------------------------------

def test_mixed_patterns_queued_while_busy_share_one_call():
    """Decodes with DIFFERENT erasure patterns queued behind a busy
    engine coalesce into ONE device call — the claim the per-stripe
    pattern index exists for — and the decode stats record the
    heterogeneity (patterns histogram mass above 1)."""
    codec = _codec(K1, M1)
    pats = _patterns(K1, M1, 4)
    stats = telemetry.DecodeDispatchStats()
    eng = DeviceDispatchEngine(max_delay_us=50_000.0, stats=stats)
    entered = threading.Event()
    release = threading.Event()

    def slow(a):
        entered.set()
        release.wait(5.0)
        return a

    try:
        blocker = eng.submit(("slow", 0), slow, np.zeros((1,), np.uint8))
        assert entered.wait(5.0)
        futs, wants = [], []
        for i, (chosen, targets) in enumerate(pats):
            data = _stripes(2 + i, K1, B1, seed=40 + i)
            futs.append(codec.submit_decode_chunks(
                eng, chosen, data, targets))
            rmat = recovery_matrix(codec.generator, list(chosen),
                                   list(targets))
            wants.append(ec_encode_ref(rmat, data))
        release.set()
        for f, want in zip(futs, wants):
            assert (np.asarray(f.result(timeout=120)) == want).all()
        blocker.result(timeout=10)
        assert stats.batches == 2, \
            "4 mixed-pattern decodes must share 1 device call"
        assert stats.coalesce.sum == 5          # 1 blocker + 4 decodes
        # heterogeneity lands in the ENGINE's own stats sink, and the
        # one coalesced call carried EXACTLY the 4 real patterns —
        # bucket padding (14 stripes -> 16) edge-repeats the last
        # pattern index instead of inventing pattern 0
        assert stats.patterns.count == 1
        assert stats.patterns.sum == len(pats)
        assert stats.pattern_table_size >= len(pats)
    finally:
        eng.stop()


def test_pattern_table_retires_at_cap(monkeypatch):
    """A cap-full pattern table retires wholesale into a fresh
    generation: the registry stays bounded on churning membership,
    in-flight indices stay valid (the fn captures its table object and
    the generation rides the engine key), and decodes spanning a
    retirement stay bit-exact."""
    from ceph_tpu.ec import base as ec_base
    monkeypatch.setattr(ec_base, "PATTERN_TABLE_CAP", 2)
    codec = _codec(K1, M1)
    pats = _patterns(K1, M1, 2 * M1)
    assert len(pats) > 2               # more patterns than the cap
    eng = DeviceDispatchEngine(stats=telemetry.DecodeDispatchStats())
    try:
        gens = set()
        for i, (chosen, targets) in enumerate(pats * 2):
            data = _stripes(3 + i % 4, K1, B1, seed=60 + i)
            got = codec.submit_decode_chunks(
                eng, chosen, data, targets).result(timeout=120)
            rmat = recovery_matrix(codec.generator, list(chosen),
                                   list(targets))
            assert (np.asarray(got) == ec_encode_ref(rmat, data)).all()
            tab = codec._pattern_tables[
                codec._target_bucket(len(targets))]
            assert len(tab["mats"]) <= 2
            gens.add(tab["gen"])
        assert len(gens) > 1, "cap never retired the table"
    finally:
        eng.stop()


# -- end-to-end: degraded read + recovery ride the decode engine -------------

def test_degraded_read_rides_decode_engine():
    """A cluster degraded read (shard object removed) reconstructs
    through submit_decode_chunks: returned bytes intact, the OSD
    ec_decode_submits counter moves, and the context decode engine's
    stats sink (the global DecodeDispatchStats) records the call."""
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        client = c.client()
        pool = c.create_pool(client, pg_num=1, pool_type="erasure",
                             k=2, m=1)
        io = client.open_ioctx(pool)
        payload = b"decode engine payload " * 200
        io.write_full("victim", payload)
        sub0 = telemetry.decode_dispatch_stats().submits
        removed = 0
        for osd in c.osds.values():
            for cid in list(osd.store.list_collections()):
                if not cid.startswith(f"{pool}."):
                    continue
                for oid in list(osd.store.list_objects(cid)):
                    if oid == "victim:0" and removed == 0:
                        from ceph_tpu.objectstore import Transaction
                        osd.store.apply_transaction(
                            Transaction().remove(cid, oid))
                        removed = 1
        assert removed == 1
        assert io.read("victim") == payload
        assert telemetry.decode_dispatch_stats().submits > sub0, \
            "degraded read did not ride the decode engine"
        assert sum(o.perf.value("ec_decode_submits")
                   for o in c.osds.values()) > 0
    finally:
        c.stop()
